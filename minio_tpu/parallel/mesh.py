"""Multi-chip sharding of the erasure data path.

Mapping of the reference's distribution axes onto a TPU mesh (reference
parallelism inventory: SURVEY §2.5):

  dp ("data")     — independent objects/blocks: batch dim of the shard
                    tensors. The analog of the reference's per-request
                    goroutine fan-out (its RAM-gated admission control).
  sp ("sequence") — byte columns of a block. Blocks are GF-columnwise
                    independent, so a huge object's bytes shard across
                    chips with zero cross-talk in encode/decode — the
                    storage analog of sequence/context parallelism (no
                    ring needed; the "attention" here is column-local).
  tp              — output-shard rows (the coding matrix's rows) can be
                    row-sharded for very wide sets; with n <= 32 shards
                    the matrix is tiny, so tp is folded into dp unless
                    explicitly requested.
  ep              — erasure-set routing (sipHashMod object->set) stays on
                    the host control plane (object/sets.py), exactly like
                    the reference's static "expert" routing.

Collectives used (all ride ICI inside a pool): all_gather to reassemble
per-shard integrity tags across sp; psum for global counters/consistency
checks. Cross-host traffic (remote drives) stays on the gRPC/HTTP data
plane (storage/), mirroring the reference's DCN split.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import rs_matrix, rs_tpu
from ..models import pipeline


def make_mesh(n_devices: int | None = None,
              devices=None) -> Mesh:
    """Factor n devices into a (dp, sp) mesh, favoring sp (byte-column
    sharding scales with object size; batch with request rate)."""
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    n = len(devices)
    sp = 1
    for cand in range(min(n, 8), 0, -1):
        if n % cand == 0:
            sp = cand
            break
    dp = n // sp
    dev_array = np.asarray(devices).reshape(dp, sp)
    return Mesh(dev_array, axis_names=("dp", "sp"))


def sharded_put_step(mesh: Mesh, k: int, m: int):
    """Build the jitted multi-chip PUT step over `mesh`: the full
    encode+bitrot pipeline with real collectives.

    In:  data (B, k, S) uint8, B % dp == 0, S % (sp*128) == 0, and
         (k+m) % sp == 0.
    Out: parity (B, m, S) column-sharded like the input; digests
         (B, k+m, 32) HighwayHash256 per shard, row-sharded along sp;
         a psum'd consistency counter.

    Encode runs column-sharded (sp = byte columns, GF-columnwise
    independent — zero collectives). Bitrot digests are sequential over a
    shard's *full* byte stream, so the pipeline re-shards (B, n, S) from
    column-sharded to shard-row-sharded with an all_to_all over sp (the
    storage analog of a sequence-parallel attention's SP->TP switch), then
    each device HighwayHashes its rows whole.
    """
    pm = np.asarray(rs_matrix.parity_matrix(k, m))
    m2 = rs_tpu._bit_expand_cached(pm.tobytes(), pm.shape)
    from ..bitrot import MAGIC_HIGHWAYHASH_KEY
    from ..ops import highwayhash_jax
    n = k + m
    sp_size = mesh.devices.shape[1]
    assert n % sp_size == 0, "total shards must divide the sp axis"

    def local_step(data):  # data: (B/dp, k, S/sp)
        parity = rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16), data)
        full = jnp.concatenate([data, parity], axis=-2)  # (B/dp, n, S/sp)
        # SP->TP reshard: split shard rows across sp, gather byte columns
        rows = jax.lax.all_to_all(full, "sp", split_axis=1, concat_axis=2,
                                  tiled=True)            # (B/dp, n/sp, S)
        b_loc, r_loc, s_full = rows.shape
        digests = highwayhash_jax._hh256_impl(
            rows.reshape(b_loc * r_loc, s_full), s_full,
            bytes(MAGIC_HIGHWAYHASH_KEY)).reshape(b_loc, r_loc, 32)
        # global consistency counter (exercises psum across both axes)
        total = jax.lax.psum(
            jax.lax.psum(jnp.sum(parity.astype(jnp.int32) & 1), "sp"), "dp")
        return parity, digests, total

    from jax.experimental.shard_map import shard_map
    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp", "sp", None), P()),
        check_rep=False)
    return jax.jit(fn)


def sharded_get_step(mesh: Mesh, k: int, m: int, present_mask: int):
    """Multi-chip fused verify+decode (the r3 flagship in SPMD form):
    survivors (B, k, S) in decode `used` order, column-sharded ->
    (missing data rows, survivor HighwayHash256 digests).

    The decode matmul is GF-columnwise independent (zero collectives);
    the digest pass reshards survivors SP->TP with an all_to_all so
    each device hashes whole shard rows — identical collective pattern
    to the PUT pipeline, so GET-with-failures scales the same way.
    k that doesn't divide the sp axis is zero-padded for the digest
    reshard (pad-row digests are dropped before returning).
    """
    dm, _used, missing = rs_matrix.missing_data_matrix(
        k, m, present_mask)
    m2 = rs_tpu._bit_expand_cached(dm.tobytes(), dm.shape)
    from ..bitrot import MAGIC_HIGHWAYHASH_KEY
    from ..ops import highwayhash_jax
    sp_size = mesh.devices.shape[1]
    # the digest all_to_all splits shard rows across sp: pad k up to a
    # multiple (padded rows hash garbage nobody reads; the matmul is
    # untouched)
    k_pad = -(-k // sp_size) * sp_size

    def local_step(survivors):  # (B/dp, k, S/sp)
        out = rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16),
                                   survivors)
        padded = jnp.pad(survivors, ((0, 0), (0, k_pad - k), (0, 0))) \
            if k_pad != k else survivors
        rows = jax.lax.all_to_all(padded, "sp", split_axis=1,
                                  concat_axis=2, tiled=True)
        b_loc, r_loc, s_full = rows.shape
        digests = highwayhash_jax._hh256_impl(
            rows.reshape(b_loc * r_loc, s_full), s_full,
            bytes(MAGIC_HIGHWAYHASH_KEY)).reshape(b_loc, r_loc, 32)
        return out, digests

    from jax.experimental.shard_map import shard_map
    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp", "sp", None)),
        check_rep=False)
    jitted = jax.jit(fn)

    def run(survivors):
        out, digests = jitted(survivors)
        return out, digests[:, :k]            # drop the pad rows
    return run, missing


def sharded_heal_step(mesh: Mesh, k: int, m: int, present_mask: int):
    """Multi-chip heal: survivors (B, k, S) -> missing shards, sp/dp
    sharded. Byte-column independence means zero collectives in the hot
    math — the win of sequence-parallel erasure coding."""
    r, _used, _missing = rs_matrix.recover_matrix(k, m, present_mask)
    r = np.asarray(r)
    m2 = rs_tpu._bit_expand_cached(r.tobytes(), r.shape)

    def local_step(survivors):
        return rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16), survivors)

    from jax.experimental.shard_map import shard_map
    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=P("dp", None, "sp"),
        check_rep=False)
    return jax.jit(fn)


def shard_array(mesh: Mesh, arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh, spec))
