"""Double-buffered stage pipeline for the object data paths.

The fork wins its throughput by *overlap*: QAT MD5 runs asynchronously
while erasure encode + shard writes proceed (cmd/erasure-encode.go:
113-124, "async kernel launch overlapped with the rest of the
pipeline"). This module generalizes that to the whole data path:

  * :class:`StagePipeline` — a small executor where each stage runs on
    its own thread, connected by BOUNDED queues. The bounds are the
    back-pressure: a fast producer blocks instead of ballooning memory,
    so staging RAM is capped by queue depth × buffer size.
  * a registry of :class:`~minio_tpu.parallel.bpool.BytePool` staging
    rings keyed by buffer width — PUT streams borrow their (B, k·S)
    encode buffers here, so total staging memory is bounded by the pool
    regardless of how many streams are in flight.
  * :data:`STATS` — always-on overlap accounting (wall vs sum-of-stage
    seconds, prefetch savings, pool pressure), exported as
    ``minio_tpu_pipeline_*`` Prometheus gauges so the win is observable
    in production, not just under the bench.

Env knobs (documented in README "Pipelined data path"):

  MINIO_TPU_PIPELINE=off          select the serial PUT/GET hot loops
  MINIO_TPU_PIPELINE_DEPTH=2      bounded queue depth between stages
  MINIO_TPU_PIPELINE_POOL=2×cores staging buffers per geometry ring
  MINIO_TPU_PIPELINE_POOL_TIMEOUT_S=60
                                  max wait for a staging buffer before
                                  the PUT fails (back-pressure made
                                  visible instead of a silent stall)
"""

from __future__ import annotations

import contextvars
import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

from ..utils import knobs, telemetry
from .bpool import BytePool

ENABLED = knobs.get_bool("MINIO_TPU_PIPELINE")
DEPTH = max(1, knobs.get_int("MINIO_TPU_PIPELINE_DEPTH"))
# staging ring size: the pool is SHARED by every stream of a geometry,
# so it must scale with the ADMITTED concurrency (each admitted stream
# keeps ~2 batches in flight) or it throttles aggregate throughput
# instead of just bounding memory. The 2×cores value is only the
# fallback for pool rings created before the server computes its
# admission budget — configure_pool_buffers() re-derives the default
# from requests_budget() at boot (the env knob always wins).
_POOL_ENV_SET = knobs.is_set("MINIO_TPU_PIPELINE_POOL")
POOL_BUFFERS = max(4, knobs.get_int("MINIO_TPU_PIPELINE_POOL"))
POOL_TIMEOUT_S = knobs.get_float("MINIO_TPU_PIPELINE_POOL_TIMEOUT_S")


def configure_pool_buffers(requests_budget: int) -> int:
    """Size the staging rings from the RAM-gated admission budget: the
    budget already bounds in-flight object requests by RAM/2 with ~2
    staging buffers per request in its per-request footprint, so
    2×budget buffers per ring is the matching capacity (the old flat
    2×cores default starved budgets above one stream per core and
    oversized tiny-RAM hosts). Applies to rings created AFTER the call;
    MINIO_TPU_PIPELINE_POOL overrides. Returns the effective size."""
    global POOL_BUFFERS
    if not _POOL_ENV_SET:
        POOL_BUFFERS = max(4, 2 * int(requests_budget))
    return POOL_BUFFERS

# GET lookahead reads run here, NOT on metadata._POOL: a prefetch task
# fans its per-reader reads out onto _POOL, and a task that waits on
# subtasks of its own pool can deadlock when the pool saturates. Sized
# with the host's concurrency (the tasks are I/O-bound waiters); when a
# lookahead is still queued behind other streams at collection time the
# GET cancels it and reads inline, so prefetch stays a strict win.
PREFETCH_POOL = ThreadPoolExecutor(
    max_workers=max(16, 4 * (os.cpu_count() or 4)),
    thread_name_prefix="get-prefetch")

_EOT = object()          # end-of-stream sentinel on the stage queues


# ---------------------------------------------------------------------------
# staging buffer rings
# ---------------------------------------------------------------------------

_pools: dict[int, BytePool] = {}
_pools_mu = threading.Lock()


def staging_pool(width: int) -> BytePool:
    """The shared staging ring for `width`-byte encode buffers — one
    ring per geometry (cap·k·S), shared by every stream with that
    geometry, so concurrent PUTs contend on a bounded pool instead of
    each allocating its own batch buffer."""
    with _pools_mu:
        pool = _pools.get(width)
        if pool is None:
            pool = BytePool(width, POOL_BUFFERS)
            _pools[width] = pool
        return pool


def pool_pressure() -> dict:
    """Aggregate wait/exhaustion counters across every staging ring."""
    with _pools_mu:
        pools = list(_pools.values())
    return {"waits": sum(p.waits for p in pools),
            "exhausted": sum(p.exhausted for p in pools),
            "rings": len(pools)}


# ---------------------------------------------------------------------------
# overlap accounting
# ---------------------------------------------------------------------------

class PipelineStats:
    """Always-on counters for the pipelined data path (a handful of
    float adds per stream — not per block — so they stay on in
    production). wall < stage_sum means the stages actually overlapped;
    stage_sum / wall is the effective parallelism."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.put_streams = 0
        self.put_batches = 0
        self.put_wall_s = 0.0
        self.put_stage_s = 0.0
        self.get_groups = 0
        self.get_prefetched = 0
        self.get_prefetch_wait_s = 0.0     # time spent waiting on lookahead
        self.get_prefetch_read_s = 0.0     # what the read actually cost

    def record_put(self, wall_s: float, stage_s: float,
                   batches: int) -> None:
        with self._mu:
            self.put_streams += 1
            self.put_batches += batches
            self.put_wall_s += wall_s
            self.put_stage_s += stage_s

    def record_get_group(self, prefetched: bool, wait_s: float = 0.0,
                         read_s: float = 0.0) -> None:
        with self._mu:
            self.get_groups += 1
            if prefetched:
                self.get_prefetched += 1
                self.get_prefetch_wait_s += wait_s
                self.get_prefetch_read_s += read_s

    def snapshot(self) -> dict:
        with self._mu:
            out = {
                "enabled": int(ENABLED),
                "put_streams": self.put_streams,
                "put_batches": self.put_batches,
                "put_wall_s": round(self.put_wall_s, 4),
                "put_stage_s": round(self.put_stage_s, 4),
                "get_groups": self.get_groups,
                "get_prefetched": self.get_prefetched,
                "get_prefetch_wait_s": round(self.get_prefetch_wait_s, 4),
                "get_prefetch_saved_s": round(
                    max(self.get_prefetch_read_s
                        - self.get_prefetch_wait_s, 0.0), 4),
            }
        out.update({f"bpool_{k}": v for k, v in pool_pressure().items()
                    if k != "rings"})
        return out


STATS = PipelineStats()

_PIPELINE_GAUGE_HELP = {
    "enabled": "1 when the pipelined PUT/GET hot loops are selected",
    "put_streams_total": "PUT streams run through the stage pipeline",
    "put_batches_total": "Encode batches fed through the PUT pipeline",
    "put_wall_seconds_total": "Wall seconds inside pipelined PUT loops",
    "put_stage_seconds_total":
        "Summed per-stage seconds (ingest+encode+write) of pipelined "
        "PUT loops; ratio vs wall = achieved overlap",
    "get_groups_total": "GET block groups read",
    "get_prefetched_total":
        "GET block groups served via the one-group lookahead",
    "get_prefetch_saved_seconds_total":
        "Drive-read seconds hidden behind verify+decode by the GET "
        "lookahead",
    "bpool_waits_total":
        "Staging-buffer gets that had to block (back-pressure)",
    "bpool_exhausted_total":
        "Staging-buffer gets that timed out (pipeline stalled)",
}
# snapshot key -> exported suffix (names predate the registry and are
# kept stable for dashboards/tests)
_PIPELINE_GAUGE_KEYS = {
    "enabled": "enabled",
    "put_streams": "put_streams_total",
    "put_batches": "put_batches_total",
    "put_wall_s": "put_wall_seconds_total",
    "put_stage_s": "put_stage_seconds_total",
    "get_groups": "get_groups_total",
    "get_prefetched": "get_prefetched_total",
    "get_prefetch_saved_s": "get_prefetch_saved_seconds_total",
    "bpool_waits": "bpool_waits_total",
    "bpool_exhausted": "bpool_exhausted_total",
}


def _collect_pipeline_metrics() -> None:
    """Registry collector: refresh minio_tpu_pipeline_* from STATS at
    exposition time (no polling thread)."""
    snap = STATS.snapshot()
    for key, suffix in _PIPELINE_GAUGE_KEYS.items():
        if key in snap:
            telemetry.REGISTRY.gauge(
                f"minio_tpu_pipeline_{suffix}",
                _PIPELINE_GAUGE_HELP[suffix]).set(snap[key])


telemetry.REGISTRY.register_collector(_collect_pipeline_metrics)


# ---------------------------------------------------------------------------
# the stage executor
# ---------------------------------------------------------------------------

class StagePipeline:
    """Run items through `stages` (each fn(item) -> next item) with one
    thread per stage and bounded hand-off queues.

    * Order-preserving: one worker per stage + FIFO queues, so shard
      frames land on the writers in block order.
    * Back-pressure: `submit()` blocks when the first queue is full; a
      stage blocked on a full downstream queue stops pulling upstream.
    * Fail-fast: the FIRST stage exception is kept and re-raised (the
      original object, so quorum errors keep their type) from the next
      `submit()` or from `close()`. After a failure workers keep
      draining but stop processing — queued items are handed to
      `on_drop` so pooled buffers return to their ring instead of
      leaking with the wreck.
    """

    def __init__(self, stages: Sequence[Callable], depth: int = DEPTH,
                 name: str = "pipeline",
                 on_drop: Optional[Callable] = None):
        assert stages, "a pipeline needs at least one stage"
        self._stages = list(stages)
        self._on_drop = on_drop
        self._queues = [queue.Queue(maxsize=max(1, depth))
                        for _ in stages]
        self._error: Optional[BaseException] = None
        self._err_mu = threading.Lock()
        # stage workers inherit the creating request's span context so
        # stage-body spans land in the right tree (one Context copy per
        # thread — a Context must not run concurrently)
        tracing = telemetry.current_span() is not None

        def _target(i: int) -> Callable:
            if not tracing:
                return lambda: self._run(i)
            cctx = contextvars.copy_context()
            return lambda: cctx.run(self._run, i)

        self._threads = [
            threading.Thread(target=_target(i),
                             name=f"{name}-stage{i}", daemon=True)
            for i in range(len(stages))]
        for t in self._threads:
            t.start()

    # -- producer side -----------------------------------------------------

    def submit(self, item) -> None:
        """Feed one item to stage 0; raises the pipeline's pending error
        (dropping `item` via on_drop) instead of queueing into a wreck."""
        while True:
            err = self._error
            if err is not None:
                self._drop(item)
                raise err
            try:
                self._queues[0].put(item, timeout=0.1)
                return
            except queue.Full:
                continue        # re-check the error while blocked

    def close(self, abort: bool = False) -> None:
        """End of stream: wait for in-flight items, join the workers,
        and re-raise the first stage error (unless `abort`, the
        caller's-own-exception path, where the pipeline error would
        mask it)."""
        if abort:
            with self._err_mu:
                if self._error is None:
                    self._error = _Aborted()
        self._queues[0].put(_EOT)
        for t in self._threads:
            t.join()
        if not abort and self._error is not None \
                and not isinstance(self._error, _Aborted):
            raise self._error

    @property
    def failed(self) -> bool:
        return self._error is not None

    # -- workers -----------------------------------------------------------

    def _drop(self, item) -> None:
        if self._on_drop is not None and item is not _EOT:
            try:
                self._on_drop(item)
            except Exception:  # noqa: BLE001 — drop hooks are best-effort
                pass

    def _run(self, idx: int) -> None:
        fn = self._stages[idx]
        q = self._queues[idx]
        nxt = self._queues[idx + 1] if idx + 1 < len(self._queues) \
            else None
        while True:
            item = q.get()
            if item is _EOT:
                if nxt is not None:
                    nxt.put(_EOT)
                return
            if self._error is not None:
                self._drop(item)
                continue
            try:
                out = fn(item)
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                with self._err_mu:
                    if self._error is None:
                        self._error = e
                self._drop(item)
                continue
            if nxt is None:
                continue
            while True:
                if self._error is not None:
                    self._drop(out)
                    break
                try:
                    nxt.put(out, timeout=0.1)
                    break
                except queue.Full:
                    continue


class _Aborted(Exception):
    """Internal sentinel error: the caller aborted the stream (its own
    exception is in flight) — workers drain, nothing re-raises."""
