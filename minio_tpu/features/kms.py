"""KMS seam for SSE-S3 (reference cmd/crypto/kes.go + kms.go shapes).

Two backends behind one duck-typed interface:

  generate_key(context) -> (plaintext DEK, sealed DEK blob)
  decrypt_key(sealed, context) -> plaintext DEK

* ``StaticKMS`` — the master key from config/env; generate returns the
  master itself with an empty sealed blob, preserving the pre-KMS
  metadata format byte-for-byte (cmd/crypto/kms.go masterKeyKMS).
* ``KESClient`` — a KES-shaped remote KMS over HTTP
  (cmd/crypto/kes.go): POST /v1/key/generate/<name> returns
  {plaintext, ciphertext}; POST /v1/key/decrypt/<name> unseals. The
  HTTP connection factory is injectable so tests run against an
  in-process fake, and a down KMS surfaces as a clean S3 error — SSE
  PUTs/GETs fail closed, nothing falls back to plaintext.

The object-key sealing chain mirrors the reference: per-object key
(OEK) sealed by the DEK; only the DEK ciphertext and the sealed OEK
persist in xl.meta — the KMS never sees object data, and losing the
KMS key renders objects unreadable (the point of remote KMS).
"""

from __future__ import annotations

import base64
import http.client
import json
import urllib.parse
from typing import Callable, Optional


class KMSError(Exception):
    pass


class StaticKMS:
    """Local master key (config kms_secret_key / MINIO_SSE_MASTER_KEY)."""

    key_id = "minio-static-key"

    def __init__(self, master_key: bytes):
        if len(master_key) != 32:
            raise ValueError("master key must be 256 bits")
        self._master = master_key

    def generate_key(self, context: dict) -> tuple[bytes, bytes]:
        # empty sealed blob = "the DEK is the master key itself";
        # byte-compatible with objects written before the KMS seam
        return self._master, b""

    def decrypt_key(self, sealed: bytes, context: dict,
                    key_id: str = "") -> bytes:
        if sealed:
            raise KMSError("static KMS cannot decrypt a remote DEK")
        return self._master


class KESClient:
    """KES-shaped HTTP KMS client (cmd/crypto/kes.go).

    Auth is a bearer API key (KES identity); the transport factory is
    injectable for offline tests and future mTLS wiring."""

    def __init__(self, endpoint: str, key_name: str, api_key: str = "",
                 timeout: float = 5.0,
                 connect: Optional[Callable[[], object]] = None):
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.hostname:
            raise ValueError(f"bad KES endpoint {endpoint!r}")
        self.endpoint = endpoint
        self.key_name = key_name
        self.key_id = f"kes:{key_name}"
        self.api_key = api_key
        self.timeout = timeout
        self._host = u.hostname
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._secure = u.scheme == "https"
        self._connect = connect or self._default_connect

    def _default_connect(self):
        cls = http.client.HTTPSConnection if self._secure \
            else http.client.HTTPConnection
        return cls(self._host, self._port, timeout=self.timeout)

    def _post(self, path: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        try:
            conn = self._connect()
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
        except OSError as e:
            raise KMSError(f"KMS unreachable: {e}") from e
        if resp.status != 200:
            raise KMSError(
                f"KMS {path} failed ({resp.status}): {data[:200]!r}")
        try:
            out = json.loads(data.decode())
        except ValueError:
            raise KMSError("KMS returned malformed JSON") from None
        if not isinstance(out, dict):
            raise KMSError("KMS returned a non-object response")
        return out

    @staticmethod
    def _ctx_b64(context: dict) -> str:
        # canonical: sorted keys, no whitespace — decrypt must present
        # the exact bytes generate was called with
        return base64.b64encode(json.dumps(
            context or {}, sort_keys=True,
            separators=(",", ":")).encode()).decode()

    def generate_key(self, context: dict) -> tuple[bytes, bytes]:
        out = self._post(f"/v1/key/generate/{self.key_name}",
                         {"context": self._ctx_b64(context)})
        try:
            plain = base64.b64decode(out["plaintext"])
            sealed = base64.b64decode(out["ciphertext"])
        except (KeyError, ValueError):
            raise KMSError("KMS generate-key response missing "
                           "plaintext/ciphertext") from None
        if len(plain) != 32:
            raise KMSError("KMS returned a non-256-bit data key")
        return plain, sealed

    def decrypt_key(self, sealed: bytes, context: dict,
                    key_id: str = "") -> bytes:
        """key_id: the key the OBJECT was sealed under (metadata
        "kes:<name>") — decrypt must route there even after the
        configured default key_name rotates, or every pre-rotation
        object dies with the rotation."""
        name = key_id[len("kes:"):] if key_id.startswith("kes:") \
            else (key_id or self.key_name)
        out = self._post(
            f"/v1/key/decrypt/{name}",
            {"ciphertext": base64.b64encode(sealed).decode(),
             "context": self._ctx_b64(context)})
        try:
            plain = base64.b64decode(out["plaintext"])
        except (KeyError, ValueError):
            raise KMSError("KMS decrypt-key response missing "
                           "plaintext") from None
        if len(plain) != 32:
            raise KMSError("KMS returned a non-256-bit data key")
        return plain
