"""Bucket event notification: config rules + target dispatch.

The reference's pkg/event: per-bucket NotificationConfiguration XML maps
event-name patterns + prefix/suffix filters to targets (ARNs); every
object operation publishes an S3-format event record to the matching
targets, asynchronously with retry. Durability matches the reference's
queuestore (pkg/event/target/queuestore.go): when the notifier has a
queue directory, every matched event is persisted BEFORE dispatch and
deleted only after the target accepts it — pending events survive a
process restart (at-least-once).

Targets (all real wire protocols, offline-tested against in-process
fakes): webhook (HTTP POST), redis (RESP2), mqtt (3.1.1), nats (text
protocol), nsq (V2 TCP), amqp (0-9-1), postgres (v3 protocol with
SCRAM-SHA-256 auth), mysql (handshake v10, native-password +
caching_sha2 auth), elasticsearch (document API), kafka (binary
broker protocol: ApiVersions/Metadata handshake + Produce v2 carrying
a MessageSet v1 of magic-1 messages with CRC32 framing — KafkaTarget
below, no client lib needed), memory (tests / ListenNotification
feed).
"""

from __future__ import annotations

import base64
import dataclasses
import fnmatch
import hashlib
import hmac
import json
import os
import queue
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
import uuid as _uuid
import xml.etree.ElementTree as ET
import zlib
from typing import Callable, Optional

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _findall(el, tag):
    return list(el.findall(tag)) + list(el.findall(_NS + tag))


def _text(el, tag, default=""):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return (r.text or "").strip() if r is not None else default


@dataclasses.dataclass
class QueueRule:
    arn: str
    events: list[str]                  # e.g. ["s3:ObjectCreated:*"]
    prefix: str = ""
    suffix: str = ""

    def matches(self, event_name: str, key: str) -> bool:
        if not any(fnmatch.fnmatchcase(event_name, pat)
                   for pat in self.events):
            return False
        if self.prefix and not key.startswith(self.prefix):
            return False
        if self.suffix and not key.endswith(self.suffix):
            return False
        return True


class NotificationConfig:
    def __init__(self, rules: list[QueueRule]):
        self.rules = rules

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "NotificationConfig":
        root = ET.fromstring(raw)
        rules = []
        for qel in (_findall(root, "QueueConfiguration")
                    + _findall(root, "TopicConfiguration")
                    + _findall(root, "CloudFunctionConfiguration")):
            arn = (_text(qel, "Queue") or _text(qel, "Topic")
                   or _text(qel, "CloudFunction"))
            events = [(e.text or "").strip()
                      for e in _findall(qel, "Event")]
            prefix = suffix = ""
            for fel in _findall(qel, "Filter"):
                for kel in _findall(fel, "S3Key"):
                    for frel in _findall(kel, "FilterRule"):
                        name = _text(frel, "Name").lower()
                        value = _text(frel, "Value")
                        if name == "prefix":
                            prefix = value
                        elif name == "suffix":
                            suffix = value
            rules.append(QueueRule(arn=arn, events=events, prefix=prefix,
                                   suffix=suffix))
        return cls(rules)


# ---------------------------------------------------------------------------
# durable queue store (pkg/event/target/queuestore.go semantics)
# ---------------------------------------------------------------------------

class QueueStore:
    """One directory of JSON event files per target. put() is atomic
    (tmp + rename); entries are deleted only after successful delivery,
    so whatever is on disk at startup is exactly the undelivered
    backlog."""

    def __init__(self, directory: str, limit: int = 10000,
                 fsync: Optional[bool] = None):
        self.dir = directory
        self.limit = limit
        if fsync is None:
            from ..utils import knobs
            fsync = knobs.get_bool("MINIO_TPU_QUEUE_FSYNC")
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._mu = threading.Lock()
        # Crash mid-put leaves '.tmp-*' files that keys() skips — sweep
        # them so they can't accumulate invisibly forever. Only stale
        # ones: a fresh store may be constructed over a directory an
        # older store object is actively put()ing into (admin config
        # re-apply), and an unconditional sweep would delete the
        # in-flight tmp file between json.dump and os.replace.
        now = time.time()
        try:
            for name in os.listdir(directory):
                if not name.startswith(".tmp-"):
                    continue
                p = os.path.join(directory, name)
                try:
                    if now - os.path.getmtime(p) > 60.0:
                        os.remove(p)
                except OSError:
                    pass
        except OSError:
            pass
        # O(1) limit enforcement: count once at startup, maintain on
        # put/delete (a per-put listdir is O(n^2) as backlog grows)
        self._count = len(self.keys())

    def put(self, record: dict) -> Optional[str]:
        """Persist; returns the entry key, or None when the store is at
        its limit (caller falls back to at-most-once)."""
        with self._mu:
            if self._count >= self.limit:
                return None
            key = f"{time.time_ns():020d}-{_uuid.uuid4().hex[:8]}"
            tmp = os.path.join(self.dir, f".tmp-{key}")
            with open(tmp, "w") as f:
                json.dump(record, f)
                if self.fsync:
                    # opt-in (MINIO_TPU_QUEUE_FSYNC=on): survives power
                    # loss, but an fsync per event on the request
                    # thread serializes the PUT hot path; process-crash
                    # durability already holds via atomic rename +
                    # redrive.
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.dir, key))
            if self.fsync:
                # the rename itself is only durable once the directory
                # metadata is flushed
                dfd = os.open(self.dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            self._count += 1
            return key

    def get(self, key: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, key)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def delete(self, key: str) -> None:
        with self._mu:
            try:
                os.remove(os.path.join(self.dir, key))
                self._count -= 1
            except OSError:
                pass

    def keys(self) -> list[str]:
        """Undelivered entry keys, oldest first (names sort by put
        time)."""
        try:
            return sorted(k for k in os.listdir(self.dir)
                          if not k.startswith("."))
        except OSError:
            return []


# ---------------------------------------------------------------------------
# targets
# ---------------------------------------------------------------------------

class WebhookTarget:
    """POST the event JSON to an endpoint (pkg/event/target/webhook)."""

    def __init__(self, arn: str, endpoint: str, timeout: float = 5.0):
        self.arn = arn
        self.endpoint = endpoint
        self.timeout = timeout

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        req = urllib.request.Request(
            self.endpoint, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()


class RedisTarget:
    """Event delivery over the actual Redis RESP2 wire protocol
    (pkg/event/target/redis.go): format="namespace" keeps a hash of
    object-key -> latest event (HSET / HDEL on delete events);
    format="access" appends every event to a list (RPUSH)."""

    def __init__(self, arn: str, addr: str, key: str,
                 format: str = "namespace", password: str = "",
                 timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        self.arn, self.addr, self.key = arn, addr, key
        self.format = format
        self.password = password
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 6379), timeout=self.timeout)

    @staticmethod
    def _resp(*args: bytes) -> bytes:
        out = b"*%d\r\n" % len(args)
        for a in args:
            out += b"$%d\r\n%s\r\n" % (len(a), a)
        return out

    @staticmethod
    def _read_reply(f) -> bytes:
        line = f.readline()
        if not line:
            raise OSError("redis connection closed")
        if line[:1] == b"-":
            raise OSError(f"redis error: {line[1:].strip().decode()}")
        if line[:1] == b"$":                    # bulk string
            n = int(line[1:])
            return f.read(n + 2)[:-2] if n >= 0 else b""
        return line.strip()                     # +OK / :n

    def send(self, record: dict) -> None:
        rec = record["Records"][0]
        obj_key = rec["s3"]["object"]["key"]
        body = json.dumps(record).encode()
        with self._connect() as s:
            f = s.makefile("rb")
            if self.password:
                s.sendall(self._resp(b"AUTH", self.password.encode()))
                self._read_reply(f)
            if self.format == "access":
                cmd = self._resp(b"RPUSH", self.key.encode(), body)
            elif rec["eventName"].startswith("s3:ObjectRemoved"):
                cmd = self._resp(b"HDEL", self.key.encode(),
                                 obj_key.encode())
            else:
                cmd = self._resp(b"HSET", self.key.encode(),
                                 obj_key.encode(), body)
            s.sendall(cmd)
            self._read_reply(f)


class MQTTTarget:
    """Event delivery over real MQTT 3.1.1 (pkg/event/target/mqtt.go):
    CONNECT, await CONNACK, PUBLISH QoS 0, DISCONNECT."""

    def __init__(self, arn: str, addr: str, topic: str,
                 client_id: str = "", timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        self.arn, self.addr, self.topic = arn, addr, topic
        self.client_id = client_id or f"minio-tpu-{_uuid.uuid4().hex[:8]}"
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 1883), timeout=self.timeout)

    @staticmethod
    def _varlen(n: int) -> bytes:
        out = b""
        while True:
            b7, n = n & 0x7F, n >> 7
            out += bytes([b7 | (0x80 if n else 0)])
            if not n:
                return out

    @staticmethod
    def _mstr(s: bytes) -> bytes:
        return len(s).to_bytes(2, "big") + s

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        var = (self._mstr(b"MQTT") + b"\x04"   # protocol level 3.1.1
               + b"\x02"                       # clean session
               + (60).to_bytes(2, "big")       # keepalive
               + self._mstr(self.client_id.encode()))
        connect = b"\x10" + self._varlen(len(var)) + var
        pub_var = self._mstr(self.topic.encode()) + body
        publish = b"\x30" + self._varlen(len(pub_var)) + pub_var
        with self._connect() as s:
            s.sendall(connect)
            ack = b""
            while len(ack) < 4:                # CONNACK may fragment
                chunk = s.recv(4 - len(ack))
                if not chunk:
                    raise OSError("MQTT connection closed before CONNACK")
                ack += chunk
            if ack[0] != 0x20 or ack[3] != 0:
                raise OSError(f"MQTT CONNACK refused: {ack.hex()}")
            s.sendall(publish)
            s.sendall(b"\xe0\x00")             # DISCONNECT


class NATSTarget:
    """Event delivery over the real NATS text protocol
    (pkg/event/target/nats.go): INFO -> CONNECT(verbose) -> +OK ->
    PUB subject len / payload -> +OK."""

    def __init__(self, arn: str, addr: str, subject: str,
                 timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        # the subject is interpolated into the PUB frame: whitespace or
        # control characters would corrupt (or inject) protocol
        # commands, so reject them at configuration time
        if not subject or any(c.isspace() or ord(c) < 0x21
                              for c in subject):
            raise ValueError(
                f"invalid NATS subject {subject!r}: must be non-empty "
                "without whitespace/control characters")
        self.arn, self.addr, self.subject = arn, addr, subject
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 4222), timeout=self.timeout)

    @staticmethod
    def _expect_ok(f) -> None:
        line = f.readline()
        if line.strip().startswith(b"-ERR"):
            raise OSError(f"NATS error: {line.strip().decode()}")
        if not line.strip().startswith(b"+OK"):
            raise OSError(f"unexpected NATS reply: {line[:80]!r}")

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        with self._connect() as s:
            f = s.makefile("rb")
            info = f.readline()
            if not info.startswith(b"INFO"):
                raise OSError(f"not a NATS server: {info[:80]!r}")
            s.sendall(b'CONNECT {"verbose":true,"pedantic":false,'
                      b'"name":"minio-tpu"}\r\n')
            self._expect_ok(f)
            s.sendall(b"PUB %s %d\r\n%s\r\n" % (
                self.subject.encode(), len(body), body))
            self._expect_ok(f)


class AMQPTarget:
    """Event delivery over real AMQP 0-9-1 (pkg/event/target/amqp.go):
    protocol header, Connection.Start/Tune/Open handshake with PLAIN
    auth, Channel.Open, then Basic.Publish with a content header +
    body frame to the configured exchange/routing key."""

    FRAME_METHOD, FRAME_HEADER, FRAME_BODY = 1, 2, 3
    FRAME_END = 0xCE

    def __init__(self, arn: str, addr: str, exchange: str = "",
                 routing_key: str = "minioevents",
                 user: str = "guest", password: str = "guest",
                 vhost: str = "/", timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        # shortstr fields are capped at 255 bytes on the wire; reject
        # at configuration time so _register skips the target instead
        # of every send() failing forever
        for name, v in (("exchange", exchange),
                        ("routing_key", routing_key), ("user", user),
                        ("vhost", vhost)):
            if len(v.encode()) > 255 or any(ord(c) < 0x20 for c in v):
                raise ValueError(
                    f"invalid AMQP {name} {v!r}: max 255 bytes, no "
                    "control characters")
        self.arn, self.addr = arn, addr
        self.exchange, self.routing_key = exchange, routing_key
        self.user, self.password, self.vhost = user, password, vhost
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 5672), timeout=self.timeout)

    # -- wire encoding -----------------------------------------------------

    @staticmethod
    def _shortstr(s: str) -> bytes:
        b = s.encode()
        return bytes([len(b)]) + b

    @staticmethod
    def _longstr(b: bytes) -> bytes:
        return len(b).to_bytes(4, "big") + b

    def _frame(self, ftype: int, channel: int, payload: bytes) -> bytes:
        return (bytes([ftype]) + channel.to_bytes(2, "big")
                + len(payload).to_bytes(4, "big") + payload
                + bytes([self.FRAME_END]))

    def _method(self, channel: int, cls: int, meth: int,
                args: bytes) -> bytes:
        return self._frame(self.FRAME_METHOD, channel,
                           cls.to_bytes(2, "big")
                           + meth.to_bytes(2, "big") + args)

    @classmethod
    def _read_frame(cls, f) -> tuple[int, int, bytes]:
        head = f.read(7)
        if len(head) < 7:
            raise OSError("AMQP connection closed")
        ftype = head[0]
        channel = int.from_bytes(head[1:3], "big")
        size = int.from_bytes(head[3:7], "big")
        payload = f.read(size)
        if f.read(1) != bytes([cls.FRAME_END]):
            raise OSError("AMQP framing error")
        return ftype, channel, payload

    def _expect_method(self, f, cls_id: int, meth_id: int) -> bytes:
        ftype, _ch, payload = self._read_frame(f)
        if ftype != self.FRAME_METHOD or len(payload) < 4:
            raise OSError("AMQP: expected method frame")
        got_cls = int.from_bytes(payload[:2], "big")
        got_meth = int.from_bytes(payload[2:4], "big")
        if (got_cls, got_meth) != (cls_id, meth_id):
            raise OSError(f"AMQP: expected {cls_id}.{meth_id}, "
                          f"got {got_cls}.{got_meth}")
        return payload[4:]

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        with self._connect() as s:
            f = s.makefile("rb")
            s.sendall(b"AMQP\x00\x00\x09\x01")
            self._expect_method(f, 10, 10)          # Connection.Start
            plain = self._longstr(
                b"\x00" + self.user.encode() + b"\x00"
                + self.password.encode())
            s.sendall(self._method(
                0, 10, 11,                          # Start-Ok
                (0).to_bytes(4, "big")              # empty client table
                + self._shortstr("PLAIN") + plain
                + self._shortstr("en_US")))
            tune = self._expect_method(f, 10, 30)   # Tune
            offered = int.from_bytes(tune[2:6], "big") \
                if len(tune) >= 6 else 0
            # the client's frame-max must not exceed the server's offer
            # (0 = no server limit)
            frame_max = min(offered or 131072, 131072)
            s.sendall(self._method(
                0, 10, 31,                          # Tune-Ok
                (0).to_bytes(2, "big")
                + frame_max.to_bytes(4, "big")
                + (0).to_bytes(2, "big")))
            s.sendall(self._method(
                0, 10, 40,                          # Open (vhost)
                self._shortstr(self.vhost)
                + self._shortstr("") + b"\x00"))
            self._expect_method(f, 10, 41)          # Open-Ok
            s.sendall(self._method(1, 20, 10,       # Channel.Open
                                   self._shortstr("")))
            self._expect_method(f, 20, 11)          # Channel.Open-Ok
            s.sendall(self._method(
                1, 60, 40,                          # Basic.Publish
                (0).to_bytes(2, "big")
                + self._shortstr(self.exchange)
                + self._shortstr(self.routing_key) + b"\x00"))
            header = ((60).to_bytes(2, "big")       # content header
                      + (0).to_bytes(2, "big")
                      + len(body).to_bytes(8, "big")
                      + (0x8000).to_bytes(2, "big")  # content-type set
                      + self._shortstr("application/json"))
            s.sendall(self._frame(self.FRAME_HEADER, 1, header))
            # split the body at frame-max (8 bytes of frame overhead)
            chunk = max(frame_max - 8, 1)
            for at in range(0, len(body), chunk):
                s.sendall(self._frame(self.FRAME_BODY, 1,
                                      body[at:at + chunk]))
            s.sendall(self._method(0, 10, 50,       # Connection.Close
                                   (200).to_bytes(2, "big")
                                   + self._shortstr("bye")
                                   + (0).to_bytes(4, "big")))
            # the broker reports async publish failures (unroutable
            # exchange etc.) as Channel.Close/Connection.Close before
            # our Close-Ok — fire-and-forget here would ack-and-delete
            # a lost event from the durable queue
            ftype, _ch, payload = self._read_frame(f)
            if ftype == self.FRAME_METHOD and len(payload) >= 4:
                cls_id = int.from_bytes(payload[:2], "big")
                meth_id = int.from_bytes(payload[2:4], "big")
                if (cls_id, meth_id) == (10, 51):   # Close-Ok: clean
                    return
                if meth_id == 40 or (cls_id, meth_id) == (10, 50):
                    code = int.from_bytes(payload[4:6], "big") \
                        if len(payload) >= 6 else 0
                    raise OSError(
                        f"AMQP publish refused ({cls_id}.{meth_id} "
                        f"reply-code {code})")
            raise OSError("AMQP: unexpected reply to Connection.Close")


class NSQTarget:
    """Event delivery over the real NSQ TCP protocol
    (pkg/event/target/nsq.go): '  V2' magic, PUB <topic> with a 4-byte
    big-endian size prefix, OK frame response."""

    def __init__(self, arn: str, addr: str, topic: str,
                 timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        # the topic is interpolated into the PUB command line: NSQ
        # names are [.a-zA-Z0-9_-], 1..64 chars — reject anything else
        # at configuration time (same reasoning as NATSTarget)
        import re as _re
        if not _re.fullmatch(r"[.a-zA-Z0-9_-]{1,64}(#ephemeral)?",
                             topic):
            raise ValueError(
                f"invalid NSQ topic {topic!r}: must match "
                "[.a-zA-Z0-9_-]{{1,64}} with optional #ephemeral")
        self.arn, self.addr, self.topic = arn, addr, topic
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 4150), timeout=self.timeout)

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        with self._connect() as s:
            s.sendall(b"  V2")
            s.sendall(b"PUB %s\n" % self.topic.encode()
                      + len(body).to_bytes(4, "big") + body)
            # response frame: [size u32][frame_type i32][data]
            head = b""
            while len(head) < 8:
                chunk = s.recv(8 - len(head))
                if not chunk:
                    raise OSError("NSQ connection closed")
                head += chunk
            size = int.from_bytes(head[:4], "big")
            frame_type = int.from_bytes(head[4:8], "big", signed=True)
            data = b""
            while len(data) < size - 4:
                chunk = s.recv(size - 4 - len(data))
                if not chunk:
                    break
                data += chunk
            if frame_type == 1 or not data.startswith(b"OK"):
                raise OSError(f"NSQ error: {data[:80]!r}")


class PostgresTarget:
    """Event delivery over the PostgreSQL v3 wire protocol
    (pkg/event/target/postgresql.go): startup + cleartext/MD5/
    SCRAM-SHA-256 password auth (the modern server default; mutual
    proof verification per RFC 7677), then simple-query INSERTs.
    format="namespace" upserts one row per object key (and deletes on
    removal events); format="access" appends. Reference table
    contract: namespace = (key TEXT PRIMARY KEY, value TEXT/JSONB),
    access = (event_time TIMESTAMP, event_data TEXT/JSONB).
    """

    def __init__(self, arn: str, addr: str, database: str, table: str,
                 user: str = "postgres", password: str = "",
                 format: str = "namespace", timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        import re as _re
        if not _re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]{0,62}", table):
            raise ValueError(
                f"invalid Postgres table name {table!r}")
        self.arn, self.addr = arn, addr
        self.database, self.table = database, table
        self.user, self.password = user, password
        self.format = format
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 5432), timeout=self.timeout)

    # -- wire plumbing -----------------------------------------------------

    @staticmethod
    def _msg(tag: bytes, payload: bytes) -> bytes:
        return tag + (len(payload) + 4).to_bytes(4, "big") + payload

    @staticmethod
    def _read_msg(f) -> tuple[bytes, bytes]:
        tag = f.read(1)
        if not tag:
            raise OSError("postgres connection closed")
        size = int.from_bytes(f.read(4), "big")
        return tag, f.read(size - 4)

    def _auth(self, s, f) -> None:
        # standard_conforming_strings rides the StartupMessage options
        # (quote-doubled literals are only injection-safe with it on;
        # pinning here costs no extra round trip)
        params = (b"user\x00" + self.user.encode() + b"\x00"
                  b"database\x00" + self.database.encode() + b"\x00"
                  b"options\x00-c standard_conforming_strings=on\x00"
                  b"\x00")
        s.sendall((len(params) + 8).to_bytes(4, "big")
                  + (196608).to_bytes(4, "big") + params)  # proto 3.0
        while True:
            tag, payload = self._read_msg(f)
            if tag == b"E":
                raise OSError(f"postgres error: {payload[:120]!r}")
            if tag != b"R":
                continue
            code = int.from_bytes(payload[:4], "big")
            if code == 0:                       # AuthenticationOk
                break
            if code == 3:                       # cleartext password
                s.sendall(self._msg(
                    b"p", self.password.encode() + b"\x00"))
            elif code == 5:                     # md5 password
                salt = payload[4:8]
                inner = hashlib.md5(
                    self.password.encode()
                    + self.user.encode()).hexdigest()
                digest = hashlib.md5(
                    inner.encode() + salt).hexdigest()
                s.sendall(self._msg(
                    b"p", b"md5" + digest.encode() + b"\x00"))
            elif code == 10:                    # SASL (RFC 5802/7677)
                # modern servers default to scram-sha-256 — speak it
                mechs = [m.decode() for m in
                         payload[4:].split(b"\x00") if m]
                if "SCRAM-SHA-256" not in mechs:
                    raise OSError(
                        "postgres offers no SCRAM-SHA-256 "
                        f"mechanism (got {mechs})")
                import secrets as _secrets
                nonce = base64.b64encode(
                    _secrets.token_bytes(18)).decode()
                # user is empty in gs2: the startup message names it
                first_bare = f"n=,r={nonce}"
                init = b"n,," + first_bare.encode()
                body = (b"SCRAM-SHA-256\x00"
                        + len(init).to_bytes(4, "big") + init)
                s.sendall(self._msg(b"p", body))
                scram_state = (nonce, first_bare)
            elif code == 11:                    # SASLContinue
                nonce, first_bare = scram_state
                server_first = payload[4:].decode()
                fields = dict(kv.split("=", 1) for kv in
                              server_first.split(","))
                srv_nonce, salt_b64 = fields["r"], fields["s"]
                iters = int(fields["i"])
                if not srv_nonce.startswith(nonce):
                    raise OSError("postgres scram: server nonce "
                                  "does not extend ours")
                salted = hashlib.pbkdf2_hmac(
                    "sha256", self.password.encode(),
                    base64.b64decode(salt_b64), iters)
                ckey = hmac.new(salted, b"Client Key",
                                hashlib.sha256).digest()
                stored = hashlib.sha256(ckey).digest()
                final_bare = f"c=biws,r={srv_nonce}"
                auth_msg = ",".join(
                    (first_bare, server_first, final_bare)).encode()
                csig = hmac.new(stored, auth_msg,
                                hashlib.sha256).digest()
                proof = bytes(a ^ b for a, b in zip(ckey, csig))
                skey = hmac.new(salted, b"Server Key",
                                hashlib.sha256).digest()
                scram_verify = hmac.new(skey, auth_msg,
                                        hashlib.sha256).digest()
                s.sendall(self._msg(
                    b"p", (final_bare + ",p="
                           + base64.b64encode(proof).decode()
                           ).encode()))
            elif code == 12:                    # SASLFinal
                fields = dict(kv.split("=", 1) for kv in
                              payload[4:].decode().split(","))
                if base64.b64decode(fields.get("v", "")) != \
                        scram_verify:
                    raise OSError("postgres scram: bad server "
                                  "signature (not the real server?)")
            else:
                raise OSError(
                    f"unsupported postgres auth method {code}")
        # drain ParameterStatus/BackendKeyData until ReadyForQuery
        while True:
            tag, payload = self._read_msg(f)
            if tag == b"Z":
                return
            if tag == b"E":
                raise OSError(f"postgres error: {payload[:120]!r}")

    def _query(self, s, f, sql: str) -> None:
        s.sendall(self._msg(b"Q", sql.encode() + b"\x00"))
        err = None
        while True:
            tag, payload = self._read_msg(f)
            if tag == b"E":
                err = payload[:200]
            if tag == b"Z":
                break
        if err is not None:
            raise OSError(f"postgres query failed: {err!r}")

    @staticmethod
    def _lit(s: str) -> str:
        """SQL string literal with quotes doubled (simple-query
        protocol has no parameter binding)."""
        return "'" + s.replace("'", "''") + "'"

    def send(self, record: dict) -> None:
        rec = record["Records"][0]
        obj_key = (rec["s3"]["bucket"]["name"] + "/"
                   + rec["s3"]["object"]["key"])
        payload = json.dumps(record)
        if self.format == "access":
            # reference access schema: (event_time, event_data)
            sql = (f"INSERT INTO {self.table} (event_time, event_data)"
                   f" VALUES (now(), {self._lit(payload)})")
        elif rec["eventName"].startswith("s3:ObjectRemoved"):
            sql = (f"DELETE FROM {self.table} WHERE key = "
                   f"{self._lit(obj_key)}")
        else:
            sql = (f"INSERT INTO {self.table} (key, value) VALUES "
                   f"({self._lit(obj_key)}, {self._lit(payload)}) "
                   f"ON CONFLICT (key) DO UPDATE SET value = "
                   f"EXCLUDED.value")
        with self._connect() as s:
            f = s.makefile("rb")
            self._auth(s, f)
            self._query(s, f, sql)
            s.sendall(self._msg(b"X", b""))     # Terminate


class MySQLTarget:
    """Event delivery over the MySQL client/server protocol
    (pkg/event/target/mysql.go): handshake v10 with
    mysql_native_password or caching_sha2_password (the 8.0+ default;
    fast-auth path — the full-auth RSA exchange needs TLS and fails
    with a clear action), honoring server AuthSwitchRequest, then
    COM_QUERY statements. Same table contract and formats as the
    Postgres target."""

    CLIENT_LONG_PASSWORD = 0x1
    CLIENT_CONNECT_WITH_DB = 0x8
    CLIENT_PROTOCOL_41 = 0x200
    CLIENT_SECURE_CONNECTION = 0x8000
    CLIENT_PLUGIN_AUTH = 0x80000

    def __init__(self, arn: str, addr: str, database: str, table: str,
                 user: str = "root", password: str = "",
                 format: str = "namespace", timeout: float = 5.0,
                 connect: Optional[Callable[[], socket.socket]] = None):
        import re as _re
        if not _re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]{0,63}", table):
            raise ValueError(f"invalid MySQL table name {table!r}")
        if database and not _re.fullmatch(
                r"[A-Za-z0-9_$-]{1,64}", database):
            raise ValueError(f"invalid MySQL database name {database!r}")
        self.arn, self.addr = arn, addr
        self.database, self.table = database, table
        self.user, self.password = user, password
        self.format = format
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.addr, 3306), timeout=self.timeout)

    # -- packet plumbing ---------------------------------------------------

    @staticmethod
    def _read_packet(f) -> tuple[int, bytes]:
        head = f.read(4)
        if len(head) < 4:
            raise OSError("mysql connection closed")
        size = int.from_bytes(head[:3], "little")
        return head[3], f.read(size)

    @staticmethod
    def _packet(seq: int, payload: bytes) -> bytes:
        return (len(payload).to_bytes(3, "little") + bytes([seq])
                + payload)

    def _scramble(self, salt: bytes) -> bytes:
        """mysql_native_password token."""
        if not self.password:
            return b""
        h1 = hashlib.sha1(self.password.encode()).digest()
        h2 = hashlib.sha1(h1).digest()
        h3 = hashlib.sha1(salt + h2).digest()
        return bytes(a ^ b for a, b in zip(h1, h3))

    def _scramble_sha2(self, nonce: bytes) -> bytes:
        """caching_sha2_password token: XOR(SHA256(pw),
        SHA256(SHA256(SHA256(pw)) || nonce)) — the modern server
        default (8.0+)."""
        if not self.password:
            return b""
        h1 = hashlib.sha256(self.password.encode()).digest()
        h2 = hashlib.sha256(hashlib.sha256(h1).digest() + nonce).digest()
        return bytes(a ^ b for a, b in zip(h1, h2))

    @staticmethod
    def _check_ok(payload: bytes, what: str) -> None:
        if payload[:1] == b"\xff":
            code = int.from_bytes(payload[1:3], "little")
            raise OSError(f"mysql {what} failed ({code}): "
                          f"{payload[9:120]!r}")

    def send(self, record: dict) -> None:
        rec = record["Records"][0]
        obj_key = (rec["s3"]["bucket"]["name"] + "/"
                   + rec["s3"]["object"]["key"])
        payload = json.dumps(record)

        def lit(s: str) -> str:
            # quote-doubling only: the connection pins
            # NO_BACKSLASH_ESCAPES, making backslashes literal in every
            # deployment (mirrors the Postgres target's
            # standard_conforming_strings pin)
            return "'" + s.replace("'", "''") + "'"

        if self.format == "access":
            # reference access schema: (event_time, event_data)
            sql = (f"INSERT INTO {self.table} (event_time, event_data)"
                   f" VALUES (NOW(), {lit(payload)})")
        elif rec["eventName"].startswith("s3:ObjectRemoved"):
            sql = (f"DELETE FROM {self.table} WHERE `key` = "
                   f"{lit(obj_key)}")
        else:
            sql = (f"REPLACE INTO {self.table} (`key`, value) VALUES "
                   f"({lit(obj_key)}, {lit(payload)})")

        with self._connect() as s:
            f = s.makefile("rb")
            _seq, greet = self._read_packet(f)
            self._check_ok(greet, "handshake")
            if greet[:1] != b"\x0a":
                raise OSError("unsupported mysql protocol version")
            at = greet.index(b"\x00", 1) + 1    # server version string
            at += 4                             # thread id
            salt = greet[at:at + 8]
            at += 8 + 1                         # salt part 1 + filler
            at += 2 + 1 + 2 + 2 + 1 + 10        # caps, charset, status…
            salt += greet[at:at + 12]           # salt part 2 (of 13-1)
            at += 12 + 1                        # salt part 2 + NUL
            end = greet.find(b"\x00", at)
            plugin = greet[at:end if end >= 0 else None].decode(
                "ascii", "replace") or "mysql_native_password"
            caps = (self.CLIENT_LONG_PASSWORD | self.CLIENT_PROTOCOL_41
                    | self.CLIENT_SECURE_CONNECTION
                    | self.CLIENT_PLUGIN_AUTH)
            if self.database:
                caps |= self.CLIENT_CONNECT_WITH_DB
            if plugin == "caching_sha2_password":
                token = self._scramble_sha2(salt)
            else:
                plugin = "mysql_native_password"
                token = self._scramble(salt)
            resp = (caps.to_bytes(4, "little")
                    + (1 << 24).to_bytes(4, "little")   # max packet
                    + bytes([33]) + bytes(23)           # utf8 + filler
                    + self.user.encode() + b"\x00"
                    + bytes([len(token)]) + token)
            if self.database:
                # selected in the handshake (CLIENT_CONNECT_WITH_DB):
                # no per-event USE round trip, no identifier splicing
                resp += self.database.encode() + b"\x00"
            resp += plugin.encode() + b"\x00"
            s.sendall(self._packet(1, resp))
            seq, auth = self._read_packet(f)
            self._check_ok(auth, "auth")
            if auth[:1] == b"\xfe":
                # AuthSwitchRequest: plugin name NUL, then new nonce
                end = auth.index(b"\x00", 1)
                new_plugin = auth[1:end].decode("ascii", "replace")
                new_salt = auth[end + 1:].rstrip(b"\x00")
                if new_plugin == "mysql_native_password":
                    token = self._scramble(new_salt)
                elif new_plugin == "caching_sha2_password":
                    token = self._scramble_sha2(new_salt)
                else:
                    raise OSError(
                        f"mysql requested unsupported auth plugin "
                        f"{new_plugin!r}")
                s.sendall(self._packet(seq + 1, token))
                seq, auth = self._read_packet(f)
                self._check_ok(auth, "auth switch")
                plugin = new_plugin
            if plugin == "caching_sha2_password" and \
                    auth[:1] == b"\x01":
                # AuthMoreData: 0x03 = fast-auth success (an OK packet
                # follows); 0x04 = full auth, which needs TLS or the
                # server RSA key exchange — fail with a clear action
                if auth[1:2] == b"\x03":
                    _seq, auth = self._read_packet(f)
                    self._check_ok(auth, "auth")
                elif auth[1:2] == b"\x04":
                    raise OSError(
                        "mysql caching_sha2_password full "
                        "authentication requires TLS (no cached "
                        "entry for this user); connect once with "
                        "another client to prime the cache, or "
                        "create the notify user WITH "
                        "mysql_native_password")
                else:
                    raise OSError("mysql: unexpected AuthMoreData "
                                  f"{auth[1:2]!r}")
            for stmt in ("SET SESSION sql_mode = "
                         "'NO_BACKSLASH_ESCAPES'", sql):
                s.sendall(self._packet(0, b"\x03" + stmt.encode()))
                _seq, reply = self._read_packet(f)
                self._check_ok(reply, "query")
            s.sendall(self._packet(0, b"\x01"))     # COM_QUIT


class ElasticsearchTarget:
    """Event delivery to an Elasticsearch index over its HTTP document
    API (pkg/event/target/elasticsearch.go): format="namespace" keeps
    one doc per object key (PUT /index/_doc/<id>, DELETE on removal);
    format="access" appends (POST /index/_doc)."""

    def __init__(self, arn: str, url: str, index: str,
                 format: str = "namespace", timeout: float = 5.0):
        self.arn = arn
        self.url = url.rstrip("/")
        self.index = index
        self.format = format
        self.timeout = timeout

    def _doc_id(self, record: dict) -> str:
        rec = record["Records"][0]
        bucket = rec["s3"]["bucket"]["name"]
        key = rec["s3"]["object"]["key"]
        import urllib.parse as _up
        return _up.quote(f"{bucket}/{key}", safe="")

    def send(self, record: dict) -> None:
        rec = record["Records"][0]
        body = json.dumps(record).encode()
        if self.format == "access":
            req = urllib.request.Request(
                f"{self.url}/{self.index}/_doc", data=body,
                method="POST",
                headers={"Content-Type": "application/json"})
        elif rec["eventName"].startswith("s3:ObjectRemoved"):
            req = urllib.request.Request(
                f"{self.url}/{self.index}/_doc/{self._doc_id(record)}",
                method="DELETE")
        else:
            req = urllib.request.Request(
                f"{self.url}/{self.index}/_doc/{self._doc_id(record)}",
                data=body, method="PUT",
                headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404 and req.get_method() == "DELETE":
                return                 # deleting a never-indexed doc
            raise


# -- Kafka wire protocol (pkg/event/target/kafka.go semantics) --------------
#
# The reference drives Kafka through sarama; this speaks the protocol
# itself: ApiVersions v0 handshake, Metadata v1 for leader discovery,
# Produce v2 with a MessageSet v1 (magic-1 messages: CRC32, timestamp,
# key = object key, value = event JSON). Partition choice mirrors
# sarama's default hash partitioner: FNV-1a(key) mod numPartitions.

_K_PRODUCE, _K_METADATA, _K_APIVERSIONS = 0, 3, 18


def _k_str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def _k_bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


class _KReader:
    """Big-endian cursor over one Kafka response payload."""

    def __init__(self, raw: bytes):
        self.raw, self.at = raw, 0

    def take(self, n: int) -> bytes:
        if self.at + n > len(self.raw):
            raise OSError("kafka: truncated response")
        out = self.raw[self.at:self.at + n]
        self.at += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def string(self) -> str:
        n = self.i16()
        return "" if n < 0 else self.take(n).decode()


def _fnv1a32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def _sarama_partition(key: bytes, n: int) -> int:
    """sarama's default hash partitioner, bit-for-bit: p = int32(fnv1a)
    % n with Go's truncate-toward-zero modulo, negated if negative
    (even the int32-min overflow case matches)."""
    h = _fnv1a32(key)
    if h >= 1 << 31:
        h -= 1 << 32                    # int32 view
    p = h - int(h / n) * n              # Go %: truncated, sign of h
    return -p if p < 0 else p


class _KafkaConn:
    """One broker connection: framed request/response with correlation
    id checking."""

    def __init__(self, host: str, port: int, client_id: str,
                 timeout: float):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.client_id = client_id
        self._corr = 0

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def call(self, api_key: int, api_version: int, body: bytes
             ) -> _KReader:
        self._corr += 1
        header = struct.pack(">hhi", api_key, api_version, self._corr) \
            + _k_str(self.client_id)
        msg = header + body
        self.sock.sendall(struct.pack(">i", len(msg)) + msg)
        raw = self._read_exact(4)
        (size,) = struct.unpack(">i", raw)
        payload = self._read_exact(size)
        r = _KReader(payload)
        corr = r.i32()
        if corr != self._corr:
            raise OSError(f"kafka: correlation id {corr} != {self._corr}")
        return r

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise OSError("kafka: connection closed")
            buf += chunk
        return buf


class KafkaTarget:
    """Kafka target speaking the real produce protocol
    (pkg/event/target/kafka.go): key = object key, value = event JSON,
    routed to `topic` on the partition leader. A custom `producer`
    remains injectable for embedding."""

    def __init__(self, arn: str, brokers: list[str], topic: str,
                 producer: Optional[Callable] = None,
                 client_id: str = "minio-tpu", timeout: float = 10.0):
        self.arn, self.brokers, self.topic = arn, brokers, topic
        self.client_id, self.timeout = client_id, timeout
        self._producer = producer    # wire client built lazily on first
        # send: connecting in __init__ would run inside ConfigSys.apply
        # on node startup and crash the boot when the broker is down —
        # deferring lets the queuestore retry machinery absorb it
        self._meta: Optional[tuple[dict, dict]] = None
        self._conns: dict[int, _KafkaConn] = {}   # node id -> conn
        self._mu = threading.Lock()

    # -- wire producer -----------------------------------------------------

    def _connect_any(self) -> _KafkaConn:
        last: Optional[Exception] = None
        for b in self.brokers:
            host, _, port = b.partition(":")
            try:
                conn = _KafkaConn(host, int(port or 9092),
                                  self.client_id, self.timeout)
                self._handshake(conn)
                return conn
            except (OSError, ValueError) as e:
                last = e
        raise OSError(f"kafka: no broker reachable: {last}")

    @staticmethod
    def _handshake(conn: _KafkaConn) -> None:
        """ApiVersions v0: confirm the broker speaks Produce v2 and
        Metadata v1 before using them."""
        r = conn.call(_K_APIVERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise OSError(f"kafka: ApiVersions error {err}")
        supported = {}
        for _ in range(r.i32()):
            key, lo, hi = r.i16(), r.i16(), r.i16()
            supported[key] = (lo, hi)
        for key, need in ((_K_PRODUCE, 2), (_K_METADATA, 1)):
            lo, hi = supported.get(key, (0, -1))
            if not lo <= need <= hi:
                raise OSError(
                    f"kafka: broker lacks api {key} v{need}")

    def _metadata(self, conn: _KafkaConn
                  ) -> tuple[dict[int, tuple[str, int]],
                             dict[int, int]]:
        """Metadata v1 for the topic: returns ({node: (host, port)},
        {partition: leader_node})."""
        body = struct.pack(">i", 1) + _k_str(self.topic)
        r = conn.call(_K_METADATA, 1, body)
        brokers = {}
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            r.string()                       # rack (nullable)
            brokers[node] = (host, port)
        r.i32()                              # controller id
        leaders: dict[int, int] = {}
        for _ in range(r.i32()):             # topics
            terr = r.i16()
            name = r.string()
            r.i8()                           # is_internal
            nparts = r.i32()
            for _ in range(nparts):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()                  # replicas
                for _ in range(r.i32()):
                    r.i32()                  # isr
                if name == self.topic and perr == 0:
                    leaders[pid] = leader
            if name == self.topic and terr:
                raise OSError(f"kafka: topic {name} error {terr}")
        if not leaders:
            raise OSError(f"kafka: topic {self.topic} has no partitions")
        return brokers, leaders

    @staticmethod
    def _message_set(key: bytes, value: bytes) -> bytes:
        """MessageSet v1: one magic-1 message, CRC over everything
        after the crc field."""
        ts_ms = int(time.time() * 1000)
        content = struct.pack(">bbq", 1, 0, ts_ms) \
            + _k_bytes(key) + _k_bytes(value)
        msg = struct.pack(">I", zlib.crc32(content)) + content
        return struct.pack(">qi", 0, len(msg)) + msg

    def _reset(self) -> None:
        for c in self._conns.values():
            c.close()
        self._conns.clear()
        self._meta = None

    def _leader_conn(self, node: int, host: str, port: int
                     ) -> _KafkaConn:
        conn = self._conns.get(node)
        if conn is None:
            conn = _KafkaConn(host, port, self.client_id, self.timeout)
            self._conns[node] = conn
        return conn

    def _wire_produce(self, topic: str, key: bytes, value: bytes
                      ) -> None:
        """Metadata and leader connections are cached across events —
        one produce is one request on a standing connection, not two
        fresh TCP connects + handshake + metadata per event. Any
        OSError drops the cache and retries once (leader moved, broker
        restarted); the second failure surfaces to the queuestore."""
        with self._mu:
            for attempt in (0, 1):
                try:
                    if self._meta is None:
                        conn = self._connect_any()
                        try:
                            self._meta = self._metadata(conn)
                        finally:
                            conn.close()
                    brokers, leaders = self._meta
                    pids = sorted(leaders)
                    pid = pids[_sarama_partition(key, len(pids))]
                    host, port = brokers[leaders[pid]]
                    conn = self._leader_conn(leaders[pid], host, port)
                    mset = self._message_set(key, value)
                    body = (struct.pack(">hi", 1,
                                        int(self.timeout * 1000))
                            + struct.pack(">i", 1) + _k_str(topic)
                            + struct.pack(">i", 1)
                            + struct.pack(">i", pid)
                            + struct.pack(">i", len(mset)) + mset)
                    r = conn.call(_K_PRODUCE, 2, body)
                    for _ in range(r.i32()):         # topics
                        r.string()
                        for _ in range(r.i32()):     # partition responses
                            r.i32()                  # partition
                            err = r.i16()
                            r.i64()                  # base offset
                            r.i64()                  # log append time
                            if err:
                                raise OSError(
                                    f"kafka: produce error {err}")
                    return
                except OSError:
                    self._reset()
                    if attempt:
                        raise

    def send(self, record: dict) -> None:
        if self._producer is None:
            self._producer = self._wire_produce
        rec = record["Records"][0]
        key = rec["s3"]["object"]["key"].encode()
        self._producer(self.topic, key, json.dumps(record).encode())


class MemoryTarget:
    """Captures records in-process (tests / ListenNotification feed)."""

    def __init__(self, arn: str):
        self.arn = arn
        self.records: list[dict] = []
        self._cond = threading.Condition()

    def send(self, record: dict) -> None:
        with self._cond:
            self.records.append(record)
            self._cond.notify_all()

    def wait_for(self, n: int, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.records) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return False
            return True


# ---------------------------------------------------------------------------
# notifier
# ---------------------------------------------------------------------------

def event_record(event_name: str, bucket: str, key: str, size: int = 0,
                 etag: str = "", region: str = "us-east-1") -> dict:
    """S3 event message structure (pkg/event/event.go)."""
    now = time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime())
    return {"Records": [{
        "eventVersion": "2.0", "eventSource": "minio:s3",
        "awsRegion": region, "eventTime": now, "eventName": event_name,
        "userIdentity": {"principalId": "minio"},
        "s3": {"s3SchemaVersion": "1.0",
               "bucket": {"name": bucket,
                          "arn": f"arn:aws:s3:::{bucket}"},
               "object": {"key": key, "size": size, "eTag": etag}},
    }]}


class EventNotifier:
    """Per-bucket rule matching + async fan-out with retries."""

    def __init__(self, bucket_meta_sys, region: str = "us-east-1",
                 retries: int = 3, queue_size: int = 10000,
                 queue_dir: Optional[str] = None,
                 redrive_interval: float = 60.0):
        self.bucket_meta = bucket_meta_sys
        self.region = region
        self.retries = retries
        self.targets: dict[str, object] = {}     # arn -> target
        # durable at-least-once backlog, one store per target (reference
        # queuestore.go); None = legacy in-memory at-most-once
        self.queue_dir = queue_dir
        self.redrive_interval = redrive_interval
        self._stores: dict[str, QueueStore] = {}
        self._inflight: set[tuple[str, str]] = set()   # (arn, key)
        # live-listen hub: every event (rule-matched or not) publishes
        # here for ListenBucketNotification subscribers (pkg/pubsub use
        # in cmd/listen-notification-handlers.go)
        from ..utils.pubsub import PubSub
        self.hub = PubSub()
        self._mu = threading.Lock()
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        if queue_dir is not None:
            self._redrive_thread = threading.Thread(
                target=self._redrive_loop, daemon=True)
            self._redrive_thread.start()

    def register_target(self, target) -> None:
        self.targets[target.arn] = target
        if self.queue_dir is not None:
            safe = "".join(c if c.isalnum() or c in "-_." else "_"
                           for c in target.arn)
            store = QueueStore(os.path.join(self.queue_dir, safe))
            self._stores[target.arn] = store
            self.redrive(target.arn)     # replay pre-restart backlog

    def unregister_target(self, arn: str) -> None:
        """Remove a target AND its queue store binding — a disabled
        target must stop accumulating (and redriving) backlog. Entries
        already on disk stay there until the target is re-enabled."""
        self.targets.pop(arn, None)
        self._stores.pop(arn, None)

    def close(self) -> None:
        self._stop.set()

    def send(self, event_name: str, bucket: str, key: str,
             size: int = 0, etag: str = "") -> None:
        if self.hub.subscriber_count:
            self.hub.publish(
                (bucket, event_record(event_name, bucket, key, size,
                                      etag, self.region)))
        bm = self.bucket_meta.get(bucket)
        if not bm.notification_xml:
            return
        try:
            cfg = NotificationConfig.from_xml(bm.notification_xml)
        except ET.ParseError:
            return
        for rule in cfg.rules:
            if not rule.matches(event_name, key):
                continue
            target = self.targets.get(rule.arn)
            if target is None:
                continue
            record = event_record(event_name, bucket, key, size, etag,
                                  self.region)
            store = self._stores.get(rule.arn)
            store_key = store.put(record) if store is not None else None
            # store full -> at-most-once fallback (store_key None)
            self._enqueue(rule.arn, record, store_key, 0)

    def _enqueue(self, arn: str, record: dict, store_key: Optional[str],
                 attempt: int) -> bool:
        if store_key is not None:
            with self._mu:
                if (arn, store_key) in self._inflight:
                    return False
                self._inflight.add((arn, store_key))
        try:
            self._q.put_nowait((arn, record, store_key, attempt))
            return True
        except queue.Full:
            # durable entries stay in the store; the redrive loop
            # re-queues them once there is room (at-least-once)
            if store_key is not None:
                with self._mu:
                    self._inflight.discard((arn, store_key))
            return False

    def redrive(self, arn: Optional[str] = None) -> int:
        """Queue every persisted-but-unqueued entry (startup replay and
        the periodic loop). Returns how many were queued."""
        n = 0
        for a, store in list(self._stores.items()):
            if arn is not None and a != arn:
                continue
            if a not in self.targets:
                continue               # disabled target: backlog waits
            for key in store.keys():
                with self._mu:
                    if (a, key) in self._inflight:
                        continue
                record = store.get(key)
                if record is None:
                    store.delete(key)       # corrupt entry
                    continue
                if self._enqueue(a, record, key, 0):
                    n += 1
        return n

    def _redrive_loop(self) -> None:
        while not self._stop.wait(self.redrive_interval):
            self.redrive()

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                arn, record, store_key, attempt = self._q.get(
                    timeout=0.25)
            except queue.Empty:
                continue
            target = self.targets.get(arn)
            try:
                if target is None:
                    raise OSError(f"no target registered for {arn}")
                target.send(record)
                if store_key is not None:
                    # The target may have been unregistered while the
                    # send was in flight (admin config re-apply); only
                    # delete from a still-mounted store — a KeyError
                    # here would land in the retry path and re-send the
                    # already-delivered event.
                    store = self._stores.get(arn)
                    if store is not None:
                        store.delete(store_key)
                    with self._mu:
                        self._inflight.discard((arn, store_key))
            except Exception:  # noqa: BLE001 — retry with backoff
                if attempt + 1 < self.retries:
                    time.sleep(0.2 * (attempt + 1))
                    try:
                        self._q.put_nowait(
                            (arn, record, store_key, attempt + 1))
                    except queue.Full:
                        if store_key is not None:
                            with self._mu:
                                self._inflight.discard((arn, store_key))
                elif store_key is not None:
                    # retries exhausted: the durable entry REMAINS in
                    # the store; the redrive loop (or next restart)
                    # tries again — at-least-once, never silent drop
                    with self._mu:
                        self._inflight.discard((arn, store_key))
            finally:
                self._q.task_done()

    def drain(self, timeout: float = 5.0) -> None:
        done = threading.Event()

        def waiter():
            self._q.join()
            done.set()

        threading.Thread(target=waiter, daemon=True).start()
        done.wait(timeout)
