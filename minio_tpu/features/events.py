"""Bucket event notification: config rules + target dispatch.

The reference's pkg/event: per-bucket NotificationConfiguration XML maps
event-name patterns + prefix/suffix filters to targets (ARNs); every
object operation publishes an S3-format event record to the matching
targets, asynchronously with retry (queue store). Here: a webhook target
(HTTP POST of the JSON record) and an in-memory target for tests, with a
bounded async queue + retries.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import queue
import threading
import time
import urllib.request
import xml.etree.ElementTree as ET
from typing import Optional

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _findall(el, tag):
    return list(el.findall(tag)) + list(el.findall(_NS + tag))


def _text(el, tag, default=""):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return (r.text or "").strip() if r is not None else default


@dataclasses.dataclass
class QueueRule:
    arn: str
    events: list[str]                  # e.g. ["s3:ObjectCreated:*"]
    prefix: str = ""
    suffix: str = ""

    def matches(self, event_name: str, key: str) -> bool:
        if not any(fnmatch.fnmatchcase(event_name, pat)
                   for pat in self.events):
            return False
        if self.prefix and not key.startswith(self.prefix):
            return False
        if self.suffix and not key.endswith(self.suffix):
            return False
        return True


class NotificationConfig:
    def __init__(self, rules: list[QueueRule]):
        self.rules = rules

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "NotificationConfig":
        root = ET.fromstring(raw)
        rules = []
        for qel in (_findall(root, "QueueConfiguration")
                    + _findall(root, "TopicConfiguration")
                    + _findall(root, "CloudFunctionConfiguration")):
            arn = (_text(qel, "Queue") or _text(qel, "Topic")
                   or _text(qel, "CloudFunction"))
            events = [(e.text or "").strip()
                      for e in _findall(qel, "Event")]
            prefix = suffix = ""
            for fel in _findall(qel, "Filter"):
                for kel in _findall(fel, "S3Key"):
                    for frel in _findall(kel, "FilterRule"):
                        name = _text(frel, "Name").lower()
                        value = _text(frel, "Value")
                        if name == "prefix":
                            prefix = value
                        elif name == "suffix":
                            suffix = value
            rules.append(QueueRule(arn=arn, events=events, prefix=prefix,
                                   suffix=suffix))
        return cls(rules)


# ---------------------------------------------------------------------------
# targets
# ---------------------------------------------------------------------------

class WebhookTarget:
    """POST the event JSON to an endpoint (pkg/event/target/webhook)."""

    def __init__(self, arn: str, endpoint: str, timeout: float = 5.0):
        self.arn = arn
        self.endpoint = endpoint
        self.timeout = timeout

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        req = urllib.request.Request(
            self.endpoint, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()


class MemoryTarget:
    """Captures records in-process (tests / ListenNotification feed)."""

    def __init__(self, arn: str):
        self.arn = arn
        self.records: list[dict] = []
        self._cond = threading.Condition()

    def send(self, record: dict) -> None:
        with self._cond:
            self.records.append(record)
            self._cond.notify_all()

    def wait_for(self, n: int, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.records) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return False
            return True


# ---------------------------------------------------------------------------
# notifier
# ---------------------------------------------------------------------------

def event_record(event_name: str, bucket: str, key: str, size: int = 0,
                 etag: str = "", region: str = "us-east-1") -> dict:
    """S3 event message structure (pkg/event/event.go)."""
    now = time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime())
    return {"Records": [{
        "eventVersion": "2.0", "eventSource": "minio:s3",
        "awsRegion": region, "eventTime": now, "eventName": event_name,
        "userIdentity": {"principalId": "minio"},
        "s3": {"s3SchemaVersion": "1.0",
               "bucket": {"name": bucket,
                          "arn": f"arn:aws:s3:::{bucket}"},
               "object": {"key": key, "size": size, "eTag": etag}},
    }]}


class EventNotifier:
    """Per-bucket rule matching + async fan-out with retries."""

    def __init__(self, bucket_meta_sys, region: str = "us-east-1",
                 retries: int = 3, queue_size: int = 10000):
        self.bucket_meta = bucket_meta_sys
        self.region = region
        self.retries = retries
        self.targets: dict[str, object] = {}     # arn -> target
        # live-listen hub: every event (rule-matched or not) publishes
        # here for ListenBucketNotification subscribers (pkg/pubsub use
        # in cmd/listen-notification-handlers.go)
        from ..utils.pubsub import PubSub
        self.hub = PubSub()
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def register_target(self, target) -> None:
        self.targets[target.arn] = target

    def close(self) -> None:
        self._stop.set()

    def send(self, event_name: str, bucket: str, key: str,
             size: int = 0, etag: str = "") -> None:
        if self.hub.subscriber_count:
            self.hub.publish(
                (bucket, event_record(event_name, bucket, key, size,
                                      etag, self.region)))
        bm = self.bucket_meta.get(bucket)
        if not bm.notification_xml:
            return
        try:
            cfg = NotificationConfig.from_xml(bm.notification_xml)
        except ET.ParseError:
            return
        for rule in cfg.rules:
            if not rule.matches(event_name, key):
                continue
            target = self.targets.get(rule.arn)
            if target is None:
                continue
            record = event_record(event_name, bucket, key, size, etag,
                                  self.region)
            try:
                self._q.put_nowait((target, record, 0))
            except queue.Full:
                pass                        # at-most-once under overload

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                target, record, attempt = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                target.send(record)
            except Exception:  # noqa: BLE001 — retry with backoff
                if attempt + 1 < self.retries:
                    time.sleep(0.2 * (attempt + 1))
                    try:
                        self._q.put_nowait((target, record, attempt + 1))
                    except queue.Full:
                        pass
            finally:
                self._q.task_done()

    def drain(self, timeout: float = 5.0) -> None:
        done = threading.Event()

        def waiter():
            self._q.join()
            done.set()

        threading.Thread(target=waiter, daemon=True).start()
        done.wait(timeout)
