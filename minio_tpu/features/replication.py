"""LEGACY one-way bucket replication (reference
cmd/bucket-replication.go replicateObject/mustReplicate +
cmd/bucket-targets.go).

The production multi-site story now lives in ``minio_tpu/replicate/``:
bidirectional active-active sync riding the engine namespace-change
feed, with loop suppression, deterministic conflict resolution,
version-faithful replay, resync seeding and MRF-style retry — cluster
boot wires THAT plane. This module remains as (a) the replication
CONFIG surface (``ReplicationConfig``/``ReplicationRule`` XML parsing,
which the new plane consults to gate keys per bucket rule) and (b) a
standalone fire-and-forget copier for embedders that want the simple
one-way shape.

A replication config (XML) names a destination bucket ARN; a target
registry maps ARNs to S3 endpoints+credentials. Every PUT/DELETE that
matches an enabled rule enqueues a replication task; a worker pool
re-reads the object from the local layer and PUTs (or DELETEs) it at the
destination with our own SigV4 client.
"""

from __future__ import annotations

import dataclasses
import hashlib
import http.client
import queue
import threading
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Optional

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _findall(el, tag):
    return list(el.findall(tag)) + list(el.findall(_NS + tag))


def _text(el, tag, default=""):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return (r.text or "").strip() if r is not None else default


@dataclasses.dataclass
class ReplicationRule:
    rule_id: str = ""
    status: str = "Enabled"
    prefix: str = ""
    target_arn: str = ""               # Destination/Bucket
    delete_replication: bool = False   # DeleteMarkerReplication status

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"


class ReplicationConfig:
    def __init__(self, rules: list[ReplicationRule], role: str = ""):
        self.rules = rules
        self.role = role

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "ReplicationConfig":
        root = ET.fromstring(raw)
        role = _text(root, "Role")
        rules = []
        for rel in _findall(root, "Rule"):
            r = ReplicationRule(
                rule_id=_text(rel, "ID"),
                status=_text(rel, "Status", "Enabled"))
            fel = rel.find("Filter")
            if fel is None:
                fel = rel.find(_NS + "Filter")
            if fel is not None:
                r.prefix = _text(fel, "Prefix")
            else:
                r.prefix = _text(rel, "Prefix")
            del_el = rel.find("DeleteMarkerReplication")
            if del_el is None:
                del_el = rel.find(_NS + "DeleteMarkerReplication")
            if del_el is not None:
                r.delete_replication = \
                    _text(del_el, "Status") == "Enabled"
            dest = rel.find("Destination")
            if dest is None:
                dest = rel.find(_NS + "Destination")
            if dest is not None:
                r.target_arn = _text(dest, "Bucket")
            rules.append(r)
        return cls(rules, role)

    def rule_for(self, object_name: str) -> Optional[ReplicationRule]:
        for r in self.rules:
            if r.enabled and object_name.startswith(r.prefix):
                return r
        return None


@dataclasses.dataclass
class ReplicationTarget:
    """One destination endpoint (cmd/bucket-targets.go TargetClient)."""
    arn: str
    host: str
    port: int
    bucket: str
    access_key: str
    secret_key: str
    region: str = "us-east-1"
    secure: bool = False


class _S3MiniClient:
    """Just enough SigV4 client for replication traffic."""

    def __init__(self, t: ReplicationTarget):
        self.t = t

    def _request(self, method: str, key: str, body: bytes = b"",
                 headers: Optional[dict] = None) -> int:
        from ..s3 import signature as sig
        from ..s3.credentials import Credentials
        path = f"/{self.t.bucket}/{key}"
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"{self.t.host}:{self.t.port}"
        payload_hash = hashlib.sha256(body).hexdigest()
        hdrs = sig.sign_v4(method, urllib.parse.quote(path), {}, hdrs,
                           payload_hash,
                           Credentials(self.t.access_key,
                                       self.t.secret_key), self.t.region)
        conn = http.client.HTTPConnection(self.t.host, self.t.port,
                                          timeout=30)
        try:
            conn.request(method, urllib.parse.quote(path), body=body,
                         headers=hdrs)
            resp = conn.getresponse()
            resp.read()
            return resp.status
        finally:
            conn.close()

    def put_object(self, key: str, body: bytes, metadata: dict) -> bool:
        hdrs = {"x-amz-replication-status": "REPLICA"}
        for k, v in metadata.items():
            if k.lower().startswith("x-amz-meta-") or k.lower() in (
                    "content-type", "content-encoding", "cache-control"):
                hdrs[k] = v
        return self._request("PUT", key, body, hdrs) == 200

    def delete_object(self, key: str) -> bool:
        return self._request("DELETE", key) in (200, 204)


class ReplicationPool:
    """Async replication workers (cmd/bucket-replication.go pool).

    With a queue_dir, every pending task is persisted BEFORE dispatch
    and deleted only after the destination accepted it — pending
    replication survives a process restart (the reference re-drives
    lost work via MRF/status headers; here the queuestore pattern from
    features/events.py serves both subsystems)."""

    def __init__(self, object_layer, bucket_meta_sys, workers: int = 2,
                 queue_size: int = 10000,
                 queue_dir: Optional[str] = None,
                 redrive_interval: float = 60.0):
        self.obj = object_layer
        self.bucket_meta = bucket_meta_sys
        self.targets: dict[str, ReplicationTarget] = {}
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self.replicated = 0            # counters for admin/metrics
        self.failed = 0
        self._mu = threading.Lock()
        self._inflight: set[str] = set()
        self.store = None
        if queue_dir is not None:
            from .events import QueueStore
            self.store = QueueStore(queue_dir)
            threading.Thread(target=self._redrive_loop, args=(
                redrive_interval,), daemon=True).start()
        for _ in range(workers):
            threading.Thread(target=self._worker, daemon=True).start()

    def register_target(self, t: ReplicationTarget) -> None:
        self.targets[t.arn] = t
        if self.store is not None:
            self.redrive()             # replay pre-restart backlog

    def mount_target_entry(self, entry: dict) -> None:
        """Register a persisted bucket-metadata target dict (the admin
        remote-target registry's on-disk shape)."""
        self.register_target(ReplicationTarget(
            arn=entry["arn"], host=entry["host"],
            port=int(entry.get("port", 9000)),
            bucket=entry["bucket"],
            access_key=entry.get("access_key", ""),
            secret_key=entry.get("secret_key", ""),
            region=entry.get("region", "us-east-1"),
            secure=bool(entry.get("secure", False))))

    def mount_persisted_targets(self, buckets: list[str]) -> None:
        """Boot-time re-registration of every bucket's remote targets
        from bucket metadata (reference loads the target registry at
        startup, cmd/bucket-targets.go)."""
        for b in buckets:
            try:
                for entry in self.bucket_meta.get(b).replication_targets:
                    self.mount_target_entry(entry)
            except Exception:  # noqa: BLE001 — per-bucket best effort
                continue

    def close(self) -> None:
        self._stop.set()

    def redrive(self) -> int:
        """Queue persisted-but-unqueued tasks (startup replay + the
        periodic loop). Tasks whose target isn't registered yet stay
        persisted."""
        if self.store is None:
            return 0
        n = 0
        for skey in self.store.keys():
            with self._mu:
                if skey in self._inflight:
                    continue
            task = self.store.get(skey)
            if task is None:
                self.store.delete(skey)
                continue
            if task.get("arn") not in self.targets:
                continue
            if self._queue_task(task, skey):
                n += 1
        return n

    def _redrive_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.redrive()

    def _queue_task(self, task: dict, skey: Optional[str]) -> bool:
        if skey is not None:
            with self._mu:
                if skey in self._inflight:
                    return False
                self._inflight.add(skey)
        try:
            self._q.put_nowait((task, skey))
            return True
        except queue.Full:
            if skey is not None:
                with self._mu:
                    self._inflight.discard(skey)
            else:
                self.failed += 1
            return False

    # -- enqueue hooks (called from the S3 handlers) -----------------------

    def _config(self, bucket: str) -> Optional[ReplicationConfig]:
        xml = self.bucket_meta.get(bucket).replication_xml
        if not xml:
            return None
        try:
            return ReplicationConfig.from_xml(xml)
        except ET.ParseError:
            return None

    def must_replicate(self, bucket: str, key: str) -> bool:
        cfg = self._config(bucket)
        return cfg is not None and cfg.rule_for(key) is not None

    def on_put(self, bucket: str, key: str) -> None:
        self._enqueue("put", bucket, key)

    def on_delete(self, bucket: str, key: str) -> None:
        self._enqueue("delete", bucket, key)

    def _enqueue(self, op: str, bucket: str, key: str) -> None:
        cfg = self._config(bucket)
        if cfg is None:
            return
        rule = cfg.rule_for(key)
        if rule is None:
            return
        if op == "delete" and not rule.delete_replication:
            return
        target = self.targets.get(rule.target_arn)
        if target is None:
            return
        task = {"op": op, "bucket": bucket, "key": key,
                "arn": rule.target_arn}
        skey = self.store.put(task) if self.store is not None else None
        self._queue_task(task, skey)

    # -- workers -----------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                task, skey = self._q.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                target = self.targets.get(task["arn"])
                if target is None:
                    raise OSError(f"no target {task['arn']}")
                self._replicate(task["op"], task["bucket"], task["key"],
                                target)
                self.replicated += 1
                if skey is not None and self.store is not None:
                    self.store.delete(skey)
            except Exception:  # noqa: BLE001 — counted; durable entries
                # stay persisted for the redrive loop / next restart
                self.failed += 1
            finally:
                if skey is not None:
                    with self._mu:
                        self._inflight.discard(skey)
                self._q.task_done()

    def _replicate(self, op: str, bucket: str, key: str,
                   target: ReplicationTarget) -> None:
        client = _S3MiniClient(target)
        if op == "delete":
            client.delete_object(key)
            return
        from ..object import api_errors
        try:
            info, stream = self.obj.get_object(bucket, key)
        except api_errors.ObjectApiError:
            return                      # deleted since enqueue
        body = b"".join(stream)
        md = dict(info.user_defined or {})
        if info.content_type:
            md["content-type"] = info.content_type
        if info.content_encoding:
            md["content-encoding"] = info.content_encoding
        client.put_object(key, body, md)

    def drain(self, timeout: float = 10.0) -> None:
        done = threading.Event()

        def waiter():
            self._q.join()
            done.set()

        threading.Thread(target=waiter, daemon=True).start()
        done.wait(timeout)
