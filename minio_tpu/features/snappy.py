"""Snappy framing format — the S2-interoperable compression codec.

The reference compresses objects with S2 (`newS2CompressReader`,
cmd/object-api-utils.go:869) and reads them back through `s2.NewReader`
(cmd/object-api-utils.go:697), tagging them
``X-Minio-Internal-compression: klauspost/compress/s2``. Snappy's
framing format + block format is a strict subset of S2's stream format,
so everything THIS framework writes is byte-valid input to the
reference's reader — that closes the cross-binary interop break of the
r4 zstd codec (VERDICT r4 missing #2). Reading reference-written
streams works for the snappy subset plus S2's basic repeat-offsets;
the extended repeat-length encodings (which cannot be validated
offline) raise a clean error, and every chunk is CRC32C-verified so a
bad decode can never pass silently.

Framing layout (the public snappy framing_format.txt):

    ff 06 00 00 "sNaPpY"                       stream identifier
    00 <len24> <crc32c-masked> <snappy block>  compressed chunk
    01 <len24> <crc32c-masked> <raw bytes>     uncompressed chunk
    fe ...                                     padding (skipped)
    80-fd ...                                  skippable (skipped)
    02-7f                                      reserved -> error

Chunk payloads cover <= 65536 uncompressed bytes; the CRC is over the
UNCOMPRESSED data, masked ((crc>>15 | crc<<17) + 0xa282ead8). S2
writers emit larger chunks (up to 4 MiB) — the reader here accepts
them.

The hot byte work (LZ match finding, CRC32C) runs in native C++
(native/snappy.cpp); without the native library the writer degrades to
spec-valid all-literal blocks and a table-driven Python CRC — same
wire format, no compression win.
"""

from __future__ import annotations

import struct
from typing import Iterator

from ..utils import native

STREAM_IDENT = b"\xff\x06\x00\x00sNaPpY"
# s2.NewWriter (the reference's writer) stamps its own magic; the
# chunk layout is identical and snappy-subset blocks decode the same
S2_IDENT_BODY = b"S2sTwO"
MAX_BLOCK = 65536                 # max uncompressed bytes per chunk
_MAX_READ_BLOCK = 4 << 20         # S2 writers may emit up to 4 MiB
_CRC_MASK_DELTA = 0xa282ead8

_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01
_CHUNK_PADDING = 0xfe
_CHUNK_STREAM_IDENT = 0xff


class SnappyError(ValueError):
    """Corrupt or unsupported snappy/S2 stream."""


# ---------------------------------------------------------------------------
# CRC32C (masked, per the framing spec)
# ---------------------------------------------------------------------------

_PY_CRC_TABLE = None


def _crc32c_py(data) -> int:
    global _PY_CRC_TABLE
    if _PY_CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82f63b78 ^ (c >> 1)) if c & 1 else c >> 1
            table.append(c)
        _PY_CRC_TABLE = table
    crc = 0xffffffff
    tab = _PY_CRC_TABLE
    for b in bytes(data):
        crc = tab[(crc ^ b) & 0xff] ^ (crc >> 8)
    return crc ^ 0xffffffff


def crc32c(data) -> int:
    if native.snappy_available():
        return native.crc32c(data)
    return _crc32c_py(data)


def masked_crc(data) -> int:
    c = crc32c(data)
    return ((c >> 15) | (c << 17)) + _CRC_MASK_DELTA & 0xffffffff


# ---------------------------------------------------------------------------
# block codec (native fast path, pure-python fallback)
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = b""
    while n >= 0x80:
        out += bytes([n & 0x7f | 0x80])
        n >>= 7
    return out + bytes([n])


def compress_block(data) -> bytes:
    """One snappy block (<= MAX_BLOCK bytes). Falls back to a spec-
    valid all-literal encoding without the native library."""
    data = bytes(data)
    if native.snappy_available():
        return native.snappy_compress_block(data)
    n1 = len(data) - 1
    if len(data) == 0:
        return _varint(0)
    if n1 < 60:
        tag = bytes([n1 << 2])
    else:
        tag = bytes([61 << 2, n1 & 0xff, n1 >> 8])
    return _varint(len(data)) + tag + data


def uncompress_block(data, max_out: int = _MAX_READ_BLOCK) -> bytes:
    if native.snappy_available():
        return native.snappy_uncompress_block(bytes(data), max_out)
    return _uncompress_block_py(bytes(data), max_out)


def _uncompress_block_py(src: bytes, max_out: int) -> bytes:
    """Pure-python snappy/S2 block decode (same subset as the C
    kernel: snappy + basic repeat-offsets)."""
    s, want, shift = 0, 0, 0
    while True:
        if s >= len(src) or shift > 63:
            raise SnappyError("corrupt block header")
        b = src[s]
        s += 1
        want |= (b & 0x7f) << shift
        if not b & 0x80:
            break
        shift += 7
    if want > max_out:
        raise SnappyError("block too large")
    dst = bytearray()
    last_offset = 0
    while s < len(src):
        tag = src[s]
        kind = tag & 3
        if kind == 0:                       # literal
            length = tag >> 2
            s += 1
            if length >= 60:
                extra = length - 59
                if s + extra > len(src):
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(src[s:s + extra], "little")
                s += extra
            length += 1
            if s + length > len(src) or len(dst) + length > max_out:
                raise SnappyError("truncated literal")
            dst += src[s:s + length]
            s += length
            continue
        if kind == 1:                       # copy1 / S2 repeat
            if s + 2 > len(src):
                raise SnappyError("truncated copy1")
            length = (tag >> 2) & 0x7
            offset = ((tag & 0xe0) << 3) | src[s + 1]
            s += 2
            if offset == 0:
                if length >= 5:
                    raise NotImplementedError(
                        "S2 extended repeat encoding outside the "
                        "decoded subset")
                offset = last_offset
                if offset == 0:
                    raise SnappyError("repeat before any copy")
            length += 4
        elif kind == 2:                     # copy2
            if s + 3 > len(src):
                raise SnappyError("truncated copy2")
            length = (tag >> 2) + 1
            offset = int.from_bytes(src[s + 1:s + 3], "little")
            s += 3
            if offset == 0:
                raise NotImplementedError("S2 extended repeat")
        else:                               # copy4
            if s + 5 > len(src):
                raise SnappyError("truncated copy4")
            length = (tag >> 2) + 1
            offset = int.from_bytes(src[s + 1:s + 5], "little")
            s += 5
            if offset == 0:
                raise NotImplementedError("S2 extended repeat")
        if offset > len(dst) or len(dst) + length > max_out:
            raise SnappyError("copy out of range")
        last_offset = offset
        for _ in range(length):             # handles overlap correctly
            dst.append(dst[-offset])
    if len(dst) != want:
        raise SnappyError("length mismatch")
    return bytes(dst)


# ---------------------------------------------------------------------------
# framing: streaming transforms (the compression codec interface)
# ---------------------------------------------------------------------------

class SnappyFramedCompress:
    """update/finalize transform emitting the snappy framing format
    (drop-in peer of crypto.ZstdCompress). A chunk whose snappy block
    doesn't shrink is written as an uncompressed chunk, per spec."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._started = False

    def _frame(self, block: bytes) -> bytes:
        comp = compress_block(block)
        crc = struct.pack("<I", masked_crc(block))
        if len(comp) < len(block):
            payload = crc + comp
            kind = _CHUNK_COMPRESSED
        else:
            payload = crc + block
            kind = _CHUNK_UNCOMPRESSED
        return bytes([kind]) + struct.pack("<I", len(payload))[:3] + \
            payload

    def update(self, data: bytes) -> bytes:
        self._buf += data
        out = bytearray()
        if not self._started:
            out += STREAM_IDENT
            self._started = True
        while len(self._buf) >= MAX_BLOCK:
            out += self._frame(bytes(self._buf[:MAX_BLOCK]))
            del self._buf[:MAX_BLOCK]
        return bytes(out)

    def finalize(self) -> bytes:
        out = bytearray()
        if not self._started:
            out += STREAM_IDENT
            self._started = True
        if self._buf:
            out += self._frame(bytes(self._buf))
            self._buf.clear()
        return bytes(out)


def decompress_stream(chunks: Iterator[bytes]) -> Iterator[bytes]:
    """Framed snappy/S2 stream -> plaintext chunks, CRC-verified.
    Accepts streams from this writer, golang/snappy (compression v1),
    and the reference's s2.NewWriter (within the decoded block
    subset)."""
    buf = bytearray()
    first = True
    it = iter(chunks)

    def fill(n: int) -> bool:
        while len(buf) < n:
            try:
                buf.extend(next(it))
            except StopIteration:
                return False
        return True

    while True:
        if not fill(4):
            if buf:
                raise SnappyError("truncated frame header")
            return
        kind = buf[0]
        length = int.from_bytes(buf[1:4], "little")
        if not fill(4 + length):
            raise SnappyError("truncated frame body")
        body = bytes(buf[4:4 + length])
        del buf[:4 + length]
        if kind == _CHUNK_STREAM_IDENT:
            # legal at any point (stream concatenation), required
            # first; the reference's s2.NewWriter stamps "S2sTwO"
            if length != 6 or body not in (STREAM_IDENT[4:],
                                           S2_IDENT_BODY):
                raise SnappyError("bad stream identifier")
            first = False
            continue
        if kind == _CHUNK_COMPRESSED or kind == _CHUNK_UNCOMPRESSED:
            if first:
                raise SnappyError("missing stream identifier")
            if length < 4:
                raise SnappyError("chunk too short")
            want_crc = struct.unpack("<I", body[:4])[0]
            data = body[4:] if kind == _CHUNK_UNCOMPRESSED else \
                uncompress_block(body[4:])
            if masked_crc(data) != want_crc:
                raise SnappyError("chunk CRC mismatch")
            if data:
                yield data
            continue
        if kind == _CHUNK_PADDING or 0x80 <= kind <= 0xfd:
            continue
        raise SnappyError(f"reserved unskippable chunk 0x{kind:02x}")
