"""Bucket lifecycle (ILM) rules + enforcement.

The reference parses lifecycle XML in pkg/bucket/lifecycle and enforces
it from the data crawler (applyActions, cmd/data-crawler.go:629-713):
each crawled object is checked against the bucket's rules and expired
(deleted / delete-markered) when eligible.

Supported rule surface: Status, Filter/Prefix (+And/Tag ignored-match),
Expiration{Days|Date}, NoncurrentVersionExpiration{NoncurrentDays},
AbortIncompleteMultipartUpload{DaysAfterInitiation},
Transition{Days|Date,StorageClass},
NoncurrentVersionTransition{NoncurrentDays,StorageClass}.

Transition rules name a remote TIER via StorageClass (the reference's
ILM tiering, pkg/bucket/lifecycle/transition.go): enforcement rides
the same crawler hooks (tier/transition.py), and expiry always wins
over transition when both are due (uploading data the same pass
deletes it would be pure waste — reference ComputeAction precedence).
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import time
import xml.etree.ElementTree as ET
from typing import Optional

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _find(el, tag):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return r


def _findall(el, tag):
    return list(el.findall(tag)) + list(el.findall(_NS + tag))


def _text(el, tag, default=""):
    r = _find(el, tag)
    return (r.text or "").strip() if r is not None else default


@dataclasses.dataclass
class Rule:
    rule_id: str = ""
    status: str = "Enabled"
    prefix: str = ""
    expiry_days: int = 0
    expiry_date: float = 0.0          # unix seconds; 0 = unset
    noncurrent_days: int = 0
    abort_mpu_days: int = 0
    # ILM tiering: move data to the tier named by StorageClass
    transition_days: int = 0
    transition_date: float = 0.0      # unix seconds; 0 = unset
    transition_tier: str = ""
    noncurrent_transition_days: int = 0
    noncurrent_transition_tier: str = ""

    @property
    def enabled(self) -> bool:
        return self.status == "Enabled"


# parsed-config memo for the crawler hot loop: several actions
# (expiry, transition, noncurrent sweeps) each re-parse the SAME
# bucket XML once per crawled object otherwise. Keyed by the raw
# document; bounded by wholesale reset (configs are tiny and few).
_PARSE_CACHE: dict[str, "Lifecycle"] = {}


class Lifecycle:
    def __init__(self, rules: list[Rule]):
        self.rules = rules

    @classmethod
    def cached(cls, raw: str | bytes) -> "Lifecycle":
        """from_xml through the memo — the crawler-action entry point
        (parse errors are never cached and re-raise every call)."""
        key = raw.decode("utf-8", "replace") \
            if isinstance(raw, (bytes, bytearray)) else raw
        lc = _PARSE_CACHE.get(key)
        if lc is None:
            lc = cls.from_xml(raw)
            if len(_PARSE_CACHE) >= 64:
                _PARSE_CACHE.clear()
            _PARSE_CACHE[key] = lc
        return lc

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "Lifecycle":
        root = ET.fromstring(raw)
        rules = []
        for rel in _findall(root, "Rule"):
            r = Rule(rule_id=_text(rel, "ID"),
                     status=_text(rel, "Status", "Enabled"))
            fel = _find(rel, "Filter")
            if fel is not None:
                r.prefix = _text(fel, "Prefix")
                andel = _find(fel, "And")
                if andel is not None and not r.prefix:
                    r.prefix = _text(andel, "Prefix")
            else:
                r.prefix = _text(rel, "Prefix")
            eel = _find(rel, "Expiration")
            if eel is not None:
                days = _text(eel, "Days")
                if days:
                    r.expiry_days = int(days)
                date = _text(eel, "Date")
                if date:
                    r.expiry_date = _dt.datetime.fromisoformat(
                        date.replace("Z", "+00:00")).timestamp()
            nel = _find(rel, "NoncurrentVersionExpiration")
            if nel is not None:
                nd = _text(nel, "NoncurrentDays")
                if nd:
                    r.noncurrent_days = int(nd)
            tel = _find(rel, "Transition")
            if tel is not None:
                days = _text(tel, "Days")
                if days:
                    r.transition_days = int(days)
                date = _text(tel, "Date")
                if date:
                    r.transition_date = _dt.datetime.fromisoformat(
                        date.replace("Z", "+00:00")).timestamp()
                r.transition_tier = _text(tel, "StorageClass")
            ntel = _find(rel, "NoncurrentVersionTransition")
            if ntel is not None:
                nd = _text(ntel, "NoncurrentDays")
                if nd:
                    r.noncurrent_transition_days = int(nd)
                r.noncurrent_transition_tier = _text(ntel, "StorageClass")
            ael = _find(rel, "AbortIncompleteMultipartUpload")
            if ael is not None:
                ad = _text(ael, "DaysAfterInitiation")
                if ad:
                    r.abort_mpu_days = int(ad)
            rules.append(r)
        return cls(rules)

    # -- evaluation --------------------------------------------------------

    def is_expired(self, object_name: str, mod_time: float,
                   now: Optional[float] = None) -> bool:
        """Current-version expiry check (ComputeAction -> DeleteAction)."""
        now = now if now is not None else time.time()
        for r in self.rules:
            if not r.enabled or not object_name.startswith(r.prefix):
                continue
            if r.expiry_date and now >= r.expiry_date:
                return True
            if r.expiry_days and now >= mod_time + r.expiry_days * 86400:
                return True
        return False

    def mpu_abort_before(self, object_name: str,
                         now: Optional[float] = None) -> Optional[float]:
        """Cutoff initiation time for aborting incomplete multipart
        uploads under this prefix, or None."""
        now = now if now is not None else time.time()
        cutoffs = [now - r.abort_mpu_days * 86400 for r in self.rules
                   if r.enabled and r.abort_mpu_days
                   and object_name.startswith(r.prefix)]
        return max(cutoffs) if cutoffs else None

    def noncurrent_expiry_days(self, object_name: str) -> int:
        """Strictest NoncurrentDays applying to this key, or 0."""
        days = [r.noncurrent_days for r in self.rules
                if r.enabled and r.noncurrent_days
                and object_name.startswith(r.prefix)]
        return min(days) if days else 0

    def transition_due(self, object_name: str, mod_time: float,
                       now: Optional[float] = None) -> str:
        """Tier name the current version should transition to NOW, or
        "". Expiry wins over transition (reference ComputeAction:
        uploading data the same pass deletes is pure waste), and a rule
        needs a StorageClass (tier name) to be actionable."""
        now = now if now is not None else time.time()
        if self.is_expired(object_name, mod_time, now):
            return ""
        for r in self.rules:
            if not r.enabled or not r.transition_tier \
                    or not object_name.startswith(r.prefix):
                continue
            if r.transition_date and now >= r.transition_date:
                return r.transition_tier
            if r.transition_days and \
                    now >= mod_time + r.transition_days * 86400:
                return r.transition_tier
        return ""

    def noncurrent_transition(self, object_name: str) -> tuple[int, str]:
        """(strictest NoncurrentDays, tier) of the
        NoncurrentVersionTransition rules applying to this key, or
        (0, "")."""
        best: tuple[int, str] = (0, "")
        for r in self.rules:
            if not r.enabled or not r.noncurrent_transition_days \
                    or not r.noncurrent_transition_tier \
                    or not object_name.startswith(r.prefix):
                continue
            if not best[0] or r.noncurrent_transition_days < best[0]:
                best = (r.noncurrent_transition_days,
                        r.noncurrent_transition_tier)
        return best


def crawler_action(bucket_meta_sys, object_layer, notifier=None,
                   now_fn=time.time, tiers=None):
    """DataUsageCrawler per-object action enforcing lifecycle expiry
    (cmd/data-crawler.go:629-713): current-version Expiration (delete or
    delete-marker when versioned) and NoncurrentVersionExpiration.
    With a tier manager, expiring a transitioned version also frees its
    remote copy (best-effort — a tier outage must not block expiry)."""

    def act(bucket: str, oi) -> None:
        from ..object import api_errors
        bm = bucket_meta_sys.get(bucket)
        if not bm.lifecycle_xml:
            return
        try:
            lc = Lifecycle.cached(bm.lifecycle_xml)
        except ET.ParseError:
            return
        now = now_fn()
        if lc.is_expired(oi.name, oi.mod_time, now):
            versioned = bm.versioning_enabled()
            try:
                object_layer.delete_object(
                    bucket, oi.name, versioned=versioned)
            except api_errors.ObjectApiError:
                return
            if tiers is not None and not versioned:
                # the data version is gone (an unversioned expiry, not
                # a delete marker): free the remote tier copy too
                from ..tier.transition import free_remote
                free_remote(tiers, oi.user_defined or {})
            if notifier is not None:
                try:
                    notifier.send("s3:ObjectRemoved:Lifecycle", bucket,
                                  oi.name)
                except Exception:  # noqa: BLE001 — best-effort
                    pass

    return act


def noncurrent_sweep_action(bucket_meta_sys, object_layer,
                            now_fn=time.time, tiers=None):
    """Per-bucket crawler action enforcing NoncurrentVersionExpiration
    over a paginated bucket-wide version walk.

    Runs per BUCKET (not per listed object) so keys whose latest version
    is a delete marker — invisible to object listings — still get their
    noncurrent versions expired. A version's clock starts when it BECAME
    noncurrent (its successor's mod time, S3 semantics), and the null
    version (empty version id, written before versioning) expires like
    any other noncurrent version.

    With a tier manager, expiring a transitioned noncurrent version
    also frees its remote copy — this sweep is the main deletion path
    for tiered data in versioned buckets (current-version expiry only
    writes markers), so skipping it would leak the tier forever.
    """

    def act(bucket: str) -> None:
        from ..object import api_errors
        bm = bucket_meta_sys.get(bucket)
        if not bm.lifecycle_xml:
            return
        try:
            lc = Lifecycle.cached(bm.lifecycle_xml)
        except ET.ParseError:
            return
        if not any(r.enabled and r.noncurrent_days for r in lc.rules):
            return
        now = now_fn()

        def expire_group(name: str, vs: list) -> None:
            days = lc.noncurrent_expiry_days(name)
            if not days:
                return
            vs = sorted(vs, key=lambda v: -v.mod_time)
            for i in range(1, len(vs)):         # index 0 = current
                became_noncurrent = vs[i - 1].mod_time
                if became_noncurrent < now - days * 86400:
                    try:
                        object_layer.delete_object(
                            bucket, name, version_id=vs[i].version_id)
                    except api_errors.ObjectApiError:
                        continue
                    if tiers is not None:
                        from ..tier.transition import free_remote
                        free_remote(tiers, vs[i].user_defined or {})

        for name, vs in iter_version_groups(object_layer, bucket,
                                            consumer="lifecycle"):
            expire_group(name, vs)

    return act


def iter_version_groups(object_layer, bucket: str,
                        consumer: str = "scanner"):
    """Yield (name, versions) groups of one bucket's whole version
    history — the shared walk of every version-driven scanner
    (noncurrent expiry/transition sweeps).

    Prefers the metacache namespace feed (no walk: the index already
    holds each name's quorum-merged version list); falls back to
    paging `list_object_versions` with the key/version-id markers,
    carrying a page-cut group across pages so a name's versions are
    always seen TOGETHER (a group split across pages would mis-clock
    which version is current)."""
    from ..object import api_errors
    mc = getattr(object_layer, "metacache", None)
    feed = mc.namespace_feed(bucket, versions=True, consumer=consumer) \
        if mc is not None else None
    if feed is not None:
        yield from feed
        return
    from ..object.metacache import walks_counter
    walks_counter().inc(consumer=consumer, source="merge")
    marker = vid_marker = ""
    carry_name: Optional[str] = None
    carry: list = []
    while True:
        try:
            versions, _pfx, nkm, nvm, trunc = \
                object_layer.list_object_versions(
                    bucket, "", marker, 1000, vid_marker)
        except api_errors.ObjectApiError:
            return
        for v in versions:
            if carry_name is not None and v.name != carry_name:
                yield carry_name, carry
                carry = []
            carry_name = v.name
            carry.append(v)
        if not trunc:
            break
        marker, vid_marker = nkm, nvm
    if carry_name is not None and carry:
        yield carry_name, carry


def mpu_abort_action(bucket_meta_sys, object_layer, now_fn=time.time):
    """Per-bucket crawler action aborting incomplete multipart uploads
    past their AbortIncompleteMultipartUpload cutoff
    (cmd/data-crawler applyActions' multipart sweep)."""

    def act(bucket: str) -> None:
        from ..object import api_errors
        bm = bucket_meta_sys.get(bucket)
        if not bm.lifecycle_xml:
            return
        try:
            lc = Lifecycle.cached(bm.lifecycle_xml)
        except ET.ParseError:
            return
        if not any(r.enabled and r.abort_mpu_days for r in lc.rules):
            return
        try:
            uploads = object_layer.list_multipart_uploads(bucket)
        except api_errors.ObjectApiError:
            return
        now = now_fn()
        for up in uploads:
            cutoff = lc.mpu_abort_before(up["object"], now)
            if cutoff is None or up.get("initiated", 0.0) >= cutoff:
                continue
            try:
                object_layer.abort_multipart_upload(
                    bucket, up["object"], up["upload_id"])
            except api_errors.ObjectApiError:
                pass

    return act
