"""Bucket federation over etcd DNS records (reference cmd/etcd.go +
cmd/config/dns + the bucket-forwarding middleware, cmd/routers.go:46).

Each cluster registers its buckets as CoreDNS-style SRV records under
``/skydns/<reversed domain>/<bucket>/<node>`` (JSON {host, port, ttl})
— the exact layout cmd/config/dns writes, so a real CoreDNS serving
the etcd backend resolves ``bucket.domain`` to this cluster; every
node of the owning cluster gets a record (the reference registers all
endpoints). A request for a bucket this cluster doesn't own is
forwarded transparently to the owning cluster: federated deployments
share credentials (the reference requires it), so the client's SigV4 —
which covers the Host header the client sent, not the forwarder's
address — verifies at the owner unchanged.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Iterator, Optional

from ..distributed.etcd import EtcdClient, EtcdError
from ..s3.handlers import HTTPResponse, RequestContext

DEFAULT_TTL = 30
LOCAL_CACHE_TTL_S = 2.0


def _reverse_domain(domain: str) -> str:
    return "/".join(reversed(domain.strip(".").split(".")))


class BucketFederation:
    def __init__(self, etcd: EtcdClient, domain: str,
                 self_host: str, self_port: int,
                 cluster_addrs: Optional[list[tuple[str, int]]] = None,
                 timeout: float = 30.0):
        self.etcd = etcd
        self.domain = domain.strip(".")
        self.self_host, self.self_port = self_host, self_port
        # every node of THIS cluster: records are written for all of
        # them and recognized as "ours" on lookup — a DELETE handled by
        # node n2 must also clear n1's record or it goes stale forever
        self.cluster_addrs = list(cluster_addrs
                                  or [(self_host, self_port)])
        if (self_host, self_port) not in self.cluster_addrs:
            self.cluster_addrs.append((self_host, self_port))
        self.timeout = timeout
        self._base = f"/skydns/{_reverse_domain(self.domain)}"
        # short positive-existence cache for LOCAL buckets: without it
        # every request would stat the bucket twice (here + in the
        # handler). Negative results are never cached, so new federated
        # buckets and fresh local creates are visible immediately.
        self._local_mu = threading.Lock()
        self._local: dict[str, float] = {}

    # -- DNS record CRUD (cmd/config/dns/etcd_dns.go shapes) --------------

    def _bucket_prefix(self, bucket: str) -> str:
        return f"{self._base}/{bucket}/"

    def register(self, bucket: str) -> None:
        for host, port in self.cluster_addrs:
            rec = json.dumps({"host": host, "port": port,
                              "ttl": DEFAULT_TTL}).encode()
            self.etcd.put(self._bucket_prefix(bucket) + f"{host}:{port}",
                          rec)

    def unregister(self, bucket: str) -> None:
        # every record of THIS cluster; another cluster may legically
        # hold the same name in a different zone, so never the prefix
        for host, port in self.cluster_addrs:
            self.etcd.delete(self._bucket_prefix(bucket)
                             + f"{host}:{port}")

    def register_existing(self, obj) -> None:
        """Startup sweep (reference initFederatorBackend): buckets that
        predate federation (or an etcd restore) get their records
        (re)published."""
        try:
            buckets = obj.list_buckets()
        except Exception:  # noqa: BLE001 — best effort at boot
            return
        for b in buckets:
            try:
                self.register(b.name)
            except EtcdError:
                return             # etcd down: next boot/create retries

    def lookup(self, bucket: str) -> list[tuple[str, int]]:
        out = []
        for _k, raw in self.etcd.get_prefix(
                self._bucket_prefix(bucket)).items():
            try:
                rec = json.loads(raw.decode())
                out.append((str(rec["host"]), int(rec["port"])))
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
        return out

    def list_buckets(self) -> list[str]:
        names = set()
        plen = len(self._base) + 1
        for k in self.etcd.get_prefix(self._base + "/"):
            rest = k[plen:]
            if "/" in rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)

    # -- request forwarding (setBucketForwardingHandler analog) -----------

    def owner_of(self, bucket: str) -> Optional[tuple[str, int]]:
        """The (host, port) to forward to, or None when the bucket is
        unknown to the federation or owned by this very cluster."""
        try:
            records = self.lookup(bucket)
        except EtcdError:
            return None               # etcd down: serve local-only
        ours = set(self.cluster_addrs)
        for rec in records:
            if rec in ours:
                return None
        return records[0] if records else None

    def forward(self, ctx: RequestContext, host: str, port: int
                ) -> HTTPResponse:
        """Transparent byte-level proxy of the current request to the
        owning cluster; request and response bodies both stream (a
        multi-GiB federated PUT never materializes here)."""
        body = ctx.body_stream if ctx.content_length > 0 else b""
        conn = http.client.HTTPConnection(host, port,
                                          timeout=self.timeout)
        path = ctx.req.path + (f"?{ctx.req.raw_query}"
                               if ctx.req.raw_query else "")
        headers = dict(ctx.req.headers)
        headers["connection"] = "close"
        try:
            conn.request(ctx.req.method, path, body=body,
                         headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException):
            conn.close()
            return HTTPResponse(
                status=503,
                body=b"federated bucket owner unreachable")
        out_headers = {}
        for k, v in resp.getheaders():
            if k.lower() in ("connection", "transfer-encoding",
                             "content-length"):
                continue
            out_headers[k] = v
        length = resp.getheader("Content-Length")
        if length is not None:
            out_headers["Content-Length"] = length

        def stream() -> Iterator[bytes]:
            try:
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        return
                    yield chunk
            except (OSError, http.client.HTTPException):
                return            # owner died mid-body: truncate
            finally:
                conn.close()

        return HTTPResponse(status=resp.status, headers=out_headers,
                            stream=stream())

    def _is_local(self, bucket: str, obj) -> bool:
        now = time.monotonic()
        with self._local_mu:
            exp = self._local.get(bucket, 0.0)
            if exp > now:
                return True
        from ..object import api_errors
        try:
            obj.get_bucket_info(bucket)
        except api_errors.BucketNotFound:
            return False
        except api_errors.ObjectApiError:
            return True           # local trouble: not a federation case
        with self._local_mu:
            self._local[bucket] = now + LOCAL_CACHE_TTL_S
            if len(self._local) > 4096:
                self._local = {b: e for b, e in self._local.items()
                               if e > now}
        return True

    def maybe_forward(self, ctx: RequestContext, bucket: str, obj
                      ) -> Optional[HTTPResponse]:
        """Forward when the bucket exists in the federation but not
        here. Local buckets always serve locally, with a short
        positive-existence cache so the hot path doesn't stat twice."""
        if self._is_local(bucket, obj):
            return None
        owner = self.owner_of(bucket)
        if owner is None:
            return None
        return self.forward(ctx, owner[0], owner[1])
