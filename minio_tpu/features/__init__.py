"""Live bucket features: lifecycle enforcement, event notification,
replication (reference pkg/bucket/lifecycle, pkg/event,
cmd/bucket-replication.go)."""

from .events import EventNotifier, NotificationConfig  # noqa: F401
from .lifecycle import Lifecycle  # noqa: F401
from .replication import ReplicationConfig, ReplicationPool  # noqa: F401
