"""SSE-S3 / SSE-C encryption + inline compression for the PUT/GET path.

The reference encrypts with DARE (sio) streams — per-object data key
sealed by the KMS/master or the SSE-C client key, payload split into
packages each AEAD-sealed (cmd/encryption-v1.go:195-364) — and
compresses eligible objects inline with S2, keeping the *actual* size in
internal metadata (cmd/object-api-utils.go:869, isCompressible).

This rebuild keeps the same architecture: AES-256-GCM packages (64 KiB
plaintext each, nonce = base^seq, 16-byte tag) for SSE, and snappy
framing (features/snappy.py — S2-interoperable, the same wire format
family as the reference) or zstd (config choice, no interop) for
compression. The ETag stays the MD5 of the CLIENT bytes: PutObjReader
pairs the raw hashing reader with the transformed stream (reference
PutObjReader, cmd/object-api-utils.go).

Internal metadata keys (never exposed over the API):
    X-Minio-Internal-Sse:             "S3" | "C"
    X-Minio-Internal-Sse-Sealed-Key:  base64(nonce||ct||tag) of the OEK
    X-Minio-Internal-Sse-Iv:          base64 12-byte package nonce base
    X-Minio-Internal-Sse-Key-Md5:     SSE-C client key MD5 (verification)
    X-Minio-Internal-compression:     "klauspost/compress/s2" | "zstd"
    X-Minio-Internal-actual-size:     plaintext byte count
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import secrets
from typing import Iterator, Optional

from ..object.hash_reader import HashReader

PKG_SIZE = 64 * 1024
TAG_SIZE = 16
_AAD = b"minio-tpu-dare-v1"

MK_SSE = "X-Minio-Internal-Sse"
MK_SSE_MP = "X-Minio-Internal-Sse-Multipart"
MK_SEALED = "X-Minio-Internal-Sse-Sealed-Key"
MK_IV = "X-Minio-Internal-Sse-Iv"
MK_KEYMD5 = "X-Minio-Internal-Sse-Key-Md5"
# exact reference bytes (cmd/object-handlers.go:997 writes
# ReservedMetadataPrefix+"compression"): the reference binary looks
# this key up case-SENSITIVELY when reading our disks
MK_COMPRESS = "X-Minio-Internal-compression"

# MK_COMPRESS values. S2/snappy is the interop default: snappy framing
# is a strict subset of the S2 stream format, so objects written here
# are readable by the reference binary and vice versa (within the
# decoded block subset — features/snappy.py). zstd remains available
# behind config (compression.algorithm=zstd) with no cross-binary
# interop.
COMPRESS_S2 = "klauspost/compress/s2"      # cmd/object-handlers.go:69
COMPRESS_SNAPPY_V1 = "golang/snappy/LZ77"  # cmd/object-handlers.go:68
COMPRESS_ZSTD = "zstd"

# pre-r5 builds wrote the key with a capital C; metadata lookups are
# case-sensitive, so reads must accept both spellings forever
MK_COMPRESS_LEGACY = "X-Minio-Internal-Compression"


def stored_compression(md: dict) -> str:
    """The stored compression algorithm under either key spelling
    ('' when the object is not compressed)."""
    return md.get(MK_COMPRESS) or md.get(MK_COMPRESS_LEGACY) or ""
# matches storage.datatypes.to_object_info's actual-size key, so
# ObjectInfo.actual_size is correct for transformed objects too
MK_ACTUAL = "X-Minio-Internal-actual-size"
MK_KMS = "X-Minio-Internal-Sse-Kms-Key-Id"
MK_KMS_SEALED = "X-Minio-Internal-Sse-Kms-Sealed-Key"
MK_KMS_CTX = "X-Minio-Internal-Sse-Kms-Context"

COMPRESSIBLE_EXT = (".txt", ".log", ".csv", ".json", ".tar", ".xml",
                    ".bin")
COMPRESSIBLE_TYPES = ("text/", "application/json", "application/xml",
                      "application/x-tar", "binary/octet-stream")


def _aesgcm(key: bytes):
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    return AESGCM(key)


def _pkg_nonce(base: bytes, seq: int) -> bytes:
    return base[:8] + bytes(a ^ b for a, b in
                            zip(base[8:12], seq.to_bytes(4, "little")))


def encrypted_size(n: int) -> int:
    if n <= 0:
        return 0
    return n + TAG_SIZE * (-(-n // PKG_SIZE))


def seal_key(sealing_key: bytes, oek: bytes) -> bytes:
    nonce = secrets.token_bytes(12)
    return nonce + _aesgcm(sealing_key).encrypt(nonce, oek, _AAD)


def unseal_key(sealing_key: bytes, sealed: bytes) -> bytes:
    return _aesgcm(sealing_key).decrypt(sealed[:12], sealed[12:], _AAD)


# ---------------------------------------------------------------------------
# streaming transforms
# ---------------------------------------------------------------------------

class ZstdCompress:
    def __init__(self) -> None:
        import zstandard
        self._c = zstandard.ZstdCompressor().compressobj()

    def update(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def finalize(self) -> bytes:
        return self._c.flush()


class Encryptor:
    """AES-256-GCM package stream (the DARE-writer analog)."""

    def __init__(self, oek: bytes, nonce_base: bytes):
        self._gcm = _aesgcm(oek)
        self._base = nonce_base
        self._buf = b""
        self._seq = 0

    def _seal(self, pt: bytes) -> bytes:
        ct = self._gcm.encrypt(_pkg_nonce(self._base, self._seq), pt,
                               _AAD + self._seq.to_bytes(8, "little"))
        self._seq += 1
        return ct

    def update(self, data: bytes) -> bytes:
        self._buf += data
        out = b""
        while len(self._buf) >= PKG_SIZE:
            out += self._seal(self._buf[:PKG_SIZE])
            self._buf = self._buf[PKG_SIZE:]
        return out

    def finalize(self) -> bytes:
        if not self._buf:
            return b""
        out = self._seal(self._buf)
        self._buf = b""
        return out


def decrypt_stream(chunks: Iterator[bytes], oek: bytes, nonce_base: bytes,
                   start_seq: int = 0) -> Iterator[bytes]:
    """Ciphertext chunk iterator -> plaintext iterator (DARE reader).
    The input must start exactly at package `start_seq`'s boundary and
    end at a package boundary (the GET path fetches aligned ranges)."""
    gcm = _aesgcm(oek)
    seq = start_seq
    buf = b""
    for chunk in chunks:
        buf += chunk
        while len(buf) >= PKG_SIZE + TAG_SIZE:
            pkg, buf = buf[:PKG_SIZE + TAG_SIZE], buf[PKG_SIZE + TAG_SIZE:]
            yield gcm.decrypt(_pkg_nonce(nonce_base, seq), pkg,
                              _AAD + seq.to_bytes(8, "little"))
            seq += 1
    if buf:
        yield gcm.decrypt(_pkg_nonce(nonce_base, seq), buf,
                          _AAD + seq.to_bytes(8, "little"))


def decompress_stream(chunks: Iterator[bytes],
                      algo: str = COMPRESS_ZSTD) -> Iterator[bytes]:
    """Stored-compression decoder, dispatched on the MK_COMPRESS value
    (both S2 v2 and golang/snappy v1 streams ride the framing
    reader)."""
    if algo in (COMPRESS_S2, COMPRESS_SNAPPY_V1):
        from . import snappy as _snappy
        yield from _snappy.decompress_stream(chunks)
        return
    import zstandard
    d = zstandard.ZstdDecompressor().decompressobj()
    for chunk in chunks:
        out = d.decompress(chunk)
        if out:
            yield out


# ---------------------------------------------------------------------------
# PutObjReader — raw hashing + transformed payload
# ---------------------------------------------------------------------------

def _finalize_chain(transforms: list) -> bytes:
    """Tail flush: finalize each transform in order, feeding its tail
    through the rest of the chain."""
    out = b""
    for i, t in enumerate(transforms):
        data = t.finalize()
        for t2 in transforms[i + 1:]:
            data = t2.update(data)
        out += data
    return out


class PutObjReader(HashReader):
    """Hashes/verifies the RAW client bytes (ETag semantics) while the
    engine consumes the transformed (compressed/encrypted) stream."""

    def __init__(self, inner: HashReader, transforms: list):
        # no super().__init__: hashing/verification delegate to `inner`
        self._inner = inner
        self._transforms = transforms
        self._out = b""
        self._eof = False

    # raw-side surface the engine/handlers consult
    @property
    def actual_size(self) -> int:           # type: ignore[override]
        return self._inner.actual_size

    @property
    def size(self) -> int:                  # type: ignore[override]
        return -1                            # transformed size unknown

    @property
    def bytes_read(self) -> int:            # type: ignore[override]
        return self._inner.bytes_read

    def verify(self) -> None:
        self._inner.verify()

    def md5_current_hex(self) -> str:
        return self._inner.md5_current_hex()

    def close(self) -> None:
        self._inner.close()

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = 1 << 62
        while len(self._out) < n and not self._eof:
            raw = self._inner.read(1 << 16)
            if raw:
                data = raw
                for t in self._transforms:
                    data = t.update(data)
                self._out += data
            else:
                self._out += _finalize_chain(self._transforms)
                self._eof = True
        out, self._out = self._out[:n], self._out[n:]
        return out

    def readinto_full(self, mv: memoryview) -> int:
        """Transformed streams can't land zero-copy (the ciphertext is
        produced chunkwise here, not in the caller's buffer) — override
        the inherited fast path, which would touch HashReader state this
        wrapper never initializes."""
        want = len(mv)
        got = 0
        while got < want:
            chunk = self.read(want - got)
            if not chunk:
                break
            mv[got:got + len(chunk)] = chunk
            got += len(chunk)
        return got


# ---------------------------------------------------------------------------
# request-level helpers (consumed by the S3 handlers)
# ---------------------------------------------------------------------------

def master_key_from_env() -> Optional[bytes]:
    raw = os.environ.get("MINIO_SSE_MASTER_KEY", "")
    if not raw:
        return None
    try:
        key = bytes.fromhex(raw)
    except ValueError:
        return None
    return key if len(key) == 32 else None


def kms_from_env():
    """The SSE-S3 KMS for this process: MINIO_SSE_MASTER_KEY gives a
    StaticKMS; the config subsystems (kms_secret_key, kms_kes) replace
    it at apply time. None = SSE-S3 requests fail with
    ServerSideEncryptionConfigurationNotFoundError."""
    from .kms import StaticKMS
    key = master_key_from_env()
    return StaticKMS(key) if key is not None else None


def parse_ssec_headers(header) -> Optional[bytes]:
    """Returns the 32-byte client key, or None when no SSE-C requested.
    `header` is a callable(name, default="")."""
    algo = header("x-amz-server-side-encryption-customer-algorithm")
    if not algo:
        return None
    from ..s3.s3errors import S3Error
    if algo != "AES256":
        raise S3Error("InvalidEncryptionAlgorithmError")
    try:
        key = base64.b64decode(
            header("x-amz-server-side-encryption-customer-key"))
    except ValueError:
        raise S3Error("InvalidArgument", "bad SSE-C key") from None
    if len(key) != 32:
        raise S3Error("InvalidArgument", "SSE-C key must be 256 bits")
    want_md5 = header("x-amz-server-side-encryption-customer-key-md5")
    have_md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    if want_md5 and want_md5 != have_md5:
        raise S3Error("InvalidArgument", "SSE-C key MD5 mismatch")
    return key


def is_compressible(key: str, content_type: str) -> bool:
    if any(key.endswith(ext) for ext in COMPRESSIBLE_EXT):
        return True
    return any(content_type.startswith(t) for t in COMPRESSIBLE_TYPES)


def setup_put_transforms(*, key_name: str, raw_reader: HashReader,
                         raw_size: int, metadata: dict,
                         ssec_key: Optional[bytes],
                         sse_s3: bool, kms, compress: bool,
                         compress_algo: str = COMPRESS_S2):
    """Build the transformed reader + metadata for a PUT.

    Returns (reader, size) — size is the stored byte count when
    computable, else -1. Mutates `metadata` with the internal keys.
    """
    from ..s3.s3errors import S3Error
    transforms: list = []
    size = raw_size

    if compress:
        if compress_algo == COMPRESS_ZSTD:
            metadata[MK_COMPRESS] = COMPRESS_ZSTD
            transforms.append(ZstdCompress())
        else:
            from .snappy import SnappyFramedCompress
            metadata[MK_COMPRESS] = COMPRESS_S2
            transforms.append(SnappyFramedCompress())
        size = -1

    if ssec_key is not None or sse_s3:
        oek, nonce_base = create_sse_seals(metadata, ssec_key, sse_s3,
                                           kms,
                                           kms_context={"object": key_name})
        transforms.append(Encryptor(oek, nonce_base))
        if size >= 0:
            size = encrypted_size(size)

    if not transforms:
        return raw_reader, raw_size
    metadata[MK_ACTUAL] = str(raw_size) if raw_size >= 0 else "-1"
    return PutObjReader(raw_reader, transforms), size


def create_sse_seals(metadata: dict, ssec_key: Optional[bytes],
                     sse_s3: bool, kms, multipart: bool = False,
                     kms_context: Optional[dict] = None
                     ) -> Optional[tuple[bytes, bytes]]:
    """Generate + seal a fresh object key into `metadata`; returns
    (object key, nonce base) for callers that wrap a stream now (the
    single-PUT path), or None when no SSE was requested. Multipart
    uploads seal at create and encrypt each part later with a per-part
    nonce (cmd/encryption-v1.go multipart part math analog).

    SSE-S3 sealing chain (cmd/crypto KES/master shapes): the KMS mints
    a DEK; the per-object key is sealed under the DEK; only the DEK's
    ciphertext (remote KMS) and the sealed OEK persist in metadata."""
    from ..s3.s3errors import S3Error
    if ssec_key is not None:
        sealing = ssec_key
        metadata[MK_SSE] = "C"
        metadata[MK_KEYMD5] = base64.b64encode(
            hashlib.md5(ssec_key).digest()).decode()
    elif sse_s3:
        if kms is None:
            raise S3Error("ServerSideEncryptionConfigurationNotFoundError")
        from .kms import KMSError
        ctx = dict(kms_context or {})
        try:
            dek, dek_ct = kms.generate_key(ctx)
        except KMSError as e:
            # fail closed: a down KMS must refuse the PUT, not fall
            # back to plaintext or a stale key
            raise S3Error("InternalError", f"KMS generate-key: {e}") \
                from e
        sealing = dek
        metadata[MK_SSE] = "S3"
        if dek_ct:
            metadata[MK_KMS] = getattr(kms, "key_id", "kms")
            metadata[MK_KMS_SEALED] = base64.b64encode(dek_ct).decode()
            metadata[MK_KMS_CTX] = base64.b64encode(json.dumps(
                ctx, sort_keys=True,
                separators=(",", ":")).encode()).decode()
    else:
        return None
    oek = secrets.token_bytes(32)
    nonce_base = secrets.token_bytes(12)
    metadata[MK_SEALED] = base64.b64encode(seal_key(sealing, oek)).decode()
    metadata[MK_IV] = base64.b64encode(nonce_base).decode()
    if multipart:
        metadata[MK_SSE_MP] = "true"
    return oek, nonce_base


def part_nonce(nonce_base: bytes, part_number: int) -> bytes:
    """Per-part package-nonce base: parts encrypt independently, so each
    needs its own nonce space under the shared object key."""
    import hmac as _hmac
    return _hmac.new(nonce_base, b"part-%d" % part_number,
                     hashlib.sha256).digest()[:12]


def resolve_get_key(info_metadata: dict, header,
                    kms) -> Optional[tuple]:
    """For an encrypted object: returns (oek, nonce_base). Raises on
    missing/wrong keys. None when the object is not encrypted."""
    from ..s3.s3errors import S3Error
    mode = info_metadata.get(MK_SSE, "")
    if not mode:
        return None
    sealed = base64.b64decode(info_metadata.get(MK_SEALED, ""))
    nonce_base = base64.b64decode(info_metadata.get(MK_IV, ""))
    if mode == "C":
        key = parse_ssec_headers(header)
        if key is None:
            raise S3Error("AccessDenied",
                          "object is SSE-C encrypted; key required")
        if base64.b64encode(hashlib.md5(key).digest()).decode() != \
                info_metadata.get(MK_KEYMD5, ""):
            raise S3Error("AccessDenied", "SSE-C key does not match")
        sealing = key
    else:
        if kms is None:
            raise S3Error("ServerSideEncryptionConfigurationNotFoundError")
        from .kms import KMSError
        dek_ct = base64.b64decode(info_metadata.get(MK_KMS_SEALED, ""))
        try:
            ctx = json.loads(base64.b64decode(
                info_metadata.get(MK_KMS_CTX, "") or "e30=").decode())
        except (ValueError, UnicodeDecodeError):
            ctx = {}
        try:
            sealing = kms.decrypt_key(dek_ct, ctx,
                                      key_id=info_metadata.get(MK_KMS,
                                                               ""))
        except KMSError as e:
            raise S3Error("InternalError", f"KMS decrypt-key: {e}") \
                from e
    try:
        oek = unseal_key(sealing, sealed)
    except Exception:
        raise S3Error("AccessDenied", "unable to unseal object key") \
            from None
    return oek, nonce_base
