"""SSE-S3 / SSE-C encryption + inline compression for the PUT/GET path.

The reference encrypts with DARE (sio) streams — per-object data key
sealed by the KMS/master or the SSE-C client key, payload split into
packages each AEAD-sealed (cmd/encryption-v1.go:195-364) — and
compresses eligible objects inline with S2, keeping the *actual* size in
internal metadata (cmd/object-api-utils.go:869, isCompressible).

This rebuild keeps the same architecture: AES-256-GCM packages (64 KiB
plaintext each, nonce = base^seq, 16-byte tag) for SSE, and snappy
framing (features/snappy.py — S2-interoperable, the same wire format
family as the reference) or zstd (config choice, no interop) for
compression. The ETag stays the MD5 of the CLIENT bytes: PutObjReader
pairs the raw hashing reader with the transformed stream (reference
PutObjReader, cmd/object-api-utils.go).

Two package ciphers (MINIO_TPU_SSE_CIPHER picks for NEW writes; reads
dispatch on the per-object X-Minio-Internal-Sse-Cipher record):

  * AES-256-GCM (default, `cryptography`-backed): interleaved
    ct||tag packages — the original on-disk format, unchanged.
  * ChaCha20-Poly1305 (`chacha20`, self-contained ops/chacha20_ref +
    device kernel ops/chacha20_jax): DETACHED tags — the stored stream
    is the pure ChaCha20 ciphertext (1:1 offsets with the plaintext)
    followed by a trailer of 16-byte Poly1305 tags, one per package.
    The detached layout is what lets the PUT batch fuse cipher +
    RS-encode + bitrot digest into ONE device launch: the kernel
    produces only keystream XOR, and the host authenticates the
    device-returned ciphertext (tag trailer) before commit — no
    laundered auth. Both the CPU transform (ChaChaEncryptor) and the
    device path (DeviceSSE + models/pipeline.sse_put_step) produce the
    SAME bytes, so either side can read the other's objects and
    `MINIO_TPU_SSE_DEVICE=off` is a pure routing switch.

This module owns ALL SSE nonce derivation (base^seq package nonces,
HMAC per-part bases) and is the only sanctioned caller of the AEAD
primitives — tools/check's crypto-hygiene rule fails any other module
that derives an SSE nonce or touches the primitives directly.

Internal metadata keys (never exposed over the API):
    X-Minio-Internal-Sse:             "S3" | "C"
    X-Minio-Internal-Sse-Sealed-Key:  base64(nonce||ct||tag) of the OEK
    X-Minio-Internal-Sse-Iv:          base64 12-byte package nonce base
    X-Minio-Internal-Sse-Cipher:      "CHACHA20-POLY1305" (absent = AES)
    X-Minio-Internal-Sse-Key-Md5:     SSE-C client key MD5 (verification)
    X-Minio-Internal-compression:     "klauspost/compress/s2" | "zstd"
    X-Minio-Internal-actual-size:     plaintext byte count
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import secrets
from typing import Iterator, Optional

from ..object.hash_reader import HashReader

PKG_SIZE = 64 * 1024
TAG_SIZE = 16
_AAD = b"minio-tpu-dare-v1"

MK_SSE = "X-Minio-Internal-Sse"
MK_CIPHER = "X-Minio-Internal-Sse-Cipher"

# MK_CIPHER values; absent means AES (every pre-chacha object)
CIPHER_AES = "AES256-GCM"
CIPHER_CHACHA = "CHACHA20-POLY1305"
MK_SSE_MP = "X-Minio-Internal-Sse-Multipart"
MK_SEALED = "X-Minio-Internal-Sse-Sealed-Key"
MK_IV = "X-Minio-Internal-Sse-Iv"
MK_KEYMD5 = "X-Minio-Internal-Sse-Key-Md5"
# exact reference bytes (cmd/object-handlers.go:997 writes
# ReservedMetadataPrefix+"compression"): the reference binary looks
# this key up case-SENSITIVELY when reading our disks
MK_COMPRESS = "X-Minio-Internal-compression"

# MK_COMPRESS values. S2/snappy is the interop default: snappy framing
# is a strict subset of the S2 stream format, so objects written here
# are readable by the reference binary and vice versa (within the
# decoded block subset — features/snappy.py). zstd remains available
# behind config (compression.algorithm=zstd) with no cross-binary
# interop.
COMPRESS_S2 = "klauspost/compress/s2"      # cmd/object-handlers.go:69
COMPRESS_SNAPPY_V1 = "golang/snappy/LZ77"  # cmd/object-handlers.go:68
COMPRESS_ZSTD = "zstd"

# pre-r5 builds wrote the key with a capital C; metadata lookups are
# case-sensitive, so reads must accept both spellings forever
MK_COMPRESS_LEGACY = "X-Minio-Internal-Compression"


def stored_compression(md: dict) -> str:
    """The stored compression algorithm under either key spelling
    ('' when the object is not compressed)."""
    return md.get(MK_COMPRESS) or md.get(MK_COMPRESS_LEGACY) or ""
# matches storage.datatypes.to_object_info's actual-size key, so
# ObjectInfo.actual_size is correct for transformed objects too
MK_ACTUAL = "X-Minio-Internal-actual-size"
MK_KMS = "X-Minio-Internal-Sse-Kms-Key-Id"
MK_KMS_SEALED = "X-Minio-Internal-Sse-Kms-Sealed-Key"
MK_KMS_CTX = "X-Minio-Internal-Sse-Kms-Context"

COMPRESSIBLE_EXT = (".txt", ".log", ".csv", ".json", ".tar", ".xml",
                    ".bin")
COMPRESSIBLE_TYPES = ("text/", "application/json", "application/xml",
                      "application/x-tar", "binary/octet-stream")


def _aesgcm(key: bytes):
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    return AESGCM(key)


def _pkg_nonce(base: bytes, seq: int) -> bytes:
    return base[:8] + bytes(a ^ b for a, b in
                            zip(base[8:12], seq.to_bytes(4, "little")))


def encrypted_size(n: int) -> int:
    if n <= 0:
        return 0
    return n + TAG_SIZE * (-(-n // PKG_SIZE))


def seal_key(sealing_key: bytes, oek: bytes,
             cipher: str = CIPHER_AES) -> bytes:
    """Seal the OEK under `sealing_key`; same nonce||ct||tag layout for
    both ciphers, so MK_SEALED stays one opaque blob."""
    nonce = secrets.token_bytes(12)
    if cipher == CIPHER_CHACHA:
        from ..ops import chacha20_ref as _c20
        ct, tag = _c20.seal_detached(sealing_key, nonce, _AAD, oek)
        return nonce + ct + tag
    return nonce + _aesgcm(sealing_key).encrypt(nonce, oek, _AAD)


def unseal_key(sealing_key: bytes, sealed: bytes,
               cipher: str = CIPHER_AES) -> bytes:
    if cipher == CIPHER_CHACHA:
        from ..ops import chacha20_ref as _c20
        return _c20.open_detached(sealing_key, sealed[:12], _AAD,
                                  sealed[12:-TAG_SIZE],
                                  sealed[-TAG_SIZE:])
    return _aesgcm(sealing_key).decrypt(sealed[:12], sealed[12:], _AAD)


def stored_sse_cipher(md: dict) -> str:
    """The package cipher an encrypted object was written with."""
    return md.get(MK_CIPHER) or CIPHER_AES


def sse_cipher_for_new_writes() -> str:
    """MINIO_TPU_SSE_CIPHER: `chacha20` opts new writes into the
    device-fusable ChaCha20-Poly1305 packages; anything else keeps the
    AES-256-GCM default."""
    from ..utils import knobs
    v = knobs.get_str("MINIO_TPU_SSE_CIPHER").strip().lower()
    return CIPHER_CHACHA if v in ("chacha20", "chacha20-poly1305",
                                  "chacha") else CIPHER_AES


# ---------------------------------------------------------------------------
# streaming transforms
# ---------------------------------------------------------------------------

class ZstdCompress:
    def __init__(self) -> None:
        import zstandard
        self._c = zstandard.ZstdCompressor().compressobj()

    def update(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def finalize(self) -> bytes:
        return self._c.flush()


class Encryptor:
    """AES-256-GCM package stream (the DARE-writer analog)."""

    def __init__(self, oek: bytes, nonce_base: bytes):
        self._gcm = _aesgcm(oek)
        self._base = nonce_base
        self._buf = b""
        self._seq = 0

    def _seal(self, pt: bytes) -> bytes:
        ct = self._gcm.encrypt(_pkg_nonce(self._base, self._seq), pt,
                               _AAD + self._seq.to_bytes(8, "little"))
        self._seq += 1
        return ct

    def update(self, data: bytes) -> bytes:
        self._buf += data
        out = b""
        while len(self._buf) >= PKG_SIZE:
            out += self._seal(self._buf[:PKG_SIZE])
            self._buf = self._buf[PKG_SIZE:]
        return out

    def finalize(self) -> bytes:
        if not self._buf:
            return b""
        out = self._seal(self._buf)
        self._buf = b""
        return out


def decrypt_stream(chunks: Iterator[bytes], oek: bytes, nonce_base: bytes,
                   start_seq: int = 0) -> Iterator[bytes]:
    """Ciphertext chunk iterator -> plaintext iterator (DARE reader).
    The input must start exactly at package `start_seq`'s boundary and
    end at a package boundary (the GET path fetches aligned ranges)."""
    gcm = _aesgcm(oek)
    seq = start_seq
    buf = b""
    for chunk in chunks:
        buf += chunk
        while len(buf) >= PKG_SIZE + TAG_SIZE:
            pkg, buf = buf[:PKG_SIZE + TAG_SIZE], buf[PKG_SIZE + TAG_SIZE:]
            yield gcm.decrypt(_pkg_nonce(nonce_base, seq), pkg,
                              _AAD + seq.to_bytes(8, "little"))
            seq += 1
    if buf:
        yield gcm.decrypt(_pkg_nonce(nonce_base, seq), buf,
                          _AAD + seq.to_bytes(8, "little"))


# ---------------------------------------------------------------------------
# ChaCha20-Poly1305 packages (detached tags; device-fusable)
# ---------------------------------------------------------------------------

def _pkg_aad(seq: int) -> bytes:
    return _AAD + seq.to_bytes(8, "little")


def chacha_ct_len(stored: int) -> tuple[int, int]:
    """(ciphertext length, package count) of a chacha object from its
    stored size — stored = ct ‖ 16·npkg tag trailer, and every package
    but the last is full, so npkg = ceil(stored / (PKG+TAG))."""
    if stored <= 0:
        return 0, 0
    npkg = -(-stored // (PKG_SIZE + TAG_SIZE))
    return stored - TAG_SIZE * npkg, npkg


class ChaChaEncryptor:
    """ChaCha20-Poly1305 package stream, detached-tag form: update()
    emits pure ciphertext (offset-preserving), finalize() emits the
    final partial package plus the tag trailer. The CPU byte-identity
    oracle of the device path (DeviceSSE produces the same stream)."""

    def __init__(self, oek: bytes, nonce_base: bytes):
        self._key = oek
        self._base = nonce_base
        self._buf = b""
        self._seq = 0
        self._tags: list[bytes] = []

    def _seal(self, pt: bytes) -> bytes:
        from ..ops import chacha20_ref as _c20
        ct, tag = _c20.seal_detached(
            self._key, _pkg_nonce(self._base, self._seq),
            _pkg_aad(self._seq), pt)
        self._tags.append(tag)
        self._seq += 1
        return ct

    def update(self, data: bytes) -> bytes:
        self._buf += data
        out = b""
        while len(self._buf) >= PKG_SIZE:
            out += self._seal(self._buf[:PKG_SIZE])
            self._buf = self._buf[PKG_SIZE:]
        return out

    def finalize(self) -> bytes:
        out = self._seal(self._buf) if self._buf else b""
        self._buf = b""
        return out + b"".join(self._tags)


def _sse_device_get() -> bool:
    """Whether GET decipher batches may launch on the device."""
    from ..utils import knobs
    if knobs.get_str("MINIO_TPU_SSE_DEVICE").strip().lower() == "off":
        return False
    from ..object.codec import _device_is_tpu, _mesh_active
    return _device_is_tpu() or _mesh_active() is not None


# GET decipher batch width: packages buffered per device launch (64
# packages = 4 MiB of ciphertext per dispatch)
_GET_PKG_BATCH = 64


def chacha_decrypt_ranged(fetch, stored: int, oek: bytes,
                          nonce_base: bytes, offset: int,
                          length: int) -> Iterator[bytes]:
    """Verify-then-decrypt a plaintext range of one chacha stream.

    fetch(off, len) -> ciphertext-chunk iterator over the STORED bytes
    (ct ‖ tag trailer) — the engine read seam. Yields plaintext from
    the covering package boundary (callers trim with their skip/take);
    every package's Poly1305 tag is checked against the trailer BEFORE
    its keystream XOR, so corrupt ciphertext surfaces as a clean auth
    error, never as garbled plaintext. Deciphers in device batches
    (one ops/chacha20_jax launch per _GET_PKG_BATCH packages) when
    routed there, byte-identically on the numpy path otherwise.
    """
    import hmac as _hmac

    import numpy as np

    from ..ops import chacha20_ref as _c20
    ct_len, npkg = chacha_ct_len(stored)
    if length <= 0 or ct_len <= 0:
        return
    start_pkg = offset // PKG_SIZE
    end_pkg = min((offset + length - 1) // PKG_SIZE, npkg - 1)
    tags = b"".join(fetch(ct_len + start_pkg * TAG_SIZE,
                          (end_pkg - start_pkg + 1) * TAG_SIZE))
    coff = start_pkg * PKG_SIZE
    clen = min(ct_len, (end_pkg + 1) * PKG_SIZE) - coff
    device = _sse_device_get()
    kw = np.frombuffer(oek, dtype="<u4")

    def _flush(pkgs: list[bytes], seq0: int) -> Iterator[bytes]:
        # authenticate FIRST — nothing deciphers until every package
        # in the batch carries a valid trailer tag
        for j, pkg in enumerate(pkgs):
            seq = seq0 + j
            at = (seq - start_pkg) * TAG_SIZE
            want = _c20.tag_detached(oek, _pkg_nonce(nonce_base, seq),
                                     _pkg_aad(seq), pkg)
            if not _hmac.compare_digest(want, tags[at:at + TAG_SIZE]):
                from ..s3.s3errors import S3Error
                raise S3Error("InternalError",
                              f"SSE package {seq} failed "
                              "authentication")
        if device:
            try:
                from ..ops import chacha20_jax as _cj
                width = -(-max(len(p) for p in pkgs) // 64) * 64
                rows = np.zeros((len(pkgs), width), dtype=np.uint8)
                for j, pkg in enumerate(pkgs):
                    rows[j, :len(pkg)] = np.frombuffer(pkg, np.uint8)
                nn = np.stack([np.frombuffer(
                    _pkg_nonce(nonce_base, seq0 + j),
                    dtype="<u4") for j in range(len(pkgs))])
                out = _cj.xor_packages(
                    rows, np.broadcast_to(kw, (len(pkgs), 8)), nn)
                for j, pkg in enumerate(pkgs):
                    yield out[j, :len(pkg)].tobytes()
                return
            except Exception:  # noqa: BLE001 — dispatch error: CPU path
                pass
        for j, pkg in enumerate(pkgs):
            yield _c20.xor_stream(pkg, oek,
                                  _pkg_nonce(nonce_base, seq0 + j))

    buf = b""
    seq = start_pkg
    pend: list[bytes] = []
    for chunk in fetch(coff, clen):
        buf += chunk
        while len(buf) >= PKG_SIZE:
            pend.append(buf[:PKG_SIZE])
            buf = buf[PKG_SIZE:]
            if len(pend) >= _GET_PKG_BATCH:
                yield from _flush(pend, seq)
                seq += len(pend)
                pend = []
    if buf:
        pend.append(buf)
    if pend:
        yield from _flush(pend, seq)


class DeviceSSE:
    """Per-PUT cipher spec for the fused device data path.

    The engine treats this as an opaque capability object: key/nonce
    word arrays for the batch former come from batch_params(), the CPU
    fallback encrypts staging rows in place byte-identically, and
    every ciphertext byte is absorbed IN STREAM ORDER so the Poly1305
    tag trailer — computed host-side over the device-produced
    ciphertext, before commit — can be appended at stream end. All
    derivation stays inside this class (crypto-hygiene lint)."""

    PKG = PKG_SIZE

    def __init__(self, oek: bytes, nonce_base: bytes):
        import numpy as np
        self._key = oek
        self._base = nonce_base
        self._kw = np.frombuffer(oek, dtype="<u4")
        self._bw = np.frombuffer(nonce_base, dtype="<u4")
        self._tags: list[bytes] = []
        self._seq = 0
        self._partial = b""

    # -- batch former / device side ------------------------------------

    def batch_params(self, offset: int, nrows: int, row_bytes: int):
        """(keys (B, 8), nonces (B, P, 3)) u32 word arrays for a batch
        of full rows starting at stream offset `offset` (a PKG
        multiple). These ride the scheduler bucket like survivor masks
        do; the bucket key carries only their SHAPE, so concurrent
        PUTs under different keys coalesce."""
        import numpy as np
        p = row_bytes // PKG_SIZE
        keys = np.broadcast_to(self._kw, (nrows, 8)).copy()
        seqs = (offset // PKG_SIZE
                + np.arange(nrows * p, dtype=np.uint64).reshape(
                    nrows, p)).astype(np.uint32)
        nonces = np.empty((nrows, p, 3), dtype=np.uint32)
        nonces[:, :, 0] = self._bw[0]
        nonces[:, :, 1] = self._bw[1]
        nonces[:, :, 2] = self._bw[2] ^ seqs
        return keys, nonces

    # -- CPU fallback (byte-identity oracle) ---------------------------

    def cpu_encrypt_rows(self, flat_rows, offset: int) -> None:
        """In-place ChaCha20 over (B, row_bytes) u8 staging-row views —
        the decline/dispatch-error fallback, producing the same bytes
        the device kernel would."""
        from ..ops import chacha20_ref as _c20
        b, row_bytes = flat_rows.shape
        for i in range(b):
            self.cpu_encrypt_tail(flat_rows[i],
                                  offset + i * row_bytes)

    def cpu_encrypt_tail(self, row, offset: int) -> None:
        """In-place ChaCha20 over one row of `len(row)` bytes (full
        packages + optional final partial) at stream offset `offset`."""
        from ..ops import chacha20_ref as _c20
        n = row.shape[0]
        seq = offset // PKG_SIZE
        for at in range(0, n, PKG_SIZE):
            _c20.xor_stream_into(row[at:at + PKG_SIZE], self._key,
                                 _pkg_nonce(self._base, seq))
            seq += 1

    # -- host-side authentication (tag trailer) ------------------------

    def _tag(self, pkg: bytes) -> None:
        from ..ops import chacha20_ref as _c20
        self._tags.append(_c20.tag_detached(
            self._key, _pkg_nonce(self._base, self._seq),
            _pkg_aad(self._seq), pkg))
        self._seq += 1

    def absorb(self, ct) -> None:
        """Feed ciphertext in stream order (device output or CPU
        fallback — the bytes are identical); packages close as they
        fill and their tags accumulate for the trailer."""
        mv = memoryview(ct)
        if self._partial:
            need = PKG_SIZE - len(self._partial)
            take = bytes(mv[:need])
            self._partial += take
            mv = mv[len(take):]
            if len(self._partial) == PKG_SIZE:
                self._tag(self._partial)
                self._partial = b""
        full = len(mv) // PKG_SIZE
        for i in range(full):
            self._tag(bytes(mv[i * PKG_SIZE:(i + 1) * PKG_SIZE]))
        rest = mv[full * PKG_SIZE:]
        if len(rest):
            self._partial = bytes(rest)

    def trailer(self) -> bytes:
        """Close the stream: the final partial package's tag plus the
        full tag trailer the engine appends after the ciphertext."""
        if self._partial:
            self._tag(self._partial)
            self._partial = b""
        return b"".join(self._tags)


def device_sse_allowed(size: int) -> bool:
    """The QAT-style gate for the fused PUT path: escape hatch
    (MINIO_TPU_SSE_DEVICE=off), device/capacity presence, and the
    size window. A False here (or ANY later decline/dispatch error)
    means the CPU ChaChaEncryptor path — same bytes either way."""
    from ..utils import eventlog, knobs
    if knobs.get_str("MINIO_TPU_SSE_DEVICE").strip().lower() == "off":
        eventlog.emit_once("device.decline", stage="sse",
                           reason="off")
        return False
    try:
        from ..object.codec import _device_is_tpu, _mesh_active
        if not _device_is_tpu() and _mesh_active() is None:
            eventlog.emit_once("device.decline", stage="sse",
                               reason="no-device")
            return False
    except Exception:  # noqa: BLE001 — no jax backend: CPU path
        eventlog.emit_once("device.decline", stage="sse",
                           reason="no-backend")
        return False
    if size < 0:
        return False
    if size < knobs.get_int("MINIO_TPU_SSE_DEVICE_MIN_BYTES"):
        return False
    max_b = knobs.get_int("MINIO_TPU_SSE_DEVICE_MAX_BYTES")
    return not (max_b and size > max_b)


def decompress_stream(chunks: Iterator[bytes],
                      algo: str = COMPRESS_ZSTD) -> Iterator[bytes]:
    """Stored-compression decoder, dispatched on the MK_COMPRESS value
    (both S2 v2 and golang/snappy v1 streams ride the framing
    reader)."""
    if algo in (COMPRESS_S2, COMPRESS_SNAPPY_V1):
        from . import snappy as _snappy
        yield from _snappy.decompress_stream(chunks)
        return
    import zstandard
    d = zstandard.ZstdDecompressor().decompressobj()
    for chunk in chunks:
        out = d.decompress(chunk)
        if out:
            yield out


# ---------------------------------------------------------------------------
# PutObjReader — raw hashing + transformed payload
# ---------------------------------------------------------------------------

def _finalize_chain(transforms: list) -> bytes:
    """Tail flush: finalize each transform in order, feeding its tail
    through the rest of the chain."""
    out = b""
    for i, t in enumerate(transforms):
        data = t.finalize()
        for t2 in transforms[i + 1:]:
            data = t2.update(data)
        out += data
    return out


class PutObjReader(HashReader):
    """Hashes/verifies the RAW client bytes (ETag semantics) while the
    engine consumes the transformed (compressed/encrypted) stream."""

    def __init__(self, inner: HashReader, transforms: list):
        # no super().__init__: hashing/verification delegate to `inner`
        self._inner = inner
        self._transforms = transforms
        self._out = b""
        self._eof = False

    # raw-side surface the engine/handlers consult
    @property
    def actual_size(self) -> int:           # type: ignore[override]
        return self._inner.actual_size

    @property
    def size(self) -> int:                  # type: ignore[override]
        return -1                            # transformed size unknown

    @property
    def bytes_read(self) -> int:            # type: ignore[override]
        return self._inner.bytes_read

    def verify(self) -> None:
        self._inner.verify()

    def md5_current_hex(self) -> str:
        return self._inner.md5_current_hex()

    def close(self) -> None:
        self._inner.close()

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = 1 << 62
        while len(self._out) < n and not self._eof:
            raw = self._inner.read(1 << 16)
            if raw:
                data = raw
                for t in self._transforms:
                    data = t.update(data)
                self._out += data
            else:
                self._out += _finalize_chain(self._transforms)
                self._eof = True
        out, self._out = self._out[:n], self._out[n:]
        return out

    def readinto_full(self, mv: memoryview) -> int:
        """Transformed streams can't land zero-copy (the ciphertext is
        produced chunkwise here, not in the caller's buffer) — override
        the inherited fast path, which would touch HashReader state this
        wrapper never initializes."""
        want = len(mv)
        got = 0
        while got < want:
            chunk = self.read(want - got)
            if not chunk:
                break
            mv[got:got + len(chunk)] = chunk
            got += len(chunk)
        return got


# ---------------------------------------------------------------------------
# request-level helpers (consumed by the S3 handlers)
# ---------------------------------------------------------------------------

def master_key_from_env() -> Optional[bytes]:
    raw = os.environ.get("MINIO_SSE_MASTER_KEY", "")
    if not raw:
        return None
    try:
        key = bytes.fromhex(raw)
    except ValueError:
        return None
    return key if len(key) == 32 else None


def kms_from_env():
    """The SSE-S3 KMS for this process: MINIO_SSE_MASTER_KEY gives a
    StaticKMS; the config subsystems (kms_secret_key, kms_kes) replace
    it at apply time. None = SSE-S3 requests fail with
    ServerSideEncryptionConfigurationNotFoundError."""
    from .kms import StaticKMS
    key = master_key_from_env()
    return StaticKMS(key) if key is not None else None


def parse_ssec_headers(header) -> Optional[bytes]:
    """Returns the 32-byte client key, or None when no SSE-C requested.
    `header` is a callable(name, default="")."""
    algo = header("x-amz-server-side-encryption-customer-algorithm")
    if not algo:
        return None
    from ..s3.s3errors import S3Error
    if algo != "AES256":
        raise S3Error("InvalidEncryptionAlgorithmError")
    try:
        key = base64.b64decode(
            header("x-amz-server-side-encryption-customer-key"))
    except ValueError:
        raise S3Error("InvalidArgument", "bad SSE-C key") from None
    if len(key) != 32:
        raise S3Error("InvalidArgument", "SSE-C key must be 256 bits")
    want_md5 = header("x-amz-server-side-encryption-customer-key-md5")
    have_md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    if want_md5 and want_md5 != have_md5:
        raise S3Error("InvalidArgument", "SSE-C key MD5 mismatch")
    return key


def is_compressible(key: str, content_type: str) -> bool:
    if any(key.endswith(ext) for ext in COMPRESSIBLE_EXT):
        return True
    return any(content_type.startswith(t) for t in COMPRESSIBLE_TYPES)


def setup_put_transforms(*, key_name: str, raw_reader: HashReader,
                         raw_size: int, metadata: dict,
                         ssec_key: Optional[bytes],
                         sse_s3: bool, kms, compress: bool,
                         compress_algo: str = COMPRESS_S2,
                         cipher: Optional[str] = None,
                         device_sse: bool = False):
    """Build the transformed reader + metadata for a PUT.

    Returns (reader, size, sse_spec) — size is the stored byte count
    when computable, else -1; sse_spec is a DeviceSSE for the engine's
    fused device path (chacha + device_sse=True and gate allows) or
    None (cipher runs as a CPU transform here). Mutates `metadata`
    with the internal keys.
    """
    from ..s3.s3errors import S3Error
    transforms: list = []
    size = raw_size
    spec = None

    if compress:
        if compress_algo == COMPRESS_ZSTD:
            metadata[MK_COMPRESS] = COMPRESS_ZSTD
            transforms.append(ZstdCompress())
        else:
            from .snappy import SnappyFramedCompress
            metadata[MK_COMPRESS] = COMPRESS_S2
            transforms.append(SnappyFramedCompress())
        size = -1

    if ssec_key is not None or sse_s3:
        if cipher is None:
            cipher = sse_cipher_for_new_writes()
        oek, nonce_base = create_sse_seals(metadata, ssec_key, sse_s3,
                                           kms,
                                           kms_context={"object": key_name},
                                           cipher=cipher)
        if cipher == CIPHER_CHACHA:
            # compressed streams fuse too: the compressor stays a host
            # transform and its output is the "plaintext" the engine
            # ciphers in-batch (raw_size gates the window — the
            # compressed stream is no larger in the cases that matter)
            if device_sse and device_sse_allowed(raw_size):
                # cipher leaves this chain: the engine fuses it into
                # the encode launch and appends the tag trailer
                spec = DeviceSSE(oek, nonce_base)
            else:
                transforms.append(ChaChaEncryptor(oek, nonce_base))
        else:
            transforms.append(Encryptor(oek, nonce_base))
        if size >= 0:
            size = encrypted_size(size)

    if not transforms and spec is None:
        return raw_reader, raw_size, None
    metadata[MK_ACTUAL] = str(raw_size) if raw_size >= 0 else "-1"
    if not transforms:
        # fused path, nothing else in the chain: the engine reads the
        # PLAINTEXT and ciphers in-batch; stored size is still known
        return raw_reader, size, spec
    return PutObjReader(raw_reader, transforms), size, spec


def create_sse_seals(metadata: dict, ssec_key: Optional[bytes],
                     sse_s3: bool, kms, multipart: bool = False,
                     kms_context: Optional[dict] = None,
                     cipher: Optional[str] = None
                     ) -> Optional[tuple[bytes, bytes]]:
    """Generate + seal a fresh object key into `metadata`; returns
    (object key, nonce base) for callers that wrap a stream now (the
    single-PUT path), or None when no SSE was requested. Multipart
    uploads seal at create and encrypt each part later with a per-part
    nonce (cmd/encryption-v1.go multipart part math analog).

    SSE-S3 sealing chain (cmd/crypto KES/master shapes): the KMS mints
    a DEK; the per-object key is sealed under the DEK; only the DEK's
    ciphertext (remote KMS) and the sealed OEK persist in metadata."""
    from ..s3.s3errors import S3Error
    if ssec_key is not None:
        sealing = ssec_key
        metadata[MK_SSE] = "C"
        metadata[MK_KEYMD5] = base64.b64encode(
            hashlib.md5(ssec_key).digest()).decode()
    elif sse_s3:
        if kms is None:
            raise S3Error("ServerSideEncryptionConfigurationNotFoundError")
        from .kms import KMSError
        ctx = dict(kms_context or {})
        try:
            dek, dek_ct = kms.generate_key(ctx)
        except KMSError as e:
            # fail closed: a down KMS must refuse the PUT, not fall
            # back to plaintext or a stale key
            raise S3Error("InternalError", f"KMS generate-key: {e}") \
                from e
        sealing = dek
        metadata[MK_SSE] = "S3"
        if dek_ct:
            metadata[MK_KMS] = getattr(kms, "key_id", "kms")
            metadata[MK_KMS_SEALED] = base64.b64encode(dek_ct).decode()
            metadata[MK_KMS_CTX] = base64.b64encode(json.dumps(
                ctx, sort_keys=True,
                separators=(",", ":")).encode()).decode()
    else:
        return None
    if cipher is None:
        cipher = sse_cipher_for_new_writes()
    oek = secrets.token_bytes(32)
    nonce_base = secrets.token_bytes(12)
    metadata[MK_SEALED] = base64.b64encode(
        seal_key(sealing, oek, cipher)).decode()
    metadata[MK_IV] = base64.b64encode(nonce_base).decode()
    if cipher == CIPHER_CHACHA:
        metadata[MK_CIPHER] = CIPHER_CHACHA
    if multipart:
        metadata[MK_SSE_MP] = "true"
    return oek, nonce_base


def part_nonce(nonce_base: bytes, part_number: int) -> bytes:
    """Per-part package-nonce base: parts encrypt independently, so each
    needs its own nonce space under the shared object key."""
    import hmac as _hmac
    return _hmac.new(nonce_base, b"part-%d" % part_number,
                     hashlib.sha256).digest()[:12]


def resolve_get_key(info_metadata: dict, header,
                    kms) -> Optional[tuple]:
    """For an encrypted object: returns (oek, nonce_base). Raises on
    missing/wrong keys. None when the object is not encrypted."""
    from ..s3.s3errors import S3Error
    mode = info_metadata.get(MK_SSE, "")
    if not mode:
        return None
    sealed = base64.b64decode(info_metadata.get(MK_SEALED, ""))
    nonce_base = base64.b64decode(info_metadata.get(MK_IV, ""))
    if mode == "C":
        key = parse_ssec_headers(header)
        if key is None:
            raise S3Error("AccessDenied",
                          "object is SSE-C encrypted; key required")
        if base64.b64encode(hashlib.md5(key).digest()).decode() != \
                info_metadata.get(MK_KEYMD5, ""):
            raise S3Error("AccessDenied", "SSE-C key does not match")
        sealing = key
    else:
        if kms is None:
            raise S3Error("ServerSideEncryptionConfigurationNotFoundError")
        from .kms import KMSError
        dek_ct = base64.b64decode(info_metadata.get(MK_KMS_SEALED, ""))
        try:
            ctx = json.loads(base64.b64decode(
                info_metadata.get(MK_KMS_CTX, "") or "e30=").decode())
        except (ValueError, UnicodeDecodeError):
            ctx = {}
        try:
            sealing = kms.decrypt_key(dek_ct, ctx,
                                      key_id=info_metadata.get(MK_KMS,
                                                               ""))
        except KMSError as e:
            raise S3Error("InternalError", f"KMS decrypt-key: {e}") \
                from e
    try:
        oek = unseal_key(sealing, sealed,
                         stored_sse_cipher(info_metadata))
    except Exception:
        raise S3Error("AccessDenied", "unable to unseal object key") \
            from None
    return oek, nonce_base
