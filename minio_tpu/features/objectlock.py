"""Object lock (WORM): retention + legal hold parsing and enforcement.

The reference stores per-object lock state in metadata headers and
enforces it on deletion (pkg/bucket/object/lock, cmd/bucket-object-lock.go
enforceRetentionForDeletion): a version under COMPLIANCE retention or
legal hold cannot be deleted; GOVERNANCE retention can be bypassed with
x-amz-bypass-governance-retention by a caller holding
s3:BypassGovernanceRetention.
"""

from __future__ import annotations

import datetime as _dt
import time
import xml.etree.ElementTree as ET
from typing import Optional

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"

# stored as (real S3) object metadata headers
MD_MODE = "x-amz-object-lock-mode"
MD_RETAIN = "x-amz-object-lock-retain-until-date"
MD_HOLD = "x-amz-object-lock-legal-hold"


def _find(el, tag):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return r


def _text(el, tag, default=""):
    r = _find(el, tag)
    return (r.text or "").strip() if r is not None else default


def parse_iso(ts: str) -> float:
    return _dt.datetime.fromisoformat(
        ts.replace("Z", "+00:00")).timestamp()


def iso(ts: float) -> str:
    return _dt.datetime.fromtimestamp(ts, _dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


class DefaultRetention:
    """Bucket-level default applied to new objects
    (<ObjectLockConfiguration><Rule><DefaultRetention>...)."""

    def __init__(self, mode: str = "", days: int = 0, years: int = 0):
        self.mode = mode
        self.days = days
        self.years = years

    @classmethod
    def from_config_xml(cls, raw: str) -> "DefaultRetention":
        try:
            root = ET.fromstring(raw)
        except ET.ParseError:
            return cls()
        rule = _find(root, "Rule")
        if rule is None:
            return cls()
        dr = _find(rule, "DefaultRetention")
        if dr is None:
            return cls()
        return cls(mode=_text(dr, "Mode"),
                   days=int(_text(dr, "Days") or 0),
                   years=int(_text(dr, "Years") or 0))

    def apply_to(self, metadata: dict, now: Optional[float] = None
                 ) -> None:
        if not self.mode:
            return
        now = now if now is not None else time.time()
        until = now + self.days * 86400 + self.years * 365 * 86400
        metadata.setdefault(MD_MODE, self.mode)
        metadata.setdefault(MD_RETAIN, iso(until))


def retention_headers_from_request(header, metadata: dict) -> None:
    """Copy x-amz-object-lock-* request headers into object metadata
    (PUT path)."""
    mode = header(MD_MODE)
    until = header(MD_RETAIN)
    hold = header(MD_HOLD)
    if mode:
        if mode not in ("GOVERNANCE", "COMPLIANCE"):
            from ..s3.s3errors import S3Error
            raise S3Error("InvalidArgument", "bad object lock mode")
        if not until:
            from ..s3.s3errors import S3Error
            raise S3Error("InvalidArgument",
                          "retain-until-date required with mode")
        metadata[MD_MODE] = mode
        metadata[MD_RETAIN] = until
    if hold:
        if hold not in ("ON", "OFF"):
            from ..s3.s3errors import S3Error
            raise S3Error("InvalidArgument", "bad legal hold")
        metadata[MD_HOLD] = hold


def check_deletable(user_defined: dict, bypass_governance: bool,
                    now: Optional[float] = None) -> Optional[str]:
    """None when deletable; else the reason (maps to ObjectLocked)."""
    now = now if now is not None else time.time()
    if user_defined.get(MD_HOLD, "").upper() == "ON":
        return "object is under legal hold"
    mode = user_defined.get(MD_MODE, "").upper()
    until_raw = user_defined.get(MD_RETAIN, "")
    if not mode or not until_raw:
        return None
    try:
        until = parse_iso(until_raw)
    except ValueError:
        # Corrupt/unparsable retain-until date on a locked object: fail
        # closed — treat retention as still active rather than deletable.
        until = None
    if until is not None and now >= until:
        return None
    if mode == "COMPLIANCE":
        return "object is under COMPLIANCE retention"
    if mode == "GOVERNANCE" and not bypass_governance:
        return "object is under GOVERNANCE retention"
    return None


def check_retention_update(user_defined: dict, new_mode: str,
                           new_until: str, bypass_governance: bool,
                           now: Optional[float] = None) -> Optional[str]:
    """None when the retention change is allowed; else the reason.

    Mirrors PutObjectRetentionHandler (cmd/object-handlers.go):
    - active COMPLIANCE retention can only be extended, never have its
      mode changed or date reduced;
    - weakening active GOVERNANCE retention (mode change away from a
      stricter setting or date reduction) requires the governance-bypass
      header plus s3:BypassGovernanceRetention (bypass_governance=True).
    Tightening is always allowed.
    """
    now = now if now is not None else time.time()
    cur_mode = user_defined.get(MD_MODE, "").upper()
    cur_raw = user_defined.get(MD_RETAIN, "")
    if not cur_mode or not cur_raw:
        return None
    try:
        cur_until = parse_iso(cur_raw)
    except ValueError:
        cur_until = None                  # corrupt date: fail closed below
    if cur_until is not None and now >= cur_until:
        return None                       # retention expired: free change
    try:
        new_ts = parse_iso(new_until)
    except ValueError:
        return "bad retain-until date"
    if cur_mode == "COMPLIANCE":
        if new_mode != "COMPLIANCE":
            return "cannot change mode while COMPLIANCE retention is active"
        if cur_until is None or new_ts < cur_until:
            return "cannot shorten COMPLIANCE retention"
        return None
    # active GOVERNANCE: shortening the date (or an unreadable stored
    # date, where extension cannot be proven) needs the bypass grant
    if cur_until is None or new_ts < cur_until:
        if not bypass_governance:
            return ("cannot weaken GOVERNANCE retention without "
                    "x-amz-bypass-governance-retention")
    return None


# -- ?retention / ?legal-hold subresource XML -------------------------------

def retention_xml(user_defined: dict) -> str:
    mode = user_defined.get(MD_MODE, "")
    until = user_defined.get(MD_RETAIN, "")
    if not mode:
        return ""
    return (f"<Retention><Mode>{mode}</Mode>"
            f"<RetainUntilDate>{until}</RetainUntilDate></Retention>")


def parse_retention_xml(raw: bytes) -> tuple[str, str]:
    root = ET.fromstring(raw)
    return _text(root, "Mode"), _text(root, "RetainUntilDate")


def legal_hold_xml(user_defined: dict) -> str:
    status = user_defined.get(MD_HOLD, "OFF")
    return f"<LegalHold><Status>{status}</Status></LegalHold>"


def parse_legal_hold_xml(raw: bytes) -> str:
    return _text(ET.fromstring(raw), "Status")
