"""Distributed runtime: internode RPC transport, quorum locks (dsync),
remote storage, peer control plane.

The rebuild of the reference's L7 (cmd/rest/, pkg/dsync/,
cmd/lock-rest-*.go, cmd/storage-rest-*.go, cmd/peer-rest-*.go): nodes
speak a thin authenticated HTTP-POST RPC over DCN; shard-batch math
stays on-device over ICI (minio_tpu/parallel)."""
