"""etcd v3 KV client over the JSON gRPC-gateway API (the transport
etcd ships for non-gRPC clients: POST /v3/kv/{put,range,deleterange}
with base64 keys/values).

The reference links the etcd Go client (cmd/etcd.go) for federation
(bucket DNS on CoreDNS/etcd, cmd/config/dns) and the IAM etcd store
(cmd/iam-etcd-store.go). This speaks the same server surface over
plain HTTP so the seam is testable against an in-process fake — the
pattern every notify target in this repo uses.
"""

from __future__ import annotations

import base64
import http.client
import json
import urllib.parse
from typing import Callable, Optional


class EtcdError(Exception):
    pass


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode()


def _prefix_range_end(key: bytes) -> bytes:
    """etcd prefix query: range_end = key with last byte + 1
    (clientv3.GetPrefix semantics)."""
    end = bytearray(key)
    for i in reversed(range(len(end))):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[:i + 1])
    return b"\x00"


class EtcdClient:
    def __init__(self, endpoint: str, timeout: float = 5.0,
                 connect: Optional[Callable[[], object]] = None):
        u = urllib.parse.urlsplit(endpoint)
        if u.scheme not in ("http", "https") or not u.hostname:
            raise ValueError(f"bad etcd endpoint {endpoint!r}")
        self.endpoint = endpoint
        self.timeout = timeout
        self._host = u.hostname
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._secure = u.scheme == "https"
        self._connect = connect or self._default_connect

    def _default_connect(self):
        cls = http.client.HTTPSConnection if self._secure \
            else http.client.HTTPConnection
        return cls(self._host, self._port, timeout=self.timeout)

    def _post(self, path: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        try:
            conn = self._connect()
            try:
                conn.request("POST", path, body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                data = resp.read()
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            # HTTPException (BadStatusLine, IncompleteRead…) must also
            # map to EtcdError or the local-only degradation path in
            # federation.owner_of never fires
            raise EtcdError(f"etcd unreachable: {e}") from e
        if resp.status != 200:
            raise EtcdError(
                f"etcd {path} failed ({resp.status}): {data[:200]!r}")
        try:
            out = json.loads(data.decode() or "{}")
        except ValueError:
            raise EtcdError("etcd returned malformed JSON") from None
        return out if isinstance(out, dict) else {}

    def put(self, key: str, value: bytes) -> None:
        self._post("/v3/kv/put", {"key": _b64(key.encode()),
                                  "value": _b64(value)})

    def get(self, key: str) -> Optional[bytes]:
        out = self._post("/v3/kv/range", {"key": _b64(key.encode())})
        kvs = out.get("kvs") or []
        if not kvs:
            return None
        try:
            return base64.b64decode(kvs[0].get("value", ""))
        except ValueError:
            raise EtcdError("etcd returned undecodable value") from None

    def get_prefix(self, prefix: str) -> dict[str, bytes]:
        kb = prefix.encode()
        out = self._post("/v3/kv/range", {
            "key": _b64(kb),
            "range_end": _b64(_prefix_range_end(kb))})
        result: dict[str, bytes] = {}
        for kv in out.get("kvs") or []:
            try:
                k = base64.b64decode(kv.get("key", "")).decode()
                result[k] = base64.b64decode(kv.get("value", ""))
            except (ValueError, UnicodeDecodeError):
                continue
        return result

    def delete(self, key: str) -> None:
        self._post("/v3/kv/deleterange", {"key": _b64(key.encode())})

    def delete_prefix(self, prefix: str) -> None:
        kb = prefix.encode()
        self._post("/v3/kv/deleterange", {
            "key": _b64(kb),
            "range_end": _b64(_prefix_range_end(kb))})
