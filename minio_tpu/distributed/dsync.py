"""dsync — distributed quorum RW lock (pkg/dsync/drwmutex.go).

A lock over N lockers (one per node) is held when a quorum grants it:
    tolerance = N // 2
    quorum    = N - tolerance   (+1 when N is even and it's a write lock)
Acquisition broadcasts Lock/RLock to all lockers concurrently, waits for
responses, and on sub-quorum releases every partial grant
(drwmutex.go:213-380). Callers retry with jitter until their timeout
(lockBlocking, drwmutex.go:143).
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Optional, Protocol

RETRY_INTERVAL_MAX = 0.25      # jittered sleep between attempts
REFRESH_INTERVAL = 30.0        # LOCK_VALIDITY / 4: keep long holds alive

# Per-broadcast collect window, self-tuning: when lockers answer slowly
# the window grows instead of thrashing retries; when they answer fast
# it shrinks back (the reference runs dsync under a dynamicTimeout,
# cmd/dynamic-timeouts.go + cmd/namespace-lock.go).
from ..utils.dyntimeout import DynamicTimeout  # noqa: E402

ACQUIRE_TIMEOUT_DYN = DynamicTimeout(1.0, 0.25, 15.0)


class NetLocker(Protocol):
    """One node's lock endpoint (pkg/dsync/rpc-client-interface.go:39).
    LocalLocker satisfies this in-process; LockRPCClient over the wire."""

    def lock(self, uid: str, resources: list[str], owner: str,
             source: str) -> bool: ...
    def rlock(self, uid: str, resources: list[str], owner: str,
              source: str) -> bool: ...
    def unlock(self, uid: str, resources: list[str]) -> bool: ...
    def runlock(self, uid: str, resources: list[str]) -> bool: ...
    def refresh(self, uid: str, resources: list[str]) -> bool: ...


def quorum_for(n: int, write: bool) -> int:
    tolerance = n // 2
    q = n - tolerance
    if write and q == tolerance:
        q += 1   # even N: write quorum must exceed half
    return q


class DRWMutex:
    """Distributed RW mutex over a fixed locker list (one resource)."""

    def __init__(self, lockers: list[Optional[NetLocker]],
                 resources: list[str], owner: str = "dsync"):
        self.lockers = lockers
        self.resources = sorted(resources)
        self.owner = owner
        self._uid = ""
        self._write = False
        self._refresh_stop: Optional[threading.Event] = None
        self.lock_lost = False   # set by the refresh loop on quorum loss

    # -- public API (DRWMutex.GetLock / GetRLock / Unlock / RUnlock) -------

    def get_lock(self, timeout: float = 30.0, source: str = "") -> bool:
        return self._lock_blocking(True, timeout, source)

    def get_rlock(self, timeout: float = 30.0, source: str = "") -> bool:
        return self._lock_blocking(False, timeout, source)

    def unlock(self) -> None:
        if self._refresh_stop is not None:
            self._refresh_stop.set()
            self._refresh_stop = None
        self._release_all(self._uid, self._write)
        self._uid = ""

    runlock = unlock

    def check(self) -> bool:
        """One SYNCHRONOUS refresh round: True while a quorum of
        lockers still holds this grant. On quorum loss, `lock_lost`
        latches — the fencing gate a holder returning from a partition
        must consult before touching the protected resource, because
        its lease may have expired and been re-granted while it was
        away. The background refresh loop does the same every
        REFRESH_INTERVAL; this is the on-demand edition for
        commit-time fencing and tests."""
        if not self._uid or self.lock_lost:
            return False
        alive = 0
        for lk in self.lockers:
            if lk is None:
                continue
            try:
                if lk.refresh(self._uid, self.resources):
                    alive += 1
            except Exception:  # noqa: BLE001 — dead locker: no vote
                pass
        if alive < quorum_for(len(self.lockers), self._write):
            self.lock_lost = True
        return not self.lock_lost

    # -- internals ---------------------------------------------------------

    def _lock_blocking(self, write: bool, timeout: float,
                       source: str) -> bool:
        deadline = time.monotonic() + timeout
        while True:
            # fresh uid per attempt: a straggler grant from a failed
            # attempt must never alias a later attempt's grant on the
            # same locker (its rollback would release both)
            uid = str(uuid.uuid4())
            if self._try_once(uid, write, source):
                self._uid, self._write = uid, write
                self._start_refresh(uid)
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(random.random() * RETRY_INTERVAL_MAX)

    def _start_refresh(self, uid: str) -> None:
        """Keep the held lock alive on every locker: a grant not refreshed
        within LOCK_VALIDITY is swept by the lockers' maintenance loop.
        When a quorum of lockers no longer knows the grant (force-unlock,
        partition-long sweep), stop refreshing and flag the lock as lost
        so the holder can abort its critical section (the reference's
        startContinousLockRefresh cancels the op context on quorum
        loss)."""
        stop = threading.Event()
        self._refresh_stop = stop
        self.lock_lost = False

        def run() -> None:
            n = len(self.lockers)
            while not stop.wait(REFRESH_INTERVAL):
                alive = 0
                for lk in self.lockers:
                    if lk is None:
                        continue
                    try:
                        if lk.refresh(uid, self.resources):
                            alive += 1
                    except Exception:  # noqa: BLE001 — dead locker: no vote
                        pass
                if alive < quorum_for(n, self._write):
                    self.lock_lost = True
                    return

        threading.Thread(target=run, daemon=True).start()

    def _try_once(self, uid: str, write: bool, source: str) -> bool:
        n = len(self.lockers)
        need = quorum_for(n, write)
        granted: list[Optional[bool]] = [None] * n
        aborted = threading.Event()
        pending = threading.Semaphore(0)

        def ask(i: int, lk: NetLocker) -> None:
            try:
                if write:
                    ok = lk.lock(uid, self.resources, self.owner, source)
                else:
                    ok = lk.rlock(uid, self.resources, self.owner, source)
            except Exception:  # noqa: BLE001 — a dead locker is a no-vote
                ok = False
            granted[i] = ok
            pending.release()
            # Straggler grant after the attempt already failed: the main
            # thread's rollback may have run before this grant landed, so
            # undo it here — otherwise it orphans the resource for up to
            # LOCK_VALIDITY.
            if ok and aborted.is_set():
                try:
                    if write:
                        lk.unlock(uid, self.resources)
                    else:
                        lk.runlock(uid, self.resources)
                except Exception:  # noqa: BLE001 — expiry sweep will reap it
                    pass

        live = 0
        for i, lk in enumerate(self.lockers):
            if lk is None:
                granted[i] = False
                pending.release()
                continue
            live += 1
            threading.Thread(target=ask, args=(i, lk), daemon=True).start()

        # collect answers up to the acquire window; stop early once the
        # outcome is decided either way
        window = ACQUIRE_TIMEOUT_DYN.timeout()
        t0 = time.monotonic()
        deadline = t0 + window
        answers = 0
        timed_out = False
        while answers < n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                timed_out = True
                break
            if not pending.acquire(timeout=remaining):
                timed_out = True
                break
            answers += 1
            yes = sum(1 for g in granted if g)
            no = sum(1 for g in granted if g is False)
            if yes >= need or no > n - need:
                break
        if timed_out and answers < n:
            ACQUIRE_TIMEOUT_DYN.log_failure()
        else:
            ACQUIRE_TIMEOUT_DYN.log_success(time.monotonic() - t0)

        if sum(1 for g in granted if g) >= need:
            return True
        # sub-quorum: roll back whatever was granted; in-flight grant
        # threads see `aborted` and undo their own late grants
        aborted.set()
        self._release_all(uid, write)
        return False

    def _release_all(self, uid: str, write: bool) -> None:
        if not uid:
            return
        for lk in self.lockers:
            if lk is None:
                continue
            try:
                if write:
                    lk.unlock(uid, self.resources)
                else:
                    lk.runlock(uid, self.resources)
            except Exception:  # noqa: BLE001 — expiry sweep will reap it
                pass


class DistNSLockMap:
    """Distributed drop-in for object.nslock.NSLockMap: new_lock returns
    an RWLocker backed by DRWMutex over the cluster's lockers
    (cmd/namespace-lock.go distLockInstance)."""

    def __init__(self, lockers: list[Optional[NetLocker]],
                 owner: str = ""):
        self.lockers = lockers
        self.owner = owner or str(uuid.uuid4())

    def new_lock(self, *paths: str) -> "DistNSLock":
        return DistNSLock(DRWMutex(self.lockers,
                                   [p for p in paths if p], self.owner))


class DistNSLock:
    def __init__(self, dm: DRWMutex):
        self._dm = dm

    def get_lock(self, timeout: float = 30.0) -> bool:
        return self._dm.get_lock(timeout)

    def get_rlock(self, timeout: float = 30.0) -> bool:
        return self._dm.get_rlock(timeout)

    def unlock(self) -> None:
        self._dm.unlock()

    runlock = unlock

    def write_locked(self, timeout: float = 30.0):
        return _DistLockCtx(self, True, timeout)

    def read_locked(self, timeout: float = 30.0):
        return _DistLockCtx(self, False, timeout)


class _DistLockCtx:
    def __init__(self, lock: DistNSLock, write: bool, timeout: float):
        self._lock, self._write, self._timeout = lock, write, timeout

    def __enter__(self):
        ok = (self._lock.get_lock(self._timeout) if self._write
              else self._lock.get_rlock(self._timeout))
        if not ok:
            from ..object import api_errors
            raise api_errors.ObjectApiError(
                "distributed lock acquisition timed out")
        return self._lock

    def __exit__(self, *exc):
        self._lock.unlock()
        return False
