"""NaughtyNet — seeded deterministic network fault injection.

NaughtyDisk's schedule/seed/replay discipline applied to the wire: the
internode transport consults a process-global controller on every
outbound dial (`RestClient._call_once`), every inbound verb
(`RPCHandler.route`) and every streamed response chunk. All of it is a
pure function of (seed, verb, call #), so a failing chaos test prints
one integer that replays the exact fault sequence.

Fault classes:

  * partitions — directional rules between named node ids
    ("host:port"), installed by `partition(a, b)`: full (both
    directions), asymmetric (`oneway=True` — A still reaches B while
    B's calls to A fail), timed windows (`after_s`/`duration_s`).
    A blocked outbound dial raises like an unreachable host
    (`conn_failure=True`); a blocked inbound verb is refused with the
    `PARTITIONED_KIND` error payload which the calling transport maps
    back to the same unreachable-host failure — so one side's admin
    verb is enough to cut a link for real subprocess clusters.
  * per-verb delay/jitter schedules (`NetSchedule.delay_rate`) —
    injected latency before the dial / before serving.
  * mid-stream resets and stalls (`NetSchedule.reset_rate`, and any
    partition that opens while a response is streaming) — exercises
    the streamed-read deadline instead of parking readers forever.

Identity: subprocess nodes set the process-local id once at boot
(`membership.set_local_node`); in-process multi-node tests tag
individual clients/handlers (`RestClient.node_id`,
`RPCHandler.node_id`) so one global controller can still tell the
nodes apart. Rules match "*" as a wildcard on either end.

Everything is OFF until `arm()` — the `enabled` flag is the only cost
on the hot path when chaos is not running.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from ..utils import eventlog, knobs, telemetry
from . import membership

# error kind a server-side drop returns; the transport maps it to
# NetworkError(conn_failure=True) so both injection sides look like an
# unreachable host to the caller
PARTITIONED_KIND = "naughtynet-partitioned"

_NET_DROPS = telemetry.REGISTRY.counter(
    "minio_tpu_net_partition_drops_total",
    "RPC exchanges dropped by an armed naughtynet partition rule",
    )
_NET_DELAYS = telemetry.REGISTRY.counter(
    "minio_tpu_net_chaos_delays_total",
    "RPC exchanges delayed by the naughtynet schedule")
_NET_RESETS = telemetry.REGISTRY.counter(
    "minio_tpu_net_chaos_resets_total",
    "streamed RPC responses reset/stalled mid-stream by naughtynet")


@dataclass(frozen=True)
class NetSchedule:
    """Deterministic per-verb fault schedule. Every decision is a pure
    function of (seed, verb, per-verb call #): replaying the same seed
    against the same call sequence reproduces the same faults."""

    seed: int = 0
    delay_rate: float = 0.0      # fraction of calls delayed
    delay_s: float = 0.0         # fixed component of injected delay
    jitter_s: float = 0.0        # seeded-uniform extra in [0, jitter_s)
    reset_rate: float = 0.0      # fraction of streamed responses reset
    fault_verbs: Tuple[str, ...] = ()   # empty = every verb

    def _roll(self, verb: str, n: int, salt: str) -> float:
        h = zlib.crc32(f"{self.seed}:{verb}:{n}:{salt}".encode())
        return (h & 0xFFFFFFFF) / 2 ** 32

    def _applies(self, verb: str) -> bool:
        return not self.fault_verbs or verb in self.fault_verbs

    def delay_for(self, verb: str, n: int) -> float:
        if not self._applies(verb) or self.delay_rate <= 0:
            return 0.0
        if self._roll(verb, n, "delay") >= self.delay_rate:
            return 0.0
        return self.delay_s + self.jitter_s * self._roll(verb, n, "jit")

    def resets(self, verb: str, n: int) -> bool:
        return (self._applies(verb) and self.reset_rate > 0
                and self._roll(verb, n, "reset") < self.reset_rate)

    def to_dict(self) -> dict:
        return {"seed": self.seed, "delay_rate": self.delay_rate,
                "delay_s": self.delay_s, "jitter_s": self.jitter_s,
                "reset_rate": self.reset_rate,
                "fault_verbs": list(self.fault_verbs)}


@dataclass
class _Rule:
    src: str                     # node id or "*"
    dst: str                     # node id or "*"
    opens: float = 0.0           # monotonic time the window opens
    closes: float = 0.0          # 0 = never (until heal())

    def active(self, now: float) -> bool:
        if now < self.opens:
            return False
        return self.closes <= 0 or now < self.closes

    def expired(self, now: float) -> bool:
        return 0 < self.closes <= now

    def matches(self, src: str, dst: str) -> bool:
        return ((self.src == "*" or self.src == src)
                and (self.dst == "*" or self.dst == dst))


@dataclass(frozen=True)
class _Action:
    blocked: bool = False
    delay: float = 0.0


_PASS = _Action()


class NaughtyNet:
    """Process-global fault controller the transport consults."""

    def __init__(self):
        self._mu = threading.Lock()
        self.enabled = False
        self._rules: List[_Rule] = []
        self._sched: Optional[NetSchedule] = None
        self._counts: dict = {}          # verb -> per-verb call #
        self.stats = {"blocked": 0, "delayed": 0, "resets": 0,
                      "stream_stalls": 0}

    # -- control surface ---------------------------------------------------

    def arm(self, schedule: Optional[NetSchedule] = None) -> None:
        with self._mu:
            if schedule is not None:
                self._sched = schedule
            self.enabled = True

    def disarm(self) -> None:
        """Stop injecting; rules stay installed for a later re-arm."""
        self.enabled = False

    def reset(self) -> None:
        """Back to factory: no rules, no schedule, disabled (tests)."""
        with self._mu:
            self.enabled = False
            self._rules.clear()
            self._sched = None
            self._counts.clear()
            for k in self.stats:
                self.stats[k] = 0

    def partition(self, a: str, b: str, oneway: bool = False,
                  after_s: float = 0.0,
                  duration_s: float = 0.0) -> None:
        """Cut a→b (and b→a unless `oneway`). `after_s` delays the
        window opening, `duration_s` auto-heals it — both relative to
        now. Arms the controller."""
        now = time.monotonic()
        opens = now + after_s
        closes = opens + duration_s if duration_s > 0 else 0.0
        with self._mu:
            self._rules.append(_Rule(a, b, opens, closes))
            if not oneway:
                self._rules.append(_Rule(b, a, opens, closes))
            self.enabled = True
        eventlog.emit("net.partition",
                      rule="oneway" if oneway else "both",
                      peers=f"{a}|{b}")

    def heal(self, a: Optional[str] = None,
             b: Optional[str] = None) -> None:
        """Remove partition rules touching (a, b) in either direction;
        with no arguments, remove every rule."""
        with self._mu:
            if a is None and b is None:
                self._rules.clear()
            else:
                ends = {x for x in (a, b) if x is not None}
                self._rules = [r for r in self._rules
                               if not ends & {r.src, r.dst}]
        eventlog.emit("net.heal",
                      peers=f"{a or '*'}|{b or '*'}")

    # -- decision points (transport hot path; enabled-flag gated there) ----

    def blocked(self, src: str, dst: str) -> bool:
        now = time.monotonic()
        with self._mu:
            if any(r.expired(now) for r in self._rules):
                self._rules = [r for r in self._rules
                               if not r.expired(now)]
            return any(r.active(now) and r.matches(src, dst)
                       for r in self._rules)

    def _next(self, verb: str) -> int:
        with self._mu:
            n = self._counts.get(verb, 0)
            self._counts[verb] = n + 1
            return n

    def _decide(self, src: str, dst: str, verb: str) -> _Action:
        if self.blocked(src, dst):
            with self._mu:
                self.stats["blocked"] += 1
            _NET_DROPS.inc()
            return _Action(blocked=True)
        sched = self._sched
        if sched is None:
            return _PASS
        delay = sched.delay_for(verb, self._next(verb))
        if delay > 0:
            with self._mu:
                self.stats["delayed"] += 1
            _NET_DELAYS.inc()
        return _Action(delay=delay)

    def on_call(self, src: str, dst: str, verb: str) -> _Action:
        """Client side, before the dial."""
        return self._decide(src or membership.local_node(), dst, verb)

    def on_serve(self, src: str, dst: str, verb: str) -> _Action:
        """Server side, before dispatching the verb. `src` comes from
        the caller's identity header ("" when it sent none)."""
        return self._decide(src, dst or membership.local_node(), verb)

    def wrap_stream(self, src: str, dst: str, verb: str,
                    it: Iterator[bytes]) -> Iterator[bytes]:
        """Server side, around a streamed response body: a schedule
        reset kills the connection after the first chunk; a partition
        that opens mid-stream goes SILENT (the classic partition-after-
        headers) until the client's streamed-read deadline fires, then
        kills the connection so the serving thread is not parked
        forever."""
        reset_after = (self._sched is not None
                       and self._sched.resets(verb, self._next(verb)))

        def gen():
            try:
                for chunk in it:
                    # a partition opening mid-stream stalls the writer
                    stalled = 0.0
                    while (self.enabled
                           and self.blocked(src, dst)
                           and stalled < 60.0):
                        if stalled == 0.0:
                            with self._mu:
                                self.stats["stream_stalls"] += 1
                            _NET_RESETS.inc()
                        time.sleep(0.25)
                        stalled += 0.25
                    if stalled >= 60.0:
                        raise ConnectionResetError(
                            "naughtynet: stream partitioned")
                    yield chunk
                    if reset_after and self.enabled:
                        with self._mu:
                            self.stats["resets"] += 1
                        _NET_RESETS.inc()
                        raise ConnectionResetError(
                            "naughtynet: mid-stream reset")
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass
        return gen()

    # -- admin surface -----------------------------------------------------

    def status(self) -> dict:
        now = time.monotonic()
        with self._mu:
            return {
                "enabled": self.enabled,
                "local_node": membership.local_node(),
                "rules": [{"src": r.src, "dst": r.dst,
                           "active": r.active(now),
                           "closes_in_s": (round(r.closes - now, 3)
                                           if r.closes > 0 else None)}
                          for r in self._rules],
                "schedule": (self._sched.to_dict()
                             if self._sched else None),
                "stats": dict(self.stats),
            }


NET = NaughtyNet()


def handle_admin(payload: dict) -> dict:
    """Ops for the test-only admin verb (gated on MINIO_TPU_NAUGHTYNET
    by the admin plane): partition / heal / configure / arm / disarm /
    status / reset. Returns the post-op status."""
    op = payload.get("op", "status")
    if op == "partition":
        NET.partition(payload.get("src", "*"), payload.get("dst", "*"),
                      oneway=bool(payload.get("oneway")),
                      after_s=float(payload.get("after_s", 0.0)),
                      duration_s=float(payload.get("duration_s", 0.0)))
    elif op == "heal":
        NET.heal(payload.get("src"), payload.get("dst"))
    elif op == "configure":
        NET.arm(NetSchedule(
            seed=int(payload.get(
                "seed", knobs.get_int("MINIO_TPU_NAUGHTYNET_SEED"))),
            delay_rate=float(payload.get("delay_rate", 0.0)),
            delay_s=float(payload.get("delay_s", 0.0)),
            jitter_s=float(payload.get("jitter_s", 0.0)),
            reset_rate=float(payload.get("reset_rate", 0.0)),
            fault_verbs=tuple(payload.get("fault_verbs", ()))))
    elif op == "arm":
        NET.arm()
    elif op == "disarm":
        NET.disarm()
    elif op == "reset":
        NET.reset()
    elif op != "status":
        raise ValueError(f"naughtynet: unknown op {op!r}")
    return NET.status()
