"""Peer membership with boot-generation fencing.

Every process mints a *boot generation* — a number that changes on
every restart. Internode RPC carries the local (node id, generation)
both ways (request headers on the client side, response headers on the
server side), so each end positively detects when a peer it has talked
to before comes back as a NEW incarnation: restarted, or partitioned
away long enough to have been replaced.

Why it matters: per-peer state accumulated against the OLD incarnation
— healthtrack latency windows, transport offline markers, replication
target client caches — is evidence about a process that no longer
exists. Left in place it poisons the new incarnation (a restarted peer
inherits its predecessor's "slow" conviction, a returning lock holder
acts on leases its previous self owned). On a generation change the
tracker fires registered listeners that reset exactly that state; it
never carries stale judgments across an incarnation boundary.

The reference encodes the same idea as the deployment ID + node uptime
checks in cmd/bootstrap-peer-server.go; here the generation is explicit
and fencing is an event, not a side effect of a failed handshake.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import eventlog, telemetry

# request/response header names carrying (node id, generation)
NODE_HEADER = "x-ntpu-node"
GEN_HEADER = "x-ntpu-gen"

_GEN_CHANGES = telemetry.REGISTRY.counter(
    "minio_tpu_peer_generation_changes_total",
    "Peer incarnation changes detected (restart or partition-and-"
    "replace) — each one resets that peer's stale local state")
_GEN_PEERS = telemetry.REGISTRY.gauge(
    "minio_tpu_peer_generation_peers",
    "Peers whose boot generation this node currently tracks")


def _mint_generation() -> int:
    """Unique-per-boot integer: wall-clock millis with random low bits
    so two restarts inside the same millisecond still differ.
    Ordering between generations is not relied on — only inequality."""
    return (int(time.time() * 1000) << 12) | (
        int.from_bytes(os.urandom(2), "big") & 0xFFF)


class _PeerGen:
    __slots__ = ("generation", "node_id", "changes", "since")

    def __init__(self, generation: int, node_id: str):
        self.generation = generation
        self.node_id = node_id
        self.changes = 0
        self.since = time.time()


class MembershipTracker:
    """Process-global (peer addr -> boot generation) table.

    `observe` is fed by the transport on every exchange that carried
    identity headers; a changed generation fires every registered
    listener with (peer, old_gen, new_gen) OUTSIDE the lock."""

    def __init__(self):
        self._mu = threading.Lock()
        self.local_generation = _mint_generation()
        self._local_node = ""
        self._peers: Dict[str, _PeerGen] = {}
        self._listeners: List[Callable[[str, int, int], None]] = []

    # -- local identity ----------------------------------------------------

    def set_local_node(self, addr: str) -> None:
        with self._mu:
            self._local_node = addr

    def local_node(self) -> str:
        with self._mu:
            return self._local_node

    # -- peer observations -------------------------------------------------

    def observe(self, peer: str, generation: int,
                node_id: str = "") -> bool:
        """Record the peer's advertised generation; True (and listener
        fan-out) when this is a NEW incarnation of a known peer. The
        first observation of a peer is not a change — there is no stale
        state to reset."""
        if not peer or not generation:
            return False
        with self._mu:
            cur = self._peers.get(peer)
            if cur is None:
                self._peers[peer] = _PeerGen(generation, node_id)
                _GEN_PEERS.set(len(self._peers))
                return False
            if cur.generation == generation:
                return False
            old = cur.generation
            cur.generation = generation
            cur.node_id = node_id or cur.node_id
            cur.changes += 1
            cur.since = time.time()
            listeners = list(self._listeners)
        _GEN_CHANGES.inc()
        eventlog.emit("membership.generation", peer=peer,
                      generation=generation)
        for fn in listeners:
            try:
                fn(peer, old, generation)
            except Exception:  # noqa: BLE001 — one listener must not
                pass           # block the fencing fan-out to the rest
        return True

    def generation_of(self, peer: str) -> Optional[int]:
        with self._mu:
            g = self._peers.get(peer)
            return g.generation if g is not None else None

    def add_listener(self, fn: Callable[[str, int, int], None]) -> None:
        """fn(peer_addr, old_generation, new_generation) — called on
        every detected incarnation change; must be fast and must not
        raise (exceptions are swallowed)."""
        with self._mu:
            self._listeners.append(fn)

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Membership table for OBD/admin."""
        with self._mu:
            return {
                "local_node": self._local_node,
                "local_generation": self.local_generation,
                "peers": {
                    addr: {"generation": g.generation,
                           "node_id": g.node_id,
                           "changes": g.changes,
                           "since": g.since}
                    for addr, g in self._peers.items()},
            }

    def reset(self, drop_listeners: bool = False) -> None:
        """Drop peers and re-mint the local generation (tests simulate
        a restart with this). Listeners registered at import time (the
        transport's fencing hook) survive unless explicitly dropped."""
        with self._mu:
            self._peers.clear()
            if drop_listeners:
                self._listeners.clear()
            self.local_generation = _mint_generation()
            _GEN_PEERS.set(0)


TRACKER = MembershipTracker()


def set_local_node(addr: str) -> None:
    TRACKER.set_local_node(addr)


def local_node() -> str:
    return TRACKER.local_node()


def local_generation() -> int:
    return TRACKER.local_generation
