"""Internode RPC transport — thin authenticated HTTP-POST verbs.

The reference's cmd/rest/client.go: each RPC is
`POST /<service>/v1/<verb>?arg=...` with an opaque body stream and a
JWT bearer derived from the cluster credentials. The client keeps a
persistent connection pool, marks the host offline on network error and
probes it back online in the background (cmd/rest/client.go:179-).

Robustness semantics (the failure-plane PR):
  * every call runs under a wall-clock deadline; idempotent verbs get
    bounded, jittered, exponentially backed-off retries inside that
    deadline before the host is declared offline;
  * only TRUE transport failures (refused/reset/timeout/unreachable)
    flip `online` — a remote that answered with an error payload
    (RPCError) or sent a malformed response is alive;
  * the offline health probe backs off exponentially (capped at
    `MINIO_TPU_PEER_PROBE_S`) instead of hammering a dead peer once a
    second forever — and any SUCCESSFUL direct call to the host (from
    any client in this process) re-admits it immediately, so a peer
    provably back never stays dark for the rest of a backoff window;
  * every successful verb feeds the per-peer latency tracker
    (`minio_tpu_peer_latency_seconds{peer,verb}`) so gray-slow peers
    are visible on OBD/admin next to the drive health states.
"""

from __future__ import annotations

import base64
import errno as _errno
import hashlib
import hmac
import http.client
import json
import random
import socket
import threading
import time
import urllib.parse
from typing import Callable, Optional

from ..utils import backoff_delay, healthtrack, knobs, telemetry
from . import membership, naughtynet

DEFAULT_TIMEOUT = 30.0

# process-wide transport fault counters (per-endpoint counts stay on
# each RestClient for the OBD bundle; these aggregates feed Prometheus)
_RPC_CALLS = telemetry.REGISTRY.counter(
    "minio_tpu_rpc_calls_total", "Internode RPC verbs attempted")
_RPC_NET_ERRORS = telemetry.REGISTRY.counter(
    "minio_tpu_rpc_net_errors_total",
    "Internode RPC transport failures (per attempt)")
_RPC_RETRIES = telemetry.REGISTRY.counter(
    "minio_tpu_rpc_retries_total", "Internode RPC retry attempts")
_RPC_OFFLINE_TRIPS = telemetry.REGISTRY.counter(
    "minio_tpu_rpc_offline_trips_total",
    "Peer online->offline transitions")
HEALTH_PROBE_INTERVAL = 1.0
HEALTH_PROBE_MAX = knobs.get_float("MINIO_TPU_PEER_PROBE_S")
# retries for idempotent verbs (attempts = retries + 1), inside the
# per-call deadline
RPC_RETRIES = knobs.get_int("MINIO_TPU_RPC_RETRIES")
RPC_RETRY_BACKOFF = knobs.get_float("MINIO_TPU_RPC_RETRY_BACKOFF")
RPC_RETRY_BACKOFF_MAX = knobs.get_float("MINIO_TPU_RPC_RETRY_BACKOFF_MAX")
# tolerated clock skew between nodes on token expiry (internode auth
# must not flap because two hosts' clocks drift a few seconds apart)
TOKEN_CLOCK_SKEW = 30.0


class RPCError(Exception):
    """Error returned by the remote handler (payload survived)."""

    def __init__(self, kind: str, message: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message


class NetworkError(Exception):
    """Transport-level failure — the peer may be down.

    `conn_failure` distinguishes connection-level failures (refused,
    reset, timeout, unreachable — the peer process is likely gone) from
    protocol-level ones (malformed response, mid-stream disconnect —
    the peer answered, so `online` must not flip)."""

    def __init__(self, message: str = "", conn_failure: bool = False):
        super().__init__(message)
        self.conn_failure = conn_failure


_CONN_ERRNOS = {_errno.ECONNREFUSED, _errno.ECONNRESET,
                _errno.ECONNABORTED, _errno.EPIPE, _errno.ETIMEDOUT,
                _errno.EHOSTUNREACH, _errno.ENETUNREACH,
                _errno.EHOSTDOWN if hasattr(_errno, "EHOSTDOWN") else
                _errno.EHOSTUNREACH}


def _is_conn_failure(e: Exception) -> bool:
    """True for failures that mean 'the peer is unreachable' rather than
    'the peer misbehaved' — only these flip a host offline."""
    if isinstance(e, (ConnectionError, socket.timeout, socket.gaierror,
                      TimeoutError)):
        return True
    if isinstance(e, OSError) and e.errno in _CONN_ERRNOS:
        return True
    return isinstance(e, (http.client.NotConnected,
                          http.client.ImproperConnectionState))


# ---------------------------------------------------------------------------
# internode auth: HMAC token over (access_key, expiry) with the secret key
# (the reference uses JWT with the same claims, cmd/jwt.go)
# ---------------------------------------------------------------------------

def make_token(access_key: str, secret_key: str,
               ttl: float = 15 * 60) -> str:
    expiry = int(time.time() + ttl)
    msg = f"{access_key}:{expiry}"
    mac = hmac.new(secret_key.encode(), msg.encode(),
                   hashlib.sha256).hexdigest()
    return base64.urlsafe_b64encode(
        f"{msg}:{mac}".encode()).decode()


def verify_token(token: str, access_key: str, secret_key: str) -> bool:
    try:
        decoded = base64.urlsafe_b64decode(token.encode()).decode()
        ak, expiry, mac = decoded.rsplit(":", 2)
        # tolerate small clock skew: a token minted by a slightly-slow
        # peer clock must not flap internode auth at the expiry edge
        expired = int(expiry) + TOKEN_CLOCK_SKEW < time.time()
    except (ValueError, UnicodeDecodeError):
        return False
    if ak != access_key or expired:
        return False
    want = hmac.new(secret_key.encode(), f"{ak}:{expiry}".encode(),
                    hashlib.sha256).hexdigest()
    return hmac.compare_digest(want, mac)


# every live RestClient per (host, port): a successful call through
# ANY of them proves the host back, so siblings still sitting out a
# probe backoff re-admit immediately (the MRFHealer.kick-on-
# re-admission pattern applied to peers). WeakSets: clients must not
# outlive their owners just because the registry saw them once.
_CLIENTS_MU = threading.Lock()
_CLIENTS: dict = {}


def _register_client(c: "RestClient") -> None:
    import weakref
    with _CLIENTS_MU:
        _CLIENTS.setdefault((c.host, c.port),
                            weakref.WeakSet()).add(c)


def _note_host_alive(host: str, port: int,
                     exclude: Optional["RestClient"] = None) -> None:
    """A verb against (host, port) just SUCCEEDED: flip every sibling
    client of that host back online — a host proven alive must not
    stay dark for the rest of a 30 s probe backoff."""
    with _CLIENTS_MU:
        peers = list(_CLIENTS.get((host, port), ()))
    for c in peers:
        if c is not exclude and not c._online:
            c._online = True        # the probe loop exits on this flag


def _on_peer_generation_change(peer: str, old_gen: int,
                               new_gen: int) -> None:
    """Generation fencing, transport side: latency evidence and
    offline markers gathered against the peer's PREVIOUS incarnation
    must not poison the new one — a restarted peer neither inherits
    its predecessor's slow-conviction windows nor stays dark for the
    rest of a probe backoff."""
    healthtrack.TRACKER.clear_samples("peer", peer)
    host, _, port = peer.rpartition(":")
    if host and port.isdigit():
        _note_host_alive(host, int(port))


membership.TRACKER.add_listener(_on_peer_generation_change)


class RestClient:
    """One peer endpoint. call() POSTs a verb; on connection failure the
    host is marked offline and a background probe re-enables it."""

    def __init__(self, host: str, port: int, service_path: str,
                 access_key: str, secret_key: str,
                 timeout: float = DEFAULT_TIMEOUT):
        self.host, self.port = host, port
        self.service_path = service_path.rstrip("/")
        self.access_key, self.secret_key = access_key, secret_key
        self.timeout = timeout
        # owning node's id for membership headers and naughtynet rule
        # matching; "" falls back to the process-local identity (one
        # node per process — the subprocess/deployment case)
        self.node_id = ""
        self._online = True
        self._mu = threading.Lock()
        self._prober: Optional[threading.Thread] = None
        self._probe_delay = HEALTH_PROBE_INTERVAL
        _register_client(self)
        # fault counters (surfaced per drive in the OBD bundle):
        # calls = verbs attempted, net_errors = transport failures
        # observed (per attempt), retries = extra attempts made,
        # offline_trips = online→offline transitions
        self.calls = 0
        self.net_errors = 0
        self.retries = 0
        self.offline_trips = 0

    @property
    def online(self) -> bool:
        return self._online

    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.service_path}"

    def call(self, verb: str, args: Optional[dict] = None,
             body: bytes = b"", stream_response: bool = False,
             body_length: Optional[int] = None,
             idempotent: bool = False,
             deadline: Optional[float] = None):
        """POST the verb. Returns response bytes (or a streamed reader
        when stream_response for large reads).

        `body` may be bytes, OR an iterable/file-like streamed to the
        wire in chunks with `body_length` as Content-Length — large
        shard bodies (CreateFile, heal writes) never materialize on
        the sending side (reference storage-rest streaming verbs).

        `idempotent` verbs with a replayable (bytes) body retry bounded
        times with jittered exponential backoff on transport failures;
        `deadline` (default `timeout`) bounds when new attempts/backoffs
        may START and caps each attempt's per-socket-op timeout — a peer
        that keeps trickling bytes can still hold one attempt past it
        (socket timeouts reset per recv). The host is marked offline
        only when a connection-level failure survives the retries."""
        if not self._online:
            raise NetworkError(f"{self.host}:{self.port} is offline",
                               conn_failure=True)
        with self._mu:
            self.calls += 1
        _RPC_CALLS.inc()
        end = time.monotonic() + (deadline if deadline is not None
                                  else self.timeout)
        attempts = 1
        if idempotent and isinstance(body, (bytes, bytearray, memoryview)):
            attempts += RPC_RETRIES
        last: Optional[NetworkError] = None
        with telemetry.span(f"rpc.{verb}",
                            host=f"{self.host}:{self.port}"):
            for attempt in range(attempts):
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                if attempt:
                    with self._mu:
                        self.retries += 1
                    _RPC_RETRIES.inc()
                try:
                    t0 = time.perf_counter()
                    out = self._call_once(verb, args, body,
                                          stream_response, body_length,
                                          timeout=min(self.timeout,
                                                      remaining))
                    # feed the gray-failure plane: per-peer latency
                    # (streamed verbs time the OPEN; the drive-level
                    # read tracker times the body) — and a successful
                    # non-probe verb proves the host alive for every
                    # sibling client still sitting out its backoff
                    healthtrack.observe_peer(
                        f"{self.host}:{self.port}", verb,
                        time.perf_counter() - t0)
                    _note_host_alive(self.host, self.port, exclude=self)
                    return out
                except NetworkError as e:
                    with self._mu:
                        self.net_errors += 1
                    _RPC_NET_ERRORS.inc()
                    last = e
                    if attempt + 1 >= attempts:
                        break
                    backoff = backoff_delay(RPC_RETRY_BACKOFF,
                                            RPC_RETRY_BACKOFF_MAX,
                                            attempt)
                    if time.monotonic() + backoff >= end:
                        break
                    time.sleep(backoff)
            if last is None:
                last = NetworkError(
                    f"{self.host}:{self.port} {verb}: deadline exceeded",
                    conn_failure=True)
            if last.conn_failure:
                self.mark_offline()
            raise last

    def _call_once(self, verb: str, args: Optional[dict], body,
                   stream_response: bool, body_length: Optional[int],
                   timeout: float):
        if naughtynet.NET.enabled:
            # deterministic chaos: a partitioned link fails like an
            # unreachable host (conn failure → retries → offline), an
            # armed delay schedule sleeps before the dial
            act = naughtynet.NET.on_call(
                self.node_id, f"{self.host}:{self.port}", verb)
            if act.delay > 0:
                time.sleep(act.delay)
            if act.blocked:
                raise NetworkError(
                    f"naughtynet: link to {self.host}:{self.port} "
                    "partitioned", conn_failure=True)
        qs = urllib.parse.urlencode(args or {})
        path = f"{self.service_path}/{verb}" + (f"?{qs}" if qs else "")
        if isinstance(body, (bytes, bytearray, memoryview)):
            length = len(body)
        else:
            assert body_length is not None, \
                "streaming bodies need body_length"
            length = body_length
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout)
        headers = {
            "Authorization":
                "Bearer " + make_token(self.access_key,
                                       self.secret_key),
            "Content-Length": str(length),
        }
        # membership: advertise who is calling and which incarnation,
        # so the serving side positively detects our restarts
        src_id = self.node_id or membership.local_node()
        if src_id:
            headers[membership.NODE_HEADER] = src_id
        headers[membership.GEN_HEADER] = str(
            membership.local_generation())
        cur = telemetry.current_span()
        if cur is not None:
            # propagate the trace identity so the serving side joins
            # this request's span tree (fragment, grafted by span id)
            headers[telemetry.TRACE_HEADER] = cur.trace_id
            headers[telemetry.SPAN_HEADER] = cur.span_id
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            self._observe_peer_generation(resp)
            if resp.status != 200:
                payload = resp.read()
                conn.close()
                try:
                    err = json.loads(payload.decode())
                except ValueError:
                    err = None
                if isinstance(err, dict):
                    if err.get("kind") == naughtynet.PARTITIONED_KIND:
                        # server-side injected drop: surface it exactly
                        # like an unreachable host
                        raise NetworkError(
                            f"naughtynet: {self.host}:{self.port} "
                            "dropped the call (partitioned)",
                            conn_failure=True)
                    raise RPCError(err.get("kind", "error"),
                                   err.get("message", ""))
                raise RPCError("http", f"status {resp.status}")
            if stream_response:
                return _StreamedResponse(conn, resp)
            data = resp.read()
            conn.close()
            return data
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            # the peer answering garbage (BadStatusLine, short body) is
            # NOT a dead peer: only connection-level failures may flip
            # the host offline (decided by call() after retries)
            raise NetworkError(str(e),
                               conn_failure=_is_conn_failure(e)) from e

    def _observe_peer_generation(self, resp) -> None:
        """Feed the membership tracker from a response's identity
        headers — a changed boot generation fires the stale-state
        fencing listeners (healthtrack windows, offline markers)."""
        gen = resp.getheader(membership.GEN_HEADER)
        if not gen:
            return
        try:
            membership.TRACKER.observe(
                f"{self.host}:{self.port}", int(gen),
                resp.getheader(membership.NODE_HEADER) or "")
        except ValueError:
            pass

    def call_json(self, verb: str, args: Optional[dict] = None,
                  payload=None):
        body = json.dumps(payload).encode() if payload is not None else b""
        out = self.call(verb, args, body)
        return json.loads(out.decode()) if out else None

    def mark_offline(self) -> None:
        """Start the background health probe (reference MarkOffline,
        cmd/rest/client.go:179)."""
        with self._mu:
            if not self._online:
                return
            self._online = False
            self.offline_trips += 1
            _RPC_OFFLINE_TRIPS.inc()
            # a fresh offline spell probes FAST again even when the
            # prober thread is reused below (its backoff may have
            # grown to the cap during an earlier spell)
            self._probe_delay = HEALTH_PROBE_INTERVAL
            if self._prober is not None and self._prober.is_alive():
                # a prober from an earlier offline spell is still in
                # its backoff sleep (a sibling's success flipped the
                # flag without joining it): it re-reads _online under
                # _mu at its loop top and keeps going — spawning
                # another would stack probers per flap. The prober
                # clears self._prober under _mu before exiting, so it
                # cannot be observed alive here AND miss this spell.
                return
            self._prober = threading.Thread(target=self._probe_loop,
                                            daemon=True)
            self._prober.start()

    def _probe_loop(self) -> None:
        # exponential backoff (capped): a host that stays dead gets
        # probed ever less often instead of a fixed 1 s hammer; the
        # first probe still fires fast so a blip recovers quickly
        # (mark_offline resets _probe_delay per spell — this thread
        # may serve several spells back to back). The ONLY exit is the
        # top-of-loop check under _mu, which also hands the prober
        # slot back — so mark_offline can never observe a live prober
        # that has already decided to die (the stuck-offline race).
        while True:
            with self._mu:
                if self._online:
                    self._prober = None
                    return
                delay = self._probe_delay
                self._probe_delay = min(delay * 2, HEALTH_PROBE_MAX)
            time.sleep(delay * (0.75 + random.random() / 2))
            if naughtynet.NET.enabled and naughtynet.NET.blocked(
                    self.node_id or membership.local_node(),
                    f"{self.host}:{self.port}"):
                # the link is (chaos-)partitioned: the probe must not
                # re-admit a host we cannot actually reach
                continue
            try:
                conn = http.client.HTTPConnection(self.host, self.port,
                                                  timeout=2.0)
                src_id = self.node_id or membership.local_node()
                conn.request("GET", self.service_path + "/health",
                             headers={membership.NODE_HEADER: src_id}
                             if src_id else {})
                resp = conn.getresponse()
                resp.read()
                conn.close()
                if resp.status in (200, 404):
                    self._online = True
                    # one prober's good news re-admits every sibling;
                    # the loop top hands the prober slot back
                    _note_host_alive(self.host, self.port, exclude=self)
            except (OSError, http.client.HTTPException):
                continue

    def net_counters(self) -> dict:
        """Transport fault counters for the OBD bundle."""
        with self._mu:
            return {"endpoint": f"{self.host}:{self.port}",
                    "online": self._online, "calls": self.calls,
                    "net_errors": self.net_errors,
                    "retries": self.retries,
                    "offline_trips": self.offline_trips}

    def close(self) -> None:
        self._online = True  # stop any probe loop


class _StreamedResponse:
    def __init__(self, conn, resp, read_timeout: Optional[float] = None):
        self._conn = conn
        self.resp = resp
        # per-READ deadline: a peer that goes silent mid-stream
        # (partition after headers) must fail the reader, not park it
        # forever — armed on the socket before every read, so a
        # trickling-but-alive stream resets it each time
        self._read_timeout = (
            knobs.get_float("MINIO_TPU_RPC_STREAM_READ_S")
            if read_timeout is None else read_timeout)

    def _arm_read_deadline(self) -> None:
        sock = getattr(self._conn, "sock", None)
        if sock is not None and self._read_timeout > 0:
            sock.settimeout(self._read_timeout)

    def read(self, n: int = -1) -> bytes:
        self._arm_read_deadline()
        try:
            return self.resp.read(n)
        except socket.timeout as e:
            # the peer went silent past the per-read deadline: that is
            # an unreachable host, not a malformed response
            self._conn.close()
            raise NetworkError(
                f"mid-stream: read deadline "
                f"({self._read_timeout:g}s) exceeded",
                conn_failure=True) from e
        except (OSError, http.client.HTTPException) as e:
            # a mid-stream disconnect is a RETRYABLE transport fault,
            # not a generic storage error — hedged readers re-read from
            # another drive; the peer is not declared offline for it
            self._conn.close()
            raise NetworkError(f"mid-stream: {e}") from e

    def readline(self) -> bytes:
        """One line, INCREMENTALLY: read(n) on a chunked response
        blocks until n bytes accumulate, which on a trickle stream
        (trace-follow heartbeats) means minutes — readline reads at
        most one chunk. Empty bytes = end of stream."""
        self._arm_read_deadline()
        try:
            return self.resp.readline()
        except socket.timeout as e:
            self._conn.close()
            raise NetworkError(
                f"mid-stream: read deadline "
                f"({self._read_timeout:g}s) exceeded",
                conn_failure=True) from e
        except (OSError, http.client.HTTPException, ValueError) as e:
            self._conn.close()
            raise NetworkError(f"mid-stream: {e}") from e

    def close(self) -> None:
        self._conn.close()


# ---------------------------------------------------------------------------
# server side: verb table mounted under a path prefix on any HTTP server
# ---------------------------------------------------------------------------

class RPCHandler:
    """Routes `POST <prefix>/<verb>` to registered python callables.

    handler(args: dict[str, str], body: bytes) -> bytes | dict | None.
    Raised exceptions are serialized as {"kind", "message"} with a 500.
    Mount into the S3Server via register_router(prefix, self.route) or
    serve standalone via serve().
    """

    def __init__(self, prefix: str, access_key: str, secret_key: str,
                 node_id: str = ""):
        self.prefix = prefix.rstrip("/")
        self.access_key, self.secret_key = access_key, secret_key
        # serving node's id ("" = process-local identity): stamped on
        # every response so callers track our boot generation, and
        # matched against inbound naughtynet partition rules
        self.node_id = node_id
        self._verbs: dict[str, Callable] = {}
        self._stream_verbs: set[str] = set()

    def register(self, verb: str, fn: Callable,
                 stream_body: bool = False) -> None:
        """stream_body verbs receive the request-body READER instead of
        bytes — big uploads (CreateFile) pass through to the drive
        without staging in RAM."""
        self._verbs[verb] = fn
        if stream_body:
            self._stream_verbs.add(verb)

    def _identity_headers(self) -> dict:
        out = {membership.GEN_HEADER: str(membership.local_generation())}
        nid = self.node_id or membership.local_node()
        if nid:
            out[membership.NODE_HEADER] = nid
        return out

    def route(self, ctx) -> "HTTPResponse":
        from ..s3.handlers import HTTPResponse
        path = ctx.req.path
        verb = path[len(self.prefix):].lstrip("/")
        ident = self._identity_headers()
        peer_id = ctx.header(membership.NODE_HEADER)
        if naughtynet.NET.enabled:
            # inbound chaos: a partitioned caller's verbs (health
            # probes included) are dropped BEFORE dispatch — the
            # PARTITIONED_KIND payload maps back to an unreachable-host
            # failure on the calling side
            act = naughtynet.NET.on_serve(
                peer_id, self.node_id or membership.local_node(), verb)
            if act.delay > 0:
                time.sleep(act.delay)
            if act.blocked:
                return HTTPResponse(status=503, body=json.dumps(
                    {"kind": naughtynet.PARTITIONED_KIND,
                     "message": "inbound link partitioned"}).encode(),
                    headers=ident)
        if verb == "health":
            return HTTPResponse(body=b"OK", headers=ident)
        auth = ctx.header("authorization")
        if not (auth.startswith("Bearer ") and verify_token(
                auth[7:], self.access_key, self.secret_key)):
            return HTTPResponse(status=403, body=json.dumps(
                {"kind": "auth", "message": "invalid token"}).encode(),
                headers=ident)
        # membership: a caller advertising a NEW boot generation is a
        # fresh incarnation — fire the stale-state fencing listeners
        peer_gen = ctx.header(membership.GEN_HEADER)
        if peer_id and peer_gen:
            try:
                membership.TRACKER.observe(peer_id, int(peer_gen),
                                           peer_id)
            except ValueError:
                pass
        fn = self._verbs.get(verb)
        if fn is None:
            return HTTPResponse(status=404, body=json.dumps(
                {"kind": "unknown-verb", "message": verb}).encode(),
                headers=ident)
        args = {k: v[0] for k, v in ctx.req.query.items()}
        body = ctx.body_stream if verb in self._stream_verbs \
            else ctx.read_body()
        # join the caller's trace when it sent one: the handler runs
        # under a remote-side span recorded as a fragment and grafted
        # back into the caller's tree by span id
        tid = ctx.header(telemetry.TRACE_HEADER)
        join_cm = telemetry.join(
            f"rpc.server.{verb}", tid,
            ctx.header(telemetry.SPAN_HEADER)) if tid else None
        try:
            if join_cm is not None:
                with join_cm:
                    out = fn(args, body)
            else:
                out = fn(args, body)
        except Exception as e:  # noqa: BLE001 — serialize to the caller
            return HTTPResponse(status=500, body=json.dumps(
                {"kind": type(e).__name__, "message": str(e)}).encode(),
                headers=ident)
        if out is None:
            return HTTPResponse(body=b"", headers=ident)
        if isinstance(out, (bytes, bytearray)):
            return HTTPResponse(body=bytes(out), headers=ident)
        if hasattr(out, "__next__") or hasattr(out, "read"):
            # streamed response (big shard reads): chunked on the wire
            if hasattr(out, "read"):
                reader = out

                def gen():
                    try:
                        while True:
                            chunk = reader.read(1 << 20)
                            if not chunk:
                                return
                            yield chunk
                    finally:
                        close = getattr(reader, "close", None)
                        if close is not None:
                            close()
                out = gen()
            if naughtynet.NET.enabled:
                # chaos may reset the stream after the first chunk or
                # go silent when a partition opens mid-stream
                out = naughtynet.NET.wrap_stream(
                    peer_id, self.node_id or membership.local_node(),
                    verb, out)
            return HTTPResponse(stream=out, headers=ident)
        headers = {"Content-Type": "application/json"}
        headers.update(ident)
        return HTTPResponse(body=json.dumps(out).encode(),
                            headers=headers)


class RPCServer:
    """Standalone HTTP host for one or more RPCHandlers (a node's
    internode port when no S3 frontend is wanted, e.g. tests or
    storage-only processes)."""

    def __init__(self, address: str = "127.0.0.1", port: int = 0):
        import urllib.parse as _up
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        from ..s3 import signature as sig
        from ..s3.handlers import RequestContext

        handlers: list[tuple[str, RPCHandler]] = []
        self._handlers = handlers

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _go(self):
                from ..s3.server import _BodyReader
                parsed = _up.urlsplit(self.path)
                headers = {k.lower(): v for k, v in self.headers.items()}
                req = sig.Request(
                    method=self.command, path=parsed.path,
                    query=_up.parse_qs(parsed.query,
                                       keep_blank_values=True),
                    headers=headers, raw_query=parsed.query)
                length = int(headers.get("content-length", 0) or 0)
                # lazy bounded reader: stream verbs (CreateFile) pass
                # big bodies straight to the drive; drain() afterwards
                # keeps the keep-alive socket clean either way
                body_reader = _BodyReader(self.rfile, length)
                ctx = RequestContext(req, body_reader, length)
                try:
                    resp = None
                    for prefix, h in handlers:
                        if parsed.path.startswith(prefix):
                            resp = h.route(ctx)
                            break
                finally:
                    body_reader.drain()
                if resp is None:
                    from ..s3.handlers import HTTPResponse
                    resp = HTTPResponse(status=404, body=b"not found")
                self.send_response(resp.status)
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                if resp.stream is not None:
                    # chunked streamed response (big shard reads)
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    try:
                        for chunk in resp.stream:
                            if chunk:
                                self.wfile.write(
                                    f"{len(chunk):x}\r\n".encode()
                                    + chunk + b"\r\n")
                        self.wfile.write(b"0\r\n\r\n")
                    except BrokenPipeError:
                        self.close_connection = True
                    finally:
                        close = getattr(resp.stream, "close", None)
                        if close is not None:
                            try:
                                close()
                            except Exception:  # noqa: BLE001
                                pass
                    return
                body = resp.body
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            do_GET = do_POST = _go

        from ..s3.server import _DeepBacklogServer
        self._httpd = _DeepBacklogServer((address, port), _H)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def mount(self, handler: RPCHandler) -> None:
        self._handlers.append((handler.prefix, handler))

    def mount_route(self, prefix: str, handler: RPCHandler) -> None:
        self._handlers.append((prefix, handler))

    def start(self) -> "RPCServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
