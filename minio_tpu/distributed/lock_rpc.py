"""Lock RPC — NetLocker over the internode transport.

The reference's cmd/lock-rest-server.go / cmd/lock-rest-client.go:
verbs /lock /rlock /unlock /runlock /force-unlock /expired mounted at
/minio/lock/v1, plus a maintenance sweep of stale grants.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from .local_locker import LOCK_VALIDITY, LocalLocker
from .transport import NetworkError, RestClient, RPCError, RPCHandler

LOCK_RPC_PREFIX = "/minio/lock/v1"
MAINTENANCE_INTERVAL = 30.0


class LockRPCServer:
    """Serves a LocalLocker's verbs; mount into any server that accepts
    (prefix, route_fn) routers (e.g. s3.server.S3Server)."""

    def __init__(self, locker: LocalLocker, access_key: str,
                 secret_key: str, start_sweeper: bool = True):
        self.locker = locker
        self.handler = RPCHandler(LOCK_RPC_PREFIX, access_key, secret_key)
        for verb in ("lock", "rlock", "unlock", "runlock", "force-unlock",
                     "refresh"):
            self.handler.register(verb, self._make(verb))
        self.handler.register("dump", lambda a, b: self.locker.dump())
        self._stop = threading.Event()
        if start_sweeper:
            threading.Thread(target=self._sweep_loop, daemon=True).start()

    def _make(self, verb: str):
        fn = {
            "lock": self.locker.lock,
            "rlock": self.locker.rlock,
            "unlock": self.locker.unlock,
            "runlock": self.locker.runlock,
            "force-unlock": lambda uid, res, **kw:
                self.locker.force_unlock(res),
            "refresh": lambda uid, res, **kw:
                self.locker.refresh(uid, res),
        }[verb]

        def handle(args: dict, body: bytes):
            req = json.loads(body.decode())
            if verb in ("lock", "rlock"):
                ok = fn(req["uid"], req["resources"],
                        owner=req.get("owner", ""),
                        source=req.get("source", ""))
            else:
                ok = fn(req["uid"], req["resources"])
            return {"granted": bool(ok)}
        return handle

    def _sweep_loop(self) -> None:
        while not self._stop.wait(MAINTENANCE_INTERVAL):
            self.locker.expire_old_locks(LOCK_VALIDITY)

    def close(self) -> None:
        self._stop.set()

    def route(self, ctx):
        return self.handler.route(ctx)


class LockRPCClient:
    """NetLocker speaking the lock verbs to a remote node."""

    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, timeout: float = 5.0):
        self.rc = RestClient(host, port, LOCK_RPC_PREFIX, access_key,
                             secret_key, timeout=timeout)

    def _call(self, verb: str, uid: str, resources: list[str],
              owner: str = "", source: str = "") -> bool:
        payload = {"uid": uid, "resources": resources, "owner": owner,
                   "source": source}
        try:
            out = self.rc.call_json(verb, payload=payload)
        except (NetworkError, RPCError):
            return False
        return bool(out and out.get("granted"))

    def lock(self, uid, resources, owner="", source=""):
        return self._call("lock", uid, resources, owner, source)

    def rlock(self, uid, resources, owner="", source=""):
        return self._call("rlock", uid, resources, owner, source)

    def unlock(self, uid, resources):
        return self._call("unlock", uid, resources)

    def runlock(self, uid, resources):
        return self._call("runlock", uid, resources)

    def force_unlock(self, resources):
        return self._call("force-unlock", "", resources)

    def refresh(self, uid, resources):
        return self._call("refresh", uid, resources)

    def dump(self) -> dict:
        try:
            return self.rc.call_json("dump") or {}
        except (NetworkError, RPCError):
            return {}

    @property
    def online(self) -> bool:
        return self.rc.online

    def close(self) -> None:
        self.rc.close()
