"""Storage RPC — every StorageAPI verb over the internode transport.

The reference's cmd/storage-rest-server.go / cmd/storage-rest-client.go:
a remote drive is just a StorageAPI whose verbs travel as
`POST /minio/storage/v1/<verb>` with JSON args and raw byte bodies.
The client maps transport failures to DiskNotFound so quorum logic
treats a dead peer exactly like a dead local drive, and the underlying
RestClient probes the host back online (cmd/storage-rest-client.go
toStorageErr + reconnect semantics).
"""

from __future__ import annotations

import dataclasses
import io
import json
from typing import BinaryIO, Iterator, Optional

from ..storage import errors as serr
from ..storage.api import BitrotVerifier, StorageAPI
from ..storage.datatypes import (ChecksumInfo, DiskInfo, ErasureInfo,
                                 FileInfo, ObjectPartInfo, VolInfo)
from ..utils import telemetry
from .transport import NetworkError, RestClient, RPCError, RPCHandler

STORAGE_RPC_PREFIX = "/minio/storage/v1"


# ---------------------------------------------------------------------------
# FileInfo wire codec (the reference uses msgp codegen on the same structs,
# cmd/storage-datatypes_gen.go)
# ---------------------------------------------------------------------------

def fi_to_dict(fi: FileInfo) -> dict:
    d = dataclasses.asdict(fi)
    for c in d["erasure"]["checksums"]:
        c["hash"] = c["hash"].hex()
    return d


def fi_from_dict(d: dict) -> FileInfo:
    e = d.get("erasure", {})
    checksums = [ChecksumInfo(part_number=c["part_number"],
                              algorithm=c["algorithm"],
                              hash=bytes.fromhex(c["hash"]))
                 for c in e.get("checksums", [])]
    erasure = ErasureInfo(
        algorithm=e.get("algorithm", ""),
        data_blocks=e.get("data_blocks", 0),
        parity_blocks=e.get("parity_blocks", 0),
        block_size=e.get("block_size", 0),
        index=e.get("index", 0),
        distribution=list(e.get("distribution", [])),
        checksums=checksums)
    parts = [ObjectPartInfo(**p) for p in d.get("parts", [])]
    return FileInfo(
        volume=d.get("volume", ""), name=d.get("name", ""),
        version_id=d.get("version_id", ""),
        is_latest=d.get("is_latest", True),
        deleted=d.get("deleted", False),
        data_dir=d.get("data_dir", ""),
        mod_time=d.get("mod_time", 0.0), size=d.get("size", 0),
        metadata=dict(d.get("metadata", {})), parts=parts,
        erasure=erasure)


# error name <-> class registry: RPC carries the class name as `kind`
_ERR_CLASSES = {name: cls for name, cls in vars(serr).items()
                if isinstance(cls, type) and issubclass(cls, Exception)}


def _to_storage_err(e: Exception) -> Exception:
    if isinstance(e, RPCError):
        # the REMOTE answered: map its storage error by name — never a
        # transport error, so is_online() stays untouched
        cls = _ERR_CLASSES.get(e.kind)
        if cls is not None:
            return cls(e.message)
        return serr.UnexpectedError(f"{e.kind}: {e.message}")
    if isinstance(e, NetworkError):
        # the WIRE broke (refused/reset/timeout/mid-stream): retryable,
        # quorum-tolerated like a gone drive
        return serr.NetworkStorageError(str(e))
    return e


# Verbs safe to replay on a transport failure (pure reads / existence
# probes — re-running them cannot double-apply a mutation). Everything
# else fails fast and lets quorum logic treat the drive as gone.
_IDEMPOTENT_VERBS = frozenset({
    "diskinfo", "getdiskid", "listvols", "statvol", "readversion",
    "readversions", "listdir", "readfile", "readall", "walk",
    "checkfile", "checkparts", "verifyfile", "readfilestream",
})


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class StorageRPCServer:
    """Exposes one node's local drives. Each drive is addressed by its
    endpoint path (the `disk` arg), mirroring the reference's
    per-endpoint route mounting."""

    def __init__(self, drives: dict[str, StorageAPI], access_key: str,
                 secret_key: str):
        self.drives = drives
        self.handler = RPCHandler(STORAGE_RPC_PREFIX, access_key,
                                  secret_key)
        for verb in ("diskinfo", "getdiskid", "setdiskid", "makevol",
                     "listvols", "statvol", "deletevol", "writemetadata",
                     "readversion", "readversions", "deleteversion",
                     "deleteversions",
                     "renamedata", "listdir", "readfile", "appendfile",
                     "renamefile", "checkparts",
                     "checkfile", "deletefile", "verifyfile", "writeall",
                     "readall", "walk", "readfilestream"):
            self.handler.register(verb, getattr(self, "_" + verb))
        # CreateFile bodies pass through to the drive as a stream —
        # a multi-GiB shard never stages in this process's RAM
        # (reference storage-rest-server.go streaming verbs)
        self.handler.register("createfile", self._createfile,
                              stream_body=True)

    def route(self, ctx):
        return self.handler.route(ctx)

    def _disk(self, args: dict) -> StorageAPI:
        d = self.drives.get(args.get("disk", ""))
        if d is None:
            raise serr.DiskNotFound(args.get("disk", ""))
        return d

    # each verb: (args, body) -> dict | bytes | None ------------------------

    def _diskinfo(self, a, b):
        info = self._disk(a).disk_info()
        return dataclasses.asdict(info)

    def _getdiskid(self, a, b):
        return {"id": self._disk(a).get_disk_id()}

    def _setdiskid(self, a, b):
        self._disk(a).set_disk_id(a.get("id", ""))

    def _makevol(self, a, b):
        self._disk(a).make_vol(a["volume"])

    def _listvols(self, a, b):
        return [{"name": v.name, "created": v.created}
                for v in self._disk(a).list_vols()]

    def _statvol(self, a, b):
        v = self._disk(a).stat_vol(a["volume"])
        return {"name": v.name, "created": v.created}

    def _deletevol(self, a, b):
        self._disk(a).delete_vol(a["volume"],
                                 force=a.get("force") == "true")

    def _writemetadata(self, a, b):
        self._disk(a).write_metadata(a["volume"], a["path"],
                                     fi_from_dict(json.loads(b.decode())))

    def _readversion(self, a, b):
        fi = self._disk(a).read_version(a["volume"], a["path"],
                                        a.get("version-id", ""))
        return fi_to_dict(fi)

    def _readversions(self, a, b):
        return [fi_to_dict(fi) for fi in
                self._disk(a).read_versions(a["volume"], a["path"])]

    def _deleteversion(self, a, b):
        self._disk(a).delete_version(a["volume"], a["path"],
                                     fi_from_dict(json.loads(b.decode())))

    def _deleteversions(self, a, b):
        """Bulk delete: N versions in one round trip (reference
        storageRESTMethodDeleteVersions). Per-item results travel as
        [null | {kind, message}]."""
        fis = [fi_from_dict(d) for d in json.loads(b.decode())]
        errs = self._disk(a).delete_versions(a["volume"], fis)
        return [None if e is None else
                {"kind": type(e).__name__, "message": str(e)}
                for e in errs]

    def _renamedata(self, a, b):
        self._disk(a).rename_data(a["src-volume"], a["src-path"],
                                  a["data-dir"], a["dst-volume"],
                                  a["dst-path"],
                                  a.get("version-id", ""))

    def _listdir(self, a, b):
        return self._disk(a).list_dir(a["volume"], a.get("dir-path", ""),
                                      int(a.get("count", "-1")))

    def _readfile(self, a, b):
        verifier = None
        if a.get("verifier-algo"):
            verifier = BitrotVerifier(a["verifier-algo"],
                                      bytes.fromhex(a["verifier-hash"]))
        return self._disk(a).read_file(a["volume"], a["path"],
                                       int(a["offset"]), int(a["length"]),
                                       verifier)

    def _appendfile(self, a, b):
        with telemetry.span("storage.appendfile",
                            disk=a.get("disk", ""), bytes=len(b)):
            self._disk(a).append_file(a["volume"], a["path"], b)

    def _createfile(self, a, body_stream):
        # stream verb: body_stream is the request-body READER. The
        # span runs under the RPC join (same thread), so the remote
        # drive write lands in the CALLER's span tree.
        with telemetry.span("storage.createfile",
                            disk=a.get("disk", "")):
            self._disk(a).create_file(a["volume"], a["path"],
                                      int(a.get("size", "-1")),
                                      body_stream)

    def _readfilestream(self, a, b):
        """Streamed read: the shard flows out chunked; neither end
        stages the whole file (reference ReadFileStream verb). The
        span must cover the BODY, not just the open — the stream is
        consumed after this verb returns, so the timing rides a
        wrapper that reports when the transport closes it."""
        import time as _time
        parent = telemetry.current_span()
        t0_wall, t0 = _time.time(), _time.perf_counter()
        stream = self._disk(a).read_file_stream(
            a["volume"], a["path"], int(a["offset"]), int(a["length"]))
        if parent is None:
            return stream
        return _TimedReadStream(stream, parent, a.get("disk", ""),
                                t0_wall, t0)

    def _renamefile(self, a, b):
        self._disk(a).rename_file(a["src-volume"], a["src-path"],
                                  a["dst-volume"], a["dst-path"])

    def _checkparts(self, a, b):
        self._disk(a).check_parts(a["volume"], a["path"],
                                  fi_from_dict(json.loads(b.decode())))

    def _checkfile(self, a, b):
        self._disk(a).check_file(a["volume"], a["path"])

    def _deletefile(self, a, b):
        self._disk(a).delete_file(a["volume"], a["path"],
                                  recursive=a.get("recursive") == "true")

    def _verifyfile(self, a, b):
        self._disk(a).verify_file(a["volume"], a["path"],
                                  fi_from_dict(json.loads(b.decode())))

    def _writeall(self, a, b):
        self._disk(a).write_all(a["volume"], a["path"], b)

    def _readall(self, a, b):
        return self._disk(a).read_all(a["volume"], a["path"])

    def _walk(self, a, b):
        return [fi_to_dict(fi) for fi in
                self._disk(a).walk(a["volume"], a.get("dir-path", ""),
                                   a.get("marker", ""),
                                   a.get("recursive", "true") == "true")]


class _TimedReadStream:
    """Times a streamed shard read end-to-end: the span is attached
    (already finished) to the RPC join span when the transport closes
    the stream after sending the last chunk — a plain `with span():`
    around the open would report ~0 ms and miss the actual I/O."""

    def __init__(self, inner, parent, disk: str, t0_wall: float,
                 t0: float):
        self._inner = inner
        self._parent = parent
        self._disk = disk
        self._t0_wall = t0_wall
        self._t0 = t0
        self._done = False

    def read(self, n: int = -1) -> bytes:
        return self._inner.read(n)

    def close(self) -> None:
        import time as _time
        try:
            close = getattr(self._inner, "close", None)
            if close is not None:
                close()
        finally:
            if not self._done:
                self._done = True
                telemetry.attach_span(
                    self._parent, "storage.readfilestream",
                    self._t0_wall, _time.perf_counter() - self._t0,
                    disk=self._disk)


# ---------------------------------------------------------------------------
# client — a remote drive as a StorageAPI
# ---------------------------------------------------------------------------

class _RemoteStream:
    """Wraps a streamed RPC response so a mid-stream transport failure
    raises the retryable NetworkStorageError instead of leaking raw
    socket/NetworkError exceptions into shard-read plumbing."""

    def __init__(self, inner):
        self._inner = inner

    def read(self, n: int = -1) -> bytes:
        try:
            return self._inner.read(n)
        except NetworkError as e:
            raise serr.NetworkStorageError(str(e)) from e

    def close(self) -> None:
        self._inner.close()


class RemoteStorage(StorageAPI):
    """StorageAPI over the wire. `disk` names the remote drive (its
    endpoint path on the serving node)."""

    def __init__(self, host: str, port: int, disk: str, access_key: str,
                 secret_key: str, timeout: float = 30.0):
        self.rc = RestClient(host, port, STORAGE_RPC_PREFIX, access_key,
                             secret_key, timeout=timeout)
        self.disk = disk
        self._disk_id = ""

    # -- plumbing ----------------------------------------------------------

    def _call(self, verb: str, args: Optional[dict] = None,
              body: bytes = b"") -> bytes:
        a = {"disk": self.disk}
        a.update(args or {})
        try:
            return self.rc.call(verb, a, body,
                                idempotent=verb in _IDEMPOTENT_VERBS)
        except (RPCError, NetworkError) as e:
            raise _to_storage_err(e) from None

    def _call_json(self, verb: str, args: Optional[dict] = None,
                   body: bytes = b""):
        out = self._call(verb, args, body)
        return json.loads(out.decode()) if out else None

    # -- identity / health -------------------------------------------------

    def __str__(self) -> str:
        return f"{self.rc.host}:{self.rc.port}{self.disk}"

    def is_online(self) -> bool:
        return self.rc.online

    def is_local(self) -> bool:
        return False

    def hostname(self) -> str:
        return self.rc.host

    def endpoint(self) -> str:
        return str(self)

    def close(self) -> None:
        self.rc.close()

    def get_disk_id(self) -> str:
        out = self._call_json("getdiskid")
        return out["id"] if out else ""

    def set_disk_id(self, disk_id: str) -> None:
        self._disk_id = disk_id
        self._call("setdiskid", {"id": disk_id})

    def disk_info(self) -> DiskInfo:
        out = self._call_json("diskinfo") or {}
        return DiskInfo(**out)

    # -- volumes -----------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        self._call("makevol", {"volume": volume})

    def list_vols(self) -> list[VolInfo]:
        return [VolInfo(v["name"], v["created"])
                for v in self._call_json("listvols") or []]

    def stat_vol(self, volume: str) -> VolInfo:
        v = self._call_json("statvol", {"volume": volume})
        return VolInfo(v["name"], v["created"])

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._call("deletevol", {"volume": volume,
                                 "force": "true" if force else "false"})

    # -- metadata ----------------------------------------------------------

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("writemetadata", {"volume": volume, "path": path},
                   json.dumps(fi_to_dict(fi)).encode())

    def read_version(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo:
        return fi_from_dict(self._call_json(
            "readversion", {"volume": volume, "path": path,
                            "version-id": version_id}))

    def read_versions(self, volume: str, path: str) -> list[FileInfo]:
        return [fi_from_dict(d) for d in self._call_json(
            "readversions", {"volume": volume, "path": path}) or []]

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("deleteversion", {"volume": volume, "path": path},
                   json.dumps(fi_to_dict(fi)).encode())

    def delete_versions(self, volume: str, versions: list[FileInfo]
                        ) -> list[Optional[Exception]]:
        """N deletes, ONE wire round trip (the r1 review's 'serial bulk
        delete' fix; reference DeleteVersions RPC)."""
        out = self._call_json(
            "deleteversions", {"volume": volume},
            json.dumps([fi_to_dict(fi) for fi in versions]).encode())
        errs: list[Optional[Exception]] = []
        for item in out or []:
            if item is None:
                errs.append(None)
                continue
            cls = _ERR_CLASSES.get(item.get("kind", ""),
                                   serr.UnexpectedError)
            errs.append(cls(item.get("message", "")))
        while len(errs) < len(versions):
            errs.append(serr.UnexpectedError("missing bulk result"))
        return errs

    def rename_data(self, src_volume: str, src_path: str, data_dir: str,
                    dst_volume: str, dst_path: str,
                    version_id: str = "") -> None:
        self._call("renamedata", {
            "src-volume": src_volume, "src-path": src_path,
            "data-dir": data_dir, "dst-volume": dst_volume,
            "dst-path": dst_path, "version-id": version_id})

    # -- files -------------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str,
                 count: int = -1) -> list[str]:
        return self._call_json("listdir", {
            "volume": volume, "dir-path": dir_path,
            "count": str(count)}) or []

    def read_file(self, volume: str, path: str, offset: int, length: int,
                  verifier: Optional[BitrotVerifier] = None) -> bytes:
        args = {"volume": volume, "path": path, "offset": str(offset),
                "length": str(length)}
        if verifier is not None:
            args["verifier-algo"] = verifier.algorithm
            args["verifier-hash"] = verifier.digest.hex()
        return self._call("readfile", args)

    def append_file(self, volume: str, path: str, buf: bytes) -> None:
        self._call("appendfile", {"volume": volume, "path": path}, buf)

    def create_file(self, volume: str, path: str, size: int,
                    reader: BinaryIO) -> None:
        """Streams `size` bytes to the remote drive in bounded chunks —
        no whole-shard staging on either end (VERDICT r4 weak #5;
        reference storage-rest streaming CreateFile)."""
        if size < 0:
            # unknown size: the wire needs a Content-Length, so this
            # rare path buffers once
            data = reader.read()
            self._call("createfile", {"volume": volume, "path": path,
                                      "size": str(size)}, data or b"")
            return

        def chunks():
            remaining = size
            while remaining > 0:
                chunk = reader.read(min(remaining, 1 << 20))
                if not chunk:
                    return            # short body: server raises
                remaining -= len(chunk)
                yield chunk

        args = {"disk": self.disk, "volume": volume, "path": path,
                "size": str(size)}
        try:
            self.rc.call("createfile", args, chunks(),
                         body_length=size)
        except (RPCError, NetworkError) as e:
            raise _to_storage_err(e) from None

    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int) -> BinaryIO:
        """Streamed shard read (chunked response); falls back to the
        buffered verb against peers that predate it. A mid-stream
        disconnect surfaces as the retryable NetworkStorageError (NOT a
        generic storage error) so hedged readers re-read elsewhere."""
        args = {"disk": self.disk, "volume": volume, "path": path,
                "offset": str(offset), "length": str(length)}
        try:
            return _RemoteStream(self.rc.call("readfilestream", args,
                                              stream_response=True,
                                              idempotent=True))
        except RPCError as e:
            if e.kind != "unknown-verb":
                raise _to_storage_err(e) from None
        except NetworkError as e:
            raise _to_storage_err(e) from None
        return io.BytesIO(self.read_file(volume, path, offset, length))

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        self._call("renamefile", {
            "src-volume": src_volume, "src-path": src_path,
            "dst-volume": dst_volume, "dst-path": dst_path})

    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("checkparts", {"volume": volume, "path": path},
                   json.dumps(fi_to_dict(fi)).encode())

    def check_file(self, volume: str, path: str) -> None:
        self._call("checkfile", {"volume": volume, "path": path})

    def delete_file(self, volume: str, path: str,
                    recursive: bool = False) -> None:
        self._call("deletefile", {
            "volume": volume, "path": path,
            "recursive": "true" if recursive else "false"})

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._call("verifyfile", {"volume": volume, "path": path},
                   json.dumps(fi_to_dict(fi)).encode())

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        self._call("writeall", {"volume": volume, "path": path}, data)

    def read_all(self, volume: str, path: str) -> bytes:
        return self._call("readall", {"volume": volume, "path": path})

    def walk(self, volume: str, dir_path: str = "", marker: str = "",
             recursive: bool = True) -> Iterator[FileInfo]:
        for d in self._call_json("walk", {
                "volume": volume, "dir-path": dir_path, "marker": marker,
                "recursive": "true" if recursive else "false"}) or []:
            yield fi_from_dict(d)
