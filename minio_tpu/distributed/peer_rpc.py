"""Peer control plane + bootstrap verify.

The reference fans control operations out to every node over peer REST
(cmd/peer-rest-client.go / cmd/peer-rest-server.go, aggregated by
NotificationSys, cmd/notification.go) and verifies cluster config
consistency at startup against the first node
(cmd/bootstrap-peer-server.go verifyServerSystemConfig).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Callable, Optional

from .transport import NetworkError, RestClient, RPCError, RPCHandler

PEER_RPC_PREFIX = "/minio/peer/v1"
BOOTSTRAP_RPC_PREFIX = "/minio/bootstrap/v1"


class PeerRPCServer:
    """This node's control-plane verbs. Hooks are injected so the server
    stays decoupled from the subsystems it pokes."""

    def __init__(self, access_key: str, secret_key: str,
                 node_id: str = ""):
        self.handler = RPCHandler(PEER_RPC_PREFIX, access_key, secret_key)
        self.node_id = node_id
        self.started = time.time()
        # injectable hooks
        self.get_server_info: Callable[[], dict] = lambda: {}
        self.get_locks: Callable[[], dict] = lambda: {}
        self.reload_bucket_metadata: Callable[[str], None] = lambda b: None
        self.reload_iam: Callable[[], None] = lambda: None
        self.signal_service: Callable[[str], None] = lambda sig: None
        self.get_metrics: Callable[[], dict] = lambda: {}
        self.get_storage_info: Callable[[], dict] = lambda: {}
        self.get_trace: Callable[[], list] = lambda: []
        self.get_bucket_usage: Callable[[], dict] = lambda: {}
        self.obd_drive_paths: list[str] = []
        # leader heal-scanner pulls + rotates this node's data-update
        # tracker each pass (None until the cluster wires it)
        self.get_update_tracker: Optional[Callable[[], dict]] = None
        self.get_bandwidth: Callable[[], dict] = lambda: {}

        h = self.handler
        h.register("server-info", lambda a, b: {
            "node": self.node_id, "uptime": time.time() - self.started,
            **self.get_server_info()})
        h.register("locks", lambda a, b: self.get_locks())
        h.register("reload-bucket-metadata", self._reload_bm)
        h.register("reload-iam", lambda a, b: self.reload_iam())
        h.register("signal", self._signal)
        h.register("metrics", lambda a, b: self.get_metrics())
        h.register("storage-info", lambda a, b: self.get_storage_info())
        h.register("trace", lambda a, b: self.get_trace())
        h.register("bucket-usage", lambda a, b: self.get_bucket_usage())
        # profiling fan-out (cmd/admin-handlers.go:461-525 peer verbs),
        # console-log ring, OBD bundle (peer-rest-common.go:29-56)
        h.register("profiling-start", self._profiling_start)
        h.register("profiling-stop", self._profiling_stop)
        h.register("console-log", self._console_log)
        h.register("obd", self._obd)
        h.register("tracker-rotate", self._tracker_rotate)
        h.register("bandwidth", lambda a, b: self.get_bandwidth())

    def _tracker_rotate(self, args, body):
        if self.get_update_tracker is None:
            return {}
        return self.get_update_tracker()

    def _profiling_start(self, args, body):
        from ..utils import profiling
        return {"node": self.node_id, "started": profiling.start()}

    def _profiling_stop(self, args, body):
        from ..utils import profiling
        return {"node": self.node_id,
                "profile": profiling.stop_text() or ""}

    def _console_log(self, args, body):
        from ..utils.console import get_console
        try:
            n = int(args.get("count", "0") or 0)
        except ValueError:
            n = 0
        return {"node": self.node_id,
                "entries": get_console().recent(n)}

    def _obd(self, args, body):
        from ..utils.obd import local_obd
        out = local_obd(self.obd_drive_paths)
        out["node"] = self.node_id
        return out

    def _reload_bm(self, args, body):
        self.reload_bucket_metadata(args.get("bucket", ""))

    def _signal(self, args, body):
        self.signal_service(args.get("sig", ""))

    def route(self, ctx):
        return self.handler.route(ctx)


class PeerRPCClient:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, timeout: float = 5.0):
        self.rc = RestClient(host, port, PEER_RPC_PREFIX, access_key,
                             secret_key, timeout=timeout)

    def server_info(self) -> Optional[dict]:
        try:
            return self.rc.call_json("server-info")
        except (NetworkError, RPCError):
            return None

    def locks(self) -> dict:
        try:
            return self.rc.call_json("locks") or {}
        except (NetworkError, RPCError):
            return {}

    def reload_bucket_metadata(self, bucket: str) -> bool:
        try:
            self.rc.call("reload-bucket-metadata", {"bucket": bucket})
            return True
        except (NetworkError, RPCError):
            return False

    def reload_iam(self) -> bool:
        try:
            self.rc.call("reload-iam")
            return True
        except (NetworkError, RPCError):
            return False

    def signal_service(self, sig: str) -> bool:
        try:
            self.rc.call("signal", {"sig": sig})
            return True
        except (NetworkError, RPCError):
            return False

    def metrics(self) -> dict:
        try:
            return self.rc.call_json("metrics") or {}
        except (NetworkError, RPCError):
            return {}

    def storage_info(self) -> dict:
        try:
            return self.rc.call_json("storage-info") or {}
        except (NetworkError, RPCError):
            return {}

    def trace(self) -> list:
        try:
            return self.rc.call_json("trace") or []
        except (NetworkError, RPCError):
            return []

    def bucket_usage(self) -> dict:
        try:
            return self.rc.call_json("bucket-usage") or {}
        except (NetworkError, RPCError):
            return {}

    def profiling_start(self) -> Optional[dict]:
        try:
            return self.rc.call_json("profiling-start")
        except (NetworkError, RPCError):
            return None

    def profiling_stop(self) -> Optional[dict]:
        try:
            return self.rc.call_json("profiling-stop")
        except (NetworkError, RPCError):
            return None

    def console_log(self, count: int = 0) -> Optional[dict]:
        try:
            return self.rc.call_json("console-log",
                                     {"count": str(count)})
        except (NetworkError, RPCError):
            return None

    def obd(self) -> Optional[dict]:
        try:
            return self.rc.call_json("obd")
        except (NetworkError, RPCError):
            return None

    def tracker_rotate(self) -> Optional[dict]:
        try:
            return self.rc.call_json("tracker-rotate")
        except (NetworkError, RPCError):
            return None

    def bandwidth(self) -> dict:
        try:
            return self.rc.call_json("bandwidth") or {}
        except (NetworkError, RPCError):
            return {}

    @property
    def online(self) -> bool:
        return self.rc.online

    def close(self) -> None:
        self.rc.close()


class NotificationSys:
    """Fan-out aggregator over all peer clients (cmd/notification.go):
    each call broadcasts concurrently and returns per-peer results."""

    def __init__(self, peers: list[PeerRPCClient]):
        self.peers = peers

    def _broadcast(self, fn: Callable[[PeerRPCClient], object]) -> list:
        out: list = [None] * len(self.peers)
        threads = []
        for i, p in enumerate(self.peers):
            def run(i=i, p=p):
                try:
                    out[i] = fn(p)
                except Exception as e:  # noqa: BLE001 — per-peer result
                    out[i] = e
            t = threading.Thread(target=run, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=10)
        return out

    def server_info_all(self) -> list:
        return self._broadcast(lambda p: p.server_info())

    def reload_bucket_metadata(self, bucket: str) -> list:
        return self._broadcast(
            lambda p: p.reload_bucket_metadata(bucket))

    def reload_iam(self) -> list:
        return self._broadcast(lambda p: p.reload_iam())

    def top_locks(self) -> dict:
        merged: dict = {}
        for locks in self._broadcast(lambda p: p.locks()):
            if isinstance(locks, dict):
                for res, holders in locks.items():
                    merged.setdefault(res, []).extend(holders)
        return merged

    def signal_all(self, sig: str) -> list:
        return self._broadcast(lambda p: p.signal_service(sig))

    def storage_info_all(self) -> list:
        return self._broadcast(lambda p: p.storage_info())

    def trace_all(self) -> list[dict]:
        """Cluster-wide recent trace entries, time-ordered."""
        merged: list[dict] = []
        for entries in self._broadcast(lambda p: p.trace()):
            if isinstance(entries, list):
                merged.extend(e for e in entries if isinstance(e, dict))
        merged.sort(key=lambda e: e.get("time", ""))
        return merged

    def profiling_start_all(self) -> list:
        return self._broadcast(lambda p: p.profiling_start())

    def profiling_stop_all(self) -> list:
        return self._broadcast(lambda p: p.profiling_stop())

    def console_log_all(self, count: int = 0) -> list[dict]:
        """Cluster-wide console entries, time-ordered."""
        merged: list[dict] = []
        for res in self._broadcast(lambda p: p.console_log(count)):
            if isinstance(res, dict):
                merged.extend(e for e in res.get("entries", [])
                              if isinstance(e, dict))
        merged.sort(key=lambda e: e.get("ts", 0))
        return merged

    def obd_all(self) -> list[dict]:
        return [r for r in self._broadcast(lambda p: p.obd())
                if isinstance(r, dict)]

    def tracker_rotate_all(self) -> list[Optional[dict]]:
        """One entry per peer: the rotated tracker snapshot, or None
        for an unreachable peer (the scanner must then assume-changed
        for that peer's window)."""
        return [r if isinstance(r, dict) else None
                for r in self._broadcast(lambda p: p.tracker_rotate())]

    def bandwidth_all(self) -> list[dict]:
        return [r for r in self._broadcast(lambda p: p.bandwidth())
                if isinstance(r, dict)]


# ---------------------------------------------------------------------------
# bootstrap verify
# ---------------------------------------------------------------------------

def system_config_hash(endpoints: list[str], access_key: str,
                       secret_key: str) -> str:
    """Digest of the node's view of cluster topology + credentials
    (the reference compares ServerSystemConfig field-by-field; a digest
    keeps secrets off the wire)."""
    blob = json.dumps({
        "endpoints": sorted(endpoints),
        "cred": hashlib.sha256(
            f"{access_key}:{secret_key}".encode()).hexdigest(),
    }, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


class BootstrapRPCServer:
    def __init__(self, access_key: str, secret_key: str,
                 endpoints: list[str]):
        self.handler = RPCHandler(BOOTSTRAP_RPC_PREFIX, access_key,
                                  secret_key)
        self.config_hash = system_config_hash(endpoints, access_key,
                                              secret_key)
        self.handler.register(
            "verify", lambda a, b: {"hash": self.config_hash})

    def route(self, ctx):
        return self.handler.route(ctx)


def verify_server_system_config(peers: list[tuple[str, int]],
                                endpoints: list[str], access_key: str,
                                secret_key: str, retries: int = 30,
                                interval: float = 1.0) -> None:
    """Block until every peer reports the same config digest
    (cmd/server-main.go:464-478 retry loop). Raises RuntimeError on a
    real mismatch; keeps retrying while peers are unreachable."""
    want = system_config_hash(endpoints, access_key, secret_key)
    remaining = {f"{h}:{p}" for h, p in peers}
    for _ in range(retries):
        for h, p in list(peers):
            key = f"{h}:{p}"
            if key not in remaining:
                continue
            rc = RestClient(h, p, BOOTSTRAP_RPC_PREFIX, access_key,
                            secret_key, timeout=2.0)
            try:
                got = rc.call_json("verify")
            except (NetworkError, RPCError):
                continue
            finally:
                rc.close()
            if got and got.get("hash") == want:
                remaining.discard(key)
            elif got:
                raise RuntimeError(
                    f"peer {key} has a different cluster config "
                    "(endpoints or credentials mismatch)")
        if not remaining:
            return
        time.sleep(interval)
    raise RuntimeError(f"peers unreachable during bootstrap: "
                       f"{sorted(remaining)}")
