"""Peer control plane + bootstrap verify.

The reference fans control operations out to every node over peer REST
(cmd/peer-rest-client.go / cmd/peer-rest-server.go, aggregated by
NotificationSys, cmd/notification.go) and verifies cluster config
consistency at startup against the first node
(cmd/bootstrap-peer-server.go verifyServerSystemConfig).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Callable, Optional

from ..utils import healthtrack, knobs, telemetry
from .transport import NetworkError, RestClient, RPCError, RPCHandler

PEER_RPC_PREFIX = "/minio/peer/v1"
BOOTSTRAP_RPC_PREFIX = "/minio/bootstrap/v1"

# per-peer partition sheds: fan-out calls that failed fast because the
# peer's transport was already marked offline — bounded degradation's
# "we didn't even dial" counter
_PARTITION_SHED = telemetry.REGISTRY.counter(
    "minio_tpu_net_partition_shed_total",
    "Cross-peer fan-out calls shed fast (peer transport offline)")


class PeerRPCServer:
    """This node's control-plane verbs. Hooks are injected so the server
    stays decoupled from the subsystems it pokes."""

    def __init__(self, access_key: str, secret_key: str,
                 node_id: str = ""):
        self.handler = RPCHandler(PEER_RPC_PREFIX, access_key,
                                  secret_key, node_id=node_id)
        self.node_id = node_id
        self.started = time.time()
        # injectable hooks
        self.get_server_info: Callable[[], dict] = lambda: {}
        self.get_locks: Callable[[], dict] = lambda: {}
        self.reload_bucket_metadata: Callable[[str], None] = lambda b: None
        self.reload_iam: Callable[[], None] = lambda: None
        # granular IAM delta application (reference per-entity
        # LoadUser/LoadGroup/LoadPolicy verbs); falls back to reload_iam
        self.apply_iam_delta: Optional[Callable[[str, str], None]] = None
        # bounded-staleness self-heal: peers also refresh the full IAM
        # cache periodically (cluster wires this), so a delta lost to a
        # transient partition can't diverge a node forever
        self.signal_service: Callable[[str], None] = lambda sig: None
        self.get_metrics: Callable[[], dict] = lambda: {}
        # federated metrics scrape: this node's full Prometheus text
        # exposition (the admin ?cluster=1 merge pulls one per peer)
        self.get_metrics_text: Callable[[], str] = lambda: ""
        # live trace subscription: the TraceSys pub/sub hub (follow
        # streams subscribe; None until the cluster wires it)
        self.trace_hub = None
        # live event-journal subscription: the EventJournal pub/sub hub
        # (incident-plane follow streams; None until the cluster wires
        # it) plus the recent-window / incident readback hooks
        self.event_hub = None
        self.get_events: Callable[[], list] = lambda: []
        self.list_incidents: Callable[[], list] = lambda: []
        self.get_incident: Callable[[str], Optional[dict]] = \
            lambda inc_id: None
        self.get_storage_info: Callable[[], dict] = lambda: {}
        self.get_trace: Callable[[], list] = lambda: []
        self.get_bucket_usage: Callable[[], dict] = lambda: {}
        self.obd_drive_paths: list[str] = []
        # leader heal-scanner pulls + rotates this node's data-update
        # tracker each pass (None until the cluster wires it)
        self.get_update_tracker: Optional[Callable[[], dict]] = None
        self.get_bandwidth: Callable[[], dict] = lambda: {}
        # bucket event notification plane: owner-node delivery hand-off
        # (a non-owner forwards the namespace event here) and registry
        # reload after an admin target mutation elsewhere
        self.notify_event: Callable[[str, str], None] = lambda b, k: None
        self.notify_reload: Callable[[], object] = lambda: None

        h = self.handler
        h.register("server-info", lambda a, b: {
            "node": self.node_id, "uptime": time.time() - self.started,
            **self.get_server_info()})
        h.register("locks", lambda a, b: self.get_locks())
        h.register("reload-bucket-metadata", self._reload_bm)
        h.register("reload-iam", lambda a, b: self.reload_iam())
        h.register("iam-delta", self._iam_delta)
        h.register("signal", self._signal)
        h.register("metrics", lambda a, b: self.get_metrics())
        h.register("metrics-text",
                   lambda a, b: self.get_metrics_text().encode())
        h.register("storage-info", lambda a, b: self.get_storage_info())
        h.register("trace", lambda a, b: self.get_trace())
        h.register("trace-stream", self._trace_stream)
        h.register("events", lambda a, b: self.get_events())
        h.register("event-stream", self._event_stream)
        h.register("incidents", lambda a, b: self.list_incidents())
        h.register("incident", self._incident)
        h.register("bucket-usage", lambda a, b: self.get_bucket_usage())
        # profiling fan-out (cmd/admin-handlers.go:461-525 peer verbs),
        # console-log ring, OBD bundle (peer-rest-common.go:29-56)
        h.register("profiling-start", self._profiling_start)
        h.register("profiling-stop", self._profiling_stop)
        h.register("console-log", self._console_log)
        h.register("obd", self._obd)
        # OBD net perf: the caller times pushing a payload here; this
        # side only confirms how much arrived (cmd/obdinfo.go's
        # peer-to-peer net throughput probes)
        h.register("net-probe", lambda a, b: {
            "node": self.node_id, "received": len(b)})
        h.register("tracker-rotate", self._tracker_rotate)
        h.register("bandwidth", lambda a, b: self.get_bandwidth())
        h.register("notify-event", self._notify_event)
        h.register("notify-reload", lambda a, b: self.notify_reload())

    def _tracker_rotate(self, args, body):
        if self.get_update_tracker is None:
            return {}
        return self.get_update_tracker()

    def _trace_stream(self, args, body):
        """Live trace subscription (the peer half of a cluster-wide
        ?follow=1 stream): ND-JSON entries from this node's TraceSys
        hub as a chunked response. Idle windows emit bare newline
        heartbeats so a dead subscriber's next write fails and the
        subscription unwinds instead of leaking; blank lines are
        skipped by the merging side. `max_s` bounds the stream's life
        (the caller re-subscribes — a forgotten stream can't pin the
        hub forever)."""
        if self.trace_hub is None:
            return b""
        try:
            max_s = float(args.get("max_s", "3600") or 3600)
        except ValueError:
            max_s = 3600.0
        hub = self.trace_hub

        def gen():
            deadline = time.monotonic() + max(max_s, 1.0)
            with hub.subscribe() as sub:
                while time.monotonic() < deadline:
                    entry = sub.get(timeout=1.0)
                    if entry is None:
                        yield b"\n"              # heartbeat
                        continue
                    yield (json.dumps(entry) + "\n").encode()

        return gen()

    def _event_stream(self, args, body):
        """Live event-journal subscription (the peer half of a
        cluster-wide /events?follow=1): same ND-JSON + heartbeat +
        max_s contract as _trace_stream, fed by the EventJournal
        hub."""
        if self.event_hub is None:
            return b""
        try:
            max_s = float(args.get("max_s", "3600") or 3600)
        except ValueError:
            max_s = 3600.0
        hub = self.event_hub

        def gen():
            deadline = time.monotonic() + max(max_s, 1.0)
            with hub.subscribe() as sub:
                while time.monotonic() < deadline:
                    entry = sub.get(timeout=1.0)
                    if entry is None:
                        yield b"\n"              # heartbeat
                        continue
                    yield (json.dumps(entry) + "\n").encode()

        return gen()

    def _incident(self, args, body):
        doc = self.get_incident(args.get("id", ""))
        return doc if isinstance(doc, dict) else {}

    def _profiling_start(self, args, body):
        from ..utils import profiling
        kinds = profiling.parse_kinds(args.get("kinds", "cpu")) or ["cpu"]
        return {"node": self.node_id,
                "started": {k: profiling.start(k) for k in kinds}}

    def _profiling_stop(self, args, body):
        from ..utils import profiling
        kinds = profiling.parse_kinds(args.get("kinds", "cpu")) or ["cpu"]
        return {"node": self.node_id,
                "profiles": {k: profiling.stop_text(k) or ""
                             for k in kinds}}

    def _console_log(self, args, body):
        from ..utils.console import get_console
        try:
            n = int(args.get("count", "0") or 0)
        except ValueError:
            n = 0
        return {"node": self.node_id,
                "entries": get_console().recent(n)}

    def _obd(self, args, body):
        from ..utils.obd import local_obd
        out = local_obd(self.obd_drive_paths)
        out["node"] = self.node_id
        return out

    def _iam_delta(self, args, body):
        # one RPC carries the whole mutation cascade (remove_user emits
        # user + mapping + every derived svcacct/sts in one batch)
        pairs: list = []
        if body:
            # malformed body MUST error (-> 500 -> the sender falls
            # back to a wholesale reload); a silent 200 ack would drop
            # the delta with no recovery until the periodic refresh
            raw = json.loads(body.decode())
            pairs = [(str(k), str(n)) for k, n in raw]
        elif args.get("kind"):
            pairs = [(args.get("kind", ""), args.get("name", ""))]
        if not pairs:
            raise ValueError("empty iam-delta")
        if self.apply_iam_delta is not None:
            for kind, name in pairs:
                self.apply_iam_delta(kind, name)
        else:
            self.reload_iam()

    def _reload_bm(self, args, body):
        self.reload_bucket_metadata(args.get("bucket", ""))

    def _notify_event(self, args, body):
        self.notify_event(args.get("bucket", ""), args.get("key", ""))

    def _signal(self, args, body):
        self.signal_service(args.get("sig", ""))

    def route(self, ctx):
        return self.handler.route(ctx)


class PeerRPCClient:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, timeout: float = 5.0,
                 node_id: str = ""):
        self.rc = RestClient(host, port, PEER_RPC_PREFIX, access_key,
                             secret_key, timeout=timeout)
        self.rc.node_id = node_id

    def _shed(self) -> bool:
        """Fail-fast gate for fan-out verbs: a peer whose transport is
        already marked offline (partitioned / down) is shed without
        dialing — counted per peer so a partition window is visible as
        sheds, not as silent Nones."""
        if self.rc.online:
            return False
        _PARTITION_SHED.inc(peer=self.addr)
        return True

    def _fanout_deadline(self, default: float) -> float:
        """Healthtrack-derived deadline tightening: once this peer's
        observed p99 is known, a fan-out should not wait the full
        default on it — bounded degradation keys the wait to how the
        peer actually behaves, floored so a healthy-but-busy peer is
        not shed on one slow sample."""
        x = knobs.get_float("MINIO_TPU_PEER_SHED_DEADLINE_X")
        if x <= 0:
            return default
        p99 = healthtrack.TRACKER.percentile("peer", self.addr, 0.99)
        if p99 is None:
            return default
        return max(0.5, min(default, p99 * x))

    def server_info(self) -> Optional[dict]:
        if self._shed():
            return None
        try:
            return self.rc.call_json("server-info")
        except (NetworkError, RPCError):
            return None

    def locks(self) -> dict:
        try:
            return self.rc.call_json("locks") or {}
        except (NetworkError, RPCError):
            return {}

    def reload_bucket_metadata(self, bucket: str) -> bool:
        try:
            self.rc.call("reload-bucket-metadata", {"bucket": bucket})
            return True
        except (NetworkError, RPCError):
            return False

    def reload_iam(self) -> bool:
        try:
            self.rc.call("reload-iam")
            return True
        except (NetworkError, RPCError):
            return False

    def notify_event(self, bucket: str, key: str) -> bool:
        """Hand one namespace event to this peer (the bucket's owner)
        for notification delivery."""
        if self._shed():
            return False
        try:
            self.rc.call("notify-event", {"bucket": bucket, "key": key})
            return True
        except (NetworkError, RPCError):
            return False

    def notify_reload(self) -> bool:
        try:
            self.rc.call("notify-reload")
            return True
        except (NetworkError, RPCError):
            return False

    def iam_delta(self, pairs: list) -> bool:
        try:
            self.rc.call_json("iam-delta", payload=list(pairs))
            return True
        except (NetworkError, RPCError):
            return False

    def signal_service(self, sig: str) -> bool:
        try:
            self.rc.call("signal", {"sig": sig})
            return True
        except (NetworkError, RPCError):
            return False

    def metrics(self) -> dict:
        try:
            return self.rc.call_json("metrics") or {}
        except (NetworkError, RPCError):
            return {}

    @property
    def addr(self) -> str:
        return f"{self.rc.host}:{self.rc.port}"

    def metrics_text(self, deadline: float = 2.0) -> Optional[str]:
        """This peer's Prometheus text exposition, or None on failure
        — the federated scrape's per-peer pull, bounded by `deadline`
        (tightened further by the peer's observed latency) so one dead
        peer degrades the cluster scrape instead of stalling it."""
        if self._shed():
            return None
        try:
            out = self.rc.call("metrics-text",
                               deadline=self._fanout_deadline(deadline))
        except (NetworkError, RPCError):
            return None
        try:
            return out.decode()
        except UnicodeDecodeError:
            return None

    def trace_stream(self, max_s: float = 3600.0):
        """Open this peer's live trace subscription: returns an
        iterator of entry dicts (ends on peer death / stream close),
        or None when the peer is unreachable. `.close()` on the
        returned iterator tears the connection down."""
        if self._shed():
            return None
        try:
            resp = self.rc.call("trace-stream",
                                {"max_s": str(max_s)},
                                stream_response=True,
                                deadline=max(max_s, 60.0))
        except (NetworkError, RPCError):
            return None
        return _TraceLineIter(resp, self.addr)

    def event_stream(self, max_s: float = 3600.0):
        """Open this peer's live event-journal subscription — same
        contract as trace_stream (entry-dict iterator or None;
        `.close()` tears the connection down)."""
        if self._shed():
            return None
        try:
            resp = self.rc.call("event-stream",
                                {"max_s": str(max_s)},
                                stream_response=True,
                                deadline=max(max_s, 60.0))
        except (NetworkError, RPCError):
            return None
        return _TraceLineIter(resp, self.addr)

    def events(self) -> list:
        """This peer's recent journal window (?cluster=1 merges)."""
        if self._shed():
            return []
        try:
            return self.rc.call_json("events") or []
        except (NetworkError, RPCError):
            return []

    def incidents(self) -> list:
        if self._shed():
            return []
        try:
            return self.rc.call_json("incidents") or []
        except (NetworkError, RPCError):
            return []

    def incident(self, inc_id: str) -> Optional[dict]:
        if self._shed():
            return None
        try:
            doc = self.rc.call_json("incident", {"id": inc_id})
        except (NetworkError, RPCError):
            return None
        return doc if isinstance(doc, dict) and doc else None

    def storage_info(self) -> dict:
        if self._shed():
            return {}
        try:
            return self.rc.call_json("storage-info") or {}
        except (NetworkError, RPCError):
            return {}

    def trace(self) -> list:
        if self._shed():
            return []
        try:
            return self.rc.call_json("trace") or []
        except (NetworkError, RPCError):
            return []

    def bucket_usage(self) -> dict:
        try:
            return self.rc.call_json("bucket-usage") or {}
        except (NetworkError, RPCError):
            return {}

    def profiling_start(self, kinds: str = "cpu") -> Optional[dict]:
        try:
            return self.rc.call_json("profiling-start",
                                     {"kinds": kinds})
        except (NetworkError, RPCError):
            return None

    def profiling_stop(self, kinds: str = "cpu") -> Optional[dict]:
        try:
            return self.rc.call_json("profiling-stop",
                                     {"kinds": kinds})
        except (NetworkError, RPCError):
            return None

    def console_log(self, count: int = 0) -> Optional[dict]:
        try:
            return self.rc.call_json("console-log",
                                     {"count": str(count)})
        except (NetworkError, RPCError):
            return None

    def obd(self) -> Optional[dict]:
        try:
            return self.rc.call_json("obd")
        except (NetworkError, RPCError):
            return None

    def net_probe(self, size: int = 4 << 20) -> Optional[dict]:
        """Timed payload push to this peer: internode throughput + a
        small-ping RTT (the OBD net perf section). Each RestClient call
        opens a fresh connection, so a warm-up ping runs first and the
        empty-call baseline (connect + request overhead) is subtracted
        from the payload timing — the reported throughput approximates
        the transfer itself, not TCP setup."""
        import json as _json
        try:
            self.rc.call("net-probe", body=b"")     # warm-up, untimed
            rtt = None
            for _ in range(2):
                t0 = time.perf_counter()
                self.rc.call("net-probe", body=b"")
                dt = time.perf_counter() - t0
                rtt = dt if rtt is None else min(rtt, dt)
            payload = b"\x00" * size
            t0 = time.perf_counter()
            raw = self.rc.call("net-probe", body=payload)
            dt = max(time.perf_counter() - t0 - (rtt or 0.0), 1e-9)
            out = _json.loads(raw.decode()) if raw else None
        except (NetworkError, RPCError, ValueError):
            return None
        if not isinstance(out, dict):
            return None
        if out.get("received") != size:
            # reachable but truncated (proxy/body limit) — distinct
            # from peer-down so the operator chases the right problem
            return {"peer": f"{self.rc.host}:{self.rc.port}",
                    "error": "short receive",
                    "expected": size, "received": out.get("received")}
        return {"peer": f"{self.rc.host}:{self.rc.port}",
                "bytes": size,
                "rtt_us": round((rtt or 0.0) * 1e6),
                "throughput_mib_s": round(size / dt / 2**20, 2)}

    def tracker_rotate(self) -> Optional[dict]:
        try:
            return self.rc.call_json("tracker-rotate")
        except (NetworkError, RPCError):
            return None

    def bandwidth(self) -> dict:
        try:
            return self.rc.call_json("bandwidth") or {}
        except (NetworkError, RPCError):
            return {}

    @property
    def online(self) -> bool:
        return self.rc.online

    def close(self) -> None:
        self.rc.close()


class _TraceLineIter:
    """ND-JSON line iterator over a streamed trace-stream response:
    yields entry dicts, skips heartbeat blanks, ends (never raises) on
    any transport fault. close() tears down the underlying connection
    — the unblocking lever the merging side pulls from another
    thread."""

    def __init__(self, resp, peer: str):
        self._resp = resp
        self.peer = peer
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        while not self._closed:
            try:
                # readline, not read(n): chunked read(n) waits for n
                # bytes, and a mostly-idle peer trickles 1-byte
                # heartbeats — lines must surface as they arrive
                # check: allow(deadline) _resp is a _StreamedResponse; it arms the per-read socket deadline itself
                line = self._resp.readline()
            except Exception:  # noqa: BLE001 — peer died: end of stream
                raise StopIteration from None
            if not line:
                raise StopIteration
            if not line.strip():
                continue                          # heartbeat
            try:
                entry = json.loads(line.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            if isinstance(entry, dict):
                return entry
        raise StopIteration

    def close(self) -> None:
        self._closed = True
        try:
            self._resp.close()
        except Exception:  # noqa: BLE001 — already torn down
            pass


class NotificationSys:
    """Fan-out aggregator over all peer clients (cmd/notification.go):
    each call broadcasts concurrently and returns per-peer results."""

    def __init__(self, peers: list[PeerRPCClient]):
        self.peers = peers

    def _broadcast(self, fn: Callable[[PeerRPCClient], object]) -> list:
        out: list = [None] * len(self.peers)
        threads = []
        for i, p in enumerate(self.peers):
            def run(i=i, p=p):
                try:
                    out[i] = fn(p)
                except Exception as e:  # noqa: BLE001 — per-peer result
                    out[i] = e
            t = threading.Thread(target=run, daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=10)
        return out

    def server_info_all(self) -> list:
        return self._broadcast(lambda p: p.server_info())

    def reload_bucket_metadata(self, bucket: str) -> list:
        return self._broadcast(
            lambda p: p.reload_bucket_metadata(bucket))

    def reload_iam(self) -> list:
        return self._broadcast(lambda p: p.reload_iam())

    def notify_reload(self) -> list:
        """Reload every peer's notification-target registry (after an
        admin target mutation here — their boot-time loads are stale)."""
        return self._broadcast(lambda p: p.notify_reload())

    def iam_delta(self, pairs: list) -> list:
        """Per-entity IAM propagation: one small RPC per peer carrying
        the mutation's whole (kind, name) batch — not an O(all-entities)
        store re-walk. A peer that misses the delta gets a wholesale
        reload attempt instead; one that misses both is offline and
        re-syncs via its periodic refresh / boot-time load."""
        def one(p: PeerRPCClient) -> bool:
            return p.iam_delta(pairs) or p.reload_iam()
        return self._broadcast(one)

    def top_locks(self) -> dict:
        merged: dict = {}
        for locks in self._broadcast(lambda p: p.locks()):
            if isinstance(locks, dict):
                for res, holders in locks.items():
                    merged.setdefault(res, []).extend(holders)
        return merged

    def signal_all(self, sig: str) -> list:
        return self._broadcast(lambda p: p.signal_service(sig))

    def storage_info_all(self) -> list:
        return self._broadcast(lambda p: p.storage_info())

    def trace_all(self) -> list[dict]:
        """Cluster-wide recent trace entries, time-ordered."""
        merged: list[dict] = []
        for entries in self._broadcast(lambda p: p.trace()):
            if isinstance(entries, list):
                merged.extend(e for e in entries if isinstance(e, dict))
        merged.sort(key=lambda e: e.get("time", ""))
        return merged

    def metrics_text_all(self, deadline: float = 2.0
                         ) -> list[tuple[str, Optional[str]]]:
        """One (peer_addr, exposition_text | None) per peer — the
        federated scrape's fan-out; None marks a peer the caller must
        count as scrape-failed rather than fail the whole scrape."""
        results = self._broadcast(
            lambda p: p.metrics_text(deadline=deadline))
        return [(p.addr, r if isinstance(r, str) else None)
                for p, r in zip(self.peers, results)]

    def trace_stream_all(self, max_s: float = 3600.0) -> list:
        """One live trace-entry iterator per reachable peer (see
        PeerRPCClient.trace_stream)."""
        return self._stream_all(
            lambda p: p.trace_stream(max_s=max_s))

    def event_stream_all(self, max_s: float = 3600.0) -> list:
        """One live event-journal iterator per reachable peer (see
        PeerRPCClient.event_stream) — the /events?follow=1&cluster=1
        fan-out."""
        return self._stream_all(
            lambda p: p.event_stream(max_s=max_s))

    def _stream_all(self, open_one: Callable[[PeerRPCClient],
                                             object]) -> list:
        """Open one live subscription per reachable peer.
        Subscriptions open concurrently; unreachable peers are simply
        absent — a follow stream degrades to the nodes it can hear. A
        peer that answers only AFTER the collection window has its
        subscription closed by the opener thread itself (nobody else
        will ever see it — an unclosed late iterator would pin that
        peer's hub + a worker for max_s)."""
        results: list = [None] * len(self.peers)
        mu = threading.Lock()
        done = [False]

        def run(i: int, p: PeerRPCClient) -> None:
            r = None
            try:
                r = open_one(p)
            except Exception:  # noqa: BLE001 — peer absent
                r = None
            late = None
            with mu:
                if done[0]:
                    late = r
                else:
                    results[i] = r
            if late is not None:
                late.close()

        threads = [threading.Thread(target=run, args=(i, p),
                                    daemon=True)
                   for i, p in enumerate(self.peers)]
        for t in threads:
            t.start()
        # ONE shared deadline across peers: per-thread join(10) would
        # stall a follow stream's start ~10s PER black-holed peer
        end = time.monotonic() + 10
        for t in threads:
            t.join(timeout=max(end - time.monotonic(), 0))
        with mu:
            done[0] = True
            return [r for r in results
                    if isinstance(r, _TraceLineIter)]

    def events_all(self) -> list[dict]:
        """Cluster-wide recent journal entries, time-ordered (the
        /events?cluster=1 merge)."""
        merged: list[dict] = []
        for entries in self._broadcast(lambda p: p.events()):
            if isinstance(entries, list):
                merged.extend(e for e in entries
                              if isinstance(e, dict))
        merged.sort(key=lambda e: e.get("ts", 0))
        return merged

    def incidents_all(self) -> list[dict]:
        """Cluster-wide incident-bundle summaries, newest first."""
        merged: list[dict] = []
        for entries in self._broadcast(lambda p: p.incidents()):
            if isinstance(entries, list):
                merged.extend(e for e in entries
                              if isinstance(e, dict))
        merged.sort(key=lambda e: e.get("time") or 0, reverse=True)
        return merged

    def incident_any(self, inc_id: str) -> Optional[dict]:
        """Fetch one bundle from whichever peer holds it (bundles are
        node-local; 'retrievable from either node' means asking
        around)."""
        for doc in self._broadcast(lambda p: p.incident(inc_id)):
            if isinstance(doc, dict) and doc:
                return doc
        return None

    def profiling_start_all(self, kinds: str = "cpu") -> list:
        return self._broadcast(lambda p: p.profiling_start(kinds))

    def profiling_stop_all(self, kinds: str = "cpu") -> list:
        return self._broadcast(lambda p: p.profiling_stop(kinds))

    def console_log_all(self, count: int = 0) -> list[dict]:
        """Cluster-wide console entries, time-ordered."""
        merged: list[dict] = []
        for res in self._broadcast(lambda p: p.console_log(count)):
            if isinstance(res, dict):
                merged.extend(e for e in res.get("entries", [])
                              if isinstance(e, dict))
        merged.sort(key=lambda e: e.get("ts", 0))
        return merged

    def obd_all(self) -> list[dict]:
        return [r for r in self._broadcast(lambda p: p.obd())
                if isinstance(r, dict)]

    def net_obd(self, size: int = 4 << 20) -> list[dict]:
        """This node's view of the interconnect: timed payload push to
        every peer, SEQUENTIALLY — concurrent probes would share the
        NIC and report contention, not per-link capacity (the reference
        probes peers one at a time for the same reason). Unreachable
        peers are reported as such rather than dropped."""
        out = []
        for p in self.peers:
            r = None
            try:
                r = p.net_probe(size)
            except Exception:  # noqa: BLE001 — per-peer result
                r = None
            if isinstance(r, dict):
                out.append(r)
            else:
                out.append({"peer": f"{p.rc.host}:{p.rc.port}",
                            "error": "unreachable"})
        return out

    def tracker_rotate_all(self) -> list[Optional[dict]]:
        """One entry per peer: the rotated tracker snapshot, or None
        for an unreachable peer (the scanner must then assume-changed
        for that peer's window)."""
        return [r if isinstance(r, dict) else None
                for r in self._broadcast(lambda p: p.tracker_rotate())]

    def bandwidth_all(self) -> list[dict]:
        return [r for r in self._broadcast(lambda p: p.bandwidth())
                if isinstance(r, dict)]


# ---------------------------------------------------------------------------
# bootstrap verify
# ---------------------------------------------------------------------------

def system_config_hash(endpoints: list[str], access_key: str,
                       secret_key: str) -> str:
    """Digest of the node's view of cluster topology + credentials
    (the reference compares ServerSystemConfig field-by-field; a digest
    keeps secrets off the wire)."""
    blob = json.dumps({
        "endpoints": sorted(endpoints),
        "cred": hashlib.sha256(
            f"{access_key}:{secret_key}".encode()).hexdigest(),
    }, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


class BootstrapRPCServer:
    def __init__(self, access_key: str, secret_key: str,
                 endpoints: list[str]):
        self.handler = RPCHandler(BOOTSTRAP_RPC_PREFIX, access_key,
                                  secret_key)
        self.config_hash = system_config_hash(endpoints, access_key,
                                              secret_key)
        self.handler.register(
            "verify", lambda a, b: {"hash": self.config_hash})

    def route(self, ctx):
        return self.handler.route(ctx)


def verify_server_system_config(peers: list[tuple[str, int]],
                                endpoints: list[str], access_key: str,
                                secret_key: str, retries: int = 30,
                                interval: float = 1.0) -> None:
    """Block until every peer reports the same config digest
    (cmd/server-main.go:464-478 retry loop). Raises RuntimeError on a
    real mismatch; keeps retrying while peers are unreachable."""
    want = system_config_hash(endpoints, access_key, secret_key)
    remaining = {f"{h}:{p}" for h, p in peers}
    for _ in range(retries):
        for h, p in list(peers):
            key = f"{h}:{p}"
            if key not in remaining:
                continue
            rc = RestClient(h, p, BOOTSTRAP_RPC_PREFIX, access_key,
                            secret_key, timeout=2.0)
            try:
                got = rc.call_json("verify")
            except (NetworkError, RPCError):
                continue
            finally:
                rc.close()
            if got and got.get("hash") == want:
                remaining.discard(key)
            elif got:
                raise RuntimeError(
                    f"peer {key} has a different cluster config "
                    "(endpoints or credentials mismatch)")
        if not remaining:
            return
        time.sleep(interval)
    raise RuntimeError(f"peers unreachable during bootstrap: "
                       f"{sorted(remaining)}")
