"""Local lock table — the per-node NetLocker (cmd/local-locker.go).

Holds lock grants for the resources this node is responsible for: a map
resource -> list of lockRequesterInfo {uid, owner, writer?, timestamp}.
Write locks are exclusive; read locks stack. Stale grants past the
expiry window are swept (the reference's lock-rest-server maintenance
loop, cmd/lock-rest-server.go lockMaintenance).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

LOCK_VALIDITY = 120.0      # seconds before an un-refreshed grant is stale


@dataclasses.dataclass
class LockInfo:
    uid: str
    owner: str
    source: str
    writer: bool
    timestamp: float


class LocalLocker:
    """NetLocker implementation backing both in-process dsync and the
    lock RPC server."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._map: dict[str, list[LockInfo]] = {}

    # -- NetLocker verbs ---------------------------------------------------

    def lock(self, uid: str, resources: list[str], owner: str = "",
             source: str = "") -> bool:
        """Exclusive write lock on all resources, all-or-nothing."""
        with self._mu:
            if any(self._map.get(r) for r in resources):
                return False
            now = time.time()
            for r in resources:
                self._map[r] = [LockInfo(uid, owner, source, True, now)]
            return True

    def rlock(self, uid: str, resources: list[str], owner: str = "",
              source: str = "") -> bool:
        """Shared read lock (single resource in practice)."""
        with self._mu:
            for r in resources:
                holders = self._map.get(r)
                if holders and holders[0].writer:
                    return False
            now = time.time()
            for r in resources:
                self._map.setdefault(r, []).append(
                    LockInfo(uid, owner, source, False, now))
            return True

    def unlock(self, uid: str, resources: list[str]) -> bool:
        with self._mu:
            ok = False
            for r in resources:
                holders = self._map.get(r, [])
                kept = [h for h in holders if h.uid != uid]
                if len(kept) != len(holders):
                    ok = True
                if kept:
                    self._map[r] = kept
                else:
                    self._map.pop(r, None)
            return ok

    runlock = unlock

    def force_unlock(self, resources: list[str]) -> bool:
        with self._mu:
            for r in resources:
                self._map.pop(r, None)
            return True

    # -- introspection / maintenance ---------------------------------------

    def dump(self) -> dict[str, list[dict]]:
        """Current grants (admin Top Locks)."""
        with self._mu:
            return {r: [dataclasses.asdict(h) for h in holders]
                    for r, holders in self._map.items()}

    def expire_old_locks(self, validity: float = LOCK_VALIDITY) -> int:
        """Sweep grants older than `validity`; returns count removed."""
        cutoff = time.time() - validity
        removed = 0
        with self._mu:
            for r in list(self._map):
                kept = [h for h in self._map[r] if h.timestamp >= cutoff]
                removed += len(self._map[r]) - len(kept)
                if kept:
                    self._map[r] = kept
                else:
                    self._map.pop(r, None)
        return removed

    def refresh(self, uid: str, resources: list[str]) -> bool:
        """Bump timestamps for a held lock (keeps long ops alive)."""
        now = time.time()
        ok = False
        with self._mu:
            for r in resources:
                for h in self._map.get(r, []):
                    if h.uid == uid:
                        h.timestamp = now
                        ok = True
        return ok
