"""Bitrot protection algorithms and shard-file framing math.

Mirrors the reference's bitrot surface (cmd/bitrot.go): four algorithms
with the same string names, HighwayHash256 keyed by the magic pi-digest
key, and the streaming variant ("highwayhash256S") that interleaves a
32-byte digest before every shard block in the shard file
(cmd/bitrot-streaming.go framing: [hash || block]*). The default algorithm
is HighwayHash256S (reference default: cmd/xl-storage-format-v1.go:119).

Engine selection follows the fork's accelerator pattern (the reference
fork's QAT engine pick in pkg/hash/reader.go:189-206): native C++ library
when available, pure-Python fallback otherwise; the TPU batch path hashes
whole shard batches device-side (ops/ + models/).
"""

from __future__ import annotations

import enum
import hashlib
from typing import Protocol

import numpy as np

# HH-256 of the first 100 decimals of pi (utf-8) with a zero key — verified
# reproducible by our own HighwayHash (see tests/test_bitrot.py).
MAGIC_HIGHWAYHASH_KEY = bytes.fromhex(
    "4be734fa8e238acd263e83e6bb968552040f935da39f441497e09d1322de36a0")


class BitrotAlgorithm(enum.Enum):
    SHA256 = "sha256"
    BLAKE2B512 = "blake2b"
    HIGHWAYHASH256 = "highwayhash256"
    HIGHWAYHASH256S = "highwayhash256S"

    @property
    def streaming(self) -> bool:
        """Streaming algorithms frame a digest per shard block inside the
        shard file; whole-file algorithms store one digest in metadata."""
        return self is BitrotAlgorithm.HIGHWAYHASH256S

    @property
    def digest_size(self) -> int:
        return 64 if self is BitrotAlgorithm.BLAKE2B512 else 32

    @classmethod
    def from_string(cls, s: str) -> "BitrotAlgorithm":
        for a in cls:
            if a.value == s:
                return a
        raise ValueError(f"unsupported bitrot algorithm: {s!r}")


DEFAULT_BITROT_ALGORITHM = BitrotAlgorithm.HIGHWAYHASH256S


class Hasher(Protocol):
    def update(self, data: bytes) -> None: ...
    def digest(self) -> bytes: ...


class _NativeHH256:
    """Streaming HighwayHash-256 over the native library."""

    def __init__(self) -> None:
        from .utils import native
        self._native = native
        self._state = np.zeros(128, dtype=np.uint8)
        key = np.frombuffer(MAGIC_HIGHWAYHASH_KEY, dtype=np.uint8)
        lib = native.get_lib()
        assert lib is not None
        self._lib = lib
        lib.hh_init(native._u8p(key), native._u8p(self._state))
        self._tail = b""

    def update(self, data: bytes) -> None:
        buf = self._tail + data
        full = len(buf) & ~31
        if full:
            d = np.frombuffer(buf[:full], dtype=np.uint8)
            self._lib.hh_update_packets(
                self._native._u8p(self._state), self._native._u8p(d), full)
        self._tail = buf[full:]

    def digest(self) -> bytes:
        state = self._state.copy()
        out = np.zeros(32, dtype=np.uint8)
        rem = np.frombuffer(self._tail, dtype=np.uint8) if self._tail else \
            np.zeros(0, dtype=np.uint8)
        self._lib.hh_final256(self._native._u8p(state),
                              self._native._u8p(rem), len(self._tail),
                              self._native._u8p(out))
        return out.tobytes()


class _PyHH256:
    def __init__(self) -> None:
        from .ops.highwayhash_py import HighwayHash
        self._h = HighwayHash(MAGIC_HIGHWAYHASH_KEY)

    def update(self, data: bytes) -> None:
        self._h.update(data)

    def digest(self) -> bytes:
        return self._h.digest256()


def new_hasher(algo: BitrotAlgorithm = DEFAULT_BITROT_ALGORITHM) -> Hasher:
    if algo in (BitrotAlgorithm.HIGHWAYHASH256,
                BitrotAlgorithm.HIGHWAYHASH256S):
        from .utils import native
        if native.available():
            return _NativeHH256()
        return _PyHH256()
    if algo is BitrotAlgorithm.SHA256:
        return hashlib.sha256()
    if algo is BitrotAlgorithm.BLAKE2B512:
        return hashlib.blake2b(digest_size=64)
    raise ValueError(f"unsupported bitrot algorithm: {algo}")


def hash_shard(data: bytes | np.ndarray,
               algo: BitrotAlgorithm = DEFAULT_BITROT_ALGORITHM) -> bytes:
    h = new_hasher(algo)
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data, np.uint8).tobytes()
    h.update(data)
    return h.digest()


def hash_shards_batch(shards: np.ndarray,
                      algo: BitrotAlgorithm = DEFAULT_BITROT_ALGORITHM
                      ) -> np.ndarray:
    """Digest every row of an (n, L) shard-block matrix -> (n, digest_size).

    One native call for HighwayHash (the per-encode-step hot path);
    hashlib loop otherwise.
    """
    shards = np.ascontiguousarray(shards, np.uint8)
    if algo in (BitrotAlgorithm.HIGHWAYHASH256,
                BitrotAlgorithm.HIGHWAYHASH256S):
        from .utils import native
        if native.available():
            return native.hh256_batch(MAGIC_HIGHWAYHASH_KEY, shards)
    out = np.zeros((shards.shape[0], algo.digest_size), dtype=np.uint8)
    for i in range(shards.shape[0]):
        out[i] = np.frombuffer(hash_shard(shards[i], algo), dtype=np.uint8)
    return out


def ceil_frac(num: int, den: int) -> int:
    return -(-num // den)


def bitrot_shard_file_size(size: int, shard_size: int,
                           algo: BitrotAlgorithm) -> int:
    """On-disk size of a shard file of `size` payload bytes.

    Streaming algorithms add one digest per shard block
    (reference math: cmd/bitrot.go:140-145)."""
    if not algo.streaming:
        return size
    if size <= 0:
        return size
    return ceil_frac(size, shard_size) * algo.digest_size + size
