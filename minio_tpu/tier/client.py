"""Warm-tier clients: where transitioned object data actually lives.

One small verb surface (put/get/head/delete) so the transition worker
and the restore path stay backend-agnostic (the reference's
WarmBackend interface, cmd/tier-handlers.go + cmd/warm-backend-*.go):

  * :class:`FSTierClient`       — a local directory (tests, NAS mounts)
  * :class:`GatewayTierClient`  — any of the existing gateway
    ObjectLayers (S3/Azure/GCS/HDFS) pinned to one bucket + prefix
  * :class:`NaughtyTierClient`  — deterministic fault wrapper (chaos
    tests: timeouts, 5xx-style errors, short reads on restore)

Remote keys are opaque strings minted by the tier manager; a client
must tolerate `/` in keys.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import uuid as _uuid
from typing import Iterator, Optional

_CHUNK = 1 << 20


class TierClientError(Exception):
    """Remote tier I/O failed (network, upstream 5xx, short object)."""


class TierObjectNotFound(TierClientError):
    """The remote copy is gone (never written, or already freed)."""


class TierClient:
    """Minimal warm-backend verb surface."""

    def put(self, key: str, reader, size: int) -> str:
        """Store `size` bytes from `reader` (file-like .read) under
        `key`; returns the backend's etag/version token ("" if none)."""
        raise NotImplementedError

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> Iterator[bytes]:
        raise NotImplementedError

    def head(self, key: str) -> int:
        """Size of the remote copy; raises TierObjectNotFound."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Free the remote copy (idempotent: missing key is a no-op)."""
        raise NotImplementedError


class FSTierClient(TierClient):
    """Filesystem tier: one directory, keys as relative paths. Writes
    are staged + atomically renamed so a crashed transition never
    leaves a short remote copy that `head` would then "verify"."""

    def __init__(self, path: str):
        self.root = os.path.abspath(path)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        fp = os.path.abspath(os.path.join(self.root, key))
        if not fp.startswith(self.root + os.sep):
            raise TierClientError(f"tier key escapes root: {key!r}")
        return fp

    def put(self, key: str, reader, size: int) -> str:
        fp = self._path(key)
        os.makedirs(os.path.dirname(fp), exist_ok=True)
        tmp = f"{fp}.tmp-{_uuid.uuid4().hex}"
        h = hashlib.md5()
        got = 0
        try:
            with open(tmp, "wb") as f:
                while size < 0 or got < size:
                    want = _CHUNK if size < 0 else min(_CHUNK, size - got)
                    chunk = reader.read(want)
                    if not chunk:
                        break
                    f.write(chunk)
                    h.update(chunk)
                    got += len(chunk)
            if 0 <= size != got:
                raise TierClientError(
                    f"short tier write: {got} of {size} bytes")
            os.replace(tmp, fp)
        except OSError as e:
            raise TierClientError(f"tier write failed: {e}") from e
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return h.hexdigest()

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> Iterator[bytes]:
        fp = self._path(key)
        try:
            f = open(fp, "rb")
        except FileNotFoundError:
            raise TierObjectNotFound(key) from None
        except OSError as e:
            raise TierClientError(f"tier read failed: {e}") from e

        def gen() -> Iterator[bytes]:
            with f:
                f.seek(offset)
                remaining = length
                while remaining != 0:
                    want = _CHUNK if remaining < 0 \
                        else min(_CHUNK, remaining)
                    chunk = f.read(want)
                    if not chunk:
                        return
                    if remaining > 0:
                        remaining -= len(chunk)
                    yield chunk

        return gen()

    def head(self, key: str) -> int:
        try:
            return os.path.getsize(self._path(key))
        except FileNotFoundError:
            raise TierObjectNotFound(key) from None
        except OSError as e:
            raise TierClientError(f"tier head failed: {e}") from e

    def delete(self, key: str) -> None:
        fp = self._path(key)
        try:
            os.unlink(fp)
        except FileNotFoundError:
            return
        except OSError as e:
            raise TierClientError(f"tier delete failed: {e}") from e
        # prune now-empty key directories back up to the root
        d = os.path.dirname(fp)
        while d.startswith(self.root + os.sep):
            try:
                os.rmdir(d)
            except OSError:
                return
            d = os.path.dirname(d)


class GatewayTierClient(TierClient):
    """Adapter: any gateway ObjectLayer (gateway/{s3,azure,gcs,...})
    pinned to one remote bucket + key prefix becomes a warm tier."""

    def __init__(self, layer, bucket: str, prefix: str = ""):
        self.layer = layer
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def _map(self, e: Exception, key: str) -> Exception:
        from ..object import api_errors
        if isinstance(e, (api_errors.ObjectNotFound,
                          api_errors.VersionNotFound)):
            return TierObjectNotFound(key)
        return TierClientError(f"tier backend error: {e!r}")

    def put(self, key: str, reader, size: int) -> str:
        from ..object import api_errors
        try:
            info = self.layer.put_object(self.bucket, self._key(key),
                                         reader, size)
        except api_errors.ObjectApiError as e:
            raise self._map(e, key) from None
        return getattr(info, "etag", "") or ""

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> Iterator[bytes]:
        from ..object import api_errors
        try:
            _, stream = self.layer.get_object(self.bucket, self._key(key),
                                              offset, length)
        except api_errors.ObjectApiError as e:
            raise self._map(e, key) from None
        return stream

    def head(self, key: str) -> int:
        from ..object import api_errors
        try:
            return self.layer.get_object_info(self.bucket,
                                              self._key(key)).size
        except api_errors.ObjectApiError as e:
            raise self._map(e, key) from None

    def delete(self, key: str) -> None:
        from ..object import api_errors
        try:
            self.layer.delete_object(self.bucket, self._key(key))
        except (api_errors.ObjectNotFound, api_errors.VersionNotFound):
            return
        except api_errors.ObjectApiError as e:
            raise self._map(e, key) from None


class NaughtyTierClient(TierClient):
    """Deterministic fault wrapper over a real tier client — the
    NaughtyDisk of the tier plane (storage/naughty.py's programmed-fault
    model applied to the warm backend):

      * ``fail_verbs[verb] = exc``       fail EVERY call of a verb
      * ``verb_errors[verb][n] = exc``   fail exactly the n-th call
        (1-based per verb, matching NaughtyDisk's errors map)
      * ``latency_s``                    sleep before every faulted verb
      * ``short_read_verbs``             truncate the returned stream
        (restore sees fewer bytes than head promised)

    Counters in ``stats`` record what was actually injected.
    """

    VERBS = ("put", "get", "head", "delete")

    def __init__(self, inner: TierClient,
                 fail_verbs: Optional[dict] = None,
                 verb_errors: Optional[dict] = None,
                 latency_s: float = 0.0,
                 short_read_verbs: tuple = ()):
        self.inner = inner
        self.fail_verbs = dict(fail_verbs or {})
        self.verb_errors = {v: dict(m)
                            for v, m in (verb_errors or {}).items()}
        self.latency_s = latency_s
        self.short_read_verbs = tuple(short_read_verbs)
        self._mu = threading.Lock()
        self.calls: dict[str, int] = {v: 0 for v in self.VERBS}
        self.stats = {"errors": 0, "latency": 0, "short_reads": 0}

    def clear_faults(self) -> None:
        with self._mu:
            self.fail_verbs.clear()
            self.verb_errors.clear()
            self.short_read_verbs = ()

    def _enter(self, verb: str) -> None:
        with self._mu:
            self.calls[verb] += 1
            n = self.calls[verb]
            err = self.fail_verbs.get(verb) \
                or self.verb_errors.get(verb, {}).get(n)
            lat = self.latency_s
        if lat:
            self.stats["latency"] += 1
            time.sleep(lat)
        if err is not None:
            self.stats["errors"] += 1
            raise err

    def put(self, key: str, reader, size: int) -> str:
        self._enter("put")
        return self.inner.put(key, reader, size)

    def get(self, key: str, offset: int = 0,
            length: int = -1) -> Iterator[bytes]:
        self._enter("get")
        stream = self.inner.get(key, offset, length)
        if "get" not in self.short_read_verbs:
            return stream

        def truncated() -> Iterator[bytes]:
            first = next(iter(stream), b"")
            if first:
                self.stats["short_reads"] += 1
                yield first[:max(1, len(first) // 2)]

        return truncated()

    def head(self, key: str) -> int:
        self._enter("head")
        return self.inner.head(key)

    def delete(self, key: str) -> None:
        self._enter("delete")
        self.inner.delete(key)


def new_tier_client(type_: str, params: dict) -> TierClient:
    """Client factory from a persisted tier config entry."""
    if type_ == "fs":
        path = params.get("path", "")
        if not path:
            raise TierClientError("fs tier needs a 'path'")
        return FSTierClient(path)
    if type_ == "s3":
        from ..s3.credentials import Credentials
        from ..utils.s3client import S3Client
        from ..gateway.s3 import S3GatewayObjects
        client = S3Client(params["host"], int(params.get("port", 9000)),
                          Credentials(params.get("access_key", ""),
                                      params.get("secret_key", "")),
                          params.get("region", "us-east-1"))
        return GatewayTierClient(S3GatewayObjects(client),
                                 params["bucket"],
                                 params.get("prefix", ""))
    if type_ in ("azure", "gcs", "hdfs"):
        from ..gateway import new_gateway
        kw = {k: v for k, v in params.items()
              if k not in ("bucket", "prefix")}
        return GatewayTierClient(new_gateway(type_, **kw),
                                 params["bucket"],
                                 params.get("prefix", ""))
    raise TierClientError(f"unknown tier type {type_!r} "
                          "(supported: fs, s3, azure, gcs, hdfs)")
