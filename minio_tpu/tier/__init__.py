"""Tiering plane: remote warm/cold tiers behind the hot erasure pools.

The reference's ILM tiering (cmd/tier.go + cmd/tier-handlers.go +
cmd/erasure-object.go transition paths): operators register named
remote tiers (S3 / Azure / GCS / filesystem), lifecycle ``Transition``
rules move cold objects' data there, the local ``xl.meta`` becomes a
zero-data stub, GETs answer ``InvalidObjectState`` until a
``RestoreObject`` pulls an expiring local copy back.

  * :mod:`.config`     — persisted, epoch-versioned tier registry
  * :mod:`.client`     — warm-tier client implementations + chaos wrapper
  * :mod:`.transition` — background transition worker, crawler actions,
                         restore + reclaim
"""

from .client import (FSTierClient, GatewayTierClient, NaughtyTierClient,
                     TierClientError, TierObjectNotFound,
                     new_tier_client)  # noqa: F401
from .config import TierConfig, TierManager, TIER_CONFIG_OBJECT  # noqa: F401
