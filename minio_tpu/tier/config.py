"""Persisted tier registry: named remote tiers, epoch-versioned.

The reference keeps tier configs in an encrypted object under the
hidden config bucket (cmd/tier.go, ``.minio.sys/tier-config.bin``) and
every lifecycle ``Transition`` rule names one. Here the registry is one
JSON doc — ``.minio.sys/tier/config.json`` — written to EVERY pool and
recovered highest-epoch-wins, exactly the durability rule the topology
plane uses (object/topology.py): any surviving subset of pools can
recover the newest registry, pools that missed an update converge on
the next save.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Optional

from ..object import api_errors
from ..utils import atomicfile, crashpoint, regfence
from ..storage.xl_storage import MINIO_META_BUCKET
from .client import TierClient, TierClientError, new_tier_client

TIER_PREFIX = "tier/"
TIER_CONFIG_OBJECT = TIER_PREFIX + "config.json"

# params whose values must never leave the server (admin GET redacts)
_SECRET_PARAMS = ("secret_key", "key_b64", "credentials_json")


class TierConfigError(api_errors.ObjectApiError):
    """Invalid tier operation (duplicate name, unknown name, bad spec)."""


@dataclasses.dataclass
class TierConfig:
    """One named remote tier: a type tag plus backend params
    (fs: path; s3: host/port/bucket/prefix/access_key/secret_key/region;
    azure/gcs: the gateway constructor kwargs + bucket/prefix)."""
    name: str
    type: str
    params: dict = dataclasses.field(default_factory=dict)

    def to_dict(self, redact: bool = False) -> dict:
        params = dict(self.params)
        if redact:
            for k in _SECRET_PARAMS:
                if params.get(k):
                    params[k] = "REDACTED"
        return {"name": self.name, "type": self.type, "params": params}

    @classmethod
    def from_dict(cls, d: dict) -> "TierConfig":
        name = str(d.get("name", "")).strip()
        type_ = str(d.get("type", "")).strip()
        if not name or not type_:
            raise TierConfigError("tier needs a name and a type")
        return cls(name=name, type=type_, params=dict(d.get("params") or {}))


class TierManager:
    """The live registry + client cache. Thread-safe; every mutation
    bumps ``epoch`` and persists BEFORE it takes effect (a crash
    mid-add replays, never forgets a tier the lifecycle already
    references)."""

    def __init__(self, object_layer=None):
        self.obj = object_layer
        self._mu = threading.Lock()
        self.epoch = 0
        self.updated = time.time()
        self.tiers: dict[str, TierConfig] = {}
        self._clients: dict[str, TierClient] = {}
        # lineage fencing: every epoch commit chains a hash of
        # (parent lineage, epoch, writer) — see utils/regfence.py
        self.writer = ""
        self.parent_lineage = ""
        self.lineage = ""

    def _advance_lineage(self) -> None:
        """Chain the fencing hash for the epoch just committed (caller
        holds ``_mu``)."""
        self.parent_lineage = self.lineage
        self.writer = regfence.default_writer()
        self.lineage = regfence.lineage(self.parent_lineage,
                                        self.epoch, self.writer)

    # ------------------------------------------------------------------
    # registry CRUD
    # ------------------------------------------------------------------

    def add(self, cfg: TierConfig, update: bool = False) -> int:
        """Register (or with ``update`` replace) a tier; verifies the
        client constructs before the registry mutates. Returns the new
        epoch."""
        try:
            client = new_tier_client(cfg.type, cfg.params)
        except (TierClientError, KeyError, ValueError) as e:
            raise TierConfigError(f"bad tier spec: {e}") from None
        with self._mu:
            if not update and cfg.name in self.tiers:
                raise TierConfigError(f"tier {cfg.name!r} already exists")
            prev = self.tiers.get(cfg.name)
            self.tiers[cfg.name] = cfg
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:          # roll the in-memory registry back
                if prev is None:
                    self.tiers.pop(cfg.name, None)
                else:
                    self.tiers[cfg.name] = prev
            raise
        with self._mu:
            self._clients[cfg.name] = client
        return epoch

    def remove(self, name: str) -> int:
        with self._mu:
            if name not in self.tiers:
                raise api_errors.TierNotFound(name)
            prev = self.tiers.pop(name)
            self._clients.pop(name, None)
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:
                self.tiers[name] = prev
            raise
        return epoch

    def list(self, redact: bool = True) -> list[dict]:
        with self._mu:
            return [t.to_dict(redact=redact)
                    for t in sorted(self.tiers.values(),
                                    key=lambda t: t.name)]

    def get(self, name: str) -> TierConfig:
        with self._mu:
            cfg = self.tiers.get(name)
        if cfg is None:
            raise api_errors.TierNotFound(name)
        return cfg

    def client(self, name: str) -> TierClient:
        with self._mu:
            c = self._clients.get(name)
            cfg = self.tiers.get(name)
        if c is not None:
            return c
        if cfg is None:
            raise api_errors.TierNotFound(name)
        c = new_tier_client(cfg.type, cfg.params)
        with self._mu:
            self._clients.setdefault(name, c)
        return c

    def set_client(self, name: str, client: TierClient) -> None:
        """Swap the live client of a registered tier (chaos tests wrap
        the real client in a NaughtyTierClient)."""
        self.get(name)
        with self._mu:
            self._clients[name] = client

    @staticmethod
    def remote_key(bucket: str, object_name: str, version_id: str) -> str:
        """Mint the remote object key for one transitioned version:
        unique per version (the reference stores a random remote name in
        xl.meta too — remote keys must survive local renames and never
        collide on overwrite)."""
        import uuid as _uuid
        vid = version_id or "null"
        return f"{bucket}/{object_name}/{vid}/{_uuid.uuid4().hex}"

    # ------------------------------------------------------------------
    # persistence (the topology plane's every-pool, highest-epoch rule)
    # ------------------------------------------------------------------

    def to_dict(self) -> dict:
        with self._mu:
            return {"epoch": self.epoch, "updated": self.updated,
                    "tiers": [t.to_dict() for t in self.tiers.values()],
                    "writer": self.writer,
                    "parent_lineage": self.parent_lineage,
                    "lineage": self.lineage}

    def _pools(self):
        if self.obj is None:
            return []
        return getattr(self.obj, "server_sets", None) or [self.obj]

    def save(self) -> int:
        """Write the registry to every pool; at least one copy must
        land or the mutation is rejected (caller rolls back)."""
        pools = self._pools()
        if not pools:
            return 0
        payload = json.dumps(self.to_dict()).encode()
        landed = 0
        last: Optional[Exception] = None
        for z in pools:
            try:
                # one hit per pool (arm :<nth>)
                crashpoint.hit("tier.save.pool")
                z.put_object(MINIO_META_BUCKET, TIER_CONFIG_OBJECT,
                             payload)
                landed += 1
            except Exception as e:  # noqa: BLE001 — per-pool durability
                last = e
        need = regfence.write_quorum(len(pools))
        if landed < need:
            # refusing a minority-side epoch bump (caller rolls back)
            raise TierConfigError(
                f"tier config epoch {self.epoch} persisted to {landed} "
                f"of {len(pools)} pool(s), need {need}: {last!r}")
        return landed

    def load(self) -> bool:
        """Recover the newest persisted registry (highest epoch across
        pools); returns True when a doc was found."""
        docs: list[dict] = []
        for z in self._pools():
            try:
                _, stream = z.get_object(MINIO_META_BUCKET,
                                         TIER_CONFIG_OBJECT)
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:     # torn/truncated copy: other pools win
                continue
            docs.append(doc)
        # deterministic winner; same-epoch/different-lineage copies are
        # a fork fsck surfaces — load never coin-flips between them
        best = regfence.pick_best(docs)
        if best is None:
            return False
        tiers = {}
        for d in best.get("tiers", []):
            try:
                cfg = TierConfig.from_dict(d)
            except TierConfigError:
                continue
            tiers[cfg.name] = cfg
        with self._mu:
            self.epoch = int(best.get("epoch", 0))
            self.updated = float(best.get("updated", time.time()))
            self.tiers = tiers
            self.writer = str(best.get("writer", ""))
            self.parent_lineage = str(best.get("parent_lineage", ""))
            self.lineage = str(best.get("lineage", ""))
            self._clients.clear()
        return True
