"""ILM transitions + RestoreObject: moving cold data to remote tiers.

The reference enforces lifecycle ``Transition`` rules from the data
crawler (cmd/data-crawler.go applyActions -> transitionObject,
cmd/erasure-object.go TransitionObject/RestoreTransitionedObject): the
object's data streams to the configured tier, the local ``xl.meta``
becomes a zero-data stub carrying the tier name + remote key, GETs
answer ``InvalidObjectState`` until ``RestoreObject`` pulls an expiring
local copy back, and the restore-expiry sweep reclaims that copy.

This module wires that flow to this repo's planes:

  * crawler actions (``transition_action`` for current versions,
    ``noncurrent_transition_action`` per bucket,
    ``restore_reclaim_action`` for expired restored copies) feed a
    bounded :class:`TransitionWorker` queue;
  * the worker moves object data through the tier client, verifies the
    remote copy (head size) BEFORE the engine rewrites xl.meta and
    frees local shards, and throttles off live ``BatchScheduler``
    occupancy + ``BytePool`` waits exactly like ``object/rebalance.py``
    (the shared ``utils/pressure.py`` probe);
  * failed transitions feed the source MRF heal queue (heal first,
    retry on the next crawler pass) and count in
    ``minio_tpu_tier_failed_total{tier}``;
  * per-object moves/restores are span roots (``tier.transition`` /
    ``tier.restore``) so slow tiers surface in ``/minio/admin/v3/spans``.
"""

from __future__ import annotations

import threading
import time
import xml.etree.ElementTree as ET
from collections import deque
from typing import Optional

from ..object import api_errors
from ..object.engine import GetOptions, PutOptions
from ..storage.datatypes import (RESTORE_EXPIRY_KEY, RESTORE_KEY,
                                 RESTORE_ONGOING, TRANSITION_TIER_KEY,
                                 TRANSITIONED_OBJECT_KEY,
                                 TRANSITIONED_VERSION_KEY, is_restored,
                                 is_transitioned)
from ..utils import knobs, telemetry
from ..utils.bandwidth import PacedReader, TokenBucket
from ..utils.pressure import ForegroundPressure
from ..utils.streams import IterStream
from .client import TierClientError, TierObjectNotFound
from .config import TierManager

QUEUE_SIZE = knobs.get_int("MINIO_TPU_TIER_QUEUE_SIZE")
BACKOFF_S = knobs.get_float("MINIO_TPU_TIER_BACKOFF_S")
BACKOFF_MAX_S = knobs.get_float("MINIO_TPU_TIER_BACKOFF_MAX_S")
BACKOFF_TRIES = knobs.get_int("MINIO_TPU_TIER_BACKOFF_TRIES")


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_tier_objects_total",
                    "Object versions transitioned to remote tiers"),
        reg.counter("minio_tpu_tier_bytes_total",
                    "Bytes moved to remote tiers"),
        reg.counter("minio_tpu_tier_failed_total",
                    "Transitions that failed (fed to MRF, retried on "
                    "the next crawler pass)"),
        reg.counter("minio_tpu_tier_restored_total",
                    "RestoreObject pulls completed"),
    )


def _throttle_metrics():
    return telemetry.REGISTRY.counter(
        "minio_tpu_tier_throttled_total",
        "Tier pushes stalled by a per-tier QoS budget (request-rate "
        "waits and byte-pacing stalls)")


def _mrf_enqueue(object_layer, bucket: str, name: str) -> bool:
    """Feed a failed transition into the MRF heal queue of the layer
    holding the object (heal-first: a degraded source heals, then the
    next crawler pass retries the transition)."""
    layers = getattr(object_layer, "server_sets", None) or [object_layer]
    for z in layers:
        mrf = getattr(z, "mrf", None)
        if mrf is None:
            continue
        try:
            if len(layers) > 1 and not z.has_object_versions(bucket, name):
                continue
        except api_errors.ObjectApiError:
            continue
        mrf.enqueue(bucket, name)
        return True
    return False


def free_remote(tiers: Optional[TierManager], metadata: dict) -> bool:
    """Best-effort delete of a transitioned version's remote copy —
    called when the stub (or its restored copy) is deleted or expired.
    Never raises: a tier outage must not fail the local delete."""
    if tiers is None or not is_transitioned(metadata):
        return False
    tier = metadata.get(TRANSITION_TIER_KEY, "")
    key = metadata.get(TRANSITIONED_OBJECT_KEY, "")
    if not tier or not key:
        return False
    try:
        tiers.client(tier).delete(key)
        return True
    except Exception:  # noqa: BLE001 — best-effort remote cleanup
        return False


class _StrictSizeReader(IterStream):
    """Iterator reader that REFUSES to end short: a truncated tier
    stream must abort the local put (which rolls back through the
    engine's tmp cleanup) instead of committing a short restored copy
    over the stub."""

    def __init__(self, it, expected: int):
        super().__init__(it)
        self.expected = expected
        self._got = 0

    def read(self, n: int = -1) -> bytes:
        out = super().read(n)
        self._got += len(out)
        if not out and 0 <= self._got < self.expected:
            raise TierClientError(
                f"short tier read: {self._got} of {self.expected} bytes")
        return out


class TransitionWorker:
    """Bounded background queue moving object versions to remote tiers.

    Entries dedup on (bucket, object, version) while queued; overflow
    drops the hint (the next crawler pass re-finds the object). One
    daemon drains entries through :meth:`_move`, throttled by the
    shared foreground-pressure probe."""

    def __init__(self, object_layer, tiers: TierManager,
                 maxsize: Optional[int] = None,
                 busy_fn=None, throttle_s: Optional[float] = None):
        self.obj = object_layer
        self.tiers = tiers
        self.maxsize = QUEUE_SIZE if maxsize is None else maxsize
        self._pressure = ForegroundPressure(object_layer, busy_fn=busy_fn)
        self._throttle_base = BACKOFF_S if throttle_s is None \
            else throttle_s
        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._pending: set[tuple[str, str, str]] = set()
        self._inflight = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-tier QoS budgets (cluster boot wires this to the QoS
        # registry's "tier" scope): name -> Budget or None. Pushes
        # pace through per-tier token buckets built from it.
        self.budget_lookup = None
        self._tier_buckets: dict = {}   # tier -> (rps, bps, rps_b, byte_b)
        # stats (admin surface / tests)
        self.queued = 0
        self.moved = 0
        self.failed = 0
        self.skipped = 0               # object changed/vanished under us
        self.dropped = 0
        self.restored = 0              # async RestoreObject pulls done
        self.restore_failed = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TransitionWorker":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tier-transition")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    # -- producer ----------------------------------------------------------

    def enqueue(self, bucket: str, name: str, version_id: str,
                tier: str, etag: str = "") -> bool:
        return self._enqueue(("move", bucket, name, version_id, tier,
                              etag))

    def enqueue_restore(self, bucket: str, name: str, version_id: str,
                        days: int = 1) -> bool:
        """Queue an ASYNC RestoreObject pull (the 202 path for large
        objects): the handler marked the version ongoing-request and
        answers immediately; this worker runs the tier pull off the
        request thread, throttled like every transition."""
        return self._enqueue(("restore", bucket, name, version_id,
                              days, ""))

    def _enqueue(self, entry: tuple) -> bool:
        key = (entry[0], entry[1], entry[2], entry[3])
        with self._cond:
            if self._stop.is_set() or key in self._pending:
                return False
            if len(self._queue) >= self.maxsize:
                self.dropped += 1
                return False
            self._pending.add(key)
            self._queue.append(entry)
            self.queued += 1
            self._cond.notify_all()
            return True

    # -- observability -----------------------------------------------------

    def pending(self) -> int:
        with self._cond:
            return len(self._queue) + self._inflight

    def stats(self) -> dict:
        with self._cond:
            return {"pending": len(self._queue) + self._inflight,
                    "queued": self.queued, "moved": self.moved,
                    "failed": self.failed, "skipped": self.skipped,
                    "dropped": self.dropped, "restored": self.restored,
                    "restore_failed": self.restore_failed}

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every queued entry finished (moved, failed, or
        skipped). Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return not (self._queue or self._inflight)
                self._cond.wait(remaining)
        return True

    # -- consumer ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop.is_set() and not self._queue:
                    self._cond.wait()
                if self._stop.is_set():
                    return
                entry = self._queue.popleft()
                self._pending.discard((entry[0], entry[1], entry[2],
                                       entry[3]))
                self._inflight += 1
            try:
                self._pressure.throttle(self._stop, self._throttle_base,
                                        BACKOFF_MAX_S, BACKOFF_TRIES)
                if self._stop.is_set():
                    return
                if entry[0] == "restore":
                    self._restore_one(entry[1], entry[2], entry[3],
                                      entry[4])
                else:
                    self._move_one(entry[1], entry[2], entry[3],
                                   entry[4], entry[5])
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _move_one(self, bucket: str, name: str, vid: str, tier: str,
                  etag: str) -> None:
        objects_c, bytes_c, failed_c, _ = _metrics()
        with telemetry.trace("tier.transition", bucket=bucket,
                             object=name, tier=tier):
            try:
                moved = self._move(bucket, name, vid, tier, etag)
            except (api_errors.ObjectNotFound,
                    api_errors.VersionNotFound,
                    api_errors.MethodNotAllowed,
                    api_errors.InvalidObjectState,
                    api_errors.PreConditionFailed):
                # deleted / markered / already-tiered / overwritten
                # since the scan: converged, nothing to do
                with self._cond:
                    self.skipped += 1
            except Exception:  # noqa: BLE001 — per-object isolation
                with self._cond:
                    self.failed += 1
                failed_c.inc(tier=tier)
                # heal-first: a degraded source heals through MRF, the
                # next crawler pass retries the transition
                _mrf_enqueue(self.obj, bucket, name)
            else:
                if moved < 0:
                    with self._cond:
                        self.skipped += 1
                else:
                    with self._cond:
                        self.moved += 1
                    objects_c.inc(tier=tier)
                    bytes_c.inc(moved, tier=tier)

    def _tier_byte_bucket(self, tier: str) -> Optional[TokenBucket]:
        """Enforce the tier's request-rate budget (blocking — the
        worker is background, it waits rather than sheds) and return
        its byte-pacing bucket, or None when the tier has no budget.
        Buckets rebuild when the registry's rates change."""
        if self.budget_lookup is None:
            return None
        b = self.budget_lookup(tier)
        rps = float(b.rps) if b is not None else 0.0
        bps = float(b.tx_bps) if b is not None else 0.0
        if rps <= 0 and bps <= 0:
            return None
        with self._cond:
            cached = self._tier_buckets.get(tier)
            if cached is None or cached[0] != rps or cached[1] != bps:
                cached = (rps, bps, TokenBucket(rps), TokenBucket(bps))
                self._tier_buckets[tier] = cached
        if cached[2].take(1) > 0:
            _throttle_metrics().inc(tier=tier)
        return cached[3]

    def _move(self, bucket: str, name: str, vid: str, tier: str,
              etag: str) -> int:
        """Move ONE version's data to `tier`. Returns bytes moved, or
        -1 when the object changed under us (skip, the crawler will
        re-evaluate). Local shards are freed only after the remote
        write verified — a crash anywhere before the stub rewrite
        leaves the object fully readable locally."""
        # budget gate BEFORE the source stream opens: a paced worker
        # must not sit on open drive streams while it waits
        byte_bucket = self._tier_byte_bucket(tier)
        opts = GetOptions(version_id=vid)
        info, stream = self.obj.get_object(bucket, name, opts=opts)
        reader = IterStream(stream)
        if byte_bucket is not None and byte_bucket.rate > 0:
            reader = PacedReader(
                reader, byte_bucket,
                on_wait=lambda s: _throttle_metrics().inc(tier=tier))
        try:
            md = info.user_defined or {}
            if is_transitioned(md):
                return -1               # already tiered (or restored)
            if not vid and etag and info.etag != etag:
                return -1               # overwritten since the scan
            client = self.tiers.client(tier)
            remote_key = self.tiers.remote_key(bucket, name,
                                               info.version_id)
            remote_version = client.put(remote_key, reader, info.size)
        finally:
            reader.close()
        # verify the remote copy BEFORE the stub rewrite frees local
        # shards: a tier that lied about the write must not eat data
        got = client.head(remote_key)
        if got != info.size:
            try:
                client.delete(remote_key)
            except TierClientError:
                pass
            raise TierClientError(
                f"remote verify failed: {got} != {info.size} bytes")
        try:
            # etag+mod_time pin the version identity INSIDE the commit's
            # write lock: an unversioned object overwritten while the
            # upload ran must abort (PreConditionFailed), not stub the
            # new data over the old remote copy
            self.obj.transition_object(
                bucket, name, version_id=info.version_id, tier=tier,
                remote_object=remote_key, remote_version=remote_version,
                expect_etag=info.etag, expect_mod_time=info.mod_time)
        except api_errors.ObjectApiError:
            # stub rewrite failed or refused: the object is still fully
            # local — free the orphaned remote copy, then surface
            try:
                client.delete(remote_key)
            except TierClientError:
                pass
            raise
        return info.size

    def _restore_one(self, bucket: str, name: str, vid: str,
                     days: int) -> None:
        """One async RestoreObject pull (the handler already marked
        the version ongoing-request and answered 202). A failed pull
        CLEARS the ongoing marker — a stuck marker would answer every
        later restore with RestoreAlreadyInProgress forever."""
        try:
            restore_object(self.obj, self.tiers, bucket, name,
                           version_id=vid, days=days)
        except (api_errors.ObjectNotFound, api_errors.VersionNotFound,
                api_errors.MethodNotAllowed):
            with self._cond:
                self.skipped += 1       # deleted/markered since the 202
        except Exception:  # noqa: BLE001 — per-object isolation
            with self._cond:
                self.restore_failed += 1
            clear_restore_ongoing(self.obj, bucket, name, vid)
            _mrf_enqueue(self.obj, bucket, name)
        else:
            with self._cond:
                self.restored += 1


# ---------------------------------------------------------------------------
# crawler actions (the DataUsageCrawler hooks)
# ---------------------------------------------------------------------------

def transition_action(bucket_meta_sys, worker: TransitionWorker,
                      now_fn=time.time):
    """Per-object crawler action: enqueue current versions whose
    lifecycle Transition rule is due (expiry wins when both apply —
    crawler_action runs first and deletes; this action re-checks so
    ordering never transitions an object the same pass expires)."""
    from ..features.lifecycle import Lifecycle

    def act(bucket: str, oi) -> None:
        bm = bucket_meta_sys.get(bucket)
        if not bm.lifecycle_xml:
            return
        try:
            lc = Lifecycle.cached(bm.lifecycle_xml)
        except ET.ParseError:
            return
        md = oi.user_defined or {}
        if is_transitioned(md):
            return                      # already tiered / restored copy
        now = now_fn()
        tier = lc.transition_due(oi.name, oi.mod_time, now)
        if tier:
            worker.enqueue(bucket, oi.name, oi.version_id, tier,
                           etag=oi.etag)

    return act


def noncurrent_transition_action(bucket_meta_sys,
                                 worker: TransitionWorker,
                                 now_fn=time.time):
    """Per-bucket crawler action enforcing NoncurrentVersionTransition
    over a paginated version walk (the noncurrent_sweep_action shape:
    a version's clock starts when it BECAME noncurrent — its
    successor's mod time)."""
    from ..features.lifecycle import Lifecycle

    def act(bucket: str) -> None:
        from ..features.lifecycle import iter_version_groups
        bm = bucket_meta_sys.get(bucket)
        if not bm.lifecycle_xml:
            return
        try:
            lc = Lifecycle.cached(bm.lifecycle_xml)
        except ET.ParseError:
            return
        if not any(r.enabled and r.noncurrent_transition_days
                   and r.noncurrent_transition_tier for r in lc.rules):
            return
        now = now_fn()
        # the shared version-group walk (metacache feed when available,
        # marker-paged merge listing otherwise) always yields a name's
        # versions TOGETHER — no page-cut mis-clocking
        for name, vs in iter_version_groups(worker.obj, bucket,
                                            consumer="transition"):
            days, tier = lc.noncurrent_transition(name)
            if not days or not tier:
                continue
            vs = sorted(vs, key=lambda v: -v.mod_time)
            for i in range(1, len(vs)):         # index 0 = current
                v = vs[i]
                if v.delete_marker or \
                        is_transitioned(v.user_defined or {}):
                    continue
                became_noncurrent = vs[i - 1].mod_time
                if became_noncurrent < now - days * 86400:
                    worker.enqueue(bucket, name, v.version_id, tier,
                                   etag=v.etag)

    return act


def restore_reclaim_action(object_layer, tiers: TierManager,
                           now_fn=time.time):
    """Per-object crawler action reclaiming EXPIRED restored copies:
    the local data is freed and the version returns to its zero-data
    stub (the remote copy was never touched by the restore, so no
    re-upload happens)."""

    def act(bucket: str, oi) -> None:
        md = oi.user_defined or {}
        if not is_transitioned(md) or not is_restored(md):
            return
        try:
            expiry = float(md.get(RESTORE_EXPIRY_KEY, 0) or 0)
        except ValueError:
            expiry = 0.0
        if not expiry or expiry > now_fn():
            return
        with telemetry.trace("tier.reclaim", bucket=bucket,
                             object=oi.name):
            try:
                object_layer.transition_object(
                    bucket, oi.name, version_id=oi.version_id,
                    tier=md.get(TRANSITION_TIER_KEY, ""),
                    remote_object=md.get(TRANSITIONED_OBJECT_KEY, ""),
                    remote_version=md.get(TRANSITIONED_VERSION_KEY, ""),
                    # identity pin: an unversioned restored copy
                    # overwritten since the scan must NOT be re-stubbed
                    # over the stale remote pointer
                    expect_etag=oi.etag)
            except api_errors.ObjectApiError:
                pass                    # next pass retries

    return act


# ---------------------------------------------------------------------------
# RestoreObject (POST ?restore)
# ---------------------------------------------------------------------------

def _http_date(t: float) -> str:
    from email.utils import formatdate
    return formatdate(t, usegmt=True)


def restore_object(object_layer, tiers: TierManager, bucket: str,
                   name: str, version_id: str = "", days: int = 1,
                   now_fn=time.time) -> dict:
    """Pull a transitioned version back as an expiring local copy.

    Returns {"status": "restored"|"updated", "expiry": ts}. The
    restored copy keeps its version id, mod time and etag (the put
    rides PutOptions.mod_time like a rebalance move), plus the
    ``x-amz-restore`` header state and the absolute expiry the reclaim
    sweep reads. Raises InvalidObjectState when the version was never
    transitioned."""
    if days < 1:
        raise api_errors.InvalidObjectState("restore Days must be >= 1")
    opts = GetOptions(version_id=version_id)
    info = object_layer.get_object_info(bucket, name, opts)
    md = dict(info.user_defined or {})
    if not is_transitioned(md):
        raise api_errors.InvalidObjectState(
            f"{bucket}/{name} is not in a remote tier")
    now = now_fn()
    expiry = now + days * 86400
    restore_val = (f'ongoing-request="false", '
                   f'expiry-date="{_http_date(expiry)}"')
    if is_restored(md):
        # already local: just extend the expiry window (S3 semantics:
        # 200 OK, restore period updated)
        md[RESTORE_KEY] = restore_val
        md[RESTORE_EXPIRY_KEY] = str(expiry)
        md["etag"] = info.etag
        if info.content_type:
            md["content-type"] = info.content_type
        object_layer.update_object_metadata(bucket, name, md,
                                            version_id=version_id)
        return {"status": "updated", "expiry": expiry}

    tier = md.get(TRANSITION_TIER_KEY, "")
    remote_key = md.get(TRANSITIONED_OBJECT_KEY, "")
    client = tiers.client(tier)
    with telemetry.trace("tier.restore", bucket=bucket, object=name,
                         tier=tier):
        try:
            stream = client.get(remote_key)
        except TierObjectNotFound:
            raise api_errors.InvalidObjectState(
                f"remote copy of {bucket}/{name} is gone") from None
        metadata = dict(md)
        metadata["etag"] = info.etag
        if info.content_type:
            metadata["content-type"] = info.content_type
        if info.content_encoding:
            metadata["content-encoding"] = info.content_encoding
        metadata[RESTORE_KEY] = restore_val
        metadata[RESTORE_EXPIRY_KEY] = str(expiry)
        reader = _StrictSizeReader(stream, info.size)
        try:
            if len(info.parts or []) > 1:
                # multipart stub: replay the recorded part boundaries
                # (object/faithful.py) so ranged reads and the
                # multipart etag survive the restore round-trip — a
                # single-part rewrite would change the stored shape
                # the next transition/replication compares against
                from ..object.faithful import replay_version, spec_of
                spec = spec_of(info)
                spec.metadata = {k: v for k, v in metadata.items()
                                 if k != "etag"}
                # conflict_gate off: the restore REWRITES the same
                # identity over its own stub (mod time/etag equal —
                # the replication gate would abort it as a tie)
                replay_version(object_layer, bucket, name, spec,
                               reader=reader, conflict_gate=False)
            else:
                put_opts = PutOptions(metadata=metadata,
                                      version_id=info.version_id,
                                      versioned=bool(info.version_id),
                                      mod_time=info.mod_time)
                object_layer.put_object(bucket, name, reader, info.size,
                                        put_opts)
        finally:
            reader.close()
    _, _, _, restored_c = _metrics()
    restored_c.inc(tier=tier)
    return {"status": "restored", "expiry": expiry}


def mark_restore_ongoing(object_layer, bucket: str, name: str,
                         version_id: str = "") -> None:
    """Record S3's ``ongoing-request="true"`` restore state on a
    transitioned version — the async-202 handler path: later GET/HEADs
    report the ongoing restore, a second RestoreObject answers
    RestoreAlreadyInProgress, and the background worker's completed
    pull overwrites this with the final expiry state."""
    info = object_layer.get_object_info(
        bucket, name, GetOptions(version_id=version_id))
    md = dict(info.user_defined or {})
    md[RESTORE_KEY] = RESTORE_ONGOING
    md["etag"] = info.etag
    if info.content_type:
        md["content-type"] = info.content_type
    object_layer.update_object_metadata(bucket, name, md,
                                        version_id=version_id)


def clear_restore_ongoing(object_layer, bucket: str, name: str,
                          version_id: str = "") -> None:
    """Best-effort removal of the ongoing marker after a FAILED async
    pull, so the client can retry instead of seeing
    RestoreAlreadyInProgress forever."""
    try:
        info = object_layer.get_object_info(
            bucket, name, GetOptions(version_id=version_id))
        md = dict(info.user_defined or {})
        if RESTORE_ONGOING not in md.get(RESTORE_KEY, ""):
            return
        md.pop(RESTORE_KEY, None)
        md.pop(RESTORE_EXPIRY_KEY, None)
        md["etag"] = info.etag
        if info.content_type:
            md["content-type"] = info.content_type
        object_layer.update_object_metadata(bucket, name, md,
                                            version_id=version_id)
    except api_errors.ObjectApiError:
        pass
