"""Core storage datatypes: FileInfo / ErasureInfo / checksums / volumes.

The shapes mirror the reference's wire/metadata structs
(cmd/storage-datatypes.go:61-116, cmd/xl-storage-format-v1.go:86-137) so
that xl.meta serialization (xl_meta.py) can emit the same field names and
the object layer can reuse the same quorum algebra.
"""

from __future__ import annotations

import dataclasses
import time as _time
import zlib
from typing import Optional

ERASURE_ALGORITHM = "rs-vandermonde"  # cmd/erasure-metadata.go:34
BLOCK_SIZE_V1 = 1 << 22               # 4 MiB, cmd/object-api-common.go:31
NULL_VERSION_ID = "null"

# Tiering-plane metadata keys (reference cmd/erasure-object.go transition
# metadata, xhttp.AmzRestore): the x-minio-internal- prefix rides xl.meta
# MetaSys, never leaks into client responses. Defined here (not in
# tier/) so the engine can gate reads without importing the tier plane.
TRANSITION_STATUS_KEY = "X-Minio-Internal-transition-status"
TRANSITION_TIER_KEY = "X-Minio-Internal-transition-tier"
TRANSITIONED_OBJECT_KEY = "X-Minio-Internal-transitioned-object"
TRANSITIONED_VERSION_KEY = "X-Minio-Internal-transitioned-versionID"
TRANSITION_COMPLETE = "complete"
# restore state of a transitioned object: the S3-visible x-amz-restore
# header value plus the internal absolute expiry the reclaim sweep uses
RESTORE_KEY = "x-amz-restore"
RESTORE_EXPIRY_KEY = "X-Minio-Internal-restore-expiry"
RESTORE_ONGOING = 'ongoing-request="true"'


def is_transitioned(metadata: dict) -> bool:
    """True when this version's data lives in a remote tier."""
    return metadata.get(TRANSITION_STATUS_KEY) == TRANSITION_COMPLETE


def is_restored(metadata: dict) -> bool:
    """True when a transitioned version currently has a live local
    restored copy (restore finished, not yet reclaimed)."""
    v = metadata.get(RESTORE_KEY, "")
    return bool(v) and RESTORE_ONGOING not in v


@dataclasses.dataclass
class ChecksumInfo:
    """Bitrot checksum of one part on one drive
    (cmd/xl-storage-format-v1.go:132)."""
    part_number: int
    algorithm: str          # bitrot algorithm string name
    hash: bytes             # empty for streaming algorithms

    def to_json(self) -> dict:
        return {
            "name": f"part.{self.part_number}",
            "algorithm": self.algorithm,
            "hash": self.hash.hex(),
        }

    @classmethod
    def from_json(cls, d: dict) -> "ChecksumInfo":
        name = d.get("name", "part.0")
        num = int(name.split(".", 1)[1]) if "." in name else 0
        return cls(part_number=num, algorithm=d.get("algorithm", ""),
                   hash=bytes.fromhex(d.get("hash", "")))


@dataclasses.dataclass
class ObjectPartInfo:
    """One completed part (cmd/xl-storage-format-v1.go:124)."""
    number: int
    size: int
    actual_size: int = -1   # pre-compression size; -1 = same as size
    etag: str = ""


@dataclasses.dataclass
class ErasureInfo:
    """Erasure geometry + placement for one object version
    (cmd/xl-storage-format-v1.go:86)."""
    algorithm: str = ERASURE_ALGORITHM
    data_blocks: int = 0
    parity_blocks: int = 0
    block_size: int = BLOCK_SIZE_V1
    index: int = 0                      # 1-based index of this drive
    distribution: list[int] = dataclasses.field(default_factory=list)
    checksums: list[ChecksumInfo] = dataclasses.field(default_factory=list)

    def shard_size(self) -> int:
        """Bytes of one shard of one full block (ceil split)."""
        return -(-self.block_size // self.data_blocks)

    def shard_file_size(self, total_length: int) -> int:
        """Final erasure-shard size for an object of total_length bytes
        (cmd/erasure-coding.go:120-131)."""
        if total_length == 0:
            return 0
        if total_length < 0:
            return -1
        full = total_length // self.block_size
        last = total_length % self.block_size
        last_shard = -(-last // self.data_blocks)
        return full * self.shard_size() + last_shard

    def shard_file_offset(self, start: int, length: int, total: int) -> int:
        """Read-until offset in the shard file for a ranged read
        (cmd/erasure-coding.go:134-143)."""
        shard_size = self.shard_size()
        sfs = self.shard_file_size(total)
        end = ((start + length) // self.block_size) * shard_size + shard_size
        return min(end, sfs)

    def get_checksum_info(self, part_number: int) -> Optional[ChecksumInfo]:
        for c in self.checksums:
            if c.part_number == part_number:
                return c
        return None

    def equals(self, other: "ErasureInfo") -> bool:
        """Quorum-comparable subset (distribution+geometry), ignoring
        per-drive index/checksums."""
        return (self.data_blocks == other.data_blocks
                and self.parity_blocks == other.parity_blocks
                and self.block_size == other.block_size
                and self.distribution == other.distribution)


@dataclasses.dataclass
class FileInfo:
    """Stat + metadata of one object version on one drive
    (cmd/storage-datatypes.go:61-116)."""
    volume: str = ""
    name: str = ""
    version_id: str = ""
    is_latest: bool = True
    deleted: bool = False               # delete marker
    data_dir: str = ""
    mod_time: float = 0.0               # unix seconds (float, ns precision)
    size: int = 0
    metadata: dict[str, str] = dataclasses.field(default_factory=dict)
    parts: list[ObjectPartInfo] = dataclasses.field(default_factory=list)
    erasure: ErasureInfo = dataclasses.field(default_factory=ErasureInfo)

    def add_object_part(self, number: int, etag: str, size: int,
                        actual_size: int) -> None:
        """Insert/replace a part, keeping parts sorted by number
        (cmd/erasure-metadata.go AddObjectPart semantics)."""
        new = ObjectPartInfo(number=number, etag=etag, size=size,
                             actual_size=actual_size)
        for i, p in enumerate(self.parts):
            if p.number == number:
                self.parts[i] = new
                return
        self.parts.append(new)
        self.parts.sort(key=lambda p: p.number)

    def object_to_part_offset(self, offset: int) -> tuple[int, int]:
        """(part index, offset inside part) for a global object offset
        (cmd/erasure-metadata.go ObjectToPartOffset)."""
        if offset == 0:
            return 0, 0
        remaining = offset
        for i, part in enumerate(self.parts):
            if remaining < part.size:
                return i, remaining
            remaining -= part.size
        raise ValueError(f"offset {offset} beyond object size")

    def light_copy(self) -> "FileInfo":
        """Per-drive copy for writeUniqueFileInfo: drives differ only in
        erasure.index and (whole-file bitrot) per-drive checksum hashes,
        so share the payload (metadata dict, parts list) and copy just
        the erasure branch — a full deepcopy per drive was the PUT
        commit path's largest host cost."""
        e = self.erasure
        new_e = dataclasses.replace(
            e, distribution=list(e.distribution),
            checksums=[dataclasses.replace(c) for c in e.checksums])
        return dataclasses.replace(self, erasure=new_e)

    def to_object_info(self, bucket: str, object_name: str) -> "ObjectInfo":
        actual = int(self.metadata.get("X-Minio-Internal-actual-size",
                                       self.size))
        return ObjectInfo(
            bucket=bucket, name=object_name, mod_time=self.mod_time,
            size=self.size, actual_size=actual,
            etag=self.metadata.get("etag", ""),
            version_id=self.version_id or "",
            is_latest=self.is_latest, delete_marker=self.deleted,
            content_type=self.metadata.get("content-type", ""),
            content_encoding=self.metadata.get("content-encoding", ""),
            user_defined={k: v for k, v in self.metadata.items()
                          if k not in ("etag", "content-type",
                                       "content-encoding")},
            parts=list(self.parts),
            data_blocks=self.erasure.data_blocks,
            parity_blocks=self.erasure.parity_blocks,
        )


@dataclasses.dataclass
class ObjectInfo:
    """API-facing object metadata (the reference's ObjectInfo,
    cmd/object-api-datatypes.go)."""
    bucket: str = ""
    name: str = ""
    mod_time: float = 0.0
    size: int = 0
    actual_size: int = 0
    is_dir: bool = False
    etag: str = ""
    version_id: str = ""
    is_latest: bool = True
    delete_marker: bool = False
    content_type: str = ""
    content_encoding: str = ""
    expires: float = 0.0
    storage_class: str = "STANDARD"
    user_defined: dict[str, str] = dataclasses.field(default_factory=dict)
    parts: list[ObjectPartInfo] = dataclasses.field(default_factory=list)
    data_blocks: int = 0
    parity_blocks: int = 0


@dataclasses.dataclass
class VolInfo:
    name: str
    created: float


@dataclasses.dataclass
class DiskInfo:
    """Capacity/health snapshot of one drive (cmd/storage-datatypes.go
    DiskInfo)."""
    total: int = 0
    free: int = 0
    used: int = 0
    fs_type: str = ""
    root_disk: bool = False
    healing: bool = False
    endpoint: str = ""
    mount_path: str = ""
    disk_id: str = ""
    error: str = ""


def hash_order(key: str, cardinality: int) -> list[int]:
    """Consistent 1-based shard distribution order, identical to the
    reference (crc32-IEEE seeded rotation, cmd/erasure-metadata-utils.go:100).
    Placement compatibility requires bit-identity here."""
    if cardinality <= 0:
        return []
    key_crc = zlib.crc32(key.encode())
    start = key_crc % cardinality
    return [1 + ((start + i) % cardinality) for i in range(1, cardinality + 1)]


def new_file_info(object_name: str, data_blocks: int,
                  parity_blocks: int) -> FileInfo:
    """Fresh FileInfo with erasure geometry + hashOrder distribution
    (cmd/storage-datatypes.go:107)."""
    fi = FileInfo()
    fi.erasure = ErasureInfo(
        algorithm=ERASURE_ALGORITHM,
        data_blocks=data_blocks,
        parity_blocks=parity_blocks,
        block_size=BLOCK_SIZE_V1,
        distribution=hash_order(object_name, data_blocks + parity_blocks),
    )
    return fi


def now() -> float:
    return _time.time()


def last_version_marker(versions, prefixes) -> tuple[str, str]:
    """Resume markers at a versions-page cut — THE single home of the
    rule (engine.paginate_versions, sets.merge_version_listings, and
    single_version_page all derive their markers here): the lexically
    LAST entry emitted (a version or a rolled-up CommonPrefix) is
    where the next page re-enters. A prefix cut carries no version-id
    marker — resume starts at the first key after the prefix (a
    prefix-only page with an empty marker would loop the pager
    forever). A null version id rides as the "null" sentinel: an
    empty marker reads as NO marker on resume and would skip the
    key's remaining versions."""
    last_v = versions[-1].name if versions else ""
    last_p = prefixes[-1] if prefixes else ""
    if last_p > last_v:
        return last_p, ""
    return last_v, versions[-1].version_id or "null"


def single_version_page(objs, truncated, prefixes=None):
    """The list_object_versions 5-tuple for single-version backends
    (FS, gateways): one "version" per key, paged on the key marker
    alone — the erasure layer's (versions, CommonPrefixes,
    NextKeyMarker, NextVersionIdMarker, is_truncated) contract; the
    backends' list_objects skips prefixes <= marker on resume."""
    prefixes = prefixes or []
    if truncated and (objs or prefixes):
        nkm, nvm = last_version_marker(objs, prefixes)
        return objs, prefixes, nkm, nvm, True
    return objs, prefixes, "", "", truncated
