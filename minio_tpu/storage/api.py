"""StorageAPI — the per-drive verb interface.

The seam between the object engine and a drive, local or remote
(reference: cmd/storage-interface.go:25-82). Every implementation —
XLStorage (POSIX, xl_storage.py), RemoteStorage (RPC client,
distributed/storage_client.py), fault-injecting test wrappers — speaks
exactly these verbs, so quorum logic, healing, and the RPC server are
implementation-agnostic.

Synchronous methods; the object engine fans out over drives with a
thread pool (the analog of the reference's per-disk goroutines).
"""

from __future__ import annotations

import abc
from typing import BinaryIO, Callable, Iterator, Optional

from .datatypes import DiskInfo, FileInfo, VolInfo


class BitrotVerifier:
    """Expected whole-file digest, checked during ReadFile
    (reference BitrotVerifier, cmd/bitrot.go)."""

    def __init__(self, algorithm: str, digest: bytes):
        self.algorithm = algorithm
        self.digest = digest


class StorageAPI(abc.ABC):
    """One drive's verb set."""

    # -- identity / health -------------------------------------------------

    @abc.abstractmethod
    def __str__(self) -> str: ...

    @abc.abstractmethod
    def is_online(self) -> bool: ...

    @abc.abstractmethod
    def is_local(self) -> bool: ...

    def hostname(self) -> str:
        return ""

    @abc.abstractmethod
    def endpoint(self) -> str: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def get_disk_id(self) -> str: ...

    @abc.abstractmethod
    def set_disk_id(self, disk_id: str) -> None: ...

    def healing(self) -> bool:
        return False

    @abc.abstractmethod
    def disk_info(self) -> DiskInfo: ...

    # -- volumes -----------------------------------------------------------

    @abc.abstractmethod
    def make_vol(self, volume: str) -> None: ...

    def make_vol_bulk(self, *volumes: str) -> None:
        for v in volumes:
            try:
                self.make_vol(v)
            except Exception:
                pass

    @abc.abstractmethod
    def list_vols(self) -> list[VolInfo]: ...

    @abc.abstractmethod
    def stat_vol(self, volume: str) -> VolInfo: ...

    @abc.abstractmethod
    def delete_vol(self, volume: str, force: bool = False) -> None: ...

    # -- metadata ----------------------------------------------------------

    @abc.abstractmethod
    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def read_version(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo: ...

    @abc.abstractmethod
    def read_versions(self, volume: str, path: str) -> list[FileInfo]: ...

    @abc.abstractmethod
    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None: ...

    def delete_versions(self, volume: str,
                        versions: list[FileInfo]
                        ) -> list[Optional[Exception]]:
        """Bulk version delete: ONE call per drive for N objects
        (reference DeleteVersions, cmd/storage-rest-common.go). The
        default loops locally; the storage-RPC client overrides it with
        a single wire round-trip."""
        out: list[Optional[Exception]] = []
        for fi in versions:
            try:
                self.delete_version(volume, fi.name, fi)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-item result
                out.append(e)
        return out

    @abc.abstractmethod
    def rename_data(self, src_volume: str, src_path: str, data_dir: str,
                    dst_volume: str, dst_path: str,
                    version_id: str = "") -> None:
        """Commit a staged write. `version_id` names the version being
        committed (empty = legacy latest-pick) — version-faithful
        replays stage versions whose mod time sorts behind the
        session placeholder, so "latest" is not "the one"."""
        ...

    # -- files -------------------------------------------------------------

    @abc.abstractmethod
    def list_dir(self, volume: str, dir_path: str,
                 count: int = -1) -> list[str]: ...

    @abc.abstractmethod
    def read_file(self, volume: str, path: str, offset: int, length: int,
                  verifier: Optional[BitrotVerifier] = None) -> bytes: ...

    @abc.abstractmethod
    def append_file(self, volume: str, path: str, buf: bytes) -> None: ...

    @abc.abstractmethod
    def create_file(self, volume: str, path: str, size: int,
                    reader: BinaryIO) -> None: ...

    @abc.abstractmethod
    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int) -> BinaryIO: ...

    @abc.abstractmethod
    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None: ...

    @abc.abstractmethod
    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def check_file(self, volume: str, path: str) -> None: ...

    @abc.abstractmethod
    def delete_file(self, volume: str, path: str,
                    recursive: bool = False) -> None: ...

    @abc.abstractmethod
    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None: ...

    @abc.abstractmethod
    def write_all(self, volume: str, path: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def read_all(self, volume: str, path: str) -> bytes: ...

    # -- listing / crawling ------------------------------------------------

    @abc.abstractmethod
    def walk(self, volume: str, dir_path: str = "", marker: str = "",
             recursive: bool = True) -> Iterator[FileInfo]: ...

    def walk_versions(self, volume: str, dir_path: str = "",
                      marker: str = "", recursive: bool = True
                      ) -> Iterator[list[FileInfo]]:
        raise NotImplementedError


OFFLINE_DISK: Optional[StorageAPI] = None  # placeholder for a gone drive
