"""Per-drive storage layer: StorageAPI verbs, xl.meta v2, format.json v3,
and the local POSIX drive (reference layer L6, SURVEY §2.3)."""

from . import errors  # noqa: F401
from .api import BitrotVerifier, StorageAPI  # noqa: F401
from .datatypes import (BLOCK_SIZE_V1, ChecksumInfo, DiskInfo,  # noqa: F401
                        ErasureInfo, FileInfo, ObjectInfo, ObjectPartInfo,
                        VolInfo, hash_order, new_file_info)
from .format import (FormatErasureV3, get_format_in_quorum,  # noqa: F401
                     new_format_erasure_v3)
from .xl_meta import XLMetaV2  # noqa: F401
from .xl_storage import (MINIO_META_BUCKET, MINIO_META_MULTIPART_BUCKET,  # noqa: F401
                         MINIO_META_TMP_BUCKET, XL_STORAGE_FORMAT_FILE,
                         XLStorage)
