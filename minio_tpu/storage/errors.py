"""Storage-layer error taxonomy.

Mirrors the reference's typed storage errors (cmd/storage-errors.go) so the
object layer's quorum reduction can count and classify per-drive failures
the same way (reduceReadQuorumErrs / reduceWriteQuorumErrs semantics,
cmd/erasure-metadata-utils.go:72-98).
"""

from __future__ import annotations


class StorageError(Exception):
    """Base class of every per-drive error."""


class DiskNotFound(StorageError):
    """Drive is offline / unreachable (errDiskNotFound)."""


class NetworkStorageError(DiskNotFound):
    """Transport-level failure talking to a REMOTE drive (connection
    refused/reset, timeout, mid-stream disconnect) — distinct from the
    remote reporting a storage error. Subclasses DiskNotFound so quorum
    logic tolerates it like any gone drive, but callers that retry or
    hedge can tell 'the wire broke' from 'the drive said no'."""


class UnformattedDisk(StorageError):
    """Drive has no format.json yet (errUnformattedDisk)."""


class CorruptedFormat(StorageError):
    """format.json unreadable/invalid (errCorruptedFormat)."""


class DiskAccessDenied(StorageError):
    """Drive root not writable (errDiskAccessDenied)."""


class FaultyDisk(StorageError):
    """I/O error talking to the drive (errFaultyDisk)."""


class StorageStalled(StorageError):
    """Drive op abandoned by the quorum-ack lane: it outlived the
    write-straggler grace after write quorum was already durable. The
    op keeps running on the background lane — this error only records
    that the commit stopped waiting (the caller's quorum reduce counts
    it as a missed write, feeding the MRF degraded-write queue)."""


class DiskFull(StorageError):
    """No space left (errDiskFull)."""


class VolumeNotFound(StorageError):
    """Bucket/volume missing on this drive (errVolumeNotFound)."""


class VolumeExists(StorageError):
    """MakeVol on an existing volume (errVolumeExists)."""


class VolumeNotEmpty(StorageError):
    """DeleteVol on a non-empty volume (errVolumeNotEmpty)."""


class FileNotFound(StorageError):
    """Object/file missing on this drive (errFileNotFound)."""


class FileVersionNotFound(StorageError):
    """Requested versionID not present in xl.meta (errFileVersionNotFound)."""


class FileNameTooLong(StorageError):
    """Path component too long (errFileNameTooLong)."""


class FileAccessDenied(StorageError):
    """Path is a directory where a file is expected, or perms
    (errFileAccessDenied)."""


class FileCorrupt(StorageError):
    """xl.meta / shard data fails to parse or verify (errFileCorrupt)."""


class FileParentIsFile(StorageError):
    """A parent path component is a regular file (errFileParentIsFile)."""


class IsNotRegular(StorageError):
    """Expected a regular file (errIsNotRegular)."""


class PathNotFound(StorageError):
    """Generic missing path (errPathNotFound)."""


class BitrotHashMismatch(StorageError):
    """Bitrot verification failed: stored digest != computed
    (hashMismatchError, cmd/storage-errors.go)."""

    def __init__(self, expected: str = "", got: str = ""):
        super().__init__(f"bitrot hash mismatch: expected {expected}, got {got}")
        self.expected = expected
        self.got = got


class LessData(StorageError):
    """Reader gave fewer bytes than promised (errLessData)."""


class MoreData(StorageError):
    """Reader gave more bytes than promised (errMoreData)."""


class DoneForNow(StorageError):
    """Internal sentinel to stop a walk early (errDoneForNow)."""


class DiskStale(StorageError):
    """diskID in request doesn't match the drive (errDiskStale) — the
    analog of xlStorageDiskIDCheck rejections."""


class InconsistentDisk(StorageError):
    """Drive returned by another node's endpoint is not the expected one."""


class CrossDeviceLink(StorageError):
    """Rename across filesystems (errCrossDeviceLink)."""


class UnexpectedError(StorageError):
    """Catch-all (errUnexpected)."""


# Errors counted as "object may exist elsewhere, keep looking" by the
# quorum reducer (objectErrs in the reference).
OBJECT_NOT_FOUND_ERRS = (FileNotFound, FileVersionNotFound, VolumeNotFound)

# Errors meaning "this drive is gone", tolerated up to parity count.
DISK_GONE_ERRS = (DiskNotFound, FaultyDisk, DiskAccessDenied, DiskStale)
