"""NaughtyDisk — deterministic fault injection for the storage plane.

The failure-plane analog of the reference's naughtyDisk
(cmd/naughty-disk_test.go): a StorageAPI wrapper that misbehaves on
schedule so quorum writes, hedged reads, bitrot verification, MRF
healing, and the background plane can be driven through realistic drive
faults in-process.

Two programming models compose:

  * **Programmed faults** — ``fail_verbs[verb] = err`` fails every call
    of a verb; ``verb_errors[verb][n] = err`` fails exactly the n-th
    call (the reference's ``errors map[int]error``); ``offline = True``
    makes every verb raise DiskNotFound until cleared;
    ``stall_verbs[verb] = seconds`` stalls every call of a verb and
    ``verb_stalls[verb][n] = seconds`` stalls exactly the n-th call —
    the gray-failure injector (the drive ANSWERS, just slowly).
  * **Scheduled faults** — a seeded :class:`FaultSchedule` decides per
    (verb, call#) whether to raise an error, inject latency, flip
    payload bytes (bitrot), truncate a read stream / short-write a
    payload, hold the drive offline for an op-count window, or stall
    the call on a heavy-tail duration (``stall_rate``/``stall_s``/
    ``stall_pareto`` + ``stall_windows`` op-count windows during which
    EVERY faultable call stalls).

Stalls on ``read_file_stream`` are deferred to the FIRST read of the
returned stream rather than the open — a gray-failing drive typically
accepts the request and then takes forever to move bytes, which is
exactly the shape the hedged reader must race.

Schedule decisions are pure functions of ``(seed, verb, call#)`` — the
same seed replays the same fault pattern per verb sequence regardless
of thread interleaving, so any chaos-test failure reproduces from its
printed seed.
"""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import BinaryIO, Iterator, Optional

from . import errors as serr
from .api import BitrotVerifier, StorageAPI
from .datatypes import DiskInfo, FileInfo, VolInfo

# Verbs that move shard payload; the default fault surface.
DATA_VERBS = ("read_file", "read_file_stream", "read_all", "append_file",
              "create_file", "write_all")
META_VERBS = ("write_metadata", "read_version", "read_versions",
              "delete_version", "rename_data", "rename_file",
              "delete_file", "check_parts", "check_file", "verify_file",
              "list_dir", "walk", "make_vol", "stat_vol", "list_vols",
              "delete_vol")
ALL_VERBS = DATA_VERBS + META_VERBS

# Verbs whose *result* carries payload (bitrot / truncation on read).
_READ_PAYLOAD_VERBS = ("read_file", "read_all")
# Verbs whose *argument* carries payload (bitrot / truncation on write).
_WRITE_PAYLOAD_VERBS = ("append_file", "write_all")


@dataclass(frozen=True)
class FaultSchedule:
    """Seeded, deterministic fault plan.

    Every decision derives from ``crc32(seed:verb:call#:salt)`` — a pure
    hash, no shared RNG state — so concurrent callers observe the same
    per-verb fault sequence for the same seed.
    """

    seed: int = 0
    # probability a faulted verb call raises `error_cls`
    error_rate: float = 0.0
    # probability a call sleeps `latency` seconds before proceeding
    latency_rate: float = 0.0
    latency: float = 0.002
    # probability a payload byte gets flipped (reads AND writes)
    bitrot_rate: float = 0.0
    # probability a payload is truncated (short read / silent short write)
    truncate_rate: float = 0.0
    # [start, end) windows in the drive's TOTAL op count during which the
    # drive is gone (go-offline/come-back transitions)
    offline_windows: tuple = ()
    # probability a faulted verb call STALLS (answers, slowly): the
    # duration is `stall_s`, heavy-tailed by `stall_pareto` > 0
    # (duration = stall_s / (1-u)^pareto, capped at stall_max_s — a
    # deterministic Pareto-ish tail from the same pure hash)
    stall_rate: float = 0.0
    stall_s: float = 0.5
    stall_pareto: float = 0.0
    stall_max_s: float = 5.0
    # [start, end) windows in the TOTAL op count during which every
    # faultable verb call stalls `stall_s` — a drive that goes gray for
    # a stretch, then recovers
    stall_windows: tuple = ()
    # which verbs the error/latency faults apply to
    fault_verbs: tuple = DATA_VERBS
    error_cls: type = serr.FaultyDisk

    # -- decision primitives ----------------------------------------------

    def _roll(self, verb: str, n: int, salt: str) -> float:
        h = zlib.crc32(f"{self.seed}:{verb}:{n}:{salt}".encode())
        return (h & 0xFFFFFFFF) / 2 ** 32

    def error_for(self, verb: str, n: int) -> Optional[Exception]:
        if verb in self.fault_verbs and \
                self._roll(verb, n, "err") < self.error_rate:
            return self.error_cls(f"naughty[{self.seed}]: {verb}#{n}")
        return None

    def latency_for(self, verb: str, n: int) -> float:
        if verb in self.fault_verbs and \
                self._roll(verb, n, "lat") < self.latency_rate:
            return self.latency
        return 0.0

    def corrupts(self, verb: str, n: int) -> bool:
        return self._roll(verb, n, "rot") < self.bitrot_rate

    def truncates(self, verb: str, n: int) -> bool:
        return self._roll(verb, n, "trunc") < self.truncate_rate

    def offline_at(self, op_no: int) -> bool:
        return any(a <= op_no < b for a, b in self.offline_windows)

    def stall_for(self, verb: str, n: int, op_no: int) -> float:
        """Stall duration for this call (0.0 = none): the op-count
        window first, then the seeded per-call roll with its
        deterministic heavy tail."""
        if verb in self.fault_verbs and \
                any(a <= op_no < b for a, b in self.stall_windows):
            return self.stall_s
        if verb in self.fault_verbs and self.stall_rate > 0 and \
                self._roll(verb, n, "stall") < self.stall_rate:
            if self.stall_pareto > 0:
                u = self._roll(verb, n, "stall-dur")
                return min(self.stall_s / max(1.0 - u, 1e-6)
                           ** self.stall_pareto, self.stall_max_s)
            return self.stall_s
        return 0.0

    # deterministic "where" for payload mutation
    def fault_offset(self, verb: str, n: int, size: int) -> int:
        if size <= 0:
            return 0
        return int(self._roll(verb, n, "off") * size) % size


@dataclass
class FaultStats:
    """What the wrapper actually injected (for test assertions)."""
    errors: int = 0
    latency: int = 0
    bitrot: int = 0
    truncated: int = 0
    offline_hits: int = 0
    stalls: int = 0
    stall_s: float = 0.0
    calls: dict = field(default_factory=dict)


class _TruncatedStream:
    """Reader that serves only a prefix of the inner stream, optionally
    flipping one byte — a mid-stream disconnect / rotted sector."""

    def __init__(self, inner, limit: int, flip_at: int = -1):
        self._inner = inner
        self._limit = limit
        self._flip_at = flip_at
        self._pos = 0

    def read(self, n: int = -1) -> bytes:
        if self._limit >= 0:
            if self._pos >= self._limit:
                return b""
            budget = self._limit - self._pos
            n = budget if n is None or n < 0 else min(n, budget)
        data = self._inner.read(n)
        if data and self._flip_at >= 0 and \
                self._pos <= self._flip_at < self._pos + len(data):
            i = self._flip_at - self._pos
            data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        self._pos += len(data)
        return data

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


class _StallFirstReadStream:
    """Defers a stall to the first read of a shard stream: the open
    returns instantly (the drive 'answered'), the payload takes
    `dur` seconds to start moving — the gray-failure read shape the
    hedged reader must race."""

    def __init__(self, inner, dur: float, stall_fn):
        self._inner = inner
        self._dur = dur
        self._stall_fn = stall_fn

    def read(self, n: int = -1) -> bytes:
        if self._dur > 0:
            dur, self._dur = self._dur, 0.0
            self._stall_fn(dur)
        return self._inner.read(n)

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


def _flip_byte(data: bytes, at: int) -> bytes:
    if not data:
        return data
    at %= len(data)
    return data[:at] + bytes([data[at] ^ 0xFF]) + data[at + 1:]


class NaughtyDisk(StorageAPI):
    """Fault-injecting StorageAPI wrapper (reference naughtyDisk)."""

    def __init__(self, inner: StorageAPI,
                 schedule: Optional[FaultSchedule] = None,
                 enabled: bool = True):
        self.inner = inner
        self.schedule = schedule
        # schedule gate: build/format the fixture quietly, then arm()
        self.enabled = enabled
        self.fail_verbs: dict[str, Exception] = {}
        self.verb_errors: dict[str, dict[int, Exception]] = {}
        # programmed stalls: every call of a verb / exactly the n-th
        self.stall_verbs: dict[str, float] = {}
        self.verb_stalls: dict[str, dict[int, float]] = {}
        self.offline = False
        self.stats = FaultStats()
        self.total_ops = 0
        self._mu = threading.Lock()

    # -- control -----------------------------------------------------------

    def arm(self) -> "NaughtyDisk":
        self.enabled = True
        return self

    def disarm(self) -> "NaughtyDisk":
        self.enabled = False
        return self

    # -- fault gate --------------------------------------------------------

    def _begin(self, verb: str) -> int:
        """Count the call, apply offline/error/latency faults; returns the
        verb's call# for payload-fault decisions."""
        with self._mu:
            n = self.stats.calls.get(verb, 0) + 1
            self.stats.calls[verb] = n
            self.total_ops += 1
            op = self.total_ops
        sched = self.schedule if self.enabled else None
        if self.offline or (sched is not None and sched.offline_at(op)):
            with self._mu:
                self.stats.offline_hits += 1
            raise serr.DiskNotFound(f"naughty: offline ({self.inner})")
        one_shot = self.verb_errors.get(verb)
        if one_shot is not None and n in one_shot:
            raise one_shot.pop(n)
        if verb in self.fail_verbs:
            raise self.fail_verbs[verb]
        if sched is not None:
            err = sched.error_for(verb, n)
            if err is not None:
                with self._mu:
                    self.stats.errors += 1
                raise err
            lat = sched.latency_for(verb, n)
            if lat > 0:
                with self._mu:
                    self.stats.latency += 1
                time.sleep(lat)
        if verb != "read_file_stream":
            dur = self._stall_duration(verb, n, op, sched)
            if dur > 0:
                self._stall(dur)
        # read_file_stream defers its stall to the first read of the
        # returned stream (read_file_stream computes it there)
        return n

    def _stall_duration(self, verb: str, n: int, op: int,
                        sched) -> float:
        one_shot = self.verb_stalls.get(verb)
        if one_shot is not None and n in one_shot:
            return one_shot.pop(n)
        dur = self.stall_verbs.get(verb, 0.0)
        if dur <= 0 and sched is not None:
            dur = sched.stall_for(verb, n, op)
        return dur

    def _stall(self, dur: float) -> None:
        with self._mu:
            self.stats.stalls += 1
            self.stats.stall_s += dur
        time.sleep(dur)

    def _mangle_read(self, verb: str, n: int, data: bytes) -> bytes:
        sched = self.schedule if self.enabled else None
        if sched is None or not data:
            return data
        if sched.truncates(verb, n):
            with self._mu:
                self.stats.truncated += 1
            data = data[:max(1, len(data) // 2)]
        if sched.corrupts(verb, n):
            with self._mu:
                self.stats.bitrot += 1
            data = _flip_byte(data, sched.fault_offset(verb, n, len(data)))
        return data

    def _mangle_write(self, verb: str, n: int, data) -> bytes:
        return self._mangle_read(verb, n, bytes(data))

    # -- identity / health -------------------------------------------------

    def __str__(self) -> str:
        return f"naughty({self.inner})"

    def is_online(self) -> bool:
        if self.offline:
            return False
        if self.enabled and self.schedule is not None and \
                self.schedule.offline_at(self.total_ops + 1):
            return False
        return self.inner.is_online()

    def is_local(self) -> bool:
        return self.inner.is_local()

    def hostname(self) -> str:
        return self.inner.hostname()

    def endpoint(self) -> str:
        return self.inner.endpoint()

    def close(self) -> None:
        self.inner.close()

    def get_disk_id(self) -> str:
        return self.inner.get_disk_id()

    def set_disk_id(self, disk_id: str) -> None:
        self.inner.set_disk_id(disk_id)

    def healing(self) -> bool:
        return self.inner.healing()

    def disk_info(self) -> DiskInfo:
        self._begin("disk_info")
        return self.inner.disk_info()

    # -- volumes -----------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        self._begin("make_vol")
        self.inner.make_vol(volume)

    def list_vols(self) -> list[VolInfo]:
        self._begin("list_vols")
        return self.inner.list_vols()

    def stat_vol(self, volume: str) -> VolInfo:
        self._begin("stat_vol")
        return self.inner.stat_vol(volume)

    def delete_vol(self, volume: str, force: bool = False) -> None:
        self._begin("delete_vol")
        self.inner.delete_vol(volume, force)

    # -- metadata ----------------------------------------------------------

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        self._begin("write_metadata")
        self.inner.write_metadata(volume, path, fi)

    def read_version(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo:
        self._begin("read_version")
        return self.inner.read_version(volume, path, version_id)

    def read_versions(self, volume: str, path: str) -> list[FileInfo]:
        self._begin("read_versions")
        return self.inner.read_versions(volume, path)

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        self._begin("delete_version")
        self.inner.delete_version(volume, path, fi)

    def rename_data(self, src_volume: str, src_path: str, data_dir: str,
                    dst_volume: str, dst_path: str,
                    version_id: str = "") -> None:
        self._begin("rename_data")
        self.inner.rename_data(src_volume, src_path, data_dir,
                               dst_volume, dst_path, version_id)

    # -- files -------------------------------------------------------------

    def list_dir(self, volume: str, dir_path: str,
                 count: int = -1) -> list[str]:
        self._begin("list_dir")
        return self.inner.list_dir(volume, dir_path, count)

    def read_file(self, volume: str, path: str, offset: int, length: int,
                  verifier: Optional[BitrotVerifier] = None) -> bytes:
        n = self._begin("read_file")
        data = self.inner.read_file(volume, path, offset, length, verifier)
        return self._mangle_read("read_file", n, data)

    def append_file(self, volume: str, path: str, buf) -> None:
        n = self._begin("append_file")
        self.inner.append_file(volume, path,
                               self._mangle_write("append_file", n, buf))

    def create_file(self, volume: str, path: str, size: int,
                    reader: BinaryIO) -> None:
        n = self._begin("create_file")
        sched = self.schedule if self.enabled else None
        if sched is not None and (sched.truncates("create_file", n)
                                  or sched.corrupts("create_file", n)):
            # silent short write / rotted sector: stage, mangle, store
            data = self._mangle_write("create_file", n, reader.read())
            import io as _io
            self.inner.create_file(volume, path, len(data),
                                   _io.BytesIO(data))
            return
        self.inner.create_file(volume, path, size, reader)

    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int) -> BinaryIO:
        n = self._begin("read_file_stream")
        stream = self.inner.read_file_stream(volume, path, offset, length)
        # stalls ride the FIRST read, not the open: a gray drive
        # accepts the request fast and then dribbles bytes
        dur = self._stall_duration("read_file_stream", n, self.total_ops,
                                   self.schedule if self.enabled
                                   else None)
        if dur > 0:
            stream = _StallFirstReadStream(stream, dur, self._stall)
        sched = self.schedule if self.enabled else None
        if sched is None:
            return stream
        limit = -1
        flip_at = -1
        if sched.truncates("read_file_stream", n):
            with self._mu:
                self.stats.truncated += 1
            limit = max(1, length // 2)
        if sched.corrupts("read_file_stream", n):
            flip_at = sched.fault_offset("read_file_stream", n, length)
            if 0 <= limit <= flip_at:
                # the flip lands past the truncation point: no byte is
                # actually mutated, so the stat must not claim one
                # (FaultStats records what was INJECTED, not rolled)
                flip_at = -1
            else:
                with self._mu:
                    self.stats.bitrot += 1
        if limit < 0 and flip_at < 0:
            return stream
        return _TruncatedStream(stream, limit, flip_at)

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        self._begin("rename_file")
        self.inner.rename_file(src_volume, src_path, dst_volume, dst_path)

    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        self._begin("check_parts")
        self.inner.check_parts(volume, path, fi)

    def check_file(self, volume: str, path: str) -> None:
        self._begin("check_file")
        self.inner.check_file(volume, path)

    def delete_file(self, volume: str, path: str,
                    recursive: bool = False) -> None:
        self._begin("delete_file")
        self.inner.delete_file(volume, path, recursive)

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        self._begin("verify_file")
        self.inner.verify_file(volume, path, fi)

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        n = self._begin("write_all")
        self.inner.write_all(volume, path,
                             self._mangle_write("write_all", n, data))

    def read_all(self, volume: str, path: str) -> bytes:
        n = self._begin("read_all")
        return self._mangle_read("read_all", n,
                                 self.inner.read_all(volume, path))

    # -- listing / crawling ------------------------------------------------

    def walk(self, volume: str, dir_path: str = "", marker: str = "",
             recursive: bool = True) -> Iterator[FileInfo]:
        self._begin("walk")
        return self.inner.walk(volume, dir_path, marker, recursive)

    def walk_versions(self, volume: str, dir_path: str = "",
                      marker: str = "", recursive: bool = True):
        self._begin("walk")
        return self.inner.walk_versions(volume, dir_path, marker, recursive)

    # extras some callers probe for (appender capability must NOT leak
    # through, or framed writes would bypass the fault gate)
    def __getattr__(self, name):
        raise AttributeError(name)
