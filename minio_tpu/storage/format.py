"""format.json v3 — per-drive identity and erasure topology.

JSON-compatible with the reference (cmd/format-erasure.go:106-127):

    {"version": "1", "format": "xl", "id": <deploymentID>,
     "xl": {"version": "3", "this": <diskUUID>,
            "sets": [[uuid, ...], ...], "distributionAlgo": "SIPMOD"}}

Every drive stores the full sets×drives UUID matrix, so any quorum of
drives can re-derive the cluster topology (getFormatErasureInQuorum,
cmd/format-erasure.go:585).
"""

from __future__ import annotations

import dataclasses
import json
import uuid as _uuid
from collections import Counter

from . import errors

FORMAT_CONFIG_FILE = "format.json"
MINIO_META_BUCKET = ".minio.sys"
OFFLINE_DISK_UUID = "ffffffff-ffff-ffff-ffff-ffffffffffff"
DISTRIBUTION_ALGO_V3 = "SIPMOD"
DISTRIBUTION_ALGO_V2 = "CRCMOD"


@dataclasses.dataclass
class FormatErasureV3:
    version: str = "1"
    format: str = "xl"
    id: str = ""                       # deployment ID
    erasure_version: str = "3"
    this: str = ""                     # this drive's UUID
    sets: list[list[str]] = dataclasses.field(default_factory=list)
    distribution_algo: str = DISTRIBUTION_ALGO_V3

    def to_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "format": self.format,
            "id": self.id,
            "xl": {
                "version": self.erasure_version,
                "this": self.this,
                "sets": self.sets,
                "distributionAlgo": self.distribution_algo,
            },
        })

    @classmethod
    def from_json(cls, data: str | bytes) -> "FormatErasureV3":
        try:
            d = json.loads(data)
        except Exception as e:
            raise errors.CorruptedFormat(str(e)) from e
        if d.get("format") != "xl":
            raise errors.CorruptedFormat(
                f"unsupported backend format {d.get('format')!r}")
        xl = d.get("xl") or {}
        if xl.get("version") != "3":
            raise errors.CorruptedFormat(
                f"unsupported xl format version {xl.get('version')!r}")
        return cls(version=d.get("version", "1"), format="xl",
                   id=d.get("id", ""), erasure_version="3",
                   this=xl.get("this", ""), sets=xl.get("sets", []),
                   distribution_algo=xl.get("distributionAlgo",
                                            DISTRIBUTION_ALGO_V3))

    def drive_count(self) -> int:
        return sum(len(s) for s in self.sets)

    def find_disk_index(self, disk_uuid: str) -> tuple[int, int]:
        """(set index, disk index) of a drive UUID
        (reference findDiskIndex)."""
        for i, s in enumerate(self.sets):
            for j, u in enumerate(s):
                if u == disk_uuid:
                    return i, j
        raise errors.DiskNotFound(f"disk uuid {disk_uuid} not in format")


def new_format_erasure_v3(num_sets: int, set_drive_count: int,
                          deployment_id: str = "") -> list[list[FormatErasureV3]]:
    """Fresh formats for numSets×setDriveCount drives
    (reference newFormatErasureV3, cmd/format-erasure.go:106-127)."""
    deployment_id = deployment_id or str(_uuid.uuid4())
    sets = [[str(_uuid.uuid4()) for _ in range(set_drive_count)]
            for _ in range(num_sets)]
    out: list[list[FormatErasureV3]] = []
    for i in range(num_sets):
        row = []
        for j in range(set_drive_count):
            row.append(FormatErasureV3(
                id=deployment_id, this=sets[i][j],
                sets=[list(s) for s in sets]))
        out.append(row)
    return out


def get_format_in_quorum(formats: list[FormatErasureV3 | None]
                         ) -> FormatErasureV3:
    """Pick the topology attested by a strict majority of drives
    (reference getFormatErasureInQuorum, cmd/format-erasure.go:585):
    formats are grouped by their sets-matrix; the largest group must
    exceed N/2."""
    live = [f for f in formats if f is not None]
    if not live:
        raise errors.UnformattedDisk("no formatted drives")
    counts: Counter[str] = Counter()
    for f in live:
        counts[json.dumps(f.sets)] += 1
    key, n = counts.most_common(1)[0]
    if n <= len(formats) // 2:
        raise errors.CorruptedFormat(
            f"no format quorum: best {n} of {len(formats)}")
    for f in live:
        if json.dumps(f.sets) == key:
            ref = dataclasses.replace(f, this="")
            return ref
    raise errors.CorruptedFormat("unreachable")


def read_format_from(disk) -> FormatErasureV3:
    """Read format.json through the StorageAPI surface (works for local
    AND remote drives — the remote bootstrap path)."""
    data = disk.read_all(MINIO_META_BUCKET, FORMAT_CONFIG_FILE)
    return FormatErasureV3.from_json(data)


def write_format_to(disk, fmt: FormatErasureV3) -> None:
    """Write format.json through the StorageAPI surface, creating the
    meta volumes (reference initFormatErasure per-disk work)."""
    for vol in (MINIO_META_BUCKET, MINIO_META_BUCKET + "/buckets",
                MINIO_META_BUCKET + "/tmp", MINIO_META_BUCKET + "/multipart"):
        try:
            disk.make_vol(vol)
        except errors.VolumeExists:
            pass
    disk.write_all(MINIO_META_BUCKET, FORMAT_CONFIG_FILE,
                   fmt.to_json().encode())
    try:
        disk.set_disk_id(fmt.this)
    except errors.StorageError:
        pass


def check_format_consistency(ref: FormatErasureV3,
                             f: FormatErasureV3) -> None:
    """A drive's format must agree with the quorum topology
    (formatErasureV3Check)."""
    if f.id != ref.id:
        raise errors.CorruptedFormat(
            f"deployment id mismatch: {f.id} != {ref.id}")
    if f.sets != ref.sets:
        raise errors.CorruptedFormat("sets topology mismatch")
    f.find_disk_index(f.this)
