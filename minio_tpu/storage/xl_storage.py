"""XLStorage — one local POSIX drive.

The local implementation of StorageAPI (reference: cmd/xl-storage.go).
On-disk layout is the reference's exactly (so its binary can read our
drives):

    <root>/.minio.sys/format.json          drive identity + topology
    <root>/<bucket>/<object>/xl.meta       version journal (xl_meta.py)
    <root>/<bucket>/<object>/<dataDir>/part.N   bitrot-framed shards
    <root>/.minio.sys/tmp/<uuid>/...       staged writes (2-phase commit)
    <root>/.minio.sys/multipart/<sha>/<uploadID>/  multipart sessions

Writes are staged in tmp and committed with an atomic os.replace-based
rename (reference RenameData, cmd/xl-storage.go:2041). Bitrot
verification reads the streaming [digest||block]* framing
(cmd/xl-storage.go bitrotVerify:2339).
"""

from __future__ import annotations

import io
import os
import shutil
import threading
import uuid as _uuid
from typing import BinaryIO, Iterator, Optional

from .. import bitrot as bitrot_mod
from ..utils import atomicfile, crashpoint, knobs, telemetry
from . import errors
from .api import BitrotVerifier, StorageAPI
from .datatypes import DiskInfo, FileInfo, VolInfo
from .format import FORMAT_CONFIG_FILE, MINIO_META_BUCKET, FormatErasureV3
from .xl_meta import XLMetaV2

XL_STORAGE_FORMAT_FILE = "xl.meta"
XL_LEGACY_FORMAT_FILE = "xl.json"   # format v1 (migrated on access)
MINIO_META_TMP_BUCKET = MINIO_META_BUCKET + "/tmp"
MINIO_META_MULTIPART_BUCKET = MINIO_META_BUCKET + "/multipart"
MAX_PATH_LEN = 4096


def _check_path_length(p: str) -> None:
    if len(p) > MAX_PATH_LEN:
        raise errors.FileNameTooLong(p)
    for comp in p.split("/"):
        if len(comp) > 255:
            raise errors.FileNameTooLong(comp)


def _check_path_safe(p: str) -> None:
    """Reject path components that would escape the drive root — S3 keys
    may legally contain '..' (the reference rejects these at the storage
    layer too; see cmd/xl-storage.go path checks)."""
    if p.startswith("/") or p.startswith("\\"):
        raise errors.FileAccessDenied(p)
    for comp in p.replace("\\", "/").split("/"):
        if comp in ("..",):
            raise errors.FileAccessDenied(p)


class _DirectWriter:
    """Sequential O_DIRECT file writer (reference CreateFile's
    odirectWriter, cmd/xl-storage.go:1664 + cmd/fallocate_linux.go):
    bytes stage in a page-aligned mmap buffer and flush to the kernel
    in ALIGN-multiple chunks, bypassing the page cache — big PUTs must
    not evict a node's read cache. The unaligned tail is written after
    clearing O_DIRECT via fcntl (Linux semantics: alignment applies
    per-write, the flag can be dropped mid-file)."""

    ALIGN = 4096
    BUF = 1 << 20

    def __init__(self, path: str, truncate: bool = True):
        import mmap
        # raises OSError on filesystems without O_DIRECT — callers
        # fall back to buffered IO. Non-truncating mode appends (the
        # open_appender contract); O_DIRECT appends stay aligned only
        # from an empty/aligned file, which open_appender checks.
        flags = os.O_WRONLY | os.O_CREAT | os.O_DIRECT \
            | (os.O_TRUNC if truncate else os.O_APPEND)
        self.fd = os.open(path, flags, 0o644)
        self._buf = mmap.mmap(-1, self.BUF)     # page-aligned
        self._fill = 0
        self._closed = False

    def fileno(self) -> int:
        return self.fd

    def _flush_exact(self, view) -> None:
        """os.write may consume a partial (aligned) prefix — e.g. disk
        full mid-flush returns a short count, not an exception; a
        silent short write would corrupt the shard mid-file."""
        at = 0
        while at < len(view):
            n = os.write(self.fd, view[at:])
            if n <= 0:
                raise OSError(f"short O_DIRECT write ({at}/{len(view)})")
            at += n

    def write(self, data) -> int:
        mv = memoryview(data).cast("B") if not isinstance(data, bytes) \
            else memoryview(data)
        n = len(mv)
        at = 0
        while at < n:
            take = min(self.BUF - self._fill, n - at)
            self._buf[self._fill:self._fill + take] = mv[at:at + take]
            self._fill += take
            at += take
            if self._fill == self.BUF:
                self._flush_exact(memoryview(self._buf)[:self.BUF])
                self._fill = 0
        return n

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            aligned = (self._fill // self.ALIGN) * self.ALIGN
            if aligned:
                self._flush_exact(memoryview(self._buf)[:aligned])
            tail = self._fill - aligned
            if tail:
                import fcntl
                flags = fcntl.fcntl(self.fd, fcntl.F_GETFL)
                fcntl.fcntl(self.fd, fcntl.F_SETFL,
                            flags & ~os.O_DIRECT)
                self._flush_exact(
                    memoryview(self._buf)[aligned:self._fill])
            # O_DIRECT bypasses the page cache for DATA only — file
            # size/allocation metadata still needs the barrier
            atomicfile.fsync_file(self.fd)
        finally:
            self._buf.close()
            os.close(self.fd)

    def __del__(self):
        # abandoned writers (a failed shard write drops the handle
        # without close) must not leak the raw fd + pinned mmap the
        # way GC-closed io objects don't
        try:
            if not self._closed:
                self._closed = True
                self._buf.close()
                os.close(self.fd)
        except (OSError, AttributeError):
            pass


class _SyncedAppender:
    """Buffered append handle that fsyncs at close — the shard-write
    barrier under MINIO_TPU_FSYNC (a shard referenced by a committed
    xl.meta must not evaporate in a power cut)."""

    def __init__(self, f):
        self._f = f

    def write(self, data) -> int:
        return self._f.write(data)

    def fileno(self) -> int:
        return self._f.fileno()

    def close(self) -> None:
        try:
            atomicfile.fsync_file(self._f)
        finally:
            self._f.close()


def _direct_io_default() -> bool:
    return knobs.get_bool("MINIO_TPU_DIRECT_IO")


class XLStorage(StorageAPI):
    def __init__(self, root: str, direct_io: Optional[bool] = None):
        self.root = os.path.abspath(root)
        try:
            os.makedirs(self.root, exist_ok=True)
        except PermissionError as e:
            raise errors.DiskAccessDenied(str(e)) from e
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        if not os.access(self.root, os.W_OK):
            raise errors.DiskAccessDenied(self.root)
        self._disk_id = ""
        self._lock = threading.Lock()
        self._online = True
        self._healing = False
        # O_DIRECT shard writes (MINIO_TPU_DIRECT_IO=on): page-cache
        # bypass on the PUT path; falls back to buffered per-file when
        # the filesystem refuses (tmpfs)
        self.direct_io = _direct_io_default() if direct_io is None \
            else direct_io

    # -- identity ----------------------------------------------------------

    def __str__(self) -> str:
        return self.root

    def is_online(self) -> bool:
        return self._online

    def is_local(self) -> bool:
        return True

    def endpoint(self) -> str:
        return self.root

    def close(self) -> None:
        pass

    def get_disk_id(self) -> str:
        """Read the drive UUID from format.json (cached; reference
        GetDiskID re-checks on change)."""
        with self._lock:
            if self._disk_id:
                return self._disk_id
            fmt_path = os.path.join(self.root, MINIO_META_BUCKET,
                                    FORMAT_CONFIG_FILE)
            try:
                with open(fmt_path, "rb") as f:
                    fmt = FormatErasureV3.from_json(f.read())
            except FileNotFoundError:
                raise errors.UnformattedDisk(self.root) from None
            except OSError as e:
                raise errors.FaultyDisk(str(e)) from e
            self._disk_id = fmt.this
            return self._disk_id

    def set_disk_id(self, disk_id: str) -> None:
        # Local drives derive their ID from format.json; setter is for
        # remote clients (reference xlStorage.SetDiskID is a no-op too).
        pass

    def healing(self) -> bool:
        return self._healing

    def disk_info(self) -> DiskInfo:
        try:
            st = os.statvfs(self.root)
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        disk_id = ""
        try:
            disk_id = self.get_disk_id()
        except errors.StorageError:
            pass
        return DiskInfo(total=total, free=free, used=total - free,
                        fs_type="posix", endpoint=self.root,
                        mount_path=self.root, disk_id=disk_id,
                        healing=self._healing)

    # -- format helpers (used by the format/bootstrap layer) ---------------

    def read_format(self) -> FormatErasureV3:
        data = self.read_all(MINIO_META_BUCKET, FORMAT_CONFIG_FILE)
        return FormatErasureV3.from_json(data)

    def write_format(self, fmt: FormatErasureV3) -> None:
        self.make_vol_bulk(MINIO_META_BUCKET, MINIO_META_TMP_BUCKET,
                           MINIO_META_MULTIPART_BUCKET,
                           MINIO_META_BUCKET + "/buckets")
        self.write_all(MINIO_META_BUCKET, FORMAT_CONFIG_FILE,
                       fmt.to_json().encode())
        with self._lock:
            self._disk_id = fmt.this

    # -- paths -------------------------------------------------------------

    def _vol_dir(self, volume: str) -> str:
        if not volume or volume == "." or volume == "..":
            raise errors.VolumeNotFound(volume)
        _check_path_safe(volume)
        return os.path.join(self.root, volume)

    def _file_path(self, volume: str, path: str) -> str:
        _check_path_safe(path)
        p = os.path.join(self._vol_dir(volume), path)
        _check_path_length(p)
        return p

    # -- volumes -----------------------------------------------------------

    def make_vol(self, volume: str) -> None:
        vdir = self._vol_dir(volume)
        if os.path.isdir(vdir):
            raise errors.VolumeExists(volume)
        try:
            os.makedirs(vdir)
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def make_vol_bulk(self, *volumes: str) -> None:
        for v in volumes:
            os.makedirs(self._vol_dir(v), exist_ok=True)

    def list_vols(self) -> list[VolInfo]:
        out = []
        try:
            for name in sorted(os.listdir(self.root)):
                full = os.path.join(self.root, name)
                if os.path.isdir(full) and name != MINIO_META_BUCKET:
                    out.append(VolInfo(name=name,
                                       created=os.stat(full).st_ctime))
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        return out

    def stat_vol(self, volume: str) -> VolInfo:
        vdir = self._vol_dir(volume)
        try:
            st = os.stat(vdir)
        except FileNotFoundError:
            raise errors.VolumeNotFound(volume) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        return VolInfo(name=volume, created=st.st_ctime)

    def delete_vol(self, volume: str, force: bool = False) -> None:
        vdir = self._vol_dir(volume)
        try:
            if force:
                shutil.rmtree(vdir)
            else:
                os.rmdir(vdir)
        except FileNotFoundError:
            raise errors.VolumeNotFound(volume) from None
        except OSError as e:
            if os.path.isdir(vdir) and os.listdir(vdir):
                raise errors.VolumeNotEmpty(volume) from e
            raise errors.FaultyDisk(str(e)) from e

    # -- raw files ---------------------------------------------------------

    def read_all(self, volume: str, path: str) -> bytes:
        fp = self._file_path(volume, path)
        try:
            with open(fp, "rb") as f:
                return f.read()
        except FileNotFoundError:
            if not os.path.isdir(self._vol_dir(volume)):
                raise errors.VolumeNotFound(volume) from None
            raise errors.FileNotFound(path) from None
        except IsADirectoryError:
            raise errors.FileNotFound(path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def write_all(self, volume: str, path: str, data: bytes) -> None:
        fp = self._file_path(volume, path)
        try:
            os.makedirs(os.path.dirname(fp), exist_ok=True)
            # torn-write injection context for in-process crash tests:
            # an armed action receives path=/data= and can commit a
            # truncated copy to the final name before aborting (what
            # power loss without the fsync discipline produces)
            crashpoint.hit("storage.write_all.commit", path=fp,
                           data=data)
            # write-temp → (fsync) → rename → (dirsync): MINIO_TPU_FSYNC
            # turns the barriers on (pkg/safe analog + ALICE safe-rename)
            atomicfile.write_atomic(fp, data)
        except NotADirectoryError:
            raise errors.FileParentIsFile(fp) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def append_file(self, volume: str, path: str, buf: bytes) -> None:
        if not os.path.isdir(self._vol_dir(volume)):
            raise errors.VolumeNotFound(volume)
        fp = self._file_path(volume, path)
        try:
            with telemetry.span("disk.append_file", bytes=len(buf)):
                os.makedirs(os.path.dirname(fp), exist_ok=True)
                with open(fp, "ab") as f:
                    f.write(buf)
                    # remote disks stream shards through THIS verb (the
                    # RPC client has no appender), so the shard-durable-
                    # before-meta-commit barrier must live here too
                    atomicfile.fsync_file(f)
        except NotADirectoryError:
            raise errors.FileParentIsFile(fp) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def has_appender(self) -> bool:
        """Capability probe for open_appender — wrappers delegate this,
        so a guard wrapper can expose open_appender unconditionally
        while the probe still reflects the backend's real support."""
        return True

    def open_appender(self, volume: str, path: str):
        """Persistent append handle for the shard-write hot path: the
        bitrot writer streams [digest‖block] frames straight into the
        OS file instead of re-buffering them in Python and re-opening
        the file per flush (one memcpy pass saved per shard file).
        Local drives only — remote disks keep the buffered append_file
        batches (one RPC per flush, not per frame)."""
        if not os.path.isdir(self._vol_dir(volume)):
            raise errors.VolumeNotFound(volume)
        fp = self._file_path(volume, path)
        try:
            os.makedirs(os.path.dirname(fp), exist_ok=True)
            if self.direct_io:
                # append semantics must match the buffered path: only
                # go direct when the append offset is aligned (fresh
                # tmp shard files — the hot path — start at zero)
                try:
                    existing = os.path.getsize(fp)
                except OSError:
                    existing = 0
                if existing % _DirectWriter.ALIGN == 0:
                    try:
                        return _DirectWriter(fp, truncate=False)
                    except OSError:
                        pass      # fs without O_DIRECT: buffered
            f = open(fp, "ab")
            # shard files must be durable BEFORE the xl.meta commit
            # references them: sync at close under the discipline
            return _SyncedAppender(f) if atomicfile.fsync_enabled() \
                else f
        except NotADirectoryError:
            raise errors.FileParentIsFile(fp) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def create_file(self, volume: str, path: str, size: int,
                    reader: BinaryIO) -> None:
        """Stream `size` bytes (exactly) from reader into a fresh file
        (reference CreateFile, cmd/xl-storage.go:1664: fallocate +
        sequential write; errLessData/errMoreData on mismatch)."""
        with telemetry.span("disk.create_file", size=size):
            self._create_file(volume, path, size, reader)

    def _create_file(self, volume: str, path: str, size: int,
                     reader: BinaryIO) -> None:
        fp = self._file_path(volume, path)
        if not os.path.isdir(self._vol_dir(volume)):
            raise errors.VolumeNotFound(volume)
        try:
            os.makedirs(os.path.dirname(fp), exist_ok=True)
            f = None
            if self.direct_io and size >= _DirectWriter.ALIGN:
                try:
                    f = _DirectWriter(fp)
                except OSError:
                    f = None              # tmpfs etc.: buffered
            if f is None:
                f = open(fp, "wb")
            try:
                if size > 0:
                    try:
                        os.posix_fallocate(f.fileno(), 0, size)
                    except OSError:
                        pass
                remaining = size
                while True:
                    chunk = reader.read(min(1 << 20, remaining)
                                        if size >= 0 else 1 << 20)
                    if not chunk:
                        break
                    if size >= 0 and len(chunk) > remaining:
                        raise errors.MoreData(path)
                    f.write(chunk)
                    remaining -= len(chunk)
                    if size >= 0 and remaining == 0:
                        if reader.read(1):
                            raise errors.MoreData(path)
                        break
                if size >= 0 and remaining > 0:
                    raise errors.LessData(path)
            finally:
                if not isinstance(f, _DirectWriter):
                    # _DirectWriter barriers inside its own close
                    # (after the unaligned-tail flush)
                    atomicfile.fsync_file(f)
                f.close()
        except NotADirectoryError:
            raise errors.FileParentIsFile(fp) from None
        except (errors.StorageError,):
            raise
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def read_file(self, volume: str, path: str, offset: int, length: int,
                  verifier: Optional[BitrotVerifier] = None) -> bytes:
        fp = self._file_path(volume, path)
        with telemetry.span("disk.read_file", length=length):
            return self._read_file(fp, volume, path, offset, length,
                                   verifier)

    def _read_file(self, fp: str, volume: str, path: str, offset: int,
                   length: int,
                   verifier: Optional[BitrotVerifier] = None) -> bytes:
        try:
            with open(fp, "rb") as f:
                if verifier is not None:
                    whole = f.read()
                    digest = bitrot_mod.hash_shard(
                        whole,
                        bitrot_mod.BitrotAlgorithm.from_string(
                            verifier.algorithm))
                    if digest != verifier.digest:
                        raise errors.BitrotHashMismatch(
                            verifier.digest.hex(), digest.hex())
                    return whole[offset:offset + length]
                f.seek(offset)
                return f.read(length)
        except FileNotFoundError:
            if not os.path.isdir(self._vol_dir(volume)):
                raise errors.VolumeNotFound(volume) from None
            raise errors.FileNotFound(path) from None
        except IsADirectoryError:
            raise errors.IsNotRegular(path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e

    def read_file_stream(self, volume: str, path: str, offset: int,
                         length: int) -> BinaryIO:
        fp = self._file_path(volume, path)
        try:
            f = open(fp, "rb")
        except FileNotFoundError:
            if not os.path.isdir(self._vol_dir(volume)):
                raise errors.VolumeNotFound(volume) from None
            raise errors.FileNotFound(path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        f.seek(offset)
        return _LimitedReader(f, length)

    def rename_file(self, src_volume: str, src_path: str,
                    dst_volume: str, dst_path: str) -> None:
        src = self._file_path(src_volume, src_path)
        dst = self._file_path(dst_volume, dst_path)
        try:
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            os.replace(src, dst)
        except FileNotFoundError:
            raise errors.FileNotFound(src_path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        atomicfile.fsync_dir(os.path.dirname(dst))
        self._cleanup_empty_parents(src_volume, os.path.dirname(src))

    def delete_file(self, volume: str, path: str,
                    recursive: bool = False) -> None:
        fp = self._file_path(volume, path)
        try:
            if os.path.isdir(fp):
                if recursive:
                    shutil.rmtree(fp)
                else:
                    os.rmdir(fp)
            else:
                os.unlink(fp)
        except FileNotFoundError:
            raise errors.FileNotFound(path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        self._cleanup_empty_parents(volume, os.path.dirname(fp))

    def _cleanup_empty_parents(self, volume: str, dirpath: str) -> None:
        """Remove now-empty parent dirs up to (not incl.) the volume root
        (reference deleteFile parent sweep)."""
        vol = self._vol_dir(volume)
        while dirpath.startswith(vol) and dirpath != vol:
            try:
                os.rmdir(dirpath)
            except OSError:
                return
            dirpath = os.path.dirname(dirpath)

    def check_file(self, volume: str, path: str) -> None:
        fp = self._file_path(volume, path)
        if not os.path.isfile(os.path.join(fp, XL_STORAGE_FORMAT_FILE)) \
                and not os.path.isfile(
                    os.path.join(fp, XL_LEGACY_FORMAT_FILE)):
            raise errors.FileNotFound(path)

    def list_dir(self, volume: str, dir_path: str,
                 count: int = -1) -> list[str]:
        """Sorted entries; directories get a trailing slash (reference
        ListDir/readDirN semantics)."""
        vdir = self._vol_dir(volume)
        if not os.path.isdir(vdir):
            raise errors.VolumeNotFound(volume)
        full = os.path.join(vdir, dir_path) if dir_path else vdir
        try:
            names = sorted(os.listdir(full))
        except FileNotFoundError:
            raise errors.FileNotFound(dir_path) from None
        except NotADirectoryError:
            raise errors.FileNotFound(dir_path) from None
        except OSError as e:
            raise errors.FaultyDisk(str(e)) from e
        out = []
        for n in names:
            if os.path.isdir(os.path.join(full, n)):
                out.append(n + "/")
            else:
                out.append(n)
            if 0 < count <= len(out):
                break
        return out

    # -- metadata ----------------------------------------------------------

    def _read_xl_meta(self, volume: str, path: str) -> XLMetaV2:
        try:
            buf = self.read_all(volume,
                                os.path.join(path, XL_STORAGE_FORMAT_FILE))
        except errors.FileNotFound:
            # legacy v1 drive: migrate xl.json -> xl.meta on first touch
            # (reference migrates at startup/access,
            # cmd/xl-storage-format-v1.go + readVersion fallback)
            from .xl_meta import from_xl_v1_json
            legacy = self.read_all(
                volume, os.path.join(path, XL_LEGACY_FORMAT_FILE))
            meta = from_xl_v1_json(legacy)
            self.write_all(volume,
                           os.path.join(path, XL_STORAGE_FORMAT_FILE),
                           meta.dumps())
            try:
                os.remove(self._file_path(
                    volume, os.path.join(path, XL_LEGACY_FORMAT_FILE)))
            except OSError:
                pass
            return meta
        return XLMetaV2.loads(buf)

    def write_metadata(self, volume: str, path: str, fi: FileInfo) -> None:
        """Append fi as a version into xl.meta (creating it if absent) —
        reference WriteMetadata (cmd/xl-storage.go:1219)."""
        try:
            meta = self._read_xl_meta(volume, path)
        except errors.FileNotFound:
            meta = XLMetaV2()
        meta.add_version(fi)
        self.write_all(volume, os.path.join(path, XL_STORAGE_FORMAT_FILE),
                       meta.dumps())

    def read_version(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo:
        meta = self._read_xl_meta(volume, path)
        return meta.to_file_info(volume, path, version_id)

    def read_versions(self, volume: str, path: str) -> list[FileInfo]:
        meta = self._read_xl_meta(volume, path)
        return meta.list_file_infos(volume, path)

    def delete_version(self, volume: str, path: str, fi: FileInfo) -> None:
        """Drop one version; purge its data dir; remove xl.meta (and the
        object dir) when the journal empties (reference DeleteVersion,
        cmd/xl-storage.go:1147)."""
        meta = self._read_xl_meta(volume, path)
        data_dir, last = meta.delete_version(fi)
        if data_dir:
            try:
                self.delete_file(volume, os.path.join(path, data_dir),
                                 recursive=True)
            except errors.FileNotFound:
                pass
        if last:
            try:
                self.delete_file(volume,
                                 os.path.join(path, XL_STORAGE_FORMAT_FILE))
            except errors.FileNotFound:
                pass
        else:
            self.write_all(volume,
                           os.path.join(path, XL_STORAGE_FORMAT_FILE),
                           meta.dumps())

    def rename_data(self, src_volume: str, src_path: str, data_dir: str,
                    dst_volume: str, dst_path: str,
                    version_id: str = "") -> None:
        """Commit a staged write: merge the committed version of src's
        xl.meta into dst's journal, move the data dir, drop src
        (reference RenameData, cmd/xl-storage.go:2041 — the
        2-phase-commit finish). `version_id` names the version being
        committed; without it the latest entry is assumed (correct
        only when the staged meta holds one version)."""
        with telemetry.span("disk.rename_data"):
            self._rename_data(src_volume, src_path, data_dir,
                              dst_volume, dst_path, version_id)

    def _rename_data(self, src_volume: str, src_path: str, data_dir: str,
                     dst_volume: str, dst_path: str,
                     version_id: str = "") -> None:
        src_meta = self._read_xl_meta(src_volume, src_path)
        # the staged multipart session meta holds the session
        # placeholder AND the final version — "latest by mod time" is
        # wrong for version-faithful replays (preserved mod times sort
        # behind the placeholder), so the commit names its version
        fi = src_meta.to_file_info(dst_volume, dst_path, version_id)
        try:
            dst_meta = self._read_xl_meta(dst_volume, dst_path)
        except errors.FileNotFound:
            dst_meta = XLMetaV2()
        dst_meta.add_version(fi)

        if data_dir:
            src_data = self._file_path(src_volume,
                                       os.path.join(src_path, data_dir))
            dst_data = self._file_path(dst_volume,
                                       os.path.join(dst_path, data_dir))
            try:
                os.makedirs(os.path.dirname(dst_data), exist_ok=True)
                if os.path.isdir(dst_data):
                    shutil.rmtree(dst_data)
                os.replace(src_data, dst_data)
            except FileNotFoundError:
                raise errors.FileNotFound(src_path) from None
            except OSError as e:
                raise errors.FaultyDisk(str(e)) from e
            atomicfile.fsync_dir(os.path.dirname(dst_data))

        # the single-drive torn window: data dir in place, xl.meta not
        # yet rewritten — restart-side fsck must reclaim the orphan
        crashpoint.hit("storage.rename_data.before_meta")
        self.write_all(dst_volume,
                       os.path.join(dst_path, XL_STORAGE_FORMAT_FILE),
                       dst_meta.dumps())
        try:
            self.delete_file(src_volume, src_path, recursive=True)
        except errors.FileNotFound:
            pass

    # -- integrity ---------------------------------------------------------

    def check_parts(self, volume: str, path: str, fi: FileInfo) -> None:
        """Every part file must exist with its exact shard-file size
        (reference CheckParts, cmd/xl-storage.go)."""
        for part in fi.parts:
            pp = os.path.join(path, fi.data_dir, f"part.{part.number}")
            fp = self._file_path(volume, pp)
            csum = fi.erasure.get_checksum_info(part.number)
            algo = (bitrot_mod.BitrotAlgorithm.from_string(csum.algorithm)
                    if csum else bitrot_mod.DEFAULT_BITROT_ALGORITHM)
            want = bitrot_mod.bitrot_shard_file_size(
                fi.erasure.shard_file_size(part.size),
                fi.erasure.shard_size(), algo)
            try:
                st = os.stat(fp)
            except FileNotFoundError:
                raise errors.FileNotFound(pp) from None
            except OSError as e:
                raise errors.FaultyDisk(str(e)) from e
            if st.st_size < want:
                raise errors.FileCorrupt(
                    f"{pp}: size {st.st_size} < expected {want}")

    def verify_file(self, volume: str, path: str, fi: FileInfo) -> None:
        """Full bitrot scan of every part (reference VerifyFile,
        cmd/xl-storage.go:2410): streaming algos verify each
        [digest||block] frame; whole-file algos hash the entire shard."""
        for part in fi.parts:
            pp = os.path.join(path, fi.data_dir, f"part.{part.number}")
            csum = fi.erasure.get_checksum_info(part.number)
            algo = bitrot_mod.BitrotAlgorithm.from_string(
                csum.algorithm) if csum else \
                bitrot_mod.DEFAULT_BITROT_ALGORITHM
            fp = self._file_path(volume, pp)
            try:
                f = open(fp, "rb")
            except FileNotFoundError:
                raise errors.FileNotFound(pp) from None
            except OSError as e:
                raise errors.FaultyDisk(str(e)) from e
            with f:
                if algo.streaming:
                    self._verify_streaming(f, fi, part.size, algo, pp)
                else:
                    h = bitrot_mod.new_hasher(algo)
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        h.update(chunk)
                    if csum and csum.hash and h.digest() != csum.hash:
                        raise errors.BitrotHashMismatch(
                            csum.hash.hex(), h.digest().hex())

    def _verify_streaming(self, f, fi: FileInfo, part_size: int,
                          algo, pp: str) -> None:
        shard_size = fi.erasure.shard_size()
        remaining = fi.erasure.shard_file_size(part_size)
        while remaining > 0:
            want_digest = f.read(algo.digest_size)
            if len(want_digest) != algo.digest_size:
                raise errors.FileCorrupt(f"{pp}: truncated bitrot frame")
            n = min(shard_size, remaining)
            block = f.read(n)
            if len(block) != n:
                raise errors.FileCorrupt(f"{pp}: truncated shard block")
            got = bitrot_mod.hash_shard(block, algo)
            if got != want_digest:
                raise errors.BitrotHashMismatch(want_digest.hex(), got.hex())
            remaining -= n

    # -- walk --------------------------------------------------------------

    def walk(self, volume: str, dir_path: str = "", marker: str = "",
             recursive: bool = True) -> Iterator[FileInfo]:
        """Lexically sorted stream of latest-version FileInfos under a
        prefix (reference Walk, cmd/xl-storage.go:1015)."""
        vdir = self._vol_dir(volume)
        if not os.path.isdir(vdir):
            raise errors.VolumeNotFound(volume)

        def _walk(rel: str) -> Iterator[FileInfo]:
            full = os.path.join(vdir, rel) if rel else vdir
            try:
                entries = sorted(os.listdir(full))
            except OSError:
                return
            if XL_STORAGE_FORMAT_FILE in entries:
                if rel and (not marker or rel > marker):
                    try:
                        yield self.read_version(volume, rel)
                    except errors.StorageError:
                        pass
                return
            for e in entries:
                sub = os.path.join(rel, e) if rel else e
                subfull = os.path.join(full, e)
                if not os.path.isdir(subfull):
                    continue
                if recursive:
                    yield from _walk(sub)
                elif os.path.isfile(
                        os.path.join(subfull, XL_STORAGE_FORMAT_FILE)):
                    # flat object: yield it, not a pseudo-prefix
                    if not marker or sub > marker:
                        try:
                            yield self.read_version(volume, sub)
                        except errors.StorageError:
                            pass
                elif not marker or sub > marker:
                    yield FileInfo(volume=volume, name=sub + "/")

        yield from _walk(dir_path)

    def walk_versions(self, volume: str, dir_path: str = "",
                      marker: str = "", recursive: bool = True
                      ) -> Iterator[list[FileInfo]]:
        for fi in self.walk(volume, dir_path, marker, recursive):
            if fi.name.endswith("/"):
                continue
            try:
                yield self.read_versions(volume, fi.name)
            except errors.StorageError:
                pass


class _LimitedReader(io.RawIOBase):
    """Reads at most `length` bytes from an underlying file, closing it
    on exhaustion (reference ReadFileStream's LimitReader)."""

    def __init__(self, f, length: int):
        self._f = f
        self._remaining = length

    def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        if n is None or n < 0:
            n = self._remaining
        data = self._f.read(min(n, self._remaining))
        self._remaining -= len(data)
        if not data:
            self._remaining = 0
        return data

    def readable(self) -> bool:
        return True

    def close(self) -> None:
        try:
            self._f.close()
        finally:
            super().close()
