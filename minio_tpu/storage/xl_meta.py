"""xl.meta v2 — the per-object version journal, msgpack-encoded.

Wire-compatible with the reference's format (cmd/xl-storage-format-v2.go):
8-byte header ``XL2 1   `` followed by a msgpack map with the same field
names/types the reference's msgp codegen emits
({"Versions": [{"Type": t, "V2Obj"/"DelObj": {...}}]}); UUIDs as 16-byte
bins, mod-times as int64 unix-nanos, EcDist as a byte string. A reference
binary should be able to read our xl.meta and vice versa.

The journal holds every version of one object: regular objects
(ObjectType), delete markers (DeleteType); the most recently modified
entry is the latest version.
"""

from __future__ import annotations

import uuid as _uuid

import msgpack

from . import errors
from .datatypes import (ChecksumInfo, ErasureInfo, FileInfo, ObjectPartInfo,
                        NULL_VERSION_ID)

XL_HEADER = b"XL2 "
XL_VERSION = b"1   "

# VersionType (cmd/xl-storage-format-v2.go:92-98)
OBJECT_TYPE = 1
DELETE_TYPE = 2
LEGACY_TYPE = 3

# ErasureAlgo / ChecksumAlgo enums (ibid :104-138)
EC_REED_SOLOMON = 1
CSUM_HIGHWAYHASH = 1

RESERVED_METADATA_PREFIX = "x-minio-internal-"
BITROT_SIDECAR_KEY = "x-minio-internal-bitrot-checksums"

_ZERO_UUID = b"\x00" * 16


def _uuid_bytes(s: str) -> bytes:
    if not s or s == NULL_VERSION_ID:
        return _ZERO_UUID
    return _uuid.UUID(s).bytes


def _uuid_str(b: bytes) -> str:
    if b == _ZERO_UUID:
        return ""
    return str(_uuid.UUID(bytes=bytes(b)))


def is_xl2_v1_format(buf: bytes) -> bool:
    return (len(buf) > 8 and buf[:4] == XL_HEADER and buf[4:8] == XL_VERSION)


def from_xl_v1_json(raw: bytes) -> "XLMetaV2":
    """Parse a legacy xl.json (format v1, cmd/xl-storage-format-v1.go)
    into a v2 journal — the read-side of the v1->v2 migration
    (formatErasureMigrate semantics at the object level).

    v1 stores ONE version per object: JSON with stat/erasure/meta/parts;
    bitrot checksums are whole-file per-part entries under
    erasure.checksum.
    """
    import json as _json
    try:
        d = _json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise errors.FileCorrupt(f"xl.json: {e}") from e
    if d.get("format") != "xl":
        raise errors.FileCorrupt("xl.json: not an xl format file")
    er = d.get("erasure", {})
    st = d.get("stat", {})
    checksums = []
    for c in er.get("checksum", []):
        checksums.append(ChecksumInfo(
            part_number=int(str(c.get("name", "part.1")
                                ).split(".")[-1] or 1),
            algorithm=c.get("algorithm", "highwayhash256S"),
            hash=bytes.fromhex(c.get("hash", "") or "")))
    parts = [ObjectPartInfo(
        number=p.get("number", i + 1), etag=p.get("etag", ""),
        size=p.get("size", 0),
        actual_size=p.get("actualSize", p.get("size", 0)))
        for i, p in enumerate(d.get("parts", []))]
    mod_time = st.get("modTime", 0)
    if isinstance(mod_time, str):
        import datetime as _dt
        try:
            mod_time = _dt.datetime.fromisoformat(
                mod_time.replace("Z", "+00:00")).timestamp()
        except ValueError:
            mod_time = 0.0
    fi = FileInfo(
        version_id="",                 # v1 is unversioned ("null")
        data_dir="",                   # v1 keeps parts beside xl.json
        size=st.get("size", 0), mod_time=float(mod_time),
        metadata=dict(d.get("meta", {})), parts=parts,
        erasure=ErasureInfo(
            algorithm=er.get("algorithm", "rs-vandermonde"),
            data_blocks=er.get("data", 0),
            parity_blocks=er.get("parity", 0),
            block_size=er.get("blockSize", 0),
            index=er.get("index", 0),
            distribution=list(er.get("distribution", [])),
            checksums=checksums))
    z = XLMetaV2()
    z.add_version(fi)
    return z


class XLMetaV2:
    """In-memory journal; versions is a list of raw msgpack-shaped dicts."""

    def __init__(self) -> None:
        self.versions: list[dict] = []

    # -- serialization ----------------------------------------------------

    def dumps(self) -> bytes:
        body = msgpack.packb({"Versions": self.versions}, use_bin_type=True)
        return XL_HEADER + XL_VERSION + body

    @classmethod
    def loads(cls, buf: bytes) -> "XLMetaV2":
        if not is_xl2_v1_format(buf):
            raise errors.FileCorrupt("xl.meta: bad XL2 header")
        z = cls()
        try:
            doc = msgpack.unpackb(buf[8:], raw=False, strict_map_key=False)
        except Exception as e:
            raise errors.FileCorrupt(f"xl.meta: msgpack decode: {e}") from e
        z.versions = list(doc.get("Versions") or [])
        return z

    # -- journal ops ------------------------------------------------------

    def add_version(self, fi: FileInfo) -> None:
        """Append/replace a version (reference AddVersion,
        cmd/xl-storage-format-v2.go:230-364): an existing entry with the
        same version ID is updated in place."""
        version_id = fi.version_id or NULL_VERSION_ID
        uv = _uuid_bytes(version_id)

        if fi.deleted:
            entry = {"Type": DELETE_TYPE,
                     "DelObj": {"ID": uv,
                                "MTime": int(fi.mod_time * 1e9)}}
            if fi.metadata:
                # the reference v2 DeleteMarker carries MetaSys for
                # exactly this: replication state riding on markers
                # (the replica-origin key) — absent for plain deletes
                entry["DelObj"]["MetaSys"] = {
                    k: v.encode() for k, v in fi.metadata.items()}
        else:
            meta_sys: dict[str, bytes] = {}
            meta_user: dict[str, str] = {}
            for k, v in fi.metadata.items():
                if k.lower().startswith(RESERVED_METADATA_PREFIX):
                    meta_sys[k] = v.encode()
                else:
                    meta_user[k] = v
            # v2 natively encodes only HighwayHash256S (CSumAlgo); other
            # bitrot algorithms + whole-file digests ride in MetaSys
            # (reference v2 is streaming-HH-only; this is our extension)
            if any(c.algorithm != "highwayhash256S" or c.hash
                   for c in fi.erasure.checksums):
                import json as _json
                meta_sys[BITROT_SIDECAR_KEY] = _json.dumps({
                    str(c.part_number): [c.algorithm, c.hash.hex()]
                    for c in fi.erasure.checksums}).encode()
            obj = {
                "ID": uv,
                "DDir": _uuid_bytes(fi.data_dir),
                "EcAlgo": EC_REED_SOLOMON,
                "EcM": fi.erasure.data_blocks,
                "EcN": fi.erasure.parity_blocks,
                "EcBSize": fi.erasure.block_size,
                "EcIndex": fi.erasure.index,
                "EcDist": bytes(fi.erasure.distribution),
                "CSumAlgo": CSUM_HIGHWAYHASH,
                "PartNums": [p.number for p in fi.parts],
                "PartETags": [p.etag for p in fi.parts],
                "PartSizes": [p.size for p in fi.parts],
                "PartASizes": [p.actual_size for p in fi.parts],
                "Size": fi.size,
                "MTime": int(fi.mod_time * 1e9),
                "MetaSys": meta_sys,
                "MetaUsr": meta_user,
            }
            entry = {"Type": OBJECT_TYPE, "V2Obj": obj}

        for i, v in enumerate(self.versions):
            if self._version_id_of(v) == uv:
                self.versions[i] = entry
                return
        self.versions.append(entry)

    def delete_version(self, fi: FileInfo) -> tuple[str, bool]:
        """Remove the version with fi.version_id.

        Returns (data_dir to purge — "" if none, last_version). Mirrors
        reference DeleteVersion (cmd/xl-storage-format-v2.go:428-).
        """
        version_id = fi.version_id or NULL_VERSION_ID
        uv = _uuid_bytes(version_id)
        for i, v in enumerate(self.versions):
            if self._version_id_of(v) != uv:
                continue
            data_dir = ""
            if v.get("Type") == OBJECT_TYPE:
                data_dir = _uuid_str(v["V2Obj"].get("DDir", _ZERO_UUID))
            del self.versions[i]
            return data_dir, len(self.versions) == 0
        raise errors.FileVersionNotFound(version_id)

    def update_version(self, fi: FileInfo) -> None:
        """Update metadata of an existing version in place (reference
        UpdateObjectVersion semantics for tags/metadata updates)."""
        uv = _uuid_bytes(fi.version_id or NULL_VERSION_ID)
        for v in self.versions:
            if self._version_id_of(v) == uv and v.get("Type") == OBJECT_TYPE:
                obj = v["V2Obj"]
                meta_sys, meta_user = {}, {}
                for k, val in fi.metadata.items():
                    if k.lower().startswith(RESERVED_METADATA_PREFIX):
                        meta_sys[k] = val.encode()
                    else:
                        meta_user[k] = val
                obj["MetaSys"], obj["MetaUsr"] = meta_sys, meta_user
                obj["MTime"] = int(fi.mod_time * 1e9)
                return
        raise errors.FileVersionNotFound(fi.version_id)

    # -- queries ----------------------------------------------------------

    @staticmethod
    def _version_id_of(v: dict) -> bytes:
        t = v.get("Type")
        if t == OBJECT_TYPE:
            return bytes(v["V2Obj"]["ID"])
        if t == DELETE_TYPE:
            return bytes(v["DelObj"]["ID"])
        return b"\xff" * 16

    @staticmethod
    def _mod_time_of(v: dict) -> int:
        t = v.get("Type")
        if t == OBJECT_TYPE:
            return v["V2Obj"]["MTime"]
        if t == DELETE_TYPE:
            return v["DelObj"]["MTime"]
        return 0

    def sorted_versions(self) -> list[dict]:
        """Versions newest-first: (ModTime, version id) descending —
        the version-id tie-break is the active-active replication
        plane's deterministic conflict order. Two sites holding the
        same version set (same-instant writes replicated both ways)
        must resolve "latest" identically, and mod-time-only ordering
        would fall back to per-site journal insertion order."""
        return sorted(
            self.versions,
            key=lambda v: (self._mod_time_of(v),
                           _uuid_str(self._version_id_of(v))),
            reverse=True)

    def to_file_info(self, volume: str, path: str,
                     version_id: str = "") -> FileInfo:
        """Resolve one version (default: latest) to a FileInfo
        (reference ToFileInfo, cmd/xl-storage-format-v2.go:366-423)."""
        if not self.versions:
            raise errors.FileNotFound(path)
        ordered = self.sorted_versions()
        if version_id and version_id != NULL_VERSION_ID:
            want = _uuid_bytes(version_id)
        else:
            want = None
        for i, v in enumerate(ordered):
            vid = self._version_id_of(v)
            if want is None:
                if version_id == NULL_VERSION_ID and vid != _ZERO_UUID:
                    continue
                return self._entry_to_fi(v, volume, path, is_latest=(i == 0))
            if vid == want:
                return self._entry_to_fi(v, volume, path, is_latest=(i == 0))
        raise errors.FileVersionNotFound(version_id or path)

    def list_file_infos(self, volume: str, path: str) -> list[FileInfo]:
        out = []
        for i, v in enumerate(self.sorted_versions()):
            out.append(self._entry_to_fi(v, volume, path, is_latest=(i == 0)))
        return out

    def _entry_to_fi(self, v: dict, volume: str, path: str,
                     is_latest: bool) -> FileInfo:
        t = v.get("Type")
        if t == DELETE_TYPE:
            d = v["DelObj"]
            md = {k: (val.decode() if isinstance(val, (bytes, bytearray))
                      else str(val))
                  for k, val in (d.get("MetaSys") or {}).items()}
            return FileInfo(
                volume=volume, name=path,
                version_id=_uuid_str(bytes(d["ID"])),
                is_latest=is_latest, deleted=True,
                mod_time=d["MTime"] / 1e9, metadata=md)
        if t != OBJECT_TYPE:
            raise errors.FileCorrupt(f"xl.meta: unsupported version type {t}")
        o = v["V2Obj"]
        parts = [ObjectPartInfo(number=n, etag=e, size=s, actual_size=a)
                 for n, e, s, a in zip(o["PartNums"], o["PartETags"],
                                       o["PartSizes"],
                                       o.get("PartASizes") or o["PartSizes"])]
        metadata: dict[str, str] = dict(o.get("MetaUsr") or {})
        for k, val in (o.get("MetaSys") or {}).items():
            if k.lower().startswith(RESERVED_METADATA_PREFIX):
                metadata[k] = (val.decode()
                               if isinstance(val, (bytes, bytearray)) else val)
        sidecar = metadata.pop(BITROT_SIDECAR_KEY, "")
        if sidecar:
            import json as _json
            side = _json.loads(sidecar)
            checksums = [ChecksumInfo(part_number=int(n), algorithm=a,
                                      hash=bytes.fromhex(h))
                         for n, (a, h) in side.items()]
        else:
            checksums = [ChecksumInfo(part_number=p.number,
                                      algorithm="highwayhash256S", hash=b"")
                         for p in parts]
        ei = ErasureInfo(
            algorithm="rs-vandermonde",
            data_blocks=o["EcM"], parity_blocks=o["EcN"],
            block_size=o["EcBSize"], index=o["EcIndex"],
            distribution=list(bytes(o["EcDist"])),
            checksums=checksums)
        return FileInfo(
            volume=volume, name=path,
            version_id=_uuid_str(bytes(o["ID"])),
            is_latest=is_latest, deleted=False,
            data_dir=_uuid_str(bytes(o["DDir"])),
            mod_time=o["MTime"] / 1e9, size=o["Size"],
            metadata=metadata, parts=parts, erasure=ei)

    def total_size(self) -> int:
        return sum(v["V2Obj"]["Size"] for v in self.versions
                   if v.get("Type") == OBJECT_TYPE)
