"""Disk-identity guard wrapper (cmd/xl-storage-disk-id-check.go).

Wraps a StorageAPI and verifies the drive still carries the expected
format UUID before letting calls through — a drive swapped or reformatted
behind a running set must read as DiskStale, never serve wrong shards.
The check is cached and re-validated on an interval (and after any
failure), not per call.
"""

from __future__ import annotations

import threading
import time
from typing import BinaryIO, Iterator, Optional

from . import errors
from .api import BitrotVerifier, StorageAPI
from .datatypes import DiskInfo, FileInfo, VolInfo

CHECK_INTERVAL = 10.0


class DiskIDCheck(StorageAPI):
    def __init__(self, inner: StorageAPI, expected_id: str,
                 interval: float = CHECK_INTERVAL):
        self.inner = inner
        self.expected = expected_id
        self.interval = interval
        self._mu = threading.Lock()
        self._checked_at = 0.0
        self._ok = False

    # -- the guard ---------------------------------------------------------

    def _verify(self) -> None:
        now = time.monotonic()
        with self._mu:
            if self._ok and now - self._checked_at < self.interval:
                return
        try:
            # read the format itself, not get_disk_id: local drives cache
            # their ID in memory and would mask an on-disk swap
            from .format import read_format_from
            got = read_format_from(self.inner).this
        except errors.StorageError:
            with self._mu:
                self._ok = False
            raise
        if got != self.expected:
            with self._mu:
                self._ok = False
            raise errors.DiskStale(
                f"disk id {got!r} != expected {self.expected!r}")
        with self._mu:
            self._ok = True
            self._checked_at = now

    def _invalidate(self) -> None:
        with self._mu:
            self._ok = False

    def _call(self, fn, *args, **kw):
        self._verify()
        try:
            return fn(*args, **kw)
        except errors.DiskNotFound:
            self._invalidate()
            raise

    # -- identity ----------------------------------------------------------

    def __getattr__(self, name):
        # passthrough for backend-specific attributes (e.g. XLStorage
        # .root, .read_format) — only called when not found on self
        return getattr(self.inner, name)

    def __str__(self) -> str:
        return str(self.inner)

    def is_online(self) -> bool:
        return self.inner.is_online()

    def is_local(self) -> bool:
        return self.inner.is_local()

    def hostname(self) -> str:
        return self.inner.hostname()

    def endpoint(self) -> str:
        return self.inner.endpoint()

    def close(self) -> None:
        self.inner.close()

    def get_disk_id(self) -> str:
        return self.inner.get_disk_id()

    def set_disk_id(self, disk_id: str) -> None:
        self.expected = disk_id
        self._invalidate()
        self.inner.set_disk_id(disk_id)

    def disk_info(self) -> DiskInfo:
        return self._call(self.inner.disk_info)

    # -- delegated verbs ---------------------------------------------------

    def make_vol(self, volume):
        return self._call(self.inner.make_vol, volume)

    def make_vol_bulk(self, *volumes):
        return self._call(self.inner.make_vol_bulk, *volumes)

    def list_vols(self):
        return self._call(self.inner.list_vols)

    def stat_vol(self, volume):
        return self._call(self.inner.stat_vol, volume)

    def delete_vol(self, volume, force=False):
        return self._call(self.inner.delete_vol, volume, force)

    def write_metadata(self, volume, path, fi):
        return self._call(self.inner.write_metadata, volume, path, fi)

    def read_version(self, volume, path, version_id=""):
        return self._call(self.inner.read_version, volume, path,
                          version_id)

    def read_versions(self, volume, path):
        return self._call(self.inner.read_versions, volume, path)

    def delete_version(self, volume, path, fi):
        return self._call(self.inner.delete_version, volume, path, fi)

    def delete_versions(self, volume, versions):
        return self._call(self.inner.delete_versions, volume, versions)

    def rename_data(self, src_volume, src_path, data_dir, dst_volume,
                    dst_path, version_id=""):
        return self._call(self.inner.rename_data, src_volume, src_path,
                          data_dir, dst_volume, dst_path, version_id)

    def list_dir(self, volume, dir_path, count=-1):
        return self._call(self.inner.list_dir, volume, dir_path, count)

    def read_file(self, volume, path, offset, length, verifier=None):
        return self._call(self.inner.read_file, volume, path, offset,
                          length, verifier)

    def append_file(self, volume, path, buf):
        return self._call(self.inner.append_file, volume, path, buf)

    def open_appender(self, volume, path):
        # identity-guarded like every other write verb: the shard-write
        # hot path must not stream frames onto a swapped drive (callers
        # probe has_appender() first — delegated via __getattr__ — so
        # this is only reached when the backend really supports it)
        return self._call(self.inner.open_appender, volume, path)

    def create_file(self, volume, path, size, reader):
        return self._call(self.inner.create_file, volume, path, size,
                          reader)

    def read_file_stream(self, volume, path, offset, length):
        return self._call(self.inner.read_file_stream, volume, path,
                          offset, length)

    def rename_file(self, src_volume, src_path, dst_volume, dst_path):
        return self._call(self.inner.rename_file, src_volume, src_path,
                          dst_volume, dst_path)

    def check_parts(self, volume, path, fi):
        return self._call(self.inner.check_parts, volume, path, fi)

    def check_file(self, volume, path):
        return self._call(self.inner.check_file, volume, path)

    def delete_file(self, volume, path, recursive=False):
        return self._call(self.inner.delete_file, volume, path,
                          recursive=recursive)

    def verify_file(self, volume, path, fi):
        return self._call(self.inner.verify_file, volume, path, fi)

    def write_all(self, volume, path, data):
        return self._call(self.inner.write_all, volume, path, data)

    def read_all(self, volume, path):
        return self._call(self.inner.read_all, volume, path)

    def walk(self, volume, dir_path="", marker="", recursive=True):
        self._verify()
        return self.inner.walk(volume, dir_path, marker, recursive)
