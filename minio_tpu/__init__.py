"""minio_tpu — a TPU-native erasure-coded object storage framework.

A ground-up rebuild of the capability surface of the reference object store
(an S3-compatible, erasure-coded distributed store with a QAT-offload fork
delta) where the entire hot data path — GF(2^8) Reed-Solomon encode /
reconstruct / heal and bitrot checksumming — runs as batched XLA/Pallas
kernels on TPU, and the host runtime (S3 API, drive layout, quorum
semantics, healing, distribution) is built around feeding that device
pipeline.

Layout:
    ops/       device kernels + host oracles (GF(2^8) RS, hashing)
    models/    the flagship jittable pipelines (encode+bitrot, decode, heal)
    erasure/   streaming erasure codec (block loop, quorum writers/readers)
    storage/   per-drive layer: xl.meta-style metadata, POSIX backend
    object/    object engine: sets, zones, multipart, healing
    s3/        S3 HTTP frontend (SigV4, handlers)
    parallel/  mesh/sharding: multi-chip encode, batch scheduler
    utils/     siphash routing, ellipses, byte pools, ...
"""

__version__ = "0.1.0"
