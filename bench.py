#!/usr/bin/env python
"""Benchmark of record: erasure encode+bitrot throughput per chip.

Measures the BASELINE.json metric — aggregate erasure encode + bitrot
GiB/s per chip on an EC 12+4 set at 1 MiB blocks (the PutObject hot-loop
device work: RS parity + per-shard HighwayHash256 streaming-bitrot
digests, one fused program) — and compares against the host-CPU SIMD
reedsolomon+highwayhash baseline (the reference's data path, natively
reimplemented in native/gf_rs.cpp + native/highwayhash.cpp since the Go
toolchain isn't present).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, ...}

Timing methodology (the r01 bench got this wrong): with the device behind
the axon tunnel, a dispatch+sync round trip costs ~700 ms regardless of
the work inside, so timing one call — or dividing one call containing an
N-iteration device loop by N without subtracting the constant — measures
the tunnel, not the kernel. Here every sample times TWO compiled
fori_loops (2 and ITERS iterations) whose bodies feed the loop carry back
into the input (so XLA can neither hoist nor dead-code the work), and the
reported time is the slope (t_long - t_short) / (ITERS - 2). Shard and
digest byte-identity against the host oracle is asserted before timing.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K, M = 12, 4
N_SHARDS = K + M
BLOCK = 1 << 20                      # 1 MiB blocks (BASELINE config)
S = -(-BLOCK // K)                   # shard bytes per block
BATCH = 32                           # concurrent PutObject streams
ITERS = 302                          # long-loop trip count (slope timing)


def bench_device() -> tuple[float, dict]:
    import jax
    import jax.numpy as jnp
    from minio_tpu import bitrot as bitrot_mod
    from minio_tpu.models.pipeline import put_step
    from minio_tpu.ops import rs_ref

    dev = jax.devices()[0]

    def sync(x):
        return np.asarray(
            jax.jit(lambda v: v.ravel()[:1].astype(jnp.float32))(x))

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, S)).astype(np.uint8)
    dd = jax.device_put(data)

    # correctness gate: shards AND digests byte-identical to the oracle
    parity, digests = put_step(dd[:1], K, M)
    parity, digests = np.asarray(parity)[0], np.asarray(digests)[0]
    want = rs_ref.encode(data[0], M)
    assert (parity == want[K:]).all(), "device encode diverges from oracle"
    for row in (0, K, N_SHARDS - 1):
        want_dg = bitrot_mod.hash_shard(
            want[row], bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256)
        assert digests[row].tobytes() == want_dg, \
            f"device digest diverges from oracle (shard {row})"

    def make_loop(iters):
        @jax.jit
        def loop(d):
            def body(i, c):
                d2 = d ^ c.astype(jnp.uint8)
                parity, digs = put_step(d2, K, M)
                # consume EVERY output element: a carry that reads one
                # element lets XLA dead-code entire branches (digests of
                # unread rows), understating the work
                return (c + digs.astype(jnp.int32).sum()
                        + parity.astype(jnp.int32).sum()) & 127
            return jax.lax.fori_loop(0, iters, body, jnp.int32(1))
        return loop

    short, long_ = make_loop(2), make_loop(ITERS)
    sync(short(dd)); sync(long_(dd))    # compile both
    best = None
    for _ in range(3):
        t0 = time.perf_counter(); sync(short(dd))
        ta = time.perf_counter() - t0
        t0 = time.perf_counter(); sync(long_(dd))
        tb = time.perf_counter() - t0
        dt = (tb - ta) / (ITERS - 2)
        if dt > 0 and (best is None or dt < best):
            best = dt
    assert best is not None, "slope timing failed (tunnel noise)"
    gib = BATCH * K * S / best / 2**30
    info = {"device": str(dev), "ms_per_batch": round(best * 1e3, 3),
            "kernel": "pallas+hh256" if dev.platform == "tpu"
            else "xla+hh256"}
    info["decode_3miss_gibs"] = round(
        _bench_matrix_op(jax, jnp, sync, data, mode="decode"), 2)
    info["heal_4miss_gibs"] = round(
        _bench_matrix_op(jax, jnp, sync, data, mode="heal"), 2)
    return gib, info


def _bench_matrix_op(jax, jnp, sync, data, mode: str) -> float:
    """Secondary kernels for BASELINE configs #3/#4: batched reconstruct
    (GetObject with 3 shards missing) and recover (full-drive heal,
    here 4 lost shards = one dead 4-drive node), slope-timed like the
    primary metric. Correctness of these kernels vs the oracle is pinned
    by tests/test_rs_tpu.py."""
    import numpy as np_
    from minio_tpu.ops import rs_matrix, rs_tpu

    if mode == "decode":
        lost = (1, 5, 13)
    else:
        lost = (0, 4, 8, 12)
    mask = sum(1 << i for i in range(N_SHARDS) if i not in lost)
    if mode == "decode":
        d, _used = rs_matrix.decode_matrix(K, M, mask)
        mat = np_.asarray(d)
    else:
        r, _used, _missing = rs_matrix.recover_matrix(K, M, mask)
        mat = np_.asarray(r)

    def op(x):
        return rs_tpu.apply_matrix(mat, x)

    def make_loop(iters):
        @jax.jit
        def loop(d):
            def body(i, c):
                d2 = d ^ c.astype(jnp.uint8)
                out = op(d2)
                return (c + out.astype(jnp.int32).sum()) & 127
            return jax.lax.fori_loop(0, iters, body, jnp.int32(1))
        return loop

    short, long_ = make_loop(2), make_loop(ITERS)
    sync(short(data)); sync(long_(data))
    best = None
    for _ in range(3):
        import time as _t
        t0 = _t.perf_counter(); sync(short(data))
        ta = _t.perf_counter() - t0
        t0 = _t.perf_counter(); sync(long_(data))
        tb = _t.perf_counter() - t0
        dt = (tb - ta) / (ITERS - 2)
        if dt > 0 and (best is None or dt < best):
            best = dt
    return BATCH * K * S / best / 2**30 if best else 0.0


def bench_cpu_baseline() -> tuple[float, dict]:
    """Reference-style CPU data path: SIMD GF(2^8) encode + HighwayHash256
    over every shard (the reference's per-PUT work), single core."""
    from minio_tpu import bitrot
    from minio_tpu.ops import rs_matrix
    from minio_tpu.utils import native

    if not native.available():
        return 0.0, {"error": "native lib unavailable"}

    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (K, S)).astype(np.uint8)
    pm = np.asarray(rs_matrix.parity_matrix(K, M))

    # per-block: encode (GFNI if present, matching "best SIMD on this CPU")
    # + HighwayHash-256 every one of the n shards (streaming bitrot)
    n_blocks = 24
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        parity = native.gf_matmul(pm, data)
        full = np.concatenate([data, parity], axis=0)
        native.hh256_batch(bitrot.MAGIC_HIGHWAYHASH_KEY, full)
    dt = (time.perf_counter() - t0) / n_blocks
    gib = K * S / dt / 2**30
    # encode-only rate for reference
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        native.gf_matmul(pm, data)
    dt_enc = (time.perf_counter() - t0) / n_blocks
    return gib, {"gfni": native.has_gfni(),
                 "cpu_encode_only_gibs": round(K * S / dt_enc / 2**30, 3)}


def main() -> int:
    dev_gib, dev_info = bench_device()
    cpu_gib, cpu_info = bench_cpu_baseline()
    out = {
        "metric": "Erasure encode+bitrot GiB/s per chip "
                  "(EC 12+4, 1 MiB block, PutObject)",
        "value": round(dev_gib, 3),
        "unit": "GiB/s",
        "vs_baseline": round(dev_gib / cpu_gib, 3) if cpu_gib else None,
        "baseline_cpu_gibs": round(cpu_gib, 3),
        "device_info": dev_info,
        "cpu_info": cpu_info,
        "config": {"k": K, "m": M, "block": BLOCK, "batch": BATCH},
        "note": "device value = fused RS encode + HighwayHash256 per-shard "
                "streaming-bitrot digests (byte-identity asserted vs the "
                "host oracle before timing); slope-timed between 2- and "
                "302-iteration compiled loops to cancel the ~700 ms axon "
                "tunnel dispatch constant; baseline = CPU SIMD encode + "
                "HighwayHash256 full reference data path, single core",
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
