#!/usr/bin/env python
"""Benchmark of record: erasure encode+bitrot throughput per chip.

Measures the BASELINE.json metric — aggregate erasure encode + bitrot
GiB/s per chip on an EC 12+4 set at 1 MiB blocks (the PutObject hot-loop
device work: RS parity + per-shard HighwayHash256 streaming-bitrot
digests, one fused program) — and compares against the host-CPU SIMD
reedsolomon+highwayhash baseline (the reference's data path, natively
reimplemented in native/gf_rs.cpp + native/highwayhash.cpp since the Go
toolchain isn't present).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, ...}

Timing methodology (r4 — ONE estimator, reported as a distribution;
VERDICT r3 weak #1/#3):

* Each sample is a SLOPE: the wall time of a compiled ITERS-iteration
  fori_loop minus a 2-iteration one, divided by (ITERS-2). The loop
  body feeds the carry back into the input so XLA can neither hoist nor
  dead-code the work, and the subtraction cancels the ~700 ms axon
  tunnel dispatch constant.
* All kernels (put, fused verify+decode, fused verify+heal, config #5
  multipart 16+4/SHA256) are sampled ROUND-ROBIN at fine grain —
  put, decode, heal, mp, put, decode, ... — so every kernel's samples
  see the same chip-throttle state; per-kernel ratios come from
  adjacent same-round samples of this one estimator. The r3 bench's
  two disagreeing estimators (adjacent re-measure vs interleaved A/B)
  are gone.
* Sampling spans >=3 windows separated by idle gaps (the shared dev
  slice throttles under sustained load and recovers when idle); the
  headline reports the median across windows and the per-window
  medians, so a regression is detectable against the best window, not
  masked by window luck. Per kernel the JSON carries
  {median_ms, iqr_ms, n}.
* Shard and digest byte-identity against the host oracle is asserted
  before any timing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional, Sequence

import numpy as np

K, M = 12, 4
N_SHARDS = K + M
BLOCK = 1 << 20                      # 1 MiB blocks (BASELINE config)
S = -(-BLOCK // K)                   # shard bytes per block
BATCH = 32                           # concurrent PutObject streams
ITERS = 302                          # long-loop trip count (slope timing)
WINDOWS = int(os.environ.get("BENCH_WINDOWS", "3"))
REPS_PER_WINDOW = int(os.environ.get("BENCH_REPS", "3"))
WINDOW_GAP_S = float(os.environ.get("BENCH_WINDOW_GAP_S", "15"))


def _median(xs: list) -> float:
    return float(np.median(np.asarray(xs)))


def _iqr(xs: list) -> float:
    a = np.asarray(xs)
    return float(np.percentile(a, 75) - np.percentile(a, 25))


class _Slope:
    """Compiled short/long loop pair for one kernel; one sample per
    measure() call."""

    def __init__(self, jax, jnp, op, dd, sync, iters: int):
        self.dd, self.sync = dd, sync
        self.iters = iters

        def make_loop(n_iters):
            @jax.jit
            def loop(d):
                def body(i, c):
                    d2 = d ^ c.astype(jnp.uint8)
                    acc = jnp.int32(0)
                    out = op(d2)
                    for leaf in (out if isinstance(out, tuple) else
                                 (out,)):
                        acc = acc + leaf.astype(jnp.int32).sum()
                    return (c + acc) & 127
                return jax.lax.fori_loop(0, n_iters, body, jnp.int32(1))
            return loop

        self.short = make_loop(2)
        self.long = make_loop(iters)
        self.sync(self.short(dd))       # compile both
        self.sync(self.long(dd))

    def delta(self) -> float:
        """Raw (long - short) wall seconds for one pair of calls."""
        t0 = time.perf_counter()
        self.sync(self.short(self.dd))
        ta = time.perf_counter() - t0
        t0 = time.perf_counter()
        self.sync(self.long(self.dd))
        tb = time.perf_counter() - t0
        return tb - ta

    def measure(self):
        """One slope sample (seconds per op call), or None when tunnel
        jitter swallowed the delta (short call slower than long) — a
        clamped value would inject absurd outliers into the medians and
        ratio distributions, so invalid rounds are dropped instead."""
        for _attempt in range(3):
            d = self.delta()
            if d > 0:
                return d / (self.iters - 2)
        return None


def bench_device() -> tuple[float, dict]:
    import jax
    import jax.numpy as jnp
    from minio_tpu import bitrot as bitrot_mod
    from minio_tpu.models.pipeline import get_step, heal_step, put_step
    from minio_tpu.ops import gf256, rs_matrix, rs_ref, rs_tpu

    dev = jax.devices()[0]

    def sync(x):
        return np.asarray(
            jax.jit(lambda v: v.ravel()[:1].astype(jnp.float32))(x))

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, S)).astype(np.uint8)
    dd = jax.device_put(data)

    # ---- identity gates (shards AND digests vs the host oracle) ------
    hh = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256
    parity, digests = put_step(dd[:1], K, M)
    parity, digests = np.asarray(parity)[0], np.asarray(digests)[0]
    want = rs_ref.encode(data[0], M)
    assert (parity == want[K:]).all(), "device encode diverges from oracle"
    for row in (0, K, N_SHARDS - 1):
        want_dg = bitrot_mod.hash_shard(want[row], hh)
        assert digests[row].tobytes() == want_dg, \
            f"device digest diverges from oracle (shard {row})"

    # fused decode (3 shards missing) / heal (4 lost rows) operands
    ops = {"put": lambda d: put_step(d, K, M)}
    for mode, lost in (("decode", (1, 5, 13)), ("heal", (0, 4, 8, 12))):
        mask = sum(1 << i for i in range(N_SHARDS) if i not in lost)
        if mode == "decode":
            mat, _u, _miss = rs_matrix.missing_data_matrix(K, M, mask)
        else:
            mat, _u, _miss = rs_matrix.recover_matrix(K, M, mask)
        mat = np.ascontiguousarray(np.asarray(mat, np.uint8))
        m2 = rs_tpu._bit_expand_cached(mat.tobytes(), mat.shape)
        r = mat.shape[0]
        step = get_step if mode == "decode" else heal_step
        ops[mode] = (lambda step, m2, r: lambda x: step(x, m2, r, K, S)
                     )(step, m2, r)
        got = [np.asarray(o) for o in ops[mode](dd[:1])]
        want_rows = gf256.gf_matmul(mat, data[0])
        assert (got[0][0] == want_rows).all(), f"device {mode} diverges"
        want_dg = bitrot_mod.hash_shard(data[0][0].tobytes(), hh)
        assert got[1][0, 0].tobytes() == want_dg, \
            f"device {mode} survivor digest diverges"
        if mode == "heal":
            want_odg = bitrot_mod.hash_shard(want_rows[0].tobytes(), hh)
            assert got[2][0, 0].tobytes() == want_odg, \
                "device heal output digest diverges"

    # config #5: multipart 16+4, SHA256 bitrot, own geometry/batch
    k5, m5 = 16, 4
    s5 = -(-BLOCK // k5)
    data5 = np.random.default_rng(7).integers(
        0, 256, (BATCH, k5, s5)).astype(np.uint8)
    dd5 = jax.device_put(data5)
    p5, dg5 = put_step(dd5[:1], k5, m5, 0, b"", "sha256")
    p5, dg5 = np.asarray(p5)[0], np.asarray(dg5)[0]
    want5 = rs_ref.encode(data5[0], m5)
    assert (p5 == want5[k5:]).all(), "config5 encode diverges"
    import hashlib
    for row in (0, k5, k5 + m5 - 1):
        assert dg5[row].tobytes() == hashlib.sha256(
            want5[row].tobytes()).digest(), "config5 digest diverges"
    ops["mp_16p4_sha256"] = lambda d: put_step(d, k5, m5, 0, b"",
                                               "sha256")

    # ---- calibrate the loop length on the put kernel -----------------
    # The DELTA (long - short), not the total, must clear the jitter
    # floor: each sync costs ~700 ms of tunnel constant regardless of
    # the work inside, so total wall time always looks "long enough".
    iters = ITERS
    probe = None
    for _escalation in range(3):
        probe = _Slope(jax, jnp, ops["put"], dd, sync, iters)
        if max(probe.delta() for _ in range(2)) > 0.2:
            break
        # too fast: the slope would hide inside tunnel jitter
        iters *= 10
        probe = None
    if probe is None:
        raise RuntimeError(
            "slope calibration failed: put_step's work delta never "
            f"cleared tunnel jitter (final iters {iters})")

    # ---- compile all loop pairs once (reuse the calibrated put) ------
    slopes = {"put": probe}
    for name, op in ops.items():
        if name != "put":
            slopes[name] = _Slope(jax, jnp, op,
                                  dd5 if name.startswith("mp_") else dd,
                                  sync, iters)

    # ---- ONE estimator: round-robin slope samples across windows -----
    # rounds[i] = {kernel: sample or None}; ratios pair only rounds
    # where BOTH kernels produced a valid sample
    rounds: list[dict] = []
    window_put_medians: list[float] = []
    for w in range(WINDOWS):
        if w:
            time.sleep(WINDOW_GAP_S)
        win_put: list[float] = []
        for _rep in range(REPS_PER_WINDOW):
            rnd = {name: slopes[name].measure() for name in ops}
            rounds.append(rnd)
            if rnd["put"] is not None:
                win_put.append(rnd["put"])
        if win_put:
            window_put_medians.append(_median(win_put))

    samples = {name: [r[name] for r in rounds if r[name] is not None]
               for name in ops}
    if not samples["put"] or not window_put_medians:
        raise RuntimeError("no valid put_step samples (tunnel noise)")
    stats = {}
    for name in ops:
        xs = samples[name]
        stats[name] = ({"median_ms": round(_median(xs) * 1e3, 3),
                        "iqr_ms": round(_iqr(xs) * 1e3, 3),
                        "n": len(xs)} if xs else {"n": 0})
    # per-kernel ratios vs put, from adjacent same-round samples
    for name in ops:
        if name == "put":
            continue
        rs = [r["put"] / r[name] for r in rounds
              if r["put"] is not None and r[name] is not None]
        if rs:
            stats[name]["vs_put_median"] = round(_median(rs), 3)
            stats[name]["vs_put_iqr"] = round(_iqr(rs), 3)

    med = _median(samples["put"])
    gib = BATCH * K * S / med / 2**30
    gib_windows = [round(BATCH * K * S / m / 2**30, 2)
                   for m in window_put_medians]
    bytes5 = BATCH * k5 * s5
    info = {
        "device": str(dev),
        "kernel": "pallas+hh256" if dev.platform == "tpu" else "xla+hh256",
        "iters": iters,
        "windows": WINDOWS, "reps_per_window": REPS_PER_WINDOW,
        "window_gap_s": WINDOW_GAP_S,
        "put_gibs_per_window": gib_windows,
        "put_gibs_min_window": min(gib_windows),
        "kernels_ms": stats,
        "decode_3miss_gibs": round(
            BATCH * K * S / _median(samples["decode"]) / 2**30, 2),
        "heal_4miss_gibs": round(
            BATCH * K * S / _median(samples["heal"]) / 2**30, 2),
        "config5_multipart_16p4_sha256_gibs": round(
            bytes5 / _median(samples["mp_16p4_sha256"]) / 2**30, 2),
        "note": "decode/heal are FUSED verify+reconstruct (HighwayHash256 "
                "verification of all survivors in-program; heal also "
                "digests rebuilt shards); all kernels sampled round-robin "
                "with one slope estimator, medians + IQR over "
                f"{WINDOWS * REPS_PER_WINDOW} samples across {WINDOWS} "
                "idle-separated windows",
    }
    return gib, info


def bench_cpu_baseline() -> tuple[float, dict]:
    """Reference-style CPU data path: SIMD GF(2^8) encode + HighwayHash256
    over every shard (the reference's per-PUT work), single core."""
    from minio_tpu import bitrot
    from minio_tpu.ops import rs_matrix
    from minio_tpu.utils import native

    if not native.available():
        return 0.0, {"error": "native lib unavailable"}

    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (K, S)).astype(np.uint8)
    pm = np.asarray(rs_matrix.parity_matrix(K, M))

    # per-block: encode (GFNI if present, matching "best SIMD on this CPU")
    # + HighwayHash-256 every one of the n shards (streaming bitrot)
    n_blocks = 24
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        parity = native.gf_matmul(pm, data)
        full = np.concatenate([data, parity], axis=0)
        native.hh256_batch(bitrot.MAGIC_HIGHWAYHASH_KEY, full)
    dt = (time.perf_counter() - t0) / n_blocks
    gib = K * S / dt / 2**30
    # encode-only rate for reference
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        native.gf_matmul(pm, data)
    dt_enc = (time.perf_counter() - t0) / n_blocks
    lib = native.get_lib()
    avx2 = False
    try:
        import ctypes
        lib.hh_has_avx2.restype = ctypes.c_int
        avx2 = bool(lib.hh_has_avx2())
    except Exception:
        pass
    return gib, {"gfni": native.has_gfni(), "hh_avx2": avx2,
                 "cpu_encode_only_gibs": round(K * S / dt_enc / 2**30, 3)}


def bench_pipeline_ab(streams: int = 32, size: int = 16 << 20,
                      drives: int = 16, parity: int = 4,
                      spans_api: str = "", spans_trace_id: str = ""
                      ) -> dict:
    """Pipeline on/off A/B on BASELINE config #2 (`streams` concurrent
    `size`-byte PutObject streams, EC 12+4, 1 MiB blocks) through the
    engine data path on tmpfs drives. Per mode: aggregate PUT/GET GiB/s,
    per-stage p50/p99 (stagetimer samples) and the overlap accounting
    (wall vs sum-of-stages — >1.0x means the stages actually ran
    concurrently)."""
    import concurrent.futures as cf
    import shutil
    import tempfile

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.parallel import pipeline as pl
    from minio_tpu.utils import stagetimer, telemetry

    # the A/B isolates HOST-path overlap: on the axon tunnel host the
    # device cannot sit on this path (~15 MiB/s host->device), matching
    # bench_e2e's default. Restored on exit — a leaked 2^60 threshold
    # would silently CPU-route later device work in this process.
    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    payload = os.urandom(size)
    was_enabled = pl.ENABLED
    was_sampling = (telemetry.SPANS.slow_s, telemetry.SPANS.sample)
    out: dict = {"config": {"streams": streams, "size": size,
                            "k": drives - parity, "m": parity,
                            "block": 1 << 20}}
    try:
        # keep every bench trace: the per-config snapshot reports the
        # top-5 slowest span trees for stage-level attribution
        telemetry.SPANS.configure(sample=1.0)
        for mode in ("serial", "pipelined"):
            pl.ENABLED = mode == "pipelined"
            root = tempfile.mkdtemp(prefix=f"bench_ab_{mode}_", dir=base)
            sets = ErasureSets.from_drives(
                [f"{root}/d{i}" for i in range(drives)], 1, drives,
                parity, block_size=1 << 20, enable_mrf=False)
            try:
                sets.make_bucket("bench")
                sets.put_object("bench", "warm", payload)   # warm path
                stagetimer.enable()
                stagetimer.reset()
                telemetry.SPANS.clear()

                def put_one(i: int, prefix: str = "o",
                            traced: bool = True) -> None:
                    if traced:
                        with telemetry.trace("bench.put", mode=mode,
                                             stream=i):
                            sets.put_object("bench", f"{prefix}{i}",
                                            payload)
                    else:
                        sets.put_object("bench", f"{prefix}{i}", payload)

                t0 = time.perf_counter()
                with cf.ThreadPoolExecutor(max_workers=streams) as ex:
                    list(ex.map(put_one, range(streams)))
                put_wall = time.perf_counter() - t0
                t0 = time.perf_counter()

                def read_back(i: int) -> None:
                    with telemetry.trace("bench.get", mode=mode,
                                         stream=i):
                        _, it = sets.get_object("bench", f"o{i}")
                        n = sum(len(c) for c in it)
                        assert n == size, (i, n)

                with cf.ThreadPoolExecutor(max_workers=streams) as ex:
                    list(ex.map(read_back, range(streams)))
                get_wall = time.perf_counter() - t0

                # multipart GET A/B (cross-part lookahead probe): one
                # object of 4 uploaded parts; the pipelined mode should
                # overlap part N's verify+decode with part N+1's first
                # group read, which the serial mode cannot
                mp_parts = 4
                part_size = max(size // mp_parts, 5 << 20)  # S3 minimum
                mp_payload = payload[:part_size] \
                    if len(payload) >= part_size \
                    else os.urandom(part_size)
                uid = sets.new_multipart_upload("bench", "mp")
                etags = []
                for pn in range(1, mp_parts + 1):
                    pi = sets.put_object_part(
                        "bench", "mp", uid, pn, mp_payload, part_size)
                    etags.append(pi.etag)
                from minio_tpu.object.multipart import CompletePart
                sets.complete_multipart_upload(
                    "bench", "mp", uid,
                    [CompletePart(i + 1, e)
                     for i, e in enumerate(etags)])
                mp_total = mp_parts * part_size

                def read_mp() -> None:
                    _, it = sets.get_object("bench", "mp")
                    nread = sum(len(c) for c in it)
                    assert nread == mp_total, nread

                read_mp()                      # warm
                t0 = time.perf_counter()
                mp_rounds = 4
                for _ in range(mp_rounds):
                    read_mp()
                mp_wall = time.perf_counter() - t0
                stagetimer.disable()
                total = streams * size
                out[mode] = {
                    "put_gib_s": round(total / put_wall / 2**30, 3),
                    "put_wall_s": round(put_wall, 2),
                    "get_gib_s": round(total / get_wall / 2**30, 3),
                    "get_wall_s": round(get_wall, 2),
                    "mp_get_gib_s": round(
                        mp_rounds * mp_total / mp_wall / 2**30, 3),
                    "mp_config": {"parts": mp_parts,
                                  "part_size": part_size},
                    "stage_percentiles_ms": stagetimer.percentiles(),
                    "overlap": stagetimer.overlap_report(),
                    # the perf trajectory carries stage-level
                    # attribution: slowest span trees are per-config
                    # (SPANS.clear() above); the registry counters are
                    # PROCESS-CUMULATIVE at snapshot time — labelled
                    # so, since earlier configs/phases contribute
                    "telemetry": {
                        "metrics_cumulative": telemetry.REGISTRY
                        .snapshot("minio_tpu_"),
                        # --spans-api/--spans-trace-id narrow the dump
                        # with the /spans endpoint's own filters
                        "top_spans": telemetry.SPANS.dump(
                            5, slowest=True, name=spans_api,
                            trace_id=spans_trace_id),
                    },
                }
                if mode == "pipelined":
                    # telemetry-on overhead: identical PUT batches with
                    # and without a root span (span() is a no-op with
                    # none active). Warm round first, then interleaved
                    # timed pairs, best-of to shave scheduler noise —
                    # comparing a cold traced round against a warm
                    # untraced one would charge the page cache to
                    # telemetry.
                    ns = min(streams, 8)

                    def put_round(traced: bool, prefix: str) -> float:
                        t0 = time.perf_counter()
                        with cf.ThreadPoolExecutor(
                                max_workers=ns) as ex:
                            list(ex.map(
                                lambda i: put_one(i, prefix=prefix,
                                                  traced=traced),
                                range(ns)))
                        return time.perf_counter() - t0

                    put_round(False, "u")          # warm (untimed)
                    plain, traced = [], []
                    for _ in range(2):             # interleaved pairs
                        plain.append(put_round(False, "u"))
                        traced.append(put_round(True, "v"))
                    out["telemetry_overhead_x"] = round(
                        min(traced) / min(plain), 4)
            finally:
                stagetimer.disable()
                sets.close()
                shutil.rmtree(root, ignore_errors=True)
        out["put_speedup_x"] = round(
            out["pipelined"]["put_gib_s"] / out["serial"]["put_gib_s"], 3)
        out["get_speedup_x"] = round(
            out["pipelined"]["get_gib_s"] / out["serial"]["get_gib_s"], 3)
        out["mp_get_speedup_x"] = round(
            out["pipelined"]["mp_get_gib_s"]
            / out["serial"]["mp_get_gib_s"], 3)
    finally:
        pl.ENABLED = was_enabled
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        telemetry.SPANS.configure(*was_sampling)
    return out


def bench_saturation(streams: Sequence[int] = (1, 2, 4, 8, 16, 32),
                     size: int = 16 << 20, drives: int = 16,
                     parity: int = 4, block: int = 1 << 20,
                     lost_shards: int = 2, ab: bool = True,
                     force_device: Optional[bool] = None,
                     sched_max_wait: Optional[float] = None) -> dict:
    """Concurrency saturation sweep (ROADMAP item #1's measurement
    mode): for each stream count, run `streams` concurrent PutObject
    streams then concurrent healthy GETs then concurrent DEGRADED GETs
    (`lost_shards` shard files removed per object, so every read group
    rides the fused verify+decode verb), reporting aggregate GiB/s per
    phase plus the batch former's per-verb dispatch occupancy (groups
    and blocks per fused device launch) at that point.

    With `ab`, each point re-runs the GET phases with the scheduler
    BYPASSED (engines built with scheduler=None → one device dispatch
    per request bucket) — the per-request-launch baseline the former is
    supposed to beat once concurrency saturates a single dispatch.

    force_device: route every batch to the device backend regardless of
    size/platform (the engine-test fixture's trick) — default on when
    the jax backend is NOT a TPU, so the former is exercised (XLA-CPU)
    on dev hosts; on a real TPU the natural routing thresholds apply.
    Caveat: forced XLA-CPU numbers are compile-dominated (coalesced
    batches hit fresh jit shapes mid-phase) — occupancy stats are
    meaningful everywhere, the GiB/s and A/B ratios only on a real
    device where the per-dispatch constant the former amortizes
    actually exists.
    """
    import concurrent.futures as cf
    import glob
    import shutil
    import tempfile

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.utils import telemetry

    def stage_snap() -> dict:
        return dict(telemetry.REGISTRY.snapshot(
            "minio_tpu_device_dispatch_seconds").get(
            "minio_tpu_device_dispatch_seconds", {}))

    def stage_split(before: dict) -> dict:
        """Per-verb mean ms per dispatch stage since `before` — the
        queue/transfer/compute/fetch attribution of ISSUE 13 pillar c,
        read back from the registry histogram deltas."""
        split: dict = {}
        for lk, v in stage_snap().items():
            b = before.get(lk, {"sum": 0, "count": 0})
            dc = v["count"] - b["count"]
            if dc <= 0:
                continue
            labels = dict(p.split("=", 1) for p in lk.split(","))
            split.setdefault(labels.get("verb", "?"), {})[
                labels.get("stage", "?")] = {
                "mean_ms": round((v["sum"] - b["sum"]) / dc * 1e3, 3),
                "n": dc}
        return split

    if force_device is None:
        force_device = not codec_mod._device_is_tpu()
    was_is_tpu = codec_mod._IS_TPU
    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    if force_device:
        codec_mod._IS_TPU = True
        codec_mod.DEVICE_MIN_BYTES = 0
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    payload = os.urandom(size)
    out: dict = {"config": {"streams": list(streams), "size": size,
                            "k": drives - parity, "m": parity,
                            "block": block, "lost_shards": lost_shards,
                            "forced_device_route": bool(force_device)},
                 "points": []}

    def run_point(n_streams: int, use_sched: bool) -> dict:
        root = tempfile.mkdtemp(
            prefix=f"bench_sat_{n_streams}_", dir=base)
        # sched_max_wait widens the coalescing grace window past the
        # production default — the smoke's tiny 2-stream points need
        # determinism (3 ms loses to CI scheduling jitter), the real
        # sweep wants production behavior
        sched = (BatchScheduler(max_wait=sched_max_wait)
                 if sched_max_wait is not None else BatchScheduler()) \
            if use_sched else None
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False, scheduler=sched)
        res: dict = {}
        try:
            sets.make_bucket("bench")
            sets.put_object("bench", "warm", payload)   # warm the path

            def stat_delta(before: Optional[dict]) -> dict:
                if sched is None:
                    return {}
                now_ = sched.stats()["verbs"]
                if before is None:
                    return now_
                d = {}
                for verb, vs in now_.items():
                    b = vs["batches"] - before[verb]["batches"]
                    c = vs["coalesced"] - before[verb]["coalesced"]
                    blk = vs["blocks"] - before[verb]["blocks"]
                    if b:
                        d[verb] = {
                            "dispatches": b, "groups": b + c,
                            "occupancy_groups": round((b + c) / b, 3),
                            "occupancy_blocks": round(blk / b, 3)}
                return d

            def put_one(i: int) -> None:
                sets.put_object("bench", f"o{i}", payload)

            def get_one(i: int) -> None:
                _, it = sets.get_object("bench", f"o{i}")
                n = sum(len(c) for c in it)
                assert n == size, (i, n)

            snap = stat_delta(None)
            sstage = stage_snap() if sched is not None else {}
            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=n_streams) as ex:
                list(ex.map(put_one, range(n_streams)))
            put_wall = time.perf_counter() - t0
            res["put_gib_s"] = round(
                n_streams * size / put_wall / 2**30, 4)
            res["sched_put"] = stat_delta(snap)
            if sched is not None:
                res["stages_put"] = stage_split(sstage)

            get_one(0)                     # warm the GET path
            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=n_streams) as ex:
                list(ex.map(get_one, range(n_streams)))
            res["get_gib_s"] = round(
                n_streams * size / (time.perf_counter() - t0) / 2**30,
                4)

            # degrade every object: drop `lost_shards` shard files so
            # each read group needs the fused verify+decode verb. Loss
            # is aligned by SHARD INDEX (each object loses data shards
            # 0..lost-1), not by drive: the per-object distribution
            # shuffle maps one dead drive to a different shard index
            # per object, i.e. a different survivor mask per request —
            # buckets that can never fuse. Index-aligned loss gives
            # concurrent requests ONE shared erasure pattern, the
            # coalescible stream the former exists to fuse.
            eng = sets.sets[0]
            for i in range(n_streams):
                dist = eng._read_one("bench",
                                     f"o{i}").erasure.distribution
                for j in range(lost_shards):
                    for f in glob.glob(os.path.join(
                            root, f"d{dist.index(j + 1)}", "bench",
                            f"o{i}", "*", "part.1")):
                        os.remove(f)
            get_one(0)     # warm (compiles the fused decode program)
            snap = stat_delta(None)
            sstage = stage_snap() if sched is not None else {}
            t0 = time.perf_counter()
            with cf.ThreadPoolExecutor(max_workers=n_streams) as ex:
                list(ex.map(get_one, range(n_streams)))
            res["deg_get_gib_s"] = round(
                n_streams * size / (time.perf_counter() - t0) / 2**30,
                4)
            res["sched_deg_get"] = stat_delta(snap)
            if sched is not None:
                res["stages_deg_get"] = stage_split(sstage)
        finally:
            sets.close()
            if sched is not None:
                sched.close()
            shutil.rmtree(root, ignore_errors=True)
        return res

    try:
        for s in streams:
            point: dict = {"streams": s}
            point.update(run_point(s, True))
            if ab:
                bypass = run_point(s, False)
                point["bypass"] = {
                    kk: bypass[kk] for kk in
                    ("put_gib_s", "get_gib_s", "deg_get_gib_s")}
                base_deg = bypass["deg_get_gib_s"]
                if base_deg:
                    point["deg_get_vs_bypass_x"] = round(
                        point["deg_get_gib_s"] / base_deg, 3)
            out["points"].append(point)
    finally:
        codec_mod._IS_TPU = was_is_tpu
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
    return out


def bench_rebalance_ab(streams: int = 8, size: int = 4 << 20,
                       drives: int = 8, parity: int = 2,
                       preload: int = 32) -> dict:
    """Foreground-PUT latency with vs without an active pool drain
    (the rebalance-throttle acceptance probe): two pools on tmpfs,
    pool 0 preloaded, then identical concurrent PUT rounds are timed
    per-op before and during a live decommission of pool 0. Reports
    p50/p99 per phase and `put_p99_degradation_x` — the throttle keeps
    it under ~2x because the walker backs off whenever the foreground
    shows scheduler/staging pressure."""
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.object.sets import ErasureSets

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_reb_", dir=base)
    payload = os.urandom(size)
    drain_payload = os.urandom(size // 2)
    out: dict = {"config": {"streams": streams, "size": size,
                            "drives_per_pool": drives, "m": parity,
                            "preload": preload}}
    try:
        zz = ErasureServerSets([ErasureSets.from_drives(
            [f"{root}/p{p}d{i}" for i in range(drives)], 1, drives,
            parity, block_size=1 << 20, enable_mrf=False)
            for p in (0, 1)])
        zz.make_bucket("bench")
        for i in range(preload):                # drain inventory
            zz.server_sets[0].put_object("bench", f"drain-{i}",
                                         drain_payload)

        def put_round(prefix: str) -> list[float]:
            lat: list[float] = []
            mu = threading.Lock()

            def one(i: int) -> None:
                t0 = time.perf_counter()
                # route directly to the ACTIVE pool's engine: the
                # foreground workload under test, not the zone probe
                zz.server_sets[1].put_object("bench", f"{prefix}{i}",
                                             payload)
                dt = time.perf_counter() - t0
                with mu:
                    lat.append(dt)

            with cf.ThreadPoolExecutor(max_workers=streams) as ex:
                list(ex.map(one, range(streams)))
            return lat

        def pcts(lat: list[float]) -> dict:
            xs = sorted(lat)
            return {"p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                    "p99_ms": round(xs[max(0, int(len(xs) * 0.99) - 1)]
                                    * 1e3, 2)}

        put_round("warm")                        # warm the path
        baseline = put_round("base") + put_round("base2")
        out["baseline"] = pcts(baseline)

        zz.start_decommission(0)        # the real admin code path
        reb = zz._rebalancer
        during = put_round("dr") + put_round("dr2")
        out["during_drain"] = pcts(during)
        out["drain_status_at_measure"] = {
            k: reb.status().get(k)
            for k in ("status", "objects_moved", "objects_failed")}
        deadline = time.monotonic() + 120
        while reb.running() and time.monotonic() < deadline:
            time.sleep(0.1)
        reb.stop()
        out["drain_final"] = {k: reb.status().get(k)
                              for k in ("status", "objects_moved",
                                        "objects_failed")}
        out["put_p99_degradation_x"] = round(
            out["during_drain"]["p99_ms"]
            / max(out["baseline"]["p99_ms"], 1e-9), 3)
        zz.close()
    finally:
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_tier_ab(streams: int = 8, size: int = 4 << 20,
                  drives: int = 8, parity: int = 2,
                  preload: int = 32) -> dict:
    """Foreground-PUT latency with vs without an active tier-transition
    drain (the tiering-throttle acceptance probe, the --ab-rebalance
    shape): one pool on tmpfs preloaded with transition inventory, then
    identical concurrent PUT rounds are timed per-op before and while
    the TransitionWorker moves that inventory to an fs tier. Reports
    p50/p99 per phase and `put_p99_degradation_x` — the shared
    foreground-pressure throttle keeps it bounded because the worker
    backs off whenever the foreground shows scheduler/staging
    pressure."""
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.tier.config import TierConfig, TierManager
    from minio_tpu.tier.transition import TransitionWorker

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_tier_", dir=base)
    payload = os.urandom(size)
    cold_payload = os.urandom(size // 2)
    out: dict = {"config": {"streams": streams, "size": size,
                            "drives": drives, "m": parity,
                            "preload": preload}}
    try:
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=1 << 20, enable_mrf=False)
        sets.make_bucket("bench")
        for i in range(preload):                # transition inventory
            sets.put_object("bench", f"cold-{i}", cold_payload)
        tiers = TierManager(sets)
        tiers.add(TierConfig("bench-cold", "fs",
                             {"path": f"{root}/tier"}))

        def put_round(prefix: str) -> list[float]:
            lat: list[float] = []
            mu = threading.Lock()

            def one(i: int) -> None:
                t0 = time.perf_counter()
                sets.put_object("bench", f"{prefix}{i}", payload)
                dt = time.perf_counter() - t0
                with mu:
                    lat.append(dt)

            with cf.ThreadPoolExecutor(max_workers=streams) as ex:
                list(ex.map(one, range(streams)))
            return lat

        def pcts(lat: list[float]) -> dict:
            xs = sorted(lat)
            return {"p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                    "p99_ms": round(xs[max(0, int(len(xs) * 0.99) - 1)]
                                    * 1e3, 2)}

        put_round("warm")                        # warm the path
        baseline = put_round("base") + put_round("base2")
        out["baseline"] = pcts(baseline)

        worker = TransitionWorker(sets, tiers).start()
        for i in range(preload):
            worker.enqueue("bench", f"cold-{i}", "", "bench-cold")
        during = put_round("dr") + put_round("dr2")
        out["during_drain"] = pcts(during)
        out["drain_status_at_measure"] = worker.stats()
        worker.drain(120)
        out["drain_final"] = worker.stats()
        out["put_p99_degradation_x"] = round(
            out["during_drain"]["p99_ms"]
            / max(out["baseline"]["p99_ms"], 1e-9), 3)
        worker.close()
        sets.close()
    finally:
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_replicate_ab(streams: int = 8, size: int = 4 << 20,
                       drives: int = 8, parity: int = 2,
                       preload: int = 48,
                       block: int = 1 << 20) -> dict:
    """Foreground-PUT latency with vs without an active replication
    resync drain (the --ab-rebalance/--ab-tier shape applied to the
    replication plane): two in-process sites on tmpfs, site A preloaded
    with resync inventory, identical concurrent PUT rounds timed per-op
    before and while the resync walker seeds site B. Reports p50/p99
    per phase, `put_p99_degradation_x` (the shared foreground-pressure
    throttle keeps it bounded), and the replication lag histogram of
    the steady-state pushes the foreground PUTs triggered."""
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.engine import PutOptions
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.replicate import (LayerReplClient, ReplicationPlane,
                                     SiteTarget, TargetRegistry, new_arn)
    from minio_tpu.utils import telemetry

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_repl_", dir=base)
    payload = os.urandom(size)
    cold_payload = os.urandom(max(size // 2, 1 << 16))
    out: dict = {"config": {"streams": streams, "size": size,
                            "drives": drives, "m": parity,
                            "preload": preload}}
    try:
        def mk_site(name: str):
            sets = ErasureSets.from_drives(
                [f"{root}/{name}/d{i}" for i in range(drives)], 1,
                drives, parity, block_size=block, enable_mrf=False)
            layer = ErasureServerSets([sets], load_topology=False)
            layer.make_bucket("bench")
            return layer

        src = mk_site("a")
        dst = mk_site("b")
        reg = TargetRegistry(src, site_id="bench-a")
        plane = ReplicationPlane(src, reg)
        src.attach_replication(plane)
        for i in range(preload):                # resync inventory
            src.put_object("bench", f"cold-{i}", cold_payload,
                           opts=PutOptions(versioned=True))

        def put_round(prefix: str) -> list[float]:
            lat: list[float] = []
            mu = threading.Lock()

            def one(i: int) -> None:
                t0 = time.perf_counter()
                src.put_object("bench", f"{prefix}{i}", payload,
                               opts=PutOptions(versioned=True))
                dt = time.perf_counter() - t0
                with mu:
                    lat.append(dt)

            with cf.ThreadPoolExecutor(max_workers=streams) as ex:
                list(ex.map(one, range(streams)))
            return lat

        def pcts(lat: list[float]) -> dict:
            xs = sorted(lat)
            return {"p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                    "p99_ms": round(xs[max(0, int(len(xs) * 0.99) - 1)]
                                    * 1e3, 2)}

        put_round("warm")                        # warm the path
        baseline = put_round("base") + put_round("base2")
        out["baseline"] = pcts(baseline)

        # register the target + start the resync drain, then measure
        # foreground PUTs racing it (their own steady-state pushes ride
        # the plane concurrently)
        arn = new_arn("bench")
        reg.add(SiteTarget(arn=arn, bucket="bench", dest_bucket="bench",
                           site="bench-b", type="layer"),
                client=LayerReplClient(dst, "bench", "bench-b"))
        resync = plane.start_resync(arn, checkpoint_every=1000)
        during = put_round("dr") + put_round("dr2")
        out["during_resync"] = pcts(during)
        out["resync_status_at_measure"] = resync.status()
        for _ in range(600):
            if not resync.running():
                break
            time.sleep(0.1)
        plane.drain(120)
        out["resync_final"] = resync.status()
        out["plane_final"] = plane.stats()
        out["put_p99_degradation_x"] = round(
            out["during_resync"]["p99_ms"]
            / max(out["baseline"]["p99_ms"], 1e-9), 3)
        # replication lag histogram (steady-state pushes of the
        # foreground PUTs): bucketed counts straight off the registry
        hist = telemetry.REGISTRY.histogram("minio_tpu_repl_lag_seconds")
        series = None
        with hist._mu:
            for _k, s in hist._series.items():
                series = {"buckets_s": list(hist.buckets),
                          "counts": list(s.counts),
                          "count": s.count,
                          "mean_s": round(s.total / s.count, 4)
                          if s.count else 0.0}
        out["lag_histogram"] = series or {}
        plane.close()
        src.close()
        dst.close()
    finally:
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_notify_ab(streams: int = 8, size: int = 4 << 20,
                    drives: int = 8, parity: int = 2,
                    webhook_delay_s: float = 0.05,
                    block: int = 1 << 20) -> dict:
    """Foreground-PUT latency with vs without bucket event
    notifications against a SLOW webhook (the --ab-replicate shape
    applied to the notification plane): one in-process layer on tmpfs,
    identical concurrent PUT rounds timed per-op before and after a
    NotificationConfiguration wires every PUT to a webhook whose every
    POST stalls `webhook_delay_s`. The plane's bounded queue + worker
    pool + foreground-pressure throttle must keep the PUT hot path
    out of the webhook's latency: reports p50/p99 per phase,
    `put_p99_degradation_x` (the acceptance bound: a dead/slow webhook
    degrades PUT p99 by <= 5%), the plane's final counters after a
    full drain (zero events lost), and the delivery-lag histogram."""
    import concurrent.futures as cf
    import http.server
    import shutil
    import socket
    import tempfile
    import threading

    from minio_tpu.notify import (NotificationPlane, NotifyTarget,
                                  NotifyTargetRegistry, new_arn)
    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.engine import PutOptions
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.utils import telemetry

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_notify_", dir=base)
    payload = os.urandom(size)
    out: dict = {"config": {"streams": streams, "size": size,
                            "drives": drives, "m": parity,
                            "webhook_delay_s": webhook_delay_s}}
    received = [0]

    class _SlowHook(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            time.sleep(webhook_delay_s)
            received[0] += 1
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), _SlowHook)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False)
        layer = ErasureServerSets([sets], load_topology=False)
        layer.make_bucket("bench")
        reg = NotifyTargetRegistry(layer)
        arn = new_arn("bench", "webhook")
        reg.add(NotifyTarget(arn=arn, type="webhook",
                             params={"endpoint":
                                     f"http://127.0.0.1:{port}/",
                                     "timeout": 5.0}))
        plane = NotificationPlane(layer, reg,
                                  queue_dir=f"{root}/notifyq",
                                  node="bench")
        layer.attach_notifications(plane)

        def put_round(prefix: str) -> list[float]:
            lat: list[float] = []
            mu = threading.Lock()

            def one(i: int) -> None:
                t0 = time.perf_counter()
                layer.put_object("bench", f"{prefix}{i}", payload,
                                 opts=PutOptions(versioned=True))
                dt = time.perf_counter() - t0
                with mu:
                    lat.append(dt)

            with cf.ThreadPoolExecutor(max_workers=streams) as ex:
                list(ex.map(one, range(streams)))
            return lat

        def pcts(lat: list[float]) -> dict:
            xs = sorted(lat)
            return {"p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                    "p99_ms": round(xs[max(0, int(len(xs) * 0.99) - 1)]
                                    * 1e3, 2)}

        put_round("warm")                        # warm the path
        baseline = put_round("base") + put_round("base2")
        out["baseline"] = pcts(baseline)

        # wire every creation to the slow webhook, then measure the
        # foreground PUTs racing their own event deliveries
        plane.set_config(
            "bench",
            "<NotificationConfiguration><QueueConfiguration>"
            f"<Queue>{arn}</Queue>"
            "<Event>s3:ObjectCreated:*</Event>"
            "</QueueConfiguration></NotificationConfiguration>")
        during = put_round("dr") + put_round("dr2")
        out["during_notify"] = pcts(during)
        out["plane_at_measure"] = plane.stats()
        assert plane.drain(180), plane.stats()   # zero loss: all land
        out["plane_final"] = plane.stats()
        out["webhook_received"] = received[0]
        out["put_p99_degradation_x"] = round(
            out["during_notify"]["p99_ms"]
            / max(out["baseline"]["p99_ms"], 1e-9), 3)
        # delivery-lag histogram: bucketed counts off the registry
        hist = telemetry.REGISTRY.histogram(
            "minio_tpu_notify_lag_seconds")
        series = None
        with hist._mu:
            for _k, s in hist._series.items():
                series = {"buckets_s": list(hist.buckets),
                          "counts": list(s.counts),
                          "count": s.count,
                          "mean_s": round(s.total / s.count, 4)
                          if s.count else 0.0}
        out["lag_histogram"] = series or {}
        plane.close()
        layer.close()
    finally:
        srv.shutdown()
        srv.server_close()
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_list_ab(keys: int = 10000, drives: int = 8, parity: int = 2,
                  page: int = 1000, versions_every: int = 20,
                  payload_bytes: int = 16) -> dict:
    """Listing A/B: merge-walk vs persisted bucket metacache.

    One pool on tmpfs seeded with `keys` small objects (a nested
    prefix every 4th key, an extra version every `versions_every`-th),
    then per mode:

      * page the whole namespace (max_keys=`page`) and report per-page
        p50/p99 — the walk mode re-runs the heap merge + per-name
        quorum metadata read every page, the index mode slices memory;
      * run one "crawler cycle" (DataUsageCrawler.scan_once plus the
        noncurrent version-group walks the lifecycle sweep and the
        tier transition action run) and report wall time + the
        namespace-walk counter delta — with the index attached the
        cycle performs ZERO merge walks: the one amortized walk
        happened at build time (reported separately as build_s).

    The index-served pages are asserted name-identical to the
    merge-walk pages before timing (the oracle discipline the erasure
    kernels use)."""
    import shutil
    import tempfile

    from minio_tpu.features.lifecycle import iter_version_groups
    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.background import DataUsageCrawler
    from minio_tpu.object.engine import PutOptions
    from minio_tpu.object.metacache import MetacacheManager, walks_counter
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.object.sets import ErasureSets

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_list_", dir=base)
    payload = os.urandom(payload_bytes)
    out: dict = {"config": {"keys": keys, "drives": drives, "m": parity,
                            "page": page,
                            "versions_every": versions_every}}

    def walk_totals() -> dict:
        c = walks_counter()
        with c._mu:
            items = dict(c._series)
        tot = {"merge": 0.0, "index": 0.0}
        for key, v in items.items():
            src = dict(key).get("source", "merge")
            tot[src] = tot.get(src, 0.0) + v
        return tot

    def pcts(lat: list) -> dict:
        xs = sorted(lat)
        return {"p50_ms": round(xs[len(xs) // 2] * 1e3, 3),
                "p99_ms": round(xs[max(0, int(len(xs) * 0.99) - 1)]
                                * 1e3, 3)}

    try:
        zz = ErasureServerSets([ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=1 << 18, enable_mrf=False)],
            load_topology=False)
        zz.make_bucket("bench")
        t0 = time.perf_counter()
        for i in range(keys):
            name = f"dir{i % 4}/obj-{i:07d}" if i % 4 else f"obj-{i:07d}"
            zz.put_object("bench", name, payload)
            if versions_every and i % versions_every == 0:
                zz.put_object("bench", name, payload,
                              opts=PutOptions(versioned=True))
        out["seed_s"] = round(time.perf_counter() - t0, 2)

        def page_walk() -> tuple[list, list]:
            lats, names, marker = [], [], ""
            while True:
                t0 = time.perf_counter()
                objs, _pfx, trunc = zz.list_objects("bench", "", marker,
                                                    "", page)
                lats.append(time.perf_counter() - t0)
                names.extend(o.name for o in objs)
                if not trunc or not objs:
                    return lats, names
                marker = objs[-1].name

        crawler = DataUsageCrawler(zz, interval=1e9, persist=False)

        def cycle() -> dict:
            before = walk_totals()
            t0 = time.perf_counter()
            crawler.scan_once()
            for _ in iter_version_groups(zz, "bench",
                                         consumer="lifecycle"):
                pass
            for _ in iter_version_groups(zz, "bench",
                                         consumer="transition"):
                pass
            wall = time.perf_counter() - t0
            after = walk_totals()
            return {"wall_s": round(wall, 3),
                    "merge_walks": round(after["merge"]
                                         - before["merge"], 1),
                    "index_reads": round(after["index"]
                                         - before["index"], 1)}

        # -- phase A: merge-walk (no index attached) -----------------------
        walk_lats, walk_names = page_walk()
        out["walk"] = dict(pcts(walk_lats), pages=len(walk_lats),
                           cycle=cycle())

        # -- phase B: metacache index --------------------------------------
        mgr = MetacacheManager(zz, flush_s=0.05).start()
        zz.attach_metacache(mgr)
        t0 = time.perf_counter()
        assert mgr.build("bench")
        out["build_s"] = round(time.perf_counter() - t0, 2)
        idx_lats, idx_names = page_walk()
        if idx_names != walk_names:     # oracle: identical pages
            raise AssertionError(
                f"index pages diverged from merge-walk: "
                f"{len(idx_names)} vs {len(walk_names)} names")
        out["index"] = dict(pcts(idx_lats), pages=len(idx_lats),
                            cycle=cycle(),
                            metacache=mgr.stats())
        out["index"]["metacache"].pop("buckets", None)
        out["page_p50_speedup_x"] = round(
            out["walk"]["p50_ms"] / max(out["index"]["p50_ms"], 1e-9), 2)
        out["cycle_speedup_x"] = round(
            out["walk"]["cycle"]["wall_s"]
            / max(out["index"]["cycle"]["wall_s"], 1e-9), 2)
    finally:
        try:
            # stop the metacache daemon BEFORE its backing tree is
            # deleted, even when a phase raised
            zz.close()
        except Exception:  # noqa: BLE001 — includes zz never assigned
            pass
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_select_ab(streams: Sequence[int] = (1, 2, 4, 8),
                    rows: int = 20000, queries_per_stream: int = 4,
                    sched_max_wait: float = 0.25) -> dict:
    """S3 Select A/B: device scan plane vs the CPU row-by-row
    evaluator at 1..N concurrent SelectObjectContent requests.

    One CSV corpus (`rows` records, mixed numeric/string cells), one
    predicate-heavy query. Per concurrency point, each of n threads
    runs `queries_per_stream` Selects:

      * cpu   — s3select.select.event_stream (the oracle),
      * device — ScanEngine riding a shared BatchScheduler with the
        kernels FORCED onto the local XLA backend; the scheduler's
        scan-verb batches/coalesced counter deltas per point prove
        concurrent requests coalesce into shared launches.

    Device output is asserted byte-identical to the CPU stream before
    any timing (the erasure kernels' oracle discipline)."""
    import io
    import csv as _csv
    import random as _random
    import threading

    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.s3select.select import SelectRequest, event_stream
    from minio_tpu.scan import ScanEngine

    rng = _random.Random(20240803)
    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(("a", "b", "c", "d"))
    words = ("x", "zz", "abc", "Par", "x y", "")
    for i in range(rows):
        w.writerow((rng.randint(-50, 50), round(rng.uniform(0, 9), 3),
                    rng.choice(words), i % 7))
    data = buf.getvalue().encode()

    req = SelectRequest()
    req.expression = ("SELECT a, b, c FROM S3Object WHERE "
                      "(a >= 0 AND b < 4.5) OR c LIKE 'x%' "
                      "OR d BETWEEN 2 AND 3")
    req.csv_header = "USE"

    was_mode = os.environ.get("MINIO_TPU_SCAN_DEVICE")
    os.environ["MINIO_TPU_SCAN_DEVICE"] = "force"
    out: dict = {"config": {"rows": rows, "streams": list(streams),
                            "queries_per_stream": queries_per_stream,
                            "expression": req.expression},
                 "points": []}
    sched = BatchScheduler(max_wait=sched_max_wait)
    try:
        oracle = b"".join(event_stream(req, data))
        out["config"]["response_bytes"] = len(oracle)
        eng = ScanEngine(sched)
        # byte-identity + jit warm BEFORE timing
        if b"".join(eng.event_stream(req, data)) != oracle:
            raise AssertionError("device Select diverged from the "
                                 "CPU evaluator")
        if eng.device_serves != 1:
            raise AssertionError(
                f"device path declined: {eng.fallback_reasons}")

        def run_point(n: int, device: bool) -> dict:
            engine = ScanEngine(sched) if device else None
            lats: list[float] = []
            errs: list[BaseException] = []
            mu = threading.Lock()
            barrier = threading.Barrier(n)

            def one() -> None:
                try:
                    barrier.wait()
                    for _ in range(queries_per_stream):
                        t0 = time.perf_counter()
                        if device:
                            body = b"".join(
                                engine.event_stream(req, data))
                        else:
                            body = b"".join(event_stream(req, data))
                        dt = time.perf_counter() - t0
                        if body != oracle:
                            raise AssertionError(
                                "device Select diverged from the CPU "
                                "evaluator under concurrency")
                        with mu:
                            lats.append(dt)
                except BaseException as e:  # noqa: BLE001 — re-raised
                    with mu:                # on the main thread below
                        errs.append(e)

            ts = [threading.Thread(target=one) for _ in range(n)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            wall = time.perf_counter() - t0
            if errs:
                raise errs[0]
            nq = n * queries_per_stream
            xs = sorted(lats)
            point = {
                "queries": nq,
                "wall_s": round(wall, 3),
                "queries_per_s": round(nq / wall, 2),
                "scanned_mb_s": round(nq * len(data) / wall / 1e6, 1),
                "p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                "p99_ms": round(xs[max(0, int(len(xs) * .99) - 1)]
                                * 1e3, 2),
            }
            if device:
                point["device_serves"] = engine.device_serves
                point["fallbacks"] = engine.fallbacks
            return point

        for n in streams:
            before = dict(sched.verb_stats["scan"])
            dev = run_point(n, device=True)
            vs = sched.verb_stats["scan"]
            dev["sched_batches"] = vs["batches"] - before["batches"]
            dev["sched_coalesced"] = (vs["coalesced"]
                                      - before["coalesced"])
            cpu = run_point(n, device=False)
            out["points"].append({
                "streams": n, "device": dev, "cpu": cpu,
                "speedup_x": round(cpu["wall_s"]
                                   / max(dev["wall_s"], 1e-9), 2)})
    finally:
        sched.close()
        if was_mode is None:
            os.environ.pop("MINIO_TPU_SCAN_DEVICE", None)
        else:
            os.environ["MINIO_TPU_SCAN_DEVICE"] = was_mode
    out["max_speedup_x"] = max(p["speedup_x"] for p in out["points"])
    return out


def bench_cache_ab(objects: int = 16, size: int = 4 << 20,
                   gets: int = 200, streams: int = 4,
                   drives: int = 6, parity: int = 2,
                   block: int = 1 << 18) -> dict:
    """Hot-GET A/B: erasure read path with the hot-object read cache
    off vs on.

    One pool on tmpfs seeded with `objects` objects; `gets` reads from
    `streams` threads over a hot subset (80/20-ish zipf pick). The
    cache-on pass wires CacheObjects the way cluster boot does
    (attach_read_cache + wrapper serving GETs) with a 1-hit admission
    bar so the second touch of every hot key serves from the cache
    WITHOUT the shard-read/verify/decode path — proven by the
    minio_tpu_erasure_get_streams_total counter delta, not just
    latency. Bytes are asserted identical to the backend read."""
    import random as _random
    import shutil
    import tempfile
    import threading

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.cache import CacheObjects
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.utils import telemetry

    def decode_streams() -> float:
        return telemetry.REGISTRY.counter(
            "minio_tpu_erasure_get_streams_total",
            "Object read streams served through the erasure "
            "shard-read/verify/decode path").value()

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_cache_", dir=base)
    out: dict = {"config": {"objects": objects, "size": size,
                            "gets": gets, "streams": streams,
                            "drives": drives, "m": parity}}
    rng = _random.Random(4096)
    # 80% of reads land on the hottest 20% of keys
    hot = max(1, objects // 5)
    picks = [rng.randrange(hot) if rng.random() < 0.8
             else rng.randrange(objects) for _ in range(gets)]
    try:
        zz = ErasureServerSets([ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False)], load_topology=False)
        zz.make_bucket("bench")
        payloads = []
        for i in range(objects):
            payloads.append(os.urandom(size))
            zz.put_object("bench", f"o-{i:04d}", payloads[i])

        def run_pass(layer) -> dict:
            lats: list[float] = []
            mu = threading.Lock()
            chunks = [picks[i::streams] for i in range(streams)]
            barrier = threading.Barrier(streams)

            def one(mine: list) -> None:
                barrier.wait()
                for idx in mine:
                    t0 = time.perf_counter()
                    _info, s = layer.get_object("bench", f"o-{idx:04d}")
                    body = b"".join(s)
                    dt = time.perf_counter() - t0
                    assert body == payloads[idx]
                    with mu:
                        lats.append(dt)

            before = decode_streams()
            ts = [threading.Thread(target=one, args=(c,))
                  for c in chunks if c]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            wall = time.perf_counter() - t0
            xs = sorted(lats)
            return {
                "wall_s": round(wall, 3),
                "get_gib_s": round(len(lats) * size / wall / (1 << 30),
                                   3),
                "p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                "p99_ms": round(xs[max(0, int(len(xs) * .99) - 1)]
                                * 1e3, 2),
                "decode_streams": round(decode_streams() - before, 1),
            }

        out["off"] = run_pass(zz)

        cache = CacheObjects(zz, os.path.join(root, "cache"),
                             budget_bytes=2 * objects * size,
                             admit_hits=1)
        zz.attach_read_cache(cache)
        out["on"] = run_pass(cache)
        out["on"]["cache"] = {k: cache.stats()[k] for k in
                              ("hits", "misses", "fills", "evictions")}
        out["speedup_x"] = round(out["off"]["wall_s"]
                                 / max(out["on"]["wall_s"], 1e-9), 2)
        out["decode_streams_saved"] = round(
            out["off"]["decode_streams"] - out["on"]["decode_streams"],
            1)
    finally:
        try:
            zz.close()
        except Exception:  # noqa: BLE001 — includes zz never assigned
            pass
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_sse_ab(streams=(1, 2, 4), size: int = 4 << 20,
                 objects: int = 3, drives: int = 6, parity: int = 2,
                 block: int = 1 << 17) -> dict:
    """Encrypted data-path A/B: device-fused cipher+RS+digest PUT (one
    launch per batch, ops/chacha20_jax inside the batch former) and the
    fused verify+decipher GET, vs the CPU ChaCha20 fallback.

    Each pass runs every concurrency point: N writers PUT `objects`
    objects each under DIFFERENT object keys — cross-request coalescing
    of encrypted batches is exactly what the geometry-keyed scheduler
    bucket buys — then read everything back through the
    verify-then-decrypt seam and byte-check against the plaintext.
    The device pass pins the fused route (TPU flag + DEVICE_MIN_BYTES=0;
    on a CPU-only host the same XLA programs run on the host backend, so
    the A/B measures program fusion + batching, not silicon) and reports
    launch/coalescing counter deltas plus the queue/transfer/compute/
    fetch dispatch attribution the scheduler histograms collect."""
    import shutil
    import tempfile
    import threading

    from minio_tpu.features import crypto as sse
    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object import engine as engine_mod
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.utils import telemetry

    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    out: dict = {"config": {"streams": list(streams), "size": size,
                            "objects": objects, "drives": drives,
                            "m": parity, "block": block},
                 "cpu": [], "device": []}
    was_tpu = codec_mod._IS_TPU
    was_min = codec_mod.DEVICE_MIN_BYTES
    was_attrib = os.environ.get("MINIO_TPU_SCHED_ATTRIB")
    was_win = os.environ.get("MINIO_TPU_SSE_DEVICE_MIN_BYTES")
    os.environ["MINIO_TPU_SCHED_ATTRIB"] = "1"
    os.environ["MINIO_TPU_SSE_DEVICE_MIN_BYTES"] = "0"
    pt = os.urandom(size)
    try:
        for mode in ("cpu", "device"):
            codec_mod._IS_TPU = mode == "device"
            codec_mod.DEVICE_MIN_BYTES = 0 if mode == "device" \
                else (1 << 60)
            for ns in streams:
                root = tempfile.mkdtemp(prefix="bench_sse_", dir=base)
                sched = BatchScheduler()
                sets_ = None
                try:
                    sets_ = ErasureSets.from_drives(
                        [f"{root}/d{i}" for i in range(drives)], 1,
                        drives, parity, block_size=block,
                        enable_mrf=False, scheduler=sched)
                    sets_.make_bucket("bench")
                    oeks = [os.urandom(32) for _ in range(ns)]
                    bases = [os.urandom(12) for _ in range(ns)]
                    # jit warmup outside the timed window
                    sets_.put_object(
                        "bench", "warm", pt,
                        opts=engine_mod.PutOptions(
                            sse_spec=sse.DeviceSSE(oeks[0], bases[0])))
                    b0, c0 = sched.batches, sched.coalesced
                    barrier = threading.Barrier(ns)
                    errs: list = []

                    def put_worker(t: int) -> None:
                        try:
                            barrier.wait()
                            for i in range(objects):
                                sets_.put_object(
                                    "bench", f"o-{t}-{i}", pt,
                                    opts=engine_mod.PutOptions(
                                        sse_spec=sse.DeviceSSE(
                                            oeks[t], bases[t])))
                        except Exception as exc:  # noqa: BLE001
                            errs.append(exc)

                    ts = [threading.Thread(target=put_worker, args=(t,))
                          for t in range(ns)]
                    t0 = time.perf_counter()
                    for th in ts:
                        th.start()
                    for th in ts:
                        th.join()
                    put_wall = time.perf_counter() - t0
                    if errs:
                        raise errs[0]

                    def get_worker(t: int) -> None:
                        try:
                            barrier.wait()
                            for i in range(objects):
                                name = f"o-{t}-{i}"

                                def fetch(off, ln, _n=name):
                                    _, it = sets_.get_object(
                                        "bench", _n, off, ln)
                                    return it

                                got = b"".join(sse.chacha_decrypt_ranged(
                                    fetch, sse.encrypted_size(size),
                                    oeks[t], bases[t], 0, size))[:size]
                                assert got == pt, "A/B byte mismatch"
                        except Exception as exc:  # noqa: BLE001
                            errs.append(exc)

                    ts = [threading.Thread(target=get_worker, args=(t,))
                          for t in range(ns)]
                    t0 = time.perf_counter()
                    for th in ts:
                        th.start()
                    for th in ts:
                        th.join()
                    get_wall = time.perf_counter() - t0
                    if errs:
                        raise errs[0]
                    nbytes = ns * objects * size
                    out[mode].append({
                        "streams": ns,
                        "put_gib_s": round(nbytes / put_wall / (1 << 30),
                                           4),
                        "get_gib_s": round(nbytes / get_wall / (1 << 30),
                                           4),
                        "launches": sched.batches - b0,
                        "coalesced": sched.coalesced - c0,
                    })
                finally:
                    if sets_ is not None:
                        sets_.close()
                    sched.close()
                    shutil.rmtree(root, ignore_errors=True)
        # compressed+encrypted at the max concurrency point: the
        # handler's exact transform chain — the snappy compressor
        # stays a host stage and its OUTPUT is the plaintext the
        # engine ciphers in-batch (fused or fallback per mode)
        from minio_tpu.features.snappy import (SnappyFramedCompress,
                                               decompress_stream)
        pt_c = (b"minio tpu sse device data path " * 97)[:4096]
        pt_c = pt_c * max(1, size // len(pt_c))
        ns = max(streams)
        for mode in ("cpu", "device"):
            codec_mod._IS_TPU = mode == "device"
            codec_mod.DEVICE_MIN_BYTES = 0 if mode == "device" \
                else (1 << 60)
            root = tempfile.mkdtemp(prefix="bench_sse_", dir=base)
            sched = BatchScheduler()
            sets_ = None
            try:
                sets_ = ErasureSets.from_drives(
                    [f"{root}/d{i}" for i in range(drives)], 1,
                    drives, parity, block_size=block,
                    enable_mrf=False, scheduler=sched)
                sets_.make_bucket("bench")
                oeks = [os.urandom(32) for _ in range(ns)]
                bases = [os.urandom(12) for _ in range(ns)]
                comp = SnappyFramedCompress()
                clen = len(comp.update(pt_c) + comp.finalize())
                barrier = threading.Barrier(ns)
                errs: list = []

                def cput(t: int) -> None:
                    try:
                        barrier.wait()
                        for i in range(objects):
                            c = SnappyFramedCompress()
                            body = c.update(pt_c) + c.finalize()
                            sets_.put_object(
                                "bench", f"c-{t}-{i}", body,
                                opts=engine_mod.PutOptions(
                                    sse_spec=sse.DeviceSSE(
                                        oeks[t], bases[t])))
                    except Exception as exc:  # noqa: BLE001
                        errs.append(exc)

                ts = [threading.Thread(target=cput, args=(t,))
                      for t in range(ns)]
                t0 = time.perf_counter()
                for th in ts:
                    th.start()
                for th in ts:
                    th.join()
                put_wall = time.perf_counter() - t0
                if errs:
                    raise errs[0]

                def cget(t: int) -> None:
                    try:
                        barrier.wait()
                        for i in range(objects):
                            name = f"c-{t}-{i}"

                            def fetch(off, ln, _n=name):
                                _, it = sets_.get_object(
                                    "bench", _n, off, ln)
                                return it

                            ct = sse.chacha_decrypt_ranged(
                                fetch, sse.encrypted_size(clen),
                                oeks[t], bases[t], 0, clen)
                            got = b"".join(decompress_stream(ct))
                            assert got == pt_c, "A/B byte mismatch"
                    except Exception as exc:  # noqa: BLE001
                        errs.append(exc)

                ts = [threading.Thread(target=cget, args=(t,))
                      for t in range(ns)]
                t0 = time.perf_counter()
                for th in ts:
                    th.start()
                for th in ts:
                    th.join()
                get_wall = time.perf_counter() - t0
                if errs:
                    raise errs[0]
                nbytes = ns * objects * len(pt_c)   # plaintext rate
                out[f"{mode}_compressed"] = {
                    "streams": ns, "ratio": round(len(pt_c) / clen, 2),
                    "put_gib_s": round(nbytes / put_wall / (1 << 30),
                                       4),
                    "get_gib_s": round(nbytes / get_wall / (1 << 30),
                                       4),
                }
            finally:
                if sets_ is not None:
                    sets_.close()
                sched.close()
                shutil.rmtree(root, ignore_errors=True)
        snap = telemetry.REGISTRY.snapshot(
            "minio_tpu_device_dispatch_seconds")
        out["dispatch_stage_seconds"] = snap.get(
            "minio_tpu_device_dispatch_seconds", {})
        last_cpu, last_dev = out["cpu"][-1], out["device"][-1]
        out["put_speedup_x"] = round(
            last_dev["put_gib_s"] / max(last_cpu["put_gib_s"], 1e-9), 2)
        out["get_speedup_x"] = round(
            last_dev["get_gib_s"] / max(last_cpu["get_gib_s"], 1e-9), 2)
    finally:
        codec_mod._IS_TPU = was_tpu
        codec_mod.DEVICE_MIN_BYTES = was_min
        for k, v in (("MINIO_TPU_SCHED_ATTRIB", was_attrib),
                     ("MINIO_TPU_SSE_DEVICE_MIN_BYTES", was_win)):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def bench_gray_ab(objects: int = 16, size: int = 1 << 20,
                  gets: int = 60, streams: int = 4, drives: int = 6,
                  parity: int = 2, block: int = 1 << 17,
                  stall_s: float = 0.5) -> dict:
    """Gray-failure A/B: PUT/GET tail latency with ONE drive stalling
    `stall_s` per I/O, the gray-failure plane off vs on.

    OFF = MINIO_TPU_HEDGE/QUORUM_ACK/QUARANTINE all off: every PUT
    waits out the stalled drive's shard writes and any GET whose read
    plan includes it waits out the stalled shard read. ON = defaults
    (tightened floors so the adaptive deadlines bite at bench scale):
    hedged reads race the staller, PUTs ack at write quorum, and a
    DiskMonitor health scan walks the drive through suspect →
    probation → heal-verified re-admission once the stall clears.

    The bench asserts its own acceptance bar: zero acked-write loss
    after the MRF drain (every object byte-identical with the staller
    disarmed) and the full quarantine round trip."""
    import shutil
    import tempfile
    import threading

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.background import DiskMonitor
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.storage import XLStorage
    from minio_tpu.storage.naughty import NaughtyDisk
    from minio_tpu.utils import healthtrack

    READ_STALLS = ("read_file_stream", "read_file", "read_all")
    WRITE_STALLS = ("append_file", "create_file", "write_all",
                    "write_metadata", "rename_data")
    KNOBS_OFF = {"MINIO_TPU_HEDGE": "off", "MINIO_TPU_QUORUM_ACK": "off",
                 "MINIO_TPU_QUARANTINE": "off"}
    KNOBS_ON = {"MINIO_TPU_HEDGE": "on", "MINIO_TPU_QUORUM_ACK": "on",
                "MINIO_TPU_QUARANTINE": "on",
                # tightened floors/ceilings: the adaptive deadline must
                # bite below the injected stall even from a cold start
                "MINIO_TPU_HEDGE_FLOOR_S": "0.05",
                "MINIO_TPU_HEDGE_CEIL_S": str(stall_s / 4),
                "MINIO_TPU_WRITE_STALL_FLOOR_S": "0.1",
                "MINIO_TPU_WRITE_STALL_CEIL_S": str(stall_s / 2),
                "MINIO_TPU_QUAR_LATENCY_S": str(stall_s / 2.5),
                "MINIO_TPU_QUAR_MIN_SAMPLES": "4",
                "MINIO_TPU_QUAR_PROBATION_S": "0",
                "MINIO_TPU_QUAR_PROBES": "2"}

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    out: dict = {"config": {"objects": objects, "size": size,
                            "gets": gets, "streams": streams,
                            "drives": drives, "m": parity,
                            "stall_s": stall_s}}
    saved = {k: os.environ.get(k)
             for k in set(KNOBS_OFF) | set(KNOBS_ON)}
    roots: list = []

    def pctls(xs: list) -> dict:
        s = sorted(xs)
        return {"p50_ms": round(s[len(s) // 2] * 1e3, 2),
                "p99_ms": round(s[max(0, int(len(s) * .99) - 1)] * 1e3,
                                2)}

    def run_pass(env: dict) -> tuple[dict, "ErasureSets", NaughtyDisk,
                                     list]:
        for k, v in env.items():
            os.environ[k] = v
        healthtrack.TRACKER.reset()
        root = tempfile.mkdtemp(prefix="bench_gray_", dir=base)
        roots.append(root)
        raw = [XLStorage(f"{root}/d{j}") for j in range(drives)]
        nd = NaughtyDisk(raw[0], enabled=False)
        drv = [nd] + raw[1:]
        sets = ErasureSets.from_storage(
            drv, set_count=1, set_drive_count=drives, parity=parity,
            block_size=block,
            mrf_options=dict(max_retries=10, backoff_base=0.02,
                             backoff_max=0.25))
        sets.make_bucket("bench")
        payloads = [os.urandom(size) for _ in range(objects)]
        nd.stall_verbs = {v: stall_s
                          for v in READ_STALLS + WRITE_STALLS}
        nd.arm()

        put_lat: list[float] = []
        for i, body in enumerate(payloads):
            t0 = time.perf_counter()
            sets.put_object("bench", f"o-{i:04d}", body)
            put_lat.append(time.perf_counter() - t0)

        # the laggard-abandoned shards converge through MRF while the
        # drive is STILL slow (quarantined drives keep taking writes);
        # settle that background heal churn so the GET phase measures
        # steady state instead of heal-lock contention
        sets.drain_mrf(120.0)

        get_lat: list[float] = []
        worker_errs: list = []
        mu = threading.Lock()
        picks = [i % objects for i in range(gets)]
        chunks = [picks[i::streams] for i in range(streams)]
        barrier = threading.Barrier(sum(1 for c in chunks if c))

        def one(mine: list) -> None:
            barrier.wait()
            for idx in mine:
                t0 = time.perf_counter()
                _info, s = sets.get_object("bench", f"o-{idx:04d}")
                body = b"".join(s)
                dt = time.perf_counter() - t0
                if body != payloads[idx]:
                    raise AssertionError(f"o-{idx:04d} bytes differ")
                with mu:
                    get_lat.append(dt)

        def guarded(mine: list) -> None:
            # a worker's failure must FAIL the bench, not silently
            # shrink the sample set while the acceptance claims stand
            try:
                one(mine)
            except BaseException as e:  # noqa: BLE001 — re-raised
                with mu:
                    worker_errs.append(e)

        ts = [threading.Thread(target=guarded, args=(c,))
              for c in chunks if c]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if worker_errs:
            raise worker_errs[0]
        res = {"put": pctls(put_lat), "get": pctls(get_lat),
               "stalls_injected": nd.stats.stalls}
        return res, sets, nd, payloads

    try:
        out["off"], sets_off, nd_off, _ = run_pass(KNOBS_OFF)
        sets_off.close()

        out["on"], sets, nd, payloads = run_pass(KNOBS_ON)

        # quarantine round trip on the ON cluster: the scan convicts
        # the staller, probation probes fail while it still stalls,
        # pass once it recovers, and re-admission is heal-verified
        mon = DiskMonitor(sets, interval=3600)
        key = healthtrack.disk_key(nd)
        nd.stall_verbs["disk_info"] = stall_s
        mon.scan_once()
        states = [healthtrack.TRACKER.state_of("drive", key)]
        mon.scan_once()                 # probation probe: still slow
        states.append(healthtrack.TRACKER.state_of("drive", key))
        nd.stall_verbs = {}
        nd.disarm()                     # the gray spell ends
        for _ in range(4):
            mon.scan_once()
            states.append(healthtrack.TRACKER.state_of("drive", key))
            if states[-1] == healthtrack.STATE_OK:
                break
        out["quarantine"] = {"states": states,
                             "events": list(mon.quarantine_events)}
        assert states[0] == healthtrack.STATE_SUSPECT, states
        assert states[-1] == healthtrack.STATE_OK, states

        # zero acked-write loss: MRF converges every laggard-abandoned
        # shard, then every acked object reads back byte-identical
        assert sets.drain_mrf(60.0), "MRF did not drain"
        lost = 0
        for i, body in enumerate(payloads):
            _info, s = sets.get_object("bench", f"o-{i:04d}")
            if b"".join(s) != body:
                lost += 1
        out["mrf"] = sets.mrf_stats()
        out["lost_after_mrf"] = lost
        assert lost == 0, f"{lost} acked writes lost"
        sets.close()

        out["get_p99_speedup_x"] = round(
            out["off"]["get"]["p99_ms"]
            / max(out["on"]["get"]["p99_ms"], 1e-9), 2)
        out["put_p99_speedup_x"] = round(
            out["off"]["put"]["p99_ms"]
            / max(out["on"]["put"]["p99_ms"], 1e-9), 2)
        # PUT acks at quorum: the stalled drive no longer binds p99
        out["put_p99_below_stall"] = \
            out["on"]["put"]["p99_ms"] < stall_s * 1e3
    finally:
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for root in roots:
            shutil.rmtree(root, ignore_errors=True)
    return out


def bench_partition_ab(peers: int = 3, rounds: int = 20,
                       deadline: float = 1.0,
                       payload_kb: int = 32) -> dict:
    """Partition-tolerance A/B: cluster-wide metrics-scrape fan-out
    latency in three phases — baseline, one peer partitioned away,
    healed — over an in-process peer mesh driven by NaughtyNet.

    The acceptance bar (asserted here, not just reported): under the
    partition every fan-out stays bounded by the scrape DEADLINE (the
    cut peer fails at the injected dial, then sheds without dialing —
    never a TCP connect/read timeout), the reachable peers keep
    serving, and the healed mesh returns to the full merge at
    baseline-shaped latency."""
    import threading as _threading  # noqa: F401 — parity with siblings

    from minio_tpu.distributed import membership
    from minio_tpu.distributed.naughtynet import NET
    from minio_tpu.distributed.peer_rpc import (NotificationSys,
                                                PeerRPCClient,
                                                PeerRPCServer)
    from minio_tpu.distributed.transport import RPCServer

    ak, sk = "benchak", "benchsecret12345"
    exposition = "".join(
        f"# HELP bench_fake_{i} synthetic series\n"
        f"bench_fake_{i}{{peer=\"x\"}} {i}\n"
        for i in range(max(1, payload_kb * 1024 // 48)))

    def pctls(xs: list) -> dict:
        s = sorted(xs)
        return {"p50_ms": round(s[len(s) // 2] * 1e3, 2),
                "p99_ms": round(s[max(0, int(len(s) * .99) - 1)] * 1e3,
                                2)}

    out: dict = {"config": {"peers": peers, "rounds": rounds,
                            "deadline_s": deadline,
                            "payload_kb": payload_kb}}
    NET.reset()
    membership.TRACKER.reset()
    hosts, clients = [], []
    victim_id = ""
    try:
        for i in range(peers):
            host = RPCServer().start()
            nid = f"127.0.0.1:{host.port}"
            srv = PeerRPCServer(ak, sk, node_id=nid)
            srv.get_metrics_text = lambda: exposition
            host.mount(srv.handler)
            hosts.append(host)
            clients.append(PeerRPCClient("127.0.0.1", host.port, ak, sk,
                                         timeout=10.0,
                                         node_id="bench-observer"))
            if i == 0:
                victim_id = nid
        ns = NotificationSys(clients)

        def phase(n: int) -> tuple[list, int, int]:
            lat, ok, failed = [], 0, 0
            for _ in range(n):
                t0 = time.perf_counter()
                res = ns.metrics_text_all(deadline=deadline)
                lat.append(time.perf_counter() - t0)
                ok += sum(1 for _a, txt in res if txt is not None)
                failed += sum(1 for _a, txt in res if txt is None)
            return lat, ok, failed

        base_lat, base_ok, base_failed = phase(rounds)
        assert base_failed == 0, "baseline scrape must be complete"
        out["baseline"] = pctls(base_lat)

        NET.partition("bench-observer", victim_id, oneway=True)
        part_lat, part_ok, part_failed = phase(rounds)
        out["partitioned"] = pctls(part_lat)
        out["partitioned"]["scrapes_ok"] = part_ok
        out["partitioned"]["scrapes_failed"] = part_failed
        out["net_stats"] = dict(NET.stats)
        # the cut peer failed every round; the rest kept serving
        assert part_failed == rounds, \
            f"cut peer must fail every round ({part_failed}/{rounds})"
        assert part_ok == rounds * (peers - 1), \
            "reachable peers must keep serving under the partition"
        # bounded degradation: every degraded fan-out finished within
        # the scrape deadline (+ scheduling slack) — the failure is the
        # injected dial error + offline shed, never a TCP timeout
        worst = max(part_lat)
        assert worst < deadline + 1.0, \
            f"degraded fan-out took {worst:.2f}s — TCP-timeout " \
            "territory, not deadline-bounded"
        # after the first refused dial the peer is shed WITHOUT dialing
        assert NET.stats["blocked"] >= 1

        NET.heal()
        deadline_mono = time.monotonic() + 20.0
        while not clients[0].rc.online:
            if time.monotonic() > deadline_mono:
                raise AssertionError("victim never re-admitted post-heal")
            time.sleep(0.25)
        heal_lat, heal_ok, heal_failed = phase(rounds)
        assert heal_failed == 0, "healed mesh must restore the full merge"
        out["healed"] = pctls(heal_lat)
        out["partition_p99_bounded_by_deadline"] = \
            out["partitioned"]["p99_ms"] < deadline * 1e3 + 1000.0
        out["healed_vs_baseline_x"] = round(
            out["healed"]["p99_ms"]
            / max(out["baseline"]["p99_ms"], 1e-9), 2)
    finally:
        NET.reset()
        membership.TRACKER.reset()
        for c in clients:
            c.close()
        for h in hosts:
            h.stop()
    return out


def bench_edge_ab(streams=(4, 16), size: int = 1 << 20,
                  rounds: int = 4, idle_conns: int = 400,
                  idle_ratio: int = 20, drives: int = 6,
                  parity: int = 2, block: int = 1 << 18) -> dict:
    """HTTP frontend A/B: the event-loop edge vs the threaded oracle
    over ONE erasure layer (ISSUE 12 success metric).

    Phase 1 — idle keep-alive capacity: each server holds open
    keep-alive connections (edge: `idle_conns`, threaded:
    `idle_conns // idle_ratio` — thread-per-connection makes more
    unkind to the CI host), reporting RSS delta per connection and the
    thread-count delta (the edge's stays flat: sockets, not threads).
    The idle pool stays OPEN through phase 2, so the load runs against
    a mostly-idle connection population like production.

    Phase 2 — matched load: per streams point, signed HTTP PUT + GET
    rounds through persistent keep-alive connections; p50/p99 per op
    for both transports at identical load.

    Phase 3 — shed-before-body probe (edge): the admission gate is
    pinched to one slot and concurrent header-only PUTs (bodies never
    sent) must all shed 503 within the deadline — proving the decision
    precedes the first body byte — with every shed counted in
    minio_tpu_requests_shed_total{reason}."""
    import hashlib
    import http.client
    import shutil
    import socket as socket_mod
    import tempfile
    import threading
    import urllib.parse

    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    from minio_tpu.utils import telemetry

    creds = Credentials("benchedgekey1", "benchedgesecret1")
    region = "us-east-1"

    def rss_kb() -> int:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
        return 0

    def shed_values() -> dict:
        c = telemetry.REGISTRY.counter("minio_tpu_requests_shed_total")
        with c._mu:
            return {dict(k).get("reason", ""): v
                    for k, v in c._series.items()}

    def signed(method, path, port, payload_hash, extra=None):
        hdrs = {"host": f"127.0.0.1:{port}"}
        hdrs.update(extra or {})
        return sig.sign_v4(method, urllib.parse.quote(path), {}, hdrs,
                           payload_hash, creds, region)

    def mk_server(layer, edge: bool) -> S3Server:
        was = os.environ.get("MINIO_TPU_EDGE")
        os.environ["MINIO_TPU_EDGE"] = "on" if edge else "off"
        try:
            return S3Server(layer, creds=creds, region=region).start()
        finally:
            if was is None:
                os.environ.pop("MINIO_TPU_EDGE", None)
            else:
                os.environ["MINIO_TPU_EDGE"] = was

    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    codec_mod.DEVICE_MIN_BYTES = 1 << 60        # host-path isolation
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_edge_", dir=base)
    out: dict = {"config": {"streams": list(streams), "size": size,
                            "rounds": rounds, "idle_conns": idle_conns,
                            "idle_ratio": idle_ratio, "drives": drives,
                            "m": parity}}
    payload = os.urandom(size)
    payload_sha = hashlib.sha256(payload).hexdigest()
    try:
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False)

        def one_server_pass(edge: bool) -> dict:
            srv = mk_server(sets, edge)
            tag = "edge" if edge else "threaded"
            bucket = f"bench-{tag}"
            port = srv.port
            res: dict = {}
            idle: list = []
            try:
                st = _http_put(port, f"/{bucket}", b"", signed, creds)
                assert st == 200, f"bucket create {st}"
                # untimed warm-up: the first PUT through a cold engine
                # pays staging-ring/hasher setup — that's the layer's
                # cost, not the frontend's, and the A/B must not charge
                # it to whichever transport runs first
                for w in range(2):
                    st = _http_put(port, f"/{bucket}/warm-{w}", payload,
                                   signed, creds)
                    assert st == 200, f"warm-up put {st}"
                # -- phase 1: idle keep-alive pool ---------------------
                target = idle_conns if edge else \
                    max(idle_conns // idle_ratio, 2)
                threads0 = threading.active_count()
                rss0 = rss_kb()
                for _ in range(target):
                    s = socket_mod.create_connection(
                        ("127.0.0.1", port), timeout=30)
                    # one real (unsigned -> 403) request marks the conn
                    # established + keep-alive
                    s.sendall((f"GET / HTTP/1.1\r\nHost: "
                               f"127.0.0.1:{port}\r\n\r\n").encode())
                    _read_resp(s)
                    idle.append(s)
                res["idle"] = {
                    "conns": len(idle),
                    "rss_delta_kb": max(rss_kb() - rss0, 0),
                    "rss_per_conn_kb": round(
                        max(rss_kb() - rss0, 0) / max(len(idle), 1), 2),
                    "threads_delta": threading.active_count() - threads0,
                }
                # -- phase 2: matched load over the idle population ----
                res["points"] = []
                for n in streams:
                    lats_put: list = []
                    lats_get: list = []
                    mu = threading.Lock()
                    errs: list = []

                    def worker(sid: int) -> None:
                        try:
                            conn = http.client.HTTPConnection(
                                "127.0.0.1", port, timeout=60)
                            for r in range(rounds):
                                path = f"/{bucket}/o-{sid}-{r}"
                                hdrs = signed("PUT", path, port,
                                              payload_sha)
                                t0 = time.perf_counter()
                                conn.request("PUT", path, body=payload,
                                             headers=hdrs)
                                resp = conn.getresponse()
                                resp.read()
                                dt = time.perf_counter() - t0
                                assert resp.status == 200, resp.status
                                with mu:
                                    lats_put.append(dt)
                            for r in range(rounds):
                                path = f"/{bucket}/o-{sid}-{r}"
                                hdrs = signed("GET", path, port,
                                              sig.UNSIGNED_PAYLOAD)
                                t0 = time.perf_counter()
                                conn.request("GET", path, headers=hdrs)
                                resp = conn.getresponse()
                                body = resp.read()
                                dt = time.perf_counter() - t0
                                assert resp.status == 200 \
                                    and body == payload
                                with mu:
                                    lats_get.append(dt)
                            conn.close()
                        except BaseException as e:  # noqa: BLE001
                            with mu:
                                errs.append(e)

                    ts = [threading.Thread(target=worker, args=(i,))
                          for i in range(n)]
                    t0 = time.perf_counter()
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
                    wall = time.perf_counter() - t0
                    if errs:
                        raise errs[0]

                    def pcts(xs):
                        xs = sorted(xs)
                        return {
                            "p50_ms": round(xs[len(xs) // 2] * 1e3, 2),
                            "p99_ms": round(
                                xs[max(0, int(len(xs) * .99) - 1)]
                                * 1e3, 2)}
                    res["points"].append({
                        "streams": n, "wall_s": round(wall, 3),
                        "put": pcts(lats_put), "get": pcts(lats_get),
                        "put_gib_s": round(
                            len(lats_put) * size / wall / (1 << 30), 3),
                    })
                # the idle pool survived the load: a sampled conn still
                # answers on its original socket
                probe = idle[len(idle) // 2]
                probe.sendall((f"GET / HTTP/1.1\r\nHost: "
                               f"127.0.0.1:{port}\r\n\r\n").encode())
                status = _read_resp(probe)
                res["idle"]["alive_after_load"] = status == 403
            finally:
                for s in idle:
                    try:
                        s.close()
                    except OSError:
                        pass
                srv.stop()
            return res

        out["edge"] = one_server_pass(edge=True)
        out["threaded"] = one_server_pass(edge=False)
        out["idle_conn_ratio_x"] = round(
            out["edge"]["idle"]["conns"]
            / max(out["threaded"]["idle"]["conns"], 1), 1)
        top = out["edge"]["points"][-1]
        base_top = out["threaded"]["points"][-1]
        out["put_p99_edge_vs_threaded_x"] = round(
            top["put"]["p99_ms"] / max(base_top["put"]["p99_ms"], 1e-9),
            3)

        # -- phase 3: shed-before-body probe on the edge ---------------
        srv = mk_server(sets, edge=True)
        try:
            srv.api.admission.resize(1)
            srv.api.admission.deadline = 0.1
            hold = srv.api.admission.admit("GET", "/x/y", {}, {})
            before = shed_values()
            refused = 0
            for _ in range(8):
                s = socket_mod.create_connection(
                    ("127.0.0.1", srv.port), timeout=30)
                s.sendall((f"PUT /{'shedb'}/k HTTP/1.1\r\n"
                           f"Host: 127.0.0.1:{srv.port}\r\n"
                           f"Content-Length: {1 << 20}\r\n\r\n"
                           ).encode())   # body NEVER sent
                if _read_resp(s) == 503:
                    refused += 1
                s.close()
            hold.release()
            after = shed_values()
            out["saturation_sheds"] = {
                "refused_503": refused,
                "counter_delta": {
                    k: after.get(k, 0) - before.get(k, 0)
                    for k in after
                    if after.get(k, 0) != before.get(k, 0)},
                "body_bytes_sent": 0,
            }
        finally:
            srv.stop()
    finally:
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_obs_ab(streams: int = 8, size: int = 1 << 20,
                 drives: int = 6, parity: int = 2, block: int = 1 << 18,
                 node_counts: Sequence[int] = (1, 2, 4, 8),
                 put_rounds: int = 4, attrib_reps: int = 12,
                 attrib_batch: int = 8) -> dict:
    """Observability-plane A/B (ISSUE 13): what the cluster
    observability layer itself costs.

    Phase 1 — federated-scrape merge latency vs node count: a real
    node-shaped exposition (this process's live registry render) is
    merged N-ways through utils/promfed — the exact path the admin
    ?cluster=1 route runs after its peer fan-out — reporting merge wall
    time and output size per node count, plus one authenticated HTTP
    scrape of the single live server's admin /metrics route
    (local_scrape_*: render + auth + transport floor — the bench's
    server has no peer plane, so the RPC fan-out itself is not in this
    number; tests/test_obs.py times the real 2-node federated path).

    Phase 2 — trace-follow overhead on the foreground: concurrent
    signed HTTP PUT rounds, p50/p99 WITHOUT vs WITH a live ?follow=1
    subscriber consuming the stream (`mc admin trace` running against
    a busy box must be near-free).

    Phase 3 — telemetry_overhead_x with dispatch attribution on/off:
    identical fused encode batches through two BatchSchedulers, one
    with MINIO_TPU_SCHED_ATTRIB=off — the cost of the stage histograms
    + stage spans themselves (device route forced so the dispatch path
    actually runs on CPU-only hosts; warmed, best-of medians).
    """
    import concurrent.futures as cf
    import hashlib
    import shutil
    import tempfile
    import threading
    import urllib.parse

    from minio_tpu import bitrot as bitrot_mod
    from minio_tpu.madmin import AdminClient
    from minio_tpu.object import codec as codec_mod
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.parallel.scheduler import BatchScheduler
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.admin import mount_admin
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    from minio_tpu.utils import promfed, telemetry

    creds = Credentials("benchobskey12", "benchobssecret12")
    region = "us-east-1"
    out: dict = {"config": {"streams": streams, "size": size,
                            "node_counts": list(node_counts),
                            "put_rounds": put_rounds,
                            "attrib_reps": attrib_reps}}

    def pcts(lat: list[float]) -> dict:
        lat = sorted(lat)
        return {"p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "p99_ms": round(lat[min(int(len(lat) * 0.99),
                                        len(lat) - 1)] * 1e3, 3)}

    # -- phase 1: merge latency vs node count ---------------------------
    exposition = telemetry.REGISTRY.render()
    merge_points = []
    for n in node_counts:
        nodes = [(f"node{i}:9000", exposition) for i in range(n)]
        reps = []
        merged = ""
        for _ in range(3):
            t0 = time.perf_counter()
            merged = promfed.merge_expositions(nodes)
            reps.append(time.perf_counter() - t0)
        merge_points.append({
            "nodes": n,
            "merge_ms": round(_median(reps) * 1e3, 3),
            "input_bytes": n * len(exposition),
            "output_bytes": len(merged)})
    out["cluster_scrape"] = {"points": merge_points,
                             "exposition_bytes": len(exposition)}

    # -- phases 2+3 need a live server / scheduler ----------------------
    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_obs_", dir=base)
    payload = os.urandom(size)
    was_is_tpu = codec_mod._IS_TPU
    was_min_bytes = codec_mod.DEVICE_MIN_BYTES
    try:
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False)
        srv = S3Server(sets, creds=creds, region=region).start()
        mount_admin(srv)
        mc = AdminClient("127.0.0.1", srv.port, creds.access_key,
                         creds.secret_key)
        try:
            t0 = time.perf_counter()
            text = mc.node_metrics()
            out["cluster_scrape"]["local_scrape_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 3)
            out["cluster_scrape"]["local_scrape_bytes"] = len(text)

            def signed(method, path, port, payload_hash, extra=None):
                hdrs = {"host": f"127.0.0.1:{port}"}
                hdrs.update(extra or {})
                return sig.sign_v4(method, urllib.parse.quote(path), {},
                                   hdrs, payload_hash, creds, region)

            assert _http_put(srv.port, "/bench-obs", b"", signed,
                             creds) == 200
            assert _http_put(srv.port, "/bench-obs/warm", payload,
                             signed, creds) == 200    # engine warm-up

            def put_round(prefix: str) -> list[float]:
                lat: list[float] = []
                mu = threading.Lock()

                def one(i: int) -> None:
                    t0 = time.perf_counter()
                    st = _http_put(srv.port,
                                   f"/bench-obs/{prefix}-{i}", payload,
                                   signed, creds)
                    dt = time.perf_counter() - t0
                    assert st == 200, st
                    with mu:
                        lat.append(dt)

                for r in range(put_rounds):
                    with cf.ThreadPoolExecutor(
                            max_workers=streams) as ex:
                        list(ex.map(one, range(r * streams,
                                               (r + 1) * streams)))
                return lat

            base_lat = put_round("base")
            stop = threading.Event()
            consumed = [0]

            def follower() -> None:
                try:
                    for _e in mc.trace_follow(timeout=120):
                        consumed[0] += 1
                        if stop.is_set():
                            return
                except Exception:  # noqa: BLE001 — stream torn at stop
                    pass

            ft = threading.Thread(target=follower, daemon=True)
            ft.start()
            time.sleep(0.3)                 # subscription armed
            follow_lat = put_round("follow")
            stop.set()
            out["trace_follow"] = {
                "baseline": pcts(base_lat),
                "with_follow": pcts(follow_lat),
                "entries_consumed": consumed[0],
                "put_p99_overhead_x": round(
                    pcts(follow_lat)["p99_ms"]
                    / max(pcts(base_lat)["p99_ms"], 1e-9), 3)}
        finally:
            srv.stop()
            sets.close()

        # -- phase 3: attribution on/off ---------------------------------
        codec_mod._IS_TPU = True            # force the device route so
        codec_mod.DEVICE_MIN_BYTES = 0      # dispatches actually happen
        algo = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256
        k = drives - parity
        data = np.random.randint(0, 255,
                                 (attrib_batch, k, block // k),
                                 dtype=np.uint8)
        codec = codec_mod.Codec(k, parity, block)
        attrib_t: dict[str, list[float]] = {"on": [], "off": []}
        for mode in ("on", "off"):
            was = os.environ.get("MINIO_TPU_SCHED_ATTRIB")
            os.environ["MINIO_TPU_SCHED_ATTRIB"] = mode
            try:
                sched = BatchScheduler(max_wait=0.001)
            finally:
                if was is None:
                    os.environ.pop("MINIO_TPU_SCHED_ATTRIB", None)
                else:
                    os.environ["MINIO_TPU_SCHED_ATTRIB"] = was
            try:
                with telemetry.trace(f"bench.obs.attrib.{mode}"):
                    r = sched.submit(codec, data, algo).result(120)
                    assert r is not None, "dispatch declined"
                    for _ in range(attrib_reps):
                        t0 = time.perf_counter()
                        sched.submit(codec, data, algo).result(120)
                        attrib_t[mode].append(
                            time.perf_counter() - t0)
            finally:
                sched.close()
        on_ms = _median(attrib_t["on"]) * 1e3
        off_ms = _median(attrib_t["off"]) * 1e3
        out["attrib"] = {
            "dispatch_ms_attrib_on": round(on_ms, 3),
            "dispatch_ms_attrib_off": round(off_ms, 3),
            "telemetry_overhead_x": round(on_ms / max(off_ms, 1e-9),
                                          3)}
    finally:
        codec_mod._IS_TPU = was_is_tpu
        codec_mod.DEVICE_MIN_BYTES = was_min_bytes
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_incident_ab(streams: int = 8, size: int = 1 << 20,
                      drives: int = 6, parity: int = 2,
                      block: int = 1 << 18, put_rounds: int = 4,
                      gets: int = 64) -> dict:
    """Incident-plane A/B (ISSUE 18): what the always-on journal +
    SLO engine cost the foreground, and how fast the black box closes.

    Phase 1 — foreground overhead: concurrent signed HTTP PUT and GET
    p50/p99 with MINIO_TPU_EVENTLOG + MINIO_TPU_SLO off, then on (SLO
    evaluator running). The journal is designed to be always-on in
    production, so put_p99_overhead_x is the number that must stay
    ~1.0 (acceptance: <= 1.05).

    Phase 2 — capture latency: with the plane on, a seeded trigger
    event (drive.probation) is emitted and the wall time until the
    flight recorder's bundle lands on disk is reported, along with
    the bundle's journal/span content counts."""
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading
    import urllib.parse

    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.admin import mount_admin
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.server import S3Server
    from minio_tpu.utils import eventlog, incidents, slo

    creds = Credentials("benchinckey123", "benchincsecret1")
    region = "us-east-1"
    out: dict = {"config": {"streams": streams, "size": size,
                            "put_rounds": put_rounds, "gets": gets}}

    def pcts(lat: list[float]) -> dict:
        lat = sorted(lat)
        return {"p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "p99_ms": round(lat[min(int(len(lat) * 0.99),
                                        len(lat) - 1)] * 1e3, 3)}

    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_inc_", dir=base)
    payload = os.urandom(size)
    knob_names = ("MINIO_TPU_EVENTLOG", "MINIO_TPU_SLO")
    saved = {k: os.environ.get(k) for k in knob_names}
    try:
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False)
        srv = S3Server(sets, creds=creds, region=region).start()
        mount_admin(srv)
        try:
            def signed(method, path, port, payload_hash, extra=None):
                hdrs = {"host": f"127.0.0.1:{port}"}
                hdrs.update(extra or {})
                return sig.sign_v4(method, urllib.parse.quote(path),
                                   {}, hdrs, payload_hash, creds,
                                   region)

            assert _http_put(srv.port, "/bench-inc", b"", signed,
                             creds) == 200
            assert _http_put(srv.port, "/bench-inc/warm", payload,
                             signed, creds) == 200   # engine warm-up

            def put_round(prefix: str) -> list[float]:
                lat: list[float] = []
                mu = threading.Lock()

                def one(i: int) -> None:
                    t0 = time.perf_counter()
                    st = _http_put(srv.port,
                                   f"/bench-inc/{prefix}-{i}",
                                   payload, signed, creds)
                    dt = time.perf_counter() - t0
                    assert st == 200, st
                    with mu:
                        lat.append(dt)

                for r in range(put_rounds):
                    with cf.ThreadPoolExecutor(
                            max_workers=streams) as ex:
                        list(ex.map(one, range(r * streams,
                                               (r + 1) * streams)))
                return lat

            def get_round() -> list[float]:
                import hashlib
                import http.client
                lat: list[float] = []
                for i in range(gets):
                    t0 = time.perf_counter()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", srv.port, timeout=60)
                    hdrs = signed("GET", "/bench-inc/warm", srv.port,
                                  hashlib.sha256(b"").hexdigest())
                    conn.request("GET", "/bench-inc/warm",
                                 headers=hdrs)
                    resp = conn.getresponse()
                    resp.read()
                    conn.close()
                    assert resp.status == 200, resp.status
                    lat.append(time.perf_counter() - t0)
                return lat

            for mode, flag in (("off", "off"), ("on", "on")):
                for k in knob_names:
                    os.environ[k] = flag
                if mode == "on":
                    slo.ENGINE.ensure_started()
                out.setdefault("put", {})[mode] = pcts(
                    put_round(mode))
                out.setdefault("get", {})[mode] = pcts(get_round())
            out["put_p99_overhead_x"] = round(
                out["put"]["on"]["p99_ms"]
                / max(out["put"]["off"]["p99_ms"], 1e-9), 3)
            out["get_p99_overhead_x"] = round(
                out["get"]["on"]["p99_ms"]
                / max(out["get"]["off"]["p99_ms"], 1e-9), 3)

            # -- phase 2: seeded-fault capture timing ------------------
            incidents.RECORDER.attach(os.path.join(root, "incidents"))
            known = {i["id"] for i in incidents.RECORDER.list()}
            t0 = time.perf_counter()
            eventlog.emit("drive.probation", drive=f"{root}/d0",
                          set=0)
            bundle = None
            while time.perf_counter() - t0 < 10.0:
                fresh = [i for i in incidents.RECORDER.list()
                         if i["id"] not in known]
                if fresh:
                    bundle = incidents.RECORDER.get(fresh[0]["id"])
                    break
                time.sleep(0.005)
            capture_ms = round((time.perf_counter() - t0) * 1e3, 3)
            out["capture"] = {
                "trigger": "drive.probation",
                "captured": bundle is not None,
                "capture_ms": capture_ms,
                "journal_events": len((bundle or {}).get("events",
                                                        ())),
                "slow_spans": len((bundle or {}).get("slow_spans",
                                                     ())),
            }
        finally:
            srv.stop()
            sets.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_tenants_ab(noisy_streams: int = 8, size: int = 1 << 20,
                     drives: int = 6, parity: int = 2,
                     block: int = 1 << 18, polite_ops: int = 24,
                     max_clients: int = 8,
                     overhead_rounds: int = 4) -> dict:
    """Multi-tenant QoS A/B (ISSUE 19): does the weighted-share gate
    actually protect a polite tenant from a noisy neighbor, and what
    does the plane cost a lone tenant.

    Phase 1 — isolation: a noisy IAM tenant hammers PUTs on
    noisy_streams concurrent connections while a polite tenant issues
    one sequential PUT at a time. With MINIO_TPU_QOS off the polite
    stream queues behind the noisy flood at the maxClients semaphore;
    with it on (equal shares) the noisy tenant is bounded to its
    share of the gate and its excess streams shed 503 SlowDown under
    reason=tenant, so the polite p99 must drop. isolation_p99_x is
    polite-p99-off / polite-p99-on (> 1 means the plane helped).

    Phase 2 — lone-tenant overhead: the same concurrent PUT round as
    the incident A/B, single (root) tenant, QoS off vs on. A lone
    tenant borrows the whole gate, so put_p99_overhead_x is pure
    bookkeeping cost (acceptance: <= 1.05)."""
    import concurrent.futures as cf
    import shutil
    import tempfile
    import threading
    import urllib.parse

    from minio_tpu.iam.sys import IAMSys
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3 import signature as sig
    from minio_tpu.s3.credentials import Credentials
    from minio_tpu.s3.qos import Budget
    from minio_tpu.s3.server import S3Server
    from minio_tpu.utils import telemetry

    creds = Credentials("benchqoskey123", "benchqossecret1")
    noisy_cred = Credentials("noisytenant123", "noisysecret1234")
    polite_cred = Credentials("politetenant12", "politesecret123")
    region = "us-east-1"
    out: dict = {"config": {"noisy_streams": noisy_streams,
                            "size": size, "polite_ops": polite_ops,
                            "max_clients": max_clients}}

    def pcts(lat: list[float]) -> dict:
        lat = sorted(lat)
        return {"p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "p99_ms": round(lat[min(int(len(lat) * 0.99),
                                        len(lat) - 1)] * 1e3, 3)}

    base = "/dev/shm" if os.path.isdir("/dev/shm") else \
        tempfile.gettempdir()
    root = tempfile.mkdtemp(prefix="bench_qos_", dir=base)
    payload = os.urandom(size)
    saved = os.environ.get("MINIO_TPU_QOS")
    try:
        sets = ErasureSets.from_drives(
            [f"{root}/d{i}" for i in range(drives)], 1, drives, parity,
            block_size=block, enable_mrf=False)
        iam = IAMSys(root_cred=creds)
        iam.add_user(noisy_cred.access_key, noisy_cred.secret_key)
        iam.add_user(polite_cred.access_key, polite_cred.secret_key)
        iam.attach_policy("readwrite", user=noisy_cred.access_key)
        iam.attach_policy("readwrite", user=polite_cred.access_key)
        srv = S3Server(sets, creds=creds, region=region,
                       iam=iam).start()
        srv.api.set_max_clients(max_clients)
        try:
            def mk_signed(cred):
                def signed(method, path, port, payload_hash,
                           extra=None):
                    hdrs = {"host": f"127.0.0.1:{port}"}
                    hdrs.update(extra or {})
                    return sig.sign_v4(method,
                                       urllib.parse.quote(path), {},
                                       hdrs, payload_hash, cred,
                                       region)
                return signed

            signed_root = mk_signed(creds)
            assert _http_put(srv.port, "/bench-qos", b"", signed_root,
                             creds) == 200
            assert _http_put(srv.port, "/bench-qos/warm", payload,
                             signed_root, creds) == 200

            # equal shares: with both tenants active the noisy tenant
            # is bounded to half the gate and its surplus streams shed
            srv.api.qos.registry.set_budget(
                "tenant", Budget(noisy_cred.access_key, share=1.0))
            srv.api.qos.registry.set_budget(
                "tenant", Budget(polite_cred.access_key, share=1.0))

            shed_counter = telemetry.REGISTRY.counter(
                "minio_tpu_requests_shed_total")

            def isolation_phase(mode: str, tag: str) -> dict:
                os.environ["MINIO_TPU_QOS"] = mode
                shed0 = shed_counter.value(reason="tenant")
                stop = threading.Event()
                mu = threading.Lock()
                noisy = {"ok": 0, "shed": 0}
                signed_noisy = mk_signed(noisy_cred)
                signed_polite = mk_signed(polite_cred)

                def noisy_worker(w: int) -> None:
                    i = 0
                    while not stop.is_set():
                        try:
                            st = _http_put(
                                srv.port,
                                f"/bench-qos/n-{tag}-{w}-{i}",
                                payload, signed_noisy, noisy_cred)
                        except OSError:
                            # the gate refused pre-body and closed the
                            # socket while this client was still
                            # streaming the payload — a shed, observed
                            # as a reset instead of the 503
                            st = 503
                        with mu:
                            if st == 200:
                                noisy["ok"] += 1
                            elif st == 503:
                                noisy["shed"] += 1
                        i += 1

                threads = [threading.Thread(target=noisy_worker,
                                            args=(w,), daemon=True)
                           for w in range(noisy_streams)]
                for t in threads:
                    t.start()
                lat: list[float] = []
                signed_p = signed_polite
                for i in range(polite_ops):
                    t0 = time.perf_counter()
                    while True:
                        try:
                            st = _http_put(srv.port,
                                           f"/bench-qos/p-{tag}-{i}",
                                           payload, signed_p,
                                           polite_cred)
                        except OSError:
                            st = 503
                        if st == 200:
                            break
                        assert st == 503, st
                        time.sleep(0.002)
                    lat.append(time.perf_counter() - t0)
                stop.set()
                for t in threads:
                    t.join(timeout=30)
                return {"polite": pcts(lat),
                        "noisy_ok": noisy["ok"],
                        "noisy_shed": noisy["shed"],
                        "shed_total_delta": int(
                            shed_counter.value(reason="tenant")
                            - shed0)}

            for mode in ("off", "on"):
                out.setdefault("isolation", {})[mode] = \
                    isolation_phase(mode, mode)
            out["isolation_p99_x"] = round(
                out["isolation"]["off"]["polite"]["p99_ms"]
                / max(out["isolation"]["on"]["polite"]["p99_ms"],
                      1e-9), 3)
            out["noisy_sheds"] = \
                out["isolation"]["on"]["shed_total_delta"]
            stats = srv.api.qos.stats()
            out["tenant_stats"] = {
                t: {"requests": s["requests"], "shed": s["shed"]}
                for t, s in stats.items()}

            # -- phase 2: lone-tenant overhead ---------------------
            def overhead_round(tag: str) -> list[float]:
                lat: list[float] = []
                mu = threading.Lock()

                def one(i: int) -> None:
                    t0 = time.perf_counter()
                    while True:
                        try:
                            st = _http_put(srv.port,
                                           f"/bench-qos/o-{tag}-{i}",
                                           payload, signed_root,
                                           creds)
                        except OSError:
                            st = 503
                        if st == 200:
                            break
                        # a 503 here is residual staging pressure from
                        # the isolation flood; retry like a client would
                        assert st == 503, st
                        time.sleep(0.01)
                    with mu:
                        lat.append(time.perf_counter() - t0)

                for r in range(overhead_rounds):
                    with cf.ThreadPoolExecutor(
                            max_workers=noisy_streams) as ex:
                        list(ex.map(one,
                                    range(r * noisy_streams,
                                          (r + 1) * noisy_streams)))
                return lat

            for mode in ("off", "on"):
                os.environ["MINIO_TPU_QOS"] = mode
                out.setdefault("overhead", {})[mode] = pcts(
                    overhead_round(mode))
            out["put_p99_overhead_x"] = round(
                out["overhead"]["on"]["p99_ms"]
                / max(out["overhead"]["off"]["p99_ms"], 1e-9), 3)
        finally:
            srv.stop()
            sets.close()
    finally:
        if saved is None:
            os.environ.pop("MINIO_TPU_QOS", None)
        else:
            os.environ["MINIO_TPU_QOS"] = saved
        shutil.rmtree(root, ignore_errors=True)
    return out


def _read_resp(sock) -> int:
    """Read one HTTP response off a raw socket; returns the status."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            return 0
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    length = 0
    for line in lines[1:]:
        if line.lower().startswith("content-length:"):
            length = int(line.split(":", 1)[1])
    while len(rest) < length:
        chunk = sock.recv(65536)
        if not chunk:
            break
        rest += chunk
    return status


def _http_put(port: int, path: str, body: bytes, signed, creds) -> int:
    import hashlib
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    hdrs = signed("PUT", path, port, hashlib.sha256(body).hexdigest())
    conn.request("PUT", path, body=body, headers=hdrs)
    st = conn.getresponse()
    st.read()
    conn.close()
    return st.status


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ab-pipeline", action="store_true",
                    help="force the pipeline on/off A/B on config #2 "
                         "(default on; BENCH_PIPELINE_AB=0 skips it)")
    ap.add_argument("--ab-only", action="store_true",
                    help="run ONLY the pipeline A/B (no device access "
                         "needed)")
    ap.add_argument("--ab-streams", type=int,
                    default=int(os.environ.get("BENCH_AB_STREAMS", "32")))
    ap.add_argument("--ab-size", type=int,
                    default=int(os.environ.get("BENCH_AB_SIZE",
                                               str(16 << 20))))
    ap.add_argument("--spans", action="store_true",
                    help="pretty-print the top-5 slowest span trees of "
                         "each A/B config to stderr")
    ap.add_argument("--spans-api", default="",
                    help="with --spans: keep only this API's root "
                         "spans (the /spans?api= filter)")
    ap.add_argument("--spans-trace-id", default="",
                    help="with --spans: keep only this trace id (the "
                         "/spans?trace_id= filter)")
    ap.add_argument("--ab-rebalance", action="store_true",
                    help="run ONLY the rebalance-throttle A/B "
                         "(foreground PUT p50/p99 with vs without an "
                         "active pool drain)")
    ap.add_argument("--saturation", action="store_true",
                    help="run ONLY the multi-stream saturation sweep: "
                         "aggregate PUT/GET/degraded-GET GiB/s + batch "
                         "former per-verb occupancy vs stream count, "
                         "with a scheduler-bypassed A/B per point")
    ap.add_argument("--saturation-streams",
                    default=os.environ.get("BENCH_SAT_STREAMS",
                                           "1,2,4,8,16,32"),
                    help="comma-separated stream counts for the sweep")
    ap.add_argument("--saturation-size", type=int,
                    default=int(os.environ.get("BENCH_SAT_SIZE",
                                               str(16 << 20))))
    ap.add_argument("--saturation-smoke", action="store_true",
                    help="tiny 2-point sweep (streams 1,2; 4-block "
                         "objects; 4+2 set) for CI — seconds, not "
                         "minutes")
    ap.add_argument("--ab-list", action="store_true",
                    help="run ONLY the listing A/B (merge-walk vs "
                         "metacache index): page p50/p99 + one "
                         "crawler-cycle wall time + walk counts")
    ap.add_argument("--ab-list-keys", type=int,
                    default=int(os.environ.get("BENCH_LIST_KEYS",
                                               "10000")))
    ap.add_argument("--ab-list-smoke", action="store_true",
                    help="tiny listing A/B (400 keys, 50-key pages) "
                         "for CI — seconds, not minutes")
    ap.add_argument("--ab-select", action="store_true",
                    help="run ONLY the S3 Select A/B (device scan "
                         "plane vs CPU evaluator) at 1..N concurrent "
                         "queries, with scan-verb coalescing counters "
                         "per point")
    ap.add_argument("--ab-select-streams",
                    default=os.environ.get("BENCH_SELECT_STREAMS",
                                           "1,2,4,8"),
                    help="comma-separated concurrency points for "
                         "--ab-select")
    ap.add_argument("--ab-select-rows", type=int,
                    default=int(os.environ.get("BENCH_SELECT_ROWS",
                                               "20000")))
    ap.add_argument("--ab-select-smoke", action="store_true",
                    help="tiny Select A/B (2 points, 3000-row corpus) "
                         "for CI — seconds, not minutes")
    ap.add_argument("--ab-sse", action="store_true",
                    help="encrypted PUT+GET A/B: device-fused "
                    "cipher+RS+digest data path vs the CPU ChaCha20 "
                    "fallback, with launch/coalescing counters")
    ap.add_argument("--ab-sse-smoke", action="store_true",
                    help="tiny CI variant of --ab-sse")
    ap.add_argument("--ab-cache", action="store_true",
                    help="run ONLY the hot-GET A/B (erasure read path "
                         "with the hot-object read cache off vs on, "
                         "decode-stream counter deltas)")
    ap.add_argument("--ab-cache-smoke", action="store_true",
                    help="tiny cache A/B (8 x 256 KiB objects, 60 "
                         "GETs) for CI — seconds, not minutes")
    ap.add_argument("--ab-tier", action="store_true",
                    help="run ONLY the tier-transition-throttle A/B "
                         "(foreground PUT p50/p99 with vs without the "
                         "transition worker draining to a tier)")
    ap.add_argument("--ab-replicate", action="store_true",
                    help="run ONLY the replication A/B (foreground PUT "
                         "p50/p99 with vs without an active resync "
                         "drain to a second in-process site, plus the "
                         "replication lag histogram)")
    ap.add_argument("--ab-replicate-smoke", action="store_true",
                    help="tiny replication A/B (2 streams, 256 KiB "
                         "objects, 8-key resync) for CI — seconds, "
                         "not minutes")
    ap.add_argument("--ab-notify", action="store_true",
                    help="run ONLY the notification A/B (foreground "
                         "PUT p50/p99 with vs without every PUT "
                         "fanning out to a deliberately SLOW webhook, "
                         "plus the delivery-lag histogram)")
    ap.add_argument("--ab-notify-smoke", action="store_true",
                    help="tiny notification A/B (2 streams, 256 KiB "
                         "objects, 10 ms webhook stall) for CI — "
                         "seconds, not minutes")
    ap.add_argument("--ab-edge", action="store_true",
                    help="run ONLY the HTTP frontend A/B (event-loop "
                         "edge vs threaded oracle): idle keep-alive "
                         "capacity at flat RSS, PUT/GET p50/p99 at "
                         "matched load, shed-before-body counters")
    ap.add_argument("--ab-edge-smoke", action="store_true",
                    help="tiny edge A/B (2 streams, 256 KiB objects, "
                         "60 idle conns) for CI — seconds, not minutes")
    ap.add_argument("--ab-gray", action="store_true",
                    help="gray-failure A/B: GET/PUT p50/p99 with one "
                    "drive stalling per I/O, hedging+quorum-ack+"
                    "quarantine on vs off")
    ap.add_argument("--ab-gray-stall", type=float, default=0.5,
                    help="--ab-gray injected per-I/O stall, seconds "
                    "(default 0.5)")
    ap.add_argument("--ab-gray-smoke", action="store_true",
                    help="tiny CI variant of --ab-gray")
    ap.add_argument("--ab-partition", action="store_true",
                    help="partition-tolerance A/B: federated-scrape "
                    "fan-out p50/p99 baseline vs one peer partitioned "
                    "away vs healed; asserts the degraded fan-out is "
                    "bounded by the scrape deadline, not TCP timeouts")
    ap.add_argument("--ab-partition-smoke", action="store_true",
                    help="tiny CI variant of --ab-partition (2 peers, "
                    "6 rounds)")
    ap.add_argument("--ab-obs", action="store_true",
                    help="run ONLY the observability-plane A/B: "
                         "federated-scrape merge latency vs node "
                         "count, trace-follow overhead on foreground "
                         "PUT p99, dispatch-attribution on/off "
                         "overhead")
    ap.add_argument("--ab-obs-smoke", action="store_true",
                    help="tiny observability A/B (2 streams, 256 KiB "
                         "objects, 2 node counts) for CI — seconds, "
                         "not minutes")
    ap.add_argument("--ab-incident", action="store_true",
                    help="run ONLY the incident-plane A/B: foreground "
                         "PUT/GET p50/p99 with the event journal + "
                         "SLO engine off vs on, plus seeded-fault "
                         "capture-to-bundle latency")
    ap.add_argument("--ab-incident-smoke", action="store_true",
                    help="tiny incident A/B (2 streams, 256 KiB "
                         "objects) for CI — seconds, not minutes")
    ap.add_argument("--ab-tenants", action="store_true",
                    help="run ONLY the multi-tenant QoS A/B: a noisy "
                         "tenant on 8 streams vs a polite tenant on "
                         "1, polite PUT p99 with the plane off vs on "
                         "(equal shares), plus lone-tenant overhead")
    ap.add_argument("--ab-tenants-smoke", action="store_true",
                    help="tiny tenants A/B (2 noisy streams, 256 KiB "
                         "objects) for CI — seconds, not minutes")
    args = ap.parse_args()

    if args.ab_gray or args.ab_gray_smoke:
        if args.ab_gray_smoke:
            ab = bench_gray_ab(objects=5, size=1 << 18, gets=20,
                               streams=4, drives=6, block=1 << 16,
                               stall_s=0.3)
        else:
            ab = bench_gray_ab(stall_s=args.ab_gray_stall)
        print(json.dumps({
            "metric": "GET p99 speedup with one drive stalling "
                      f"{ab['config']['stall_s']}s/I-O, gray-failure "
                      "plane on vs off (PUT acks at quorum, zero "
                      "acked-write loss after MRF drain)",
            "value": ab.get("get_p99_speedup_x"),
            "unit": "x",
            "gray_ab": ab,
        }))
        return 0

    if args.ab_partition or args.ab_partition_smoke:
        if args.ab_partition_smoke:
            ab = bench_partition_ab(peers=2, rounds=6, deadline=1.0,
                                    payload_kb=8)
        else:
            ab = bench_partition_ab()
        print(json.dumps({
            "metric": "federated-scrape fan-out p99 with one peer "
                      "partitioned away (deadline-bounded, reachable "
                      "peers keep serving; heal restores the full "
                      "merge)",
            "value": ab["partitioned"]["p99_ms"],
            "unit": "ms",
            "partition_ab": ab,
        }))
        return 0

    if args.ab_obs or args.ab_obs_smoke:
        if args.ab_obs_smoke:
            ab = bench_obs_ab(streams=2, size=1 << 18, drives=6,
                              node_counts=(1, 2), put_rounds=2,
                              attrib_reps=4, block=1 << 16)
        else:
            ab = bench_obs_ab(streams=min(args.ab_streams, 8),
                              size=args.ab_size)
        print(json.dumps({
            "metric": "foreground PUT p99 overhead with a live "
                      "cluster trace-follow subscriber attached "
                      "(observability-plane A/B)",
            "value": ab.get("trace_follow", {}).get(
                "put_p99_overhead_x"),
            "unit": "x",
            "obs_ab": ab,
        }))
        return 0

    if args.ab_incident or args.ab_incident_smoke:
        if args.ab_incident_smoke:
            ab = bench_incident_ab(streams=2, size=1 << 18, drives=6,
                                   put_rounds=2, gets=16,
                                   block=1 << 16)
        else:
            ab = bench_incident_ab(streams=min(args.ab_streams, 8),
                                   size=args.ab_size)
        print(json.dumps({
            "metric": "foreground PUT p99 overhead with the event "
                      "journal + SLO engine on vs off (incident-plane "
                      "A/B; capture_ms = trigger-to-bundle latency)",
            "value": ab.get("put_p99_overhead_x"),
            "unit": "x",
            "incident_ab": ab,
        }))
        return 0

    if args.ab_tenants or args.ab_tenants_smoke:
        if args.ab_tenants_smoke:
            ab = bench_tenants_ab(noisy_streams=2, size=1 << 18,
                                  drives=6, block=1 << 16,
                                  polite_ops=8, max_clients=2,
                                  overhead_rounds=2)
        else:
            ab = bench_tenants_ab(noisy_streams=min(args.ab_streams,
                                                    8),
                                  size=args.ab_size)
        print(json.dumps({
            "metric": "polite-tenant PUT p99 with the QoS plane off "
                      "vs on under a noisy neighbor (isolation_p99_x "
                      "> 1 = the plane helped; put_p99_overhead_x = "
                      "lone-tenant cost)",
            "value": ab.get("isolation_p99_x"),
            "unit": "x",
            "tenants_ab": ab,
        }))
        return 0

    if args.ab_edge or args.ab_edge_smoke:
        if args.ab_edge_smoke:
            ab = bench_edge_ab(streams=(2,), size=1 << 18, rounds=2,
                               idle_conns=60, idle_ratio=20, drives=6,
                               block=1 << 16)
        else:
            ab = bench_edge_ab(streams=(4, 16, 32), size=args.ab_size,
                               idle_conns=2000)
        print(json.dumps({
            "metric": "idle keep-alive connections held by the edge "
                      "per threaded-frontend connection (flat RSS), "
                      "with PUT/GET p99 at matched load",
            "value": ab.get("idle_conn_ratio_x"),
            "unit": "x",
            "edge_ab": ab,
        }))
        return 0

    if args.saturation or args.saturation_smoke:
        if args.saturation_smoke:
            sat = bench_saturation(streams=(1, 2), size=4 << 16,
                                   drives=6, parity=2, block=1 << 16,
                                   force_device=True,
                                   sched_max_wait=0.25)
        else:
            sat = bench_saturation(
                streams=tuple(int(x) for x in
                              args.saturation_streams.split(",") if x),
                size=args.saturation_size)
        top = sat["points"][-1] if sat["points"] else {}
        print(json.dumps({
            "metric": "aggregate degraded-GET GiB/s at max streams "
                      "(multi-verb batch-former saturation sweep)",
            "value": top.get("deg_get_gib_s"),
            "unit": "GiB/s",
            "saturation": sat,
        }))
        return 0

    if args.ab_list or args.ab_list_smoke:
        if args.ab_list_smoke:
            ab = bench_list_ab(keys=400, drives=6, page=50,
                               versions_every=16)
        else:
            ab = bench_list_ab(keys=args.ab_list_keys)
        print(json.dumps({
            "metric": "listing page p50 speedup, metacache index vs "
                      "merge-walk (persisted bucket index A/B)",
            "value": ab.get("page_p50_speedup_x"),
            "unit": "x",
            "list_ab": ab,
        }))
        return 0

    if args.ab_select or args.ab_select_smoke:
        if args.ab_select_smoke:
            ab = bench_select_ab(streams=(1, 2), rows=3000,
                                 queries_per_stream=2)
        else:
            ab = bench_select_ab(
                streams=tuple(int(x) for x in
                              args.ab_select_streams.split(",") if x),
                rows=args.ab_select_rows)
        print(json.dumps({
            "metric": "S3 Select aggregate speedup, device scan plane "
                      "vs CPU evaluator (max over concurrency points)",
            "value": ab.get("max_speedup_x"),
            "unit": "x",
            "select_ab": ab,
        }))
        return 0

    if args.ab_sse or args.ab_sse_smoke:
        if args.ab_sse_smoke:
            ab = bench_sse_ab(streams=(1, 2), size=1 << 18, objects=2,
                              drives=6, parity=2, block=1 << 16)
        else:
            ab = bench_sse_ab()
        print(json.dumps({
            "metric": "encrypted PUT throughput, device-fused "
                      "cipher+RS+digest path vs CPU cipher fallback "
                      "(max concurrency point)",
            "value": ab.get("put_speedup_x"),
            "unit": "x",
            "sse_ab": ab,
        }))
        return 0

    if args.ab_cache or args.ab_cache_smoke:
        if args.ab_cache_smoke:
            ab = bench_cache_ab(objects=8, size=1 << 18, gets=60,
                                streams=2)
        else:
            ab = bench_cache_ab()
        print(json.dumps({
            "metric": "hot-GET speedup with the erasure-path "
                      "hot-object read cache (80/20 workload)",
            "value": ab.get("speedup_x"),
            "unit": "x",
            "cache_ab": ab,
        }))
        return 0

    if args.ab_replicate or args.ab_replicate_smoke:
        if args.ab_replicate_smoke:
            ab = bench_replicate_ab(streams=2, size=1 << 18, drives=6,
                                    preload=8, block=1 << 16)
        else:
            ab = bench_replicate_ab(streams=min(args.ab_streams, 8),
                                    size=args.ab_size)
        print(json.dumps({
            "metric": "foreground PUT p99 degradation with an active "
                      "replication resync drain (active-active plane "
                      "throttle A/B)",
            "value": ab.get("put_p99_degradation_x"),
            "unit": "x",
            "replicate_ab": ab,
        }))
        return 0

    if args.ab_notify or args.ab_notify_smoke:
        if args.ab_notify_smoke:
            ab = bench_notify_ab(streams=2, size=1 << 18, drives=6,
                                 webhook_delay_s=0.01, block=1 << 16)
        else:
            ab = bench_notify_ab(streams=min(args.ab_streams, 8),
                                 size=args.ab_size)
        print(json.dumps({
            "metric": "foreground PUT p99 degradation with every PUT "
                      "fanning out to a slow webhook (notification "
                      "plane isolation A/B)",
            "value": ab.get("put_p99_degradation_x"),
            "unit": "x",
            "notify_ab": ab,
        }))
        return 0

    if args.ab_tier:
        print(json.dumps({
            "metric": "foreground PUT p99 degradation with an active "
                      "tier-transition drain (tiering throttle A/B)",
            "tier_ab": bench_tier_ab(
                streams=min(args.ab_streams, 8), size=args.ab_size),
        }))
        return 0

    if args.ab_rebalance:
        print(json.dumps({
            "metric": "foreground PUT p99 degradation with an active "
                      "pool drain (rebalance throttle A/B)",
            "rebalance_ab": bench_rebalance_ab(
                streams=min(args.ab_streams, 8), size=args.ab_size),
        }))
        return 0

    def emit_spans(ab: dict) -> None:
        if not args.spans or not isinstance(ab, dict):
            return

        def walk(node, indent=0):
            attrs = node.get("attrs", {})
            label = " ".join(f"{k}={v}" for k, v in attrs.items())
            print(f"{'  ' * indent}{node['name']} "
                  f"{node['duration_ms']:.2f}ms {label}".rstrip(),
                  file=sys.stderr)
            for c in node.get("children", ()):
                walk(c, indent + 1)

        for mode in ("serial", "pipelined"):
            trees = (ab.get(mode) or {}).get(
                "telemetry", {}).get("top_spans") or []
            print(f"-- {mode}: top {len(trees)} slowest traces --",
                  file=sys.stderr)
            for t in trees:
                walk(t)

    if args.ab_only:
        ab = bench_pipeline_ab(args.ab_streams, args.ab_size,
                               spans_api=args.spans_api,
                               spans_trace_id=args.spans_trace_id)
        emit_spans(ab)
        print(json.dumps({
            "metric": "e2e PutObject pipeline A/B "
                      "(engine path, config #2)",
            "value": ab["pipelined"]["put_gib_s"],
            "unit": "GiB/s",
            "pipeline_ab": ab,
        }))
        return 0

    dev_gib, dev_info = bench_device()
    cpu_gib, cpu_info = bench_cpu_baseline()

    # pipeline on/off A/B on config #2, recorded alongside the kernel
    # metric (BENCH json). Best-effort: the metric of record must not
    # sink with a host-path hiccup. BENCH_PIPELINE_AB=0 skips.
    ab = None
    if args.ab_pipeline or os.environ.get(
            "BENCH_PIPELINE_AB", "1").lower() not in ("0", "false", "no"):
        try:
            ab = bench_pipeline_ab(args.ab_streams, args.ab_size,
                                   spans_api=args.spans_api,
                                   spans_trace_id=args.spans_trace_id)
            emit_spans(ab)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            ab = {"error": repr(e)}
    out = {
        "metric": "Erasure encode+bitrot GiB/s per chip "
                  "(EC 12+4, 1 MiB block, PutObject)",
        "value": round(dev_gib, 3),
        "unit": "GiB/s",
        "vs_baseline": round(dev_gib / cpu_gib, 3) if cpu_gib else None,
        "baseline_cpu_gibs": round(cpu_gib, 3),
        "device_info": dev_info,
        "cpu_info": cpu_info,
        "config": {"k": K, "m": M, "block": BLOCK, "batch": BATCH},
        "note": "device value = fused RS encode + HighwayHash256 per-shard "
                "streaming-bitrot digests (byte-identity asserted vs the "
                "host oracle before timing); value = median of round-robin "
                "slope samples across idle-separated windows (per-window "
                "medians + min in device_info); baseline = CPU SIMD "
                "(GFNI + AVX2 HighwayHash) full reference data path, "
                "single core",
    }
    if ab is not None:
        out["pipeline_ab"] = ab
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
