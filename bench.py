#!/usr/bin/env python
"""Benchmark of record: erasure encode+bitrot throughput per chip.

Measures the BASELINE.json metric — aggregate erasure encode + bitrot
GiB/s per chip on an EC 12+4 set at 1 MiB blocks (the PutObject hot-loop
device work: RS parity + per-shard HighwayHash256 streaming-bitrot
digests, one fused program) — and compares against the host-CPU SIMD
reedsolomon+highwayhash baseline (the reference's data path, natively
reimplemented in native/gf_rs.cpp + native/highwayhash.cpp since the Go
toolchain isn't present).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, ...}

Timing methodology (the r01 bench got this wrong): with the device behind
the axon tunnel, a dispatch+sync round trip costs ~700 ms regardless of
the work inside, so timing one call — or dividing one call containing an
N-iteration device loop by N without subtracting the constant — measures
the tunnel, not the kernel. Here every sample times TWO compiled
fori_loops (2 and ITERS iterations) whose bodies feed the loop carry back
into the input (so XLA can neither hoist nor dead-code the work), and the
reported time is the slope (t_long - t_short) / (ITERS - 2). Shard and
digest byte-identity against the host oracle is asserted before timing.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K, M = 12, 4
N_SHARDS = K + M
BLOCK = 1 << 20                      # 1 MiB blocks (BASELINE config)
S = -(-BLOCK // K)                   # shard bytes per block
BATCH = 32                           # concurrent PutObject streams
ITERS = 302                          # long-loop trip count (slope timing)


def bench_device() -> tuple[float, dict]:
    import jax
    import jax.numpy as jnp
    from minio_tpu import bitrot as bitrot_mod
    from minio_tpu.models.pipeline import put_step
    from minio_tpu.ops import rs_ref

    dev = jax.devices()[0]

    def sync(x):
        return np.asarray(
            jax.jit(lambda v: v.ravel()[:1].astype(jnp.float32))(x))

    def slope_time(op, dd) -> float:
        """Slope-timed seconds-per-call of op over device-resident dd,
        with a carry that consumes EVERY output element (a single-element
        carry lets XLA dead-code whole branches and overstate
        throughput)."""
        def make_loop(iters):
            @jax.jit
            def loop(d):
                def body(i, c):
                    d2 = d ^ c.astype(jnp.uint8)
                    acc = jnp.int32(0)
                    out = op(d2)
                    for leaf in (out if isinstance(out, tuple) else
                                 (out,)):
                        acc = acc + leaf.astype(jnp.int32).sum()
                    return (c + acc) & 127
                return jax.lax.fori_loop(0, iters, body, jnp.int32(1))
            return loop

        iters = ITERS
        for _escalation in range(3):
            short, long_ = make_loop(2), make_loop(iters)
            sync(short(dd)); sync(long_(dd))    # compile both
            best = None
            deltas = []
            for _ in range(3):
                t0 = time.perf_counter(); sync(short(dd))
                ta = time.perf_counter() - t0
                t0 = time.perf_counter(); sync(long_(dd))
                tb = time.perf_counter() - t0
                deltas.append(tb - ta)
                dt = (tb - ta) / (iters - 2)
                if dt > 0 and (best is None or dt < best):
                    best = dt
            # a kernel fast enough that its total delta hides inside the
            # ~tens-of-ms tunnel jitter needs a longer loop, not a guess
            if best is not None and max(deltas) > 0.2:
                return best
            iters *= 10
        assert best is not None, "slope timing failed (tunnel noise)"
        return best

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, S)).astype(np.uint8)
    dd = jax.device_put(data)

    # correctness gate: shards AND digests byte-identical to the oracle
    parity, digests = put_step(dd[:1], K, M)
    parity, digests = np.asarray(parity)[0], np.asarray(digests)[0]
    want = rs_ref.encode(data[0], M)
    assert (parity == want[K:]).all(), "device encode diverges from oracle"
    for row in (0, K, N_SHARDS - 1):
        want_dg = bitrot_mod.hash_shard(
            want[row], bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256)
        assert digests[row].tobytes() == want_dg, \
            f"device digest diverges from oracle (shard {row})"

    best = slope_time(lambda d: put_step(d, K, M), dd)
    gib = BATCH * K * S / best / 2**30
    info = {"device": str(dev), "ms_per_batch": round(best * 1e3, 3),
            "kernel": "pallas+hh256" if dev.platform == "tpu"
            else "xla+hh256"}
    for name, mode in (("decode_3miss_gibs", "decode"),
                       ("heal_4miss_gibs", "heal")):
        gibs, ratio = _bench_matrix_op(slope_time, dd, data, mode,
                                       put_ref=lambda: slope_time(
                                           lambda d: put_step(d, K, M),
                                           dd))
        info[name] = round(gibs, 2)
        info[name.replace("_gibs", "_vs_put")] = round(ratio, 2)
    info["secondary_note"] = (
        "decode/heal rows are FUSED verify+reconstruct: each includes "
        "HighwayHash256 bitrot verification of all 12 survivor shards "
        "in the same device program (heal also digests the rebuilt "
        "shards for their new frames); identity gated vs host oracle. "
        "The *_vs_put ratios are measured against an ADJACENT put_step "
        "re-measurement in the same chip window — the shared dev slice "
        "throttles under sustained load, so only same-window ratios "
        "are comparable (interleaved A/B measured decode at 0.77x and "
        "heal at ~1.0x of put_step's time)")
    info["config5_multipart_16p4_sha256_gibs"] = round(
        _bench_config5(slope_time), 2)
    return gib, info


def _bench_config5(slope_time) -> float:
    """BASELINE config #5: multipart PUT device work — 16+4 geometry,
    1 MiB blocks, SHA256 bitrot (fused encode+digest, one program).
    The batch models 2 server sets' concurrent part streams coalesced by
    the shared per-node BatchScheduler into one dispatch (cross-set
    shard batching: cluster.py wires ONE scheduler into every set;
    tests/test_scheduler.py proves the coalescing + no head-of-line).
    Identity gated (parity + SHA256 digests) vs the host oracle."""
    import jax
    from minio_tpu.models.pipeline import put_step
    from minio_tpu.ops import rs_ref

    k5, m5 = 16, 4
    s5 = -(-BLOCK // k5)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (BATCH, k5, s5)).astype(np.uint8)
    dd = jax.device_put(data)

    parity, digests = put_step(dd[:1], k5, m5, 0, b"", "sha256")
    parity, digests = np.asarray(parity)[0], np.asarray(digests)[0]
    want = rs_ref.encode(data[0], m5)
    assert (parity == want[k5:]).all(), "config5 encode diverges"
    import hashlib
    for row in (0, k5, k5 + m5 - 1):
        assert digests[row].tobytes() == hashlib.sha256(
            want[row].tobytes()).digest(), "config5 digest diverges"

    best = slope_time(lambda d: put_step(d, k5, m5, 0, b"", "sha256"), dd)
    return BATCH * k5 * s5 / best / 2**30


def _bench_matrix_op(slope_time, dd, data_host, mode: str,
                     put_ref=None) -> tuple[float, float]:
    """Secondary kernels for BASELINE configs #3/#4, FUSED with bitrot
    verification (r3): one device program per batch hashes every
    survivor shard (HighwayHash256 streaming-bitrot verify — the
    reference's inseparable verify-then-decode,
    cmd/erasure-decode.go:111-150) AND

      decode: reconstructs only the missing DATA rows (GetObject with 3
              shards missing — a GET never rematerializes rows it read);
      heal:   recovers all 4 lost rows (one dead 4-drive node) and also
              digests the rebuilt shards for their new bitrot frames.

    Slope-timed on the device-resident batch with a one-block identity
    gate (rows AND digests) vs the host oracle."""
    from minio_tpu import bitrot as bitrot_mod
    from minio_tpu.models.pipeline import get_step, heal_step
    from minio_tpu.ops import gf256, rs_matrix, rs_tpu

    lost = (1, 5, 13) if mode == "decode" else (0, 4, 8, 12)
    mask = sum(1 << i for i in range(N_SHARDS) if i not in lost)
    if mode == "decode":
        mat, _used, missing = rs_matrix.missing_data_matrix(K, M, mask)
    else:
        mat, _used, missing = rs_matrix.recover_matrix(K, M, mask)
    mat = np.ascontiguousarray(np.asarray(mat, np.uint8))
    m2 = rs_tpu._bit_expand_cached(mat.tobytes(), mat.shape)
    r = mat.shape[0]
    step = get_step if mode == "decode" else heal_step

    def op(x):
        return step(x, m2, r, K, S)

    hh = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256
    got = [np.asarray(o) for o in op(dd[:1])]
    want_rows = gf256.gf_matmul(mat, data_host[0])
    assert (got[0][0] == want_rows).all(), f"device {mode} rows diverge"
    want_dg = bitrot_mod.hash_shard(data_host[0][0].tobytes(), hh)
    assert got[1][0, 0].tobytes() == want_dg, \
        f"device {mode} survivor digest diverges"
    if mode == "heal":
        want_odg = bitrot_mod.hash_shard(want_rows[0].tobytes(), hh)
        assert got[2][0, 0].tobytes() == want_odg, \
            "device heal output digest diverges"

    best = slope_time(op, dd)
    # adjacent same-window put_step reference: the chip throttles under
    # sustained load, so absolute numbers from different moments of the
    # bench are incomparable — the ratio is the stable signal
    ratio = 0.0
    if put_ref is not None:
        ref = put_ref()
        if ref:
            ratio = ref / best          # >1 = faster than put_step
    return BATCH * K * S / best / 2**30, ratio


def bench_cpu_baseline() -> tuple[float, dict]:
    """Reference-style CPU data path: SIMD GF(2^8) encode + HighwayHash256
    over every shard (the reference's per-PUT work), single core."""
    from minio_tpu import bitrot
    from minio_tpu.ops import rs_matrix
    from minio_tpu.utils import native

    if not native.available():
        return 0.0, {"error": "native lib unavailable"}

    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (K, S)).astype(np.uint8)
    pm = np.asarray(rs_matrix.parity_matrix(K, M))

    # per-block: encode (GFNI if present, matching "best SIMD on this CPU")
    # + HighwayHash-256 every one of the n shards (streaming bitrot)
    n_blocks = 24
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        parity = native.gf_matmul(pm, data)
        full = np.concatenate([data, parity], axis=0)
        native.hh256_batch(bitrot.MAGIC_HIGHWAYHASH_KEY, full)
    dt = (time.perf_counter() - t0) / n_blocks
    gib = K * S / dt / 2**30
    # encode-only rate for reference
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        native.gf_matmul(pm, data)
    dt_enc = (time.perf_counter() - t0) / n_blocks
    return gib, {"gfni": native.has_gfni(),
                 "cpu_encode_only_gibs": round(K * S / dt_enc / 2**30, 3)}


def main() -> int:
    dev_gib, dev_info = bench_device()
    cpu_gib, cpu_info = bench_cpu_baseline()
    out = {
        "metric": "Erasure encode+bitrot GiB/s per chip "
                  "(EC 12+4, 1 MiB block, PutObject)",
        "value": round(dev_gib, 3),
        "unit": "GiB/s",
        "vs_baseline": round(dev_gib / cpu_gib, 3) if cpu_gib else None,
        "baseline_cpu_gibs": round(cpu_gib, 3),
        "device_info": dev_info,
        "cpu_info": cpu_info,
        "config": {"k": K, "m": M, "block": BLOCK, "batch": BATCH},
        "note": "device value = fused RS encode + HighwayHash256 per-shard "
                "streaming-bitrot digests (byte-identity asserted vs the "
                "host oracle before timing); slope-timed between 2- and "
                "302-iteration compiled loops to cancel the ~700 ms axon "
                "tunnel dispatch constant; baseline = CPU SIMD encode + "
                "HighwayHash256 full reference data path, single core",
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
