#!/usr/bin/env python
"""Benchmark of record: erasure encode+bitrot throughput per chip.

Measures the BASELINE.json metric — aggregate erasure encode + bitrot
GiB/s per chip on an EC 12+4 set at 1 MiB blocks (PutObject hot loop,
batch of concurrent streams) — and compares against the host-CPU SIMD
reedsolomon+highwayhash baseline (the reference's data path: SIMD
GF(2^8) tables + HighwayHash, here natively reimplemented in
native/gf_rs.cpp + native/highwayhash.cpp since the Go toolchain isn't
present).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, ...}

Device timing notes: dispatch over the axon tunnel costs ~10 ms/op and
device->host readback is slow, so the measured loop runs entirely inside
one jitted fori_loop (single dispatch) and syncs by fetching one element.
This measures sustained device pipeline throughput — the quantity that
scales with chips — not tunnel latency.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

K, M = 12, 4
BLOCK = 1 << 20                      # 1 MiB blocks (BASELINE config)
S = -(-BLOCK // K)                   # shard bytes per block
BATCH = 32                           # concurrent PutObject streams
ITERS = 20


def bench_device() -> tuple[float, dict]:
    import jax
    import jax.numpy as jnp
    from minio_tpu.ops import gf256, rs_matrix, rs_ref, rs_tpu
    from minio_tpu.ops.rs_pallas import _TS, gf_matmul_pallas_dev

    dev = jax.devices()[0]
    use_pallas = dev.platform == "tpu"

    def sync(x):
        return np.asarray(
            jax.jit(lambda v: v.ravel()[:1].astype(jnp.float32))(x))

    pad = (-S) % _TS if use_pallas else (-S) % 128
    sp = S + pad
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (BATCH, K, sp)).astype(np.uint8)

    pm = np.asarray(rs_matrix.parity_matrix(K, M))
    m2 = jnp.asarray(gf256.expand_to_gf2(pm), jnp.bfloat16)

    def encode(m2v, d):
        if use_pallas:
            return gf_matmul_pallas_dev(m2v, d, M, K)
        return rs_tpu.gf_matmul_xla(m2v, d)

    dd = jax.device_put(data)

    # correctness gate: device output must be byte-identical to the oracle
    got = np.asarray(encode(m2, dd[:1]))[0][:, :S]
    want = rs_ref.encode(data[0][:, :S], M)[K:]
    assert (got == want).all(), "device encode diverges from oracle"

    @jax.jit
    def loop(m2v, d):
        def body(i, mv):
            p = encode(mv, d)
            return mv + p[0, 0, 0].astype(jnp.bfloat16) * 0
        return jax.lax.fori_loop(0, ITERS, body, m2v)

    r = loop(m2, dd)
    sync(r)  # warm + compile
    t0 = time.perf_counter()
    r = loop(m2, dd)
    sync(r)
    dt = (time.perf_counter() - t0) / ITERS
    gib = BATCH * K * S / dt / 2**30
    return gib, {"device": str(dev), "ms_per_batch": round(dt * 1e3, 3),
                 "kernel": "pallas" if use_pallas else "xla"}


def bench_cpu_baseline() -> tuple[float, dict]:
    """Reference-style CPU data path: SIMD GF(2^8) encode + HighwayHash256
    over every shard (the reference's per-PUT work), single core."""
    from minio_tpu import bitrot
    from minio_tpu.ops import rs_matrix
    from minio_tpu.utils import native

    if not native.available():
        return 0.0, {"error": "native lib unavailable"}

    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (K, S)).astype(np.uint8)
    pm = np.asarray(rs_matrix.parity_matrix(K, M))

    # per-block: encode (GFNI if present, matching "best SIMD on this CPU")
    # + HighwayHash-256 every one of the n shards (streaming bitrot)
    n_blocks = 24
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        parity = native.gf_matmul(pm, data)
        full = np.concatenate([data, parity], axis=0)
        native.hh256_batch(bitrot.MAGIC_HIGHWAYHASH_KEY, full)
    dt = (time.perf_counter() - t0) / n_blocks
    gib = K * S / dt / 2**30
    # encode-only rate for reference
    t0 = time.perf_counter()
    for _ in range(n_blocks):
        native.gf_matmul(pm, data)
    dt_enc = (time.perf_counter() - t0) / n_blocks
    return gib, {"gfni": native.has_gfni(),
                 "cpu_encode_only_gibs": round(K * S / dt_enc / 2**30, 3)}


def main() -> int:
    dev_gib, dev_info = bench_device()
    cpu_gib, cpu_info = bench_cpu_baseline()
    out = {
        "metric": "Erasure encode+bitrot GiB/s per chip "
                  "(EC 12+4, 1 MiB block, PutObject)",
        "value": round(dev_gib, 3),
        "unit": "GiB/s",
        "vs_baseline": round(dev_gib / cpu_gib, 3) if cpu_gib else None,
        "baseline_cpu_gibs": round(cpu_gib, 3),
        "device_info": dev_info,
        "cpu_info": cpu_info,
        "config": {"k": K, "m": M, "block": BLOCK, "batch": BATCH},
        "note": "device value = RS encode kernel (bitrot-on-device lands "
                "in a later round); baseline = CPU SIMD encode + "
                "HighwayHash256 full reference data path, single core",
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
