"""Sets/zones tests: SipHash routing identity, multi-set CRUD, MRF heal,
zone expansion (reference cmd/erasure-sets_test.go shapes)."""

import hashlib

import pytest

from minio_tpu.object import api_errors
from minio_tpu.object.sets import ErasureSets
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.utils.siphash import crc_hash_mod, sip_hash_mod, siphash24

BLOCK = 1 << 16


# ---------------------------------------------------------------------------
# SipHash-2-4 reference vectors (Aumasson & Bernstein, official test vectors
# for key 000102...0f over messages 0..7 bytes) — placement compatibility.
# ---------------------------------------------------------------------------

SIPHASH_VECTORS = [
    0x726FDB47DD0E0E31, 0x74F839C593DC67FD, 0x0D6C8009D9A94F5A,
    0x85676696D7FB7E2D, 0xCF2794E0277187B7, 0x18765564CD99A68D,
    0xCBC9466E58FEE3CE, 0xAB0200F58B01D137,
]


def test_siphash_reference_vectors():
    key = bytes(range(16))
    for n, want in enumerate(SIPHASH_VECTORS):
        assert siphash24(key, bytes(range(n))) == want, n


def test_sip_hash_mod_stability():
    id16 = bytes(range(16))
    # routing must be deterministic and within range
    for name in ["obj", "a/b/c", "x" * 300, ""]:
        i = sip_hash_mod(name, 4, id16)
        assert 0 <= i < 4
        assert i == sip_hash_mod(name, 4, id16)
    assert sip_hash_mod("x", 0, id16) == -1
    assert crc_hash_mod("x", 0) == -1
    assert 0 <= crc_hash_mod("obj", 7) < 7


# ---------------------------------------------------------------------------
# ErasureSets over 2 sets × 4 drives (2+2)
# ---------------------------------------------------------------------------

@pytest.fixture()
def sets(tmp_path):
    roots = [str(tmp_path / f"d{i}") for i in range(8)]
    s = ErasureSets.from_drives(roots, set_count=2, set_drive_count=4,
                                parity=2, block_size=BLOCK)
    s.make_bucket("b")
    yield s
    s.close()


def test_sets_routing_and_crud(sets):
    datas = {}
    for i in range(20):
        name = f"obj-{i}"
        data = hashlib.sha256(name.encode()).digest() * 100
        sets.put_object("b", name, data)
        datas[name] = data
    # objects distributed across both sets
    counts = [0, 0]
    for name in datas:
        counts[sets.get_hashed_set_index(name)] += 1
    assert counts[0] > 0 and counts[1] > 0
    for name, data in datas.items():
        _, it = sets.get_object("b", name)
        assert b"".join(it) == data
    objs, _, _ = sets.list_objects("b", max_keys=100)
    assert len(objs) == 20
    sets.delete_object("b", "obj-0")
    with pytest.raises(api_errors.ObjectNotFound):
        sets.get_object_info("b", "obj-0")


def test_sets_bucket_fanout(sets):
    sets.make_bucket("b2")
    for s in sets.sets:
        assert s.bucket_exists("b2")
    with pytest.raises(api_errors.BucketExists):
        sets.make_bucket("b2")
    sets.put_object("b2", "x", b"1")
    with pytest.raises(api_errors.BucketNotEmpty):
        sets.delete_bucket("b2")
    sets.delete_bucket("b2", force=True)
    assert not sets.bucket_exists("b2")


def test_sets_format_reload(tmp_path):
    """Reopening the same drives preserves deployment id + placement."""
    roots = [str(tmp_path / f"d{i}") for i in range(8)]
    s1 = ErasureSets.from_drives(roots, 2, 4, 2, block_size=BLOCK)
    s1.make_bucket("b")
    s1.put_object("b", "persist", b"data-1")
    dep1 = s1.deployment_id
    s1.close()

    s2 = ErasureSets.from_drives(roots, 2, 4, 2, block_size=BLOCK)
    assert s2.deployment_id == dep1
    _, it = s2.get_object("b", "persist")
    assert b"".join(it) == b"data-1"
    s2.close()


def test_sets_format_heal_missing_drive(tmp_path):
    """A wiped drive gets re-formatted with its positional UUID."""
    import shutil
    roots = [str(tmp_path / f"d{i}") for i in range(8)]
    s1 = ErasureSets.from_drives(roots, 2, 4, 2, block_size=BLOCK)
    uuid_before = s1.sets[0].disks[1].get_disk_id()
    s1.close()
    shutil.rmtree(roots[1])

    s2 = ErasureSets.from_drives(roots, 2, 4, 2, block_size=BLOCK)
    healed = [d for s in s2.sets for d in s.disks
              if d is not None and d.root == roots[1]]
    assert healed and healed[0].get_disk_id() == uuid_before
    s2.close()


def test_sets_mrf_heal_on_degraded_read(sets, tmp_path):
    import glob
    import os
    import shutil
    name = "heal-me"
    data = b"z" * (2 * BLOCK)
    sets.put_object("b", name, data)
    si = sets.get_hashed_set_index(name)
    # wipe this object from one drive of its set
    victim = sets.sets[si].disks[0]
    objdir = glob.glob(os.path.join(victim.root, "b", name))
    assert objdir
    shutil.rmtree(objdir[0])
    # degraded read queues an MRF heal
    _, it = sets.get_object("b", name)
    assert b"".join(it) == data
    sets.drain_mrf()
    import time
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        try:
            victim.read_version("b", name)
            break
        except Exception:
            time.sleep(0.05)
    fi = victim.read_version("b", name)
    victim.verify_file("b", name, fi)


# ---------------------------------------------------------------------------
# zones
# ---------------------------------------------------------------------------

@pytest.fixture()
def zones(tmp_path):
    z1 = ErasureSets.from_drives(
        [str(tmp_path / f"z1d{i}") for i in range(4)], 1, 4, 2,
        block_size=BLOCK, enable_mrf=False)
    z2 = ErasureSets.from_drives(
        [str(tmp_path / f"z2d{i}") for i in range(4)], 1, 4, 2,
        block_size=BLOCK, enable_mrf=False)
    zz = ErasureServerSets([z1, z2])
    zz.make_bucket("b")
    yield zz
    zz.close()


def test_zones_put_get_overwrite_affinity(zones):
    zones.put_object("b", "o", b"v1")
    # find which zone holds it; overwrite must stay in that zone
    holders = []
    for i, z in enumerate(zones.server_sets):
        try:
            z.get_object_info("b", "o")
            holders.append(i)
        except api_errors.ObjectNotFound:
            pass
    assert len(holders) == 1
    zones.put_object("b", "o", b"v2-longer")
    holders2 = []
    for i, z in enumerate(zones.server_sets):
        try:
            z.get_object_info("b", "o")
            holders2.append(i)
        except api_errors.ObjectNotFound:
            pass
    assert holders2 == holders
    _, it = zones.get_object("b", "o")
    assert b"".join(it) == b"v2-longer"
    zones.delete_object("b", "o")
    with pytest.raises(api_errors.ObjectNotFound):
        zones.get_object_info("b", "o")


def test_zones_listing_merges(zones):
    # force objects into specific zones by writing directly
    zones.server_sets[0].put_object("b", "za", b"1")
    zones.server_sets[1].put_object("b", "zb", b"2")
    objs, _, _ = zones.list_objects("b")
    assert [o.name for o in objs] == ["za", "zb"]
    _, it = zones.get_object("b", "zb")
    assert b"".join(it) == b"2"


def test_zones_delete_marker_affinity(zones):
    """A delete marker pins the object's zone: re-PUT must land in the
    same zone so version history stays together."""
    zones.put_object("b", "o", b"v1", opts=__import__(
        "minio_tpu.object.engine", fromlist=["PutOptions"]
    ).PutOptions(versioned=True))
    holder = next(i for i, z in enumerate(zones.server_sets)
                  if z.has_object_versions("b", "o"))
    zones.delete_object("b", "o", versioned=True)
    # latest is now a delete marker; plain GET -> not found in all zones
    with pytest.raises(api_errors.ObjectNotFound):
        zones.get_object_info("b", "o")
    assert zones.get_zone_idx("b", "o", 100) == holder
    zones.put_object("b", "o", b"v2")
    holders = [i for i, z in enumerate(zones.server_sets)
               if z.has_object_versions("b", "o")]
    assert holders == [holder]
    _, it = zones.get_object("b", "o")
    assert b"".join(it) == b"v2"


def test_zones_listing_cross_zone_interleaved_order(zones):
    """Objects of one bucket spread over BOTH zones come back as one
    lexically sorted page with correct truncation — the pre-req for
    rebalance dual-read (mid-drain a bucket ALWAYS spans zones)."""
    names0 = [f"k-{i:02d}" for i in range(0, 12, 2)]     # even -> zone 0
    names1 = [f"k-{i:02d}" for i in range(1, 12, 2)]     # odd  -> zone 1
    for n in names0:
        zones.server_sets[0].put_object("b", n, b"z0")
    for n in names1:
        zones.server_sets[1].put_object("b", n, b"z1")
    objs, _, trunc = zones.list_objects("b", max_keys=100)
    assert [o.name for o in objs] == sorted(names0 + names1)
    assert not trunc
    # truncation cuts at max_keys across the MERGED order, not per zone
    objs, _, trunc = zones.list_objects("b", max_keys=5)
    assert [o.name for o in objs] == sorted(names0 + names1)[:5]
    assert trunc
    # marker resumes mid-interleave
    objs, _, _ = zones.list_objects("b", marker="k-04", max_keys=3)
    assert [o.name for o in objs] == ["k-05", "k-06", "k-07"]
    # delimiter folds prefixes that exist in DIFFERENT zones into one
    zones.server_sets[0].put_object("b", "dir/a", b"1")
    zones.server_sets[1].put_object("b", "dir/b", b"2")
    _, prefixes, _ = zones.list_objects("b", prefix="dir/",
                                        delimiter="/", max_keys=100)
    assert prefixes == [] or prefixes == ["dir/"]  # folded, never dup
    objs, pfx, _ = zones.list_objects("b", delimiter="/", max_keys=100)
    assert pfx.count("dir/") == 1


def test_zones_list_object_versions_merge_order(zones):
    """list_object_versions across zones: one (name, newest-first)
    stream even when the same object's history spans two zones —
    exactly the mid-rebalance state."""
    from minio_tpu.object.engine import PutOptions
    import time as _time
    v1 = "00000000-0000-4000-8000-0000000000a1"
    v2 = "00000000-0000-4000-8000-0000000000a2"
    # "split" has v1 in zone 0, newer v2 in zone 1 (mid-move overwrite)
    zones.server_sets[0].put_object(
        "b", "split", b"old", opts=PutOptions(versioned=True,
                                              version_id=v1))
    _time.sleep(0.01)
    zones.server_sets[1].put_object(
        "b", "split", b"new!", opts=PutOptions(versioned=True,
                                               version_id=v2))
    zones.server_sets[0].put_object("b", "aaa", b"1")
    zones.server_sets[1].put_object("b", "zzz", b"2")
    out, _pfx, _nkm, _nvm, _trunc = \
        zones.list_object_versions("b", max_keys=100)
    names = [o.name for o in out]
    assert names == sorted(names)               # name-major order
    split = [(o.version_id, o.mod_time) for o in out
             if o.name == "split"]
    assert [v for v, _ in split] == [v2, v1]    # newest first per name
    assert split[0][1] > split[1][1]
    # max_keys bounds the MERGED stream
    page3, _, _, _, trunc3 = zones.list_object_versions("b", max_keys=3)
    assert len(page3) == 3 and trunc3


def test_zones_multipart_finds_owner(zones):
    uid = zones.new_multipart_upload("b", "mp")
    pi = zones.put_object_part("b", "mp", uid, 1, b"part-data")
    from minio_tpu.object import CompletePart
    oi = zones.complete_multipart_upload("b", "mp", uid,
                                         [CompletePart(1, pi.etag)])
    assert oi.size == len(b"part-data")
    _, it = zones.get_object("b", "mp")
    assert b"".join(it) == b"part-data"
