"""Static browser UI (VERDICT r4 #3): the /minio/ page serves, exact-
path routing never shadows other /minio/* routers, and the endpoint
sequence the page's JS drives (login -> buckets -> upload -> list ->
url-token download -> share -> delete) round-trips over HTTP."""

from __future__ import annotations

import http.client
import json

import pytest

from minio_tpu.iam.sys import IAMSys
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3.server import S3Server
from minio_tpu.s3.web import mount
from tests.test_s3 import CREDS, REGION
from tests.test_web import _call, _http, _login


@pytest.fixture(scope="module")
def ui_server(tmp_path_factory):
    root = tmp_path_factory.mktemp("uidrives")
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16)
    iam = IAMSys(sets, root_cred=CREDS)
    srv = S3Server(sets, creds=CREDS, region=REGION, iam=iam).start()
    from minio_tpu.s3.admin import mount_admin
    mount_admin(srv)                  # before web, like cluster boot
    mount(srv)

    # a router registered AFTER web.mount under /minio/, like the
    # cluster's storage/lock/peer RPC mounts
    from minio_tpu.s3.handlers import HTTPResponse
    srv.register_router("/minio/fakerpc/",
                        lambda ctx: HTTPResponse(status=299,
                                                 body=b"rpc-ok"))
    yield srv
    srv.stop()
    sets.close()


def test_ui_page_serves(ui_server):
    srv = ui_server
    for path in ("/minio/", "/minio", "/minio/index.html",
                 "/minio/login"):
        st, hdrs, body = _http(srv.port, "GET", path)
        assert st == 200, path
        assert hdrs["content-type"].startswith("text/html"), path
        text = body.decode()
        assert "minio-tpu" in text and "/minio/webrpc" in text, path
        assert "content-security-policy" in hdrs, path
    # POST to the page is not a thing
    st, _, _ = _http(srv.port, "POST", "/minio/")
    assert st == 405


def test_ui_routing_never_shadows_other_minio_routes(ui_server):
    srv = ui_server
    # a later-mounted internode router still gets its traffic
    st, _, body = _http(srv.port, "GET", "/minio/fakerpc/ping")
    assert st == 299 and body == b"rpc-ok"
    # health (mounted before web) still answers
    st, _, _ = _http(srv.port, "GET",
                     "/minio/health/live")
    assert st in (200, 204)
    # an unknown /minio/* path falls through to S3 routing (its error
    # shape), not the UI page
    st, hdrs, _ = _http(srv.port, "GET", "/minio/unknown-thing")
    assert not hdrs.get("content-type", "").startswith("text/html")


def test_session_vs_authorization_error_codes(ui_server):
    """The page logs out ONLY when the session token is dead: token
    failures are JSON-RPC code 401; IAM authorization denials are 403
    (review r5 — a readonly user browsing must not be kicked out)."""
    import time as _time

    from minio_tpu.s3.web import jwt_encode

    srv = ui_server
    # expired session token -> 401
    expired = jwt_encode({"sub": CREDS.access_key, "typ": "web",
                          "exp": _time.time() - 5}, CREDS.secret_key)
    out = _call(srv.port, "ListBuckets", token=expired)
    assert out["error"]["code"] == 401
    # forged signature -> 401
    forged = jwt_encode({"sub": CREDS.access_key, "typ": "web",
                         "exp": _time.time() + 600}, "wrong")
    out = _call(srv.port, "ListBuckets", token=forged)
    assert out["error"]["code"] == 401

    # a real but non-owner user hitting an authorization wall -> 403
    srv.api.iam.add_user("uiviewer", "uiviewer-secret1")
    srv.api.iam.attach_policy("readonly", user="uiviewer")
    vtoken = _login(srv.port, "uiviewer", "uiviewer-secret1")
    out = _call(srv.port, "GetBucketPolicy",
                {"bucketName": "somebucket", "prefix": ""},
                token=vtoken)
    assert out["error"]["code"] == 403
    # and the session keeps working afterwards
    assert "result" in _call(srv.port, "ListBuckets", token=vtoken)


def test_ui_head_request(ui_server):
    srv = ui_server
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    conn.request("HEAD", "/minio/")
    resp = conn.getresponse()
    resp.read()
    assert resp.status == 200
    assert resp.getheader("Content-Type", "").startswith("text/html")
    conn.close()


def test_ui_endpoint_flow_roundtrip(ui_server):
    """The exact call sequence webui.html's JS makes, over plain
    HTTP."""
    srv = ui_server
    token = _login(srv.port)                         # Web.Login
    assert "result" in _call(srv.port, "ServerInfo", token=token)
    assert "result" in _call(srv.port, "MakeBucket",
                             {"bucketName": "uibkt"}, token=token)
    names = [b["name"] for b in _call(
        srv.port, "ListBuckets", token=token)["result"]["buckets"]]
    assert "uibkt" in names

    # upload (fetch PUT with Bearer), under a prefix like the page does
    body = b"ui-payload-" * 1000
    st, _, _ = _http(srv.port, "PUT", "/minio/web/upload/uibkt/docs/f.bin",
                     body=body,
                     headers={"Authorization": f"Bearer {token}",
                              "Content-Length": str(len(body))})
    assert st == 200

    # delimiter listing shows the prefix, then the object inside it
    out = _call(srv.port, "ListObjects", {"bucketName": "uibkt"},
                token=token)["result"]
    assert {o["name"] for o in out["objects"]} == {"docs/"}
    out = _call(srv.port, "ListObjects",
                {"bucketName": "uibkt", "prefix": "docs/"},
                token=token)["result"]
    assert [o["name"] for o in out["objects"]] == ["docs/f.bin"]

    # download via CreateURLToken exactly like the page's <a> click
    url_token = _call(srv.port, "CreateURLToken",
                      token=token)["result"]["token"]
    st, hdrs, got = _http(
        srv.port, "GET",
        f"/minio/web/download/uibkt/docs/f.bin?token={url_token}")
    assert st == 200 and got == body
    assert "attachment" in hdrs.get("content-disposition", "")

    # share: presigned URL works unauthenticated
    out = _call(srv.port, "PresignedGet",
                {"bucketName": "uibkt", "objectName": "docs/f.bin",
                 "hostName": f"127.0.0.1:{srv.port}", "expiry": 600},
                token=token)["result"]
    path = out["url"].split(str(srv.port), 1)[1]
    st, _, got = _http(srv.port, "GET", path)
    assert st == 200 and got == body

    # policy dropdown -> SetBucketPolicy -> GetBucketPolicy readback
    assert "result" in _call(
        srv.port, "SetBucketPolicy",
        {"bucketName": "uibkt", "prefix": "", "policy": "readonly"},
        token=token)
    assert _call(srv.port, "GetBucketPolicy",
                 {"bucketName": "uibkt", "prefix": ""},
                 token=token)["result"]["policy"] == "readonly"

    # delete object then bucket, like the page's delete buttons
    assert "result" in _call(
        srv.port, "RemoveObject",
        {"bucketName": "uibkt", "objects": ["docs/f.bin"]},
        token=token)
    out = _call(srv.port, "ListObjects",
                {"bucketName": "uibkt", "prefix": "docs/"},
                token=token)["result"]
    assert out["objects"] == []
    assert "result" in _call(srv.port, "DeleteBucket",
                             {"bucketName": "uibkt"}, token=token)
