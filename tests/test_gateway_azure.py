"""Azure Blob gateway (VERDICT r2 item 9; reference
cmd/gateway/azure/gateway-azure.go): the whole gateway runs against an
in-process blob server that verifies SharedKey signatures and
implements the container/blob/block REST subset — tests cover the
shared gateway matrix (buckets, roundtrip, ranged get, metadata,
listing with delimiter, deletes) plus azure-native block multipart.
"""

from __future__ import annotations

import base64
import http.server
import re
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from minio_tpu.gateway import new_gateway
from minio_tpu.object import api_errors
from minio_tpu.object.engine import PutOptions
from minio_tpu.utils.azureclient import (AzureClientError,
                                         shared_key_signature)

ACCOUNT = "testaccount"
KEY_B64 = base64.b64encode(b"azure-test-key-0123456789abcdef0").decode()


class FakeAzureBlob(http.server.BaseHTTPRequestHandler):
    """Azurite-style in-process blob service subset with SharedKey
    signature verification on every request."""

    store: dict = {}      # container -> {"blobs": {...}, "blocks": {...}}
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    # -- helpers -----------------------------------------------------------

    def _fail(self, status: int, code: str):
        body = (f"<?xml version='1.0'?><Error><Code>{code}</Code>"
                "</Error>").encode()
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _ok(self, status: int = 200, body: bytes = b"",
            headers: dict | None = None):
        self.send_response(status)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD" and body:
            self.wfile.write(body)

    def _check_sig(self, path: str, query: dict) -> bool:
        auth = self.headers.get("Authorization", "")
        m = re.match(rf"SharedKey {ACCOUNT}:(.+)", auth)
        if not m:
            return False
        hdrs = {k.lower(): v for k, v in self.headers.items()}
        want = shared_key_signature(ACCOUNT, KEY_B64, self.command,
                                    path, query, hdrs)
        return m.group(1) == want

    def _dispatch(self):
        parsed = urllib.parse.urlsplit(self.path)
        path = urllib.parse.unquote(parsed.path)
        query = {k: v[0] for k, v in
                 urllib.parse.parse_qs(parsed.query,
                                       keep_blank_values=True).items()}
        # Real Azure canonicalizes the *escaped* request path, so the
        # fake verifies the signature over the raw (still-encoded)
        # request-line path — a client signing the unencoded path fails.
        if not self._check_sig(parsed.path, query):
            return self._fail(403, "AuthenticationFailed")
        n = int(self.headers.get("Content-Length", 0) or 0)
        body = self.rfile.read(n) if n else b""
        parts = path.lstrip("/").split("/", 1)
        container = parts[0]
        blob = parts[1] if len(parts) > 1 else ""
        m = self.command

        if not container and query.get("comp") == "list":
            xml = "<EnumerationResults><Containers>" + "".join(
                f"<Container><Name>{c}</Name></Container>"
                for c in sorted(self.store)) + \
                "</Containers></EnumerationResults>"
            return self._ok(200, xml.encode())

        if query.get("restype") == "container" and not blob:
            if m == "PUT":
                if container in self.store:
                    return self._fail(409, "ContainerAlreadyExists")
                self.store[container] = {"blobs": {}, "blocks": {}}
                return self._ok(201)
            if container not in self.store:
                return self._fail(404, "ContainerNotFound")
            if m == "DELETE":
                del self.store[container]
                return self._ok(202)
            if m == "HEAD":
                return self._ok(200)
            if m == "GET" and query.get("comp") == "list":
                return self._list_blobs(container, query)
            return self._fail(400, "InvalidQueryParameterValue")

        if container not in self.store:
            return self._fail(404, "ContainerNotFound")
        c = self.store[container]

        if m == "PUT" and query.get("comp") == "block":
            c["blocks"].setdefault(blob, {})[query["blockid"]] = body
            return self._ok(201)
        if m == "PUT" and query.get("comp") == "blocklist":
            ids = [el.text or "" for el in
                   ET.fromstring(body).iter("Uncommitted")]
            staged = c["blocks"].get(blob, {})
            if any(i not in staged for i in ids):
                return self._fail(400, "InvalidBlockList")
            data = b"".join(staged[i] for i in ids)
            meta = {k.lower()[len("x-ms-meta-"):]: v
                    for k, v in self.headers.items()
                    if k.lower().startswith("x-ms-meta-")}
            ctype = self.headers.get("x-ms-blob-content-type", "")
            c["blobs"][blob] = (data, meta, ctype, time.time())
            c["blocks"].pop(blob, None)
            return self._ok(201, headers={"ETag": f'"bl-{len(data)}"'})
        if m == "PUT":
            if self.headers.get("x-ms-blob-type") != "BlockBlob":
                return self._fail(400, "InvalidHeaderValue")
            meta = {k.lower()[len("x-ms-meta-"):]: v
                    for k, v in self.headers.items()
                    if k.lower().startswith("x-ms-meta-")}
            ctype = self.headers.get("Content-Type", "")
            c["blobs"][blob] = (body, meta, ctype, time.time())
            return self._ok(201, headers={"ETag": f'"e-{len(body)}"'})

        if blob not in c["blobs"]:
            return self._fail(404, "BlobNotFound")
        data, meta, ctype, mtime = c["blobs"][blob]

        if m == "DELETE":
            del c["blobs"][blob]
            return self._ok(202)
        hdrs = {"ETag": f'"e-{len(data)}"',
                "Last-Modified": time.strftime(
                    "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(mtime)),
                "Content-Type": ctype or "application/octet-stream"}
        for k, v in meta.items():
            hdrs[f"x-ms-meta-{k}"] = v
        if m == "HEAD":
            hdrs["Content-Length"] = str(len(data))
            self.send_response(200)
            for k, v in hdrs.items():
                self.send_header(k, v)
            self.end_headers()
            return None
        if m == "GET":
            rng = self.headers.get("x-ms-range", "")
            mm = re.match(r"bytes=(\d+)-(\d*)", rng)
            if mm:
                lo = int(mm.group(1))
                hi = int(mm.group(2)) if mm.group(2) else len(data) - 1
                return self._ok(206, data[lo:hi + 1], hdrs)
            return self._ok(200, data, hdrs)
        return self._fail(400, "UnsupportedVerb")

    def _list_blobs(self, container: str, query: dict):
        """Opaque continuation tokens ('tok:<name>'): a key name passed
        as marker is rejected like real Azure — this is what catches a
        gateway that forwards S3 markers verbatim."""
        prefix = query.get("prefix", "")
        delim = query.get("delimiter", "")
        marker = query.get("marker", "")
        maxr = int(query.get("maxresults", "5000"))
        if marker and not marker.startswith("tok:"):
            return self._fail(400, "OutOfRangeInput")
        after = marker[4:] if marker else ""
        blobs = self.store[container]["blobs"]
        include_meta = "metadata" in query.get("include", "")
        out, prefixes = [], set()
        next_marker = ""
        n = 0
        for name in sorted(blobs):
            if not name.startswith(prefix) or (after and name <= after):
                continue
            if n >= maxr:
                next_marker = f"tok:{last}"          # noqa: F821
                break
            last = name
            n += 1
            if delim:
                rest = name[len(prefix):]
                d = rest.find(delim)
                if d >= 0:
                    prefixes.add(prefix + rest[:d + len(delim)])
                    continue
            data, meta, _ct, mtime = blobs[name]
            lm = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                               time.gmtime(mtime))
            meta_xml = ""
            if include_meta and meta:
                meta_xml = "<Metadata>" + "".join(
                    f"<{k}>{v}</{k}>" for k, v in meta.items()) \
                    + "</Metadata>"
            out.append(
                f"<Blob><Name>{name}</Name><Properties>"
                f"<Content-Length>{len(data)}</Content-Length>"
                f"<Etag>\"e-{len(data)}\"</Etag>"
                f"<Last-Modified>{lm}</Last-Modified>"
                f"</Properties>{meta_xml}</Blob>")
        xml = ("<EnumerationResults><Blobs>" + "".join(out)
               + "".join(f"<BlobPrefix><Name>{p}</Name></BlobPrefix>"
                         for p in sorted(prefixes))
               + f"</Blobs><NextMarker>{next_marker}</NextMarker>"
               "</EnumerationResults>")
        return self._ok(200, xml.encode())

    do_GET = do_PUT = do_DELETE = do_HEAD = _dispatch


@pytest.fixture()
def azure_server():
    FakeAzureBlob.store = {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                          FakeAzureBlob)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


@pytest.fixture()
def gw(azure_server):
    return new_gateway("azure", account=ACCOUNT, key_b64=KEY_B64,
                       host="127.0.0.1", port=azure_server)


def test_azure_bucket_lifecycle(gw):
    gw.make_bucket("cont")
    assert gw.bucket_exists("cont")
    assert "cont" in [v.name for v in gw.list_buckets()]
    with pytest.raises(api_errors.BucketExists):
        gw.make_bucket("cont")
    gw.delete_bucket("cont")
    assert not gw.bucket_exists("cont")
    with pytest.raises(api_errors.BucketNotFound):
        gw.get_bucket_info("nope")


def test_azure_object_roundtrip_and_range(gw):
    import os as _os
    gw.make_bucket("cont")
    payload = _os.urandom(100_000)
    info = gw.put_object("cont", "dir/obj", payload, opts=PutOptions(
        metadata={"x-amz-meta-color": "blue",
                  "content-type": "app/x-test"}))
    assert info.size == len(payload)

    got = gw.get_object_info("cont", "dir/obj")
    assert got.size == len(payload)
    assert got.content_type == "app/x-test"
    assert got.user_defined.get("x-amz-meta-color") == "blue"

    _i, stream = gw.get_object("cont", "dir/obj")
    assert b"".join(stream) == payload
    _i, stream = gw.get_object("cont", "dir/obj", offset=100,
                               length=500)
    assert b"".join(stream) == payload[100:600]

    with pytest.raises(api_errors.ObjectNotFound):
        gw.get_object_info("cont", "missing")
    gw.delete_object("cont", "dir/obj")
    with pytest.raises(api_errors.ObjectNotFound):
        gw.get_object_info("cont", "dir/obj")


def test_azure_listing_with_delimiter(gw):
    gw.make_bucket("cont")
    for k in ("a/1", "a/2", "b/1", "top"):
        gw.put_object("cont", k, b"x")
    objs, prefixes, _t = gw.list_objects("cont", delimiter="/")
    assert [o.name for o in objs] == ["top"]
    assert sorted(prefixes) == ["a/", "b/"]
    objs, _p, _t = gw.list_objects("cont", prefix="a/")
    assert [o.name for o in objs] == ["a/1", "a/2"]


def test_azure_multipart_block_commit(gw, azure_server):
    """Parts stage as uncommitted blocks on the service (never buffered
    in the gateway) and commit in part order via Put Block List."""
    gw.make_bucket("cont")
    uid = gw.new_multipart_upload("cont", "big", PutOptions(
        metadata={"x-amz-meta-kind": "mp"}))
    p2 = gw.put_object_part("cont", "big", uid, 2, b"BBBB" * 1000)
    p1 = gw.put_object_part("cont", "big", uid, 1, b"AAAA" * 1000)
    # blocks staged server-side, blob not yet visible
    with pytest.raises(api_errors.ObjectNotFound):
        gw.get_object_info("cont", "big")
    assert [p.number for p in
            gw.list_object_parts("cont", "big", uid)] == [1, 2]

    from minio_tpu.object import CompletePart
    info = gw.complete_multipart_upload(
        "cont", "big", uid,
        [CompletePart(1, p1.etag), CompletePart(2, p2.etag)])
    assert info.etag.endswith("-2")
    _i, stream = gw.get_object("cont", "big")
    assert b"".join(stream) == b"AAAA" * 1000 + b"BBBB" * 1000
    got = gw.get_object_info("cont", "big")
    assert got.user_defined.get("x-amz-meta-kind") == "mp"

    # wrong part etag refuses to commit
    uid2 = gw.new_multipart_upload("cont", "bad", None)
    gw.put_object_part("cont", "bad", uid2, 1, b"zz")
    with pytest.raises(api_errors.InvalidPart):
        gw.complete_multipart_upload("cont", "bad", uid2,
                                     [CompletePart(1, "wrong")])


def test_azure_special_char_names_sign_encoded_path(gw):
    """Advisor r3 (medium): SharedKey must sign the percent-encoded
    request path. Names that quote() rewrites (space, '#', unicode,
    '+') only authenticate when client and service canonicalize the
    same escaped string — the fake verifies over the raw request-line
    path, so signing the unencoded path would 403 here."""
    gw.make_bucket("cont")
    for key in ("dir with space/a b", "hash#frag", "uni-ü-ß",
                "plus+sign"):
        gw.put_object("cont", key, key.encode())
        _i, stream = gw.get_object("cont", key)
        assert b"".join(stream) == key.encode()
        assert gw.get_object_info("cont", key).size == len(key.encode())
        gw.delete_object("cont", key)


def test_azure_bad_signature_rejected(azure_server):
    from minio_tpu.utils.azureclient import AzureBlobClient
    bad = AzureBlobClient(ACCOUNT,
                          base64.b64encode(b"wrong-key").decode(),
                          "127.0.0.1", azure_server)
    with pytest.raises(AzureClientError) as ei:
        bad.create_container("x")
    assert ei.value.status == 403


def test_azure_gateway_behind_live_s3_server(azure_server, tmp_path):
    """The azure gateway serves as the ObjectLayer of a full S3 server:
    SigV4 clients read/write Azure-backed objects."""
    from minio_tpu.s3.server import S3Server
    from tests.test_s3 import CREDS, REGION, S3TestClient
    gw = new_gateway("azure", account=ACCOUNT, key_b64=KEY_B64,
                     host="127.0.0.1", port=azure_server)
    srv = S3Server(gw, creds=CREDS, region=REGION).start()
    try:
        c = S3TestClient("127.0.0.1", srv.port)
        assert c.request("PUT", "/azbucket")[0] == 200
        assert c.request("PUT", "/azbucket/o", body=b"via-s3")[0] == 200
        st, _, got = c.request("GET", "/azbucket/o")
        assert st == 200 and got == b"via-s3"
        st, _, got = c.request(
            "GET", "/azbucket/o", headers={"Range": "bytes=1-3"})
        assert st == 206 and got == b"ia-"
    finally:
        srv.stop()


def test_azure_zero_byte_and_etag_stability(gw):
    """Review r3: zero-byte GETs must not send 'bytes=0--1'; the ETag a
    PUT returns must be the one HEAD and listings report (pinned md5,
    not the service ETag)."""
    gw.make_bucket("cont")
    info = gw.put_object("cont", "empty", b"")
    _i, stream = gw.get_object("cont", "empty")
    assert b"".join(stream) == b""

    info = gw.put_object("cont", "obj", b"stable etag")
    head = gw.get_object_info("cont", "obj")
    assert head.etag == info.etag
    objs, _p, _t = gw.list_objects("cont", prefix="obj")
    assert objs[0].etag == info.etag


def test_azure_control_metadata_roundtrip(gw):
    """Tagging / object-lock metadata keys must survive the gateway
    (review r3: only x-amz-meta-* survived before)."""
    gw.make_bucket("cont")
    md = {"X-Amz-Tagging": "k=v&a=b",
          "x-amz-object-lock-mode": "GOVERNANCE",
          "x-amz-meta-plain": "p"}
    gw.put_object("cont", "locked", b"d", opts=PutOptions(metadata=md))
    got = gw.get_object_info("cont", "locked").user_defined
    assert got.get("x-amz-tagging") == "k=v&a=b"
    assert got.get("x-amz-object-lock-mode") == "GOVERNANCE"
    assert got.get("x-amz-meta-plain") == "p"


def test_azure_listing_pagination_opaque_tokens(gw):
    """Continuation across pages uses Azure tokens, never raw S3 key
    markers (the fake server 400s on a non-token marker)."""
    gw.make_bucket("cont")
    for i in range(25):
        gw.put_object("cont", f"k{i:03d}", b"x")
    seen = []
    marker = ""
    for _ in range(10):
        objs, _p, trunc = gw.list_objects("cont", marker=marker,
                                          max_keys=10)
        seen.extend(o.name for o in objs)
        if not trunc or not objs:
            break
        marker = objs[-1].name
    assert seen == [f"k{i:03d}" for i in range(25)]


def test_azure_streamed_put_constant_memory(gw, monkeypatch):
    """Above the stream threshold, PUT stages blocks instead of one
    whole-body blob (review r3: docstring promised it)."""
    import io as _io
    from minio_tpu.gateway.azure import AzureGatewayObjects
    monkeypatch.setattr(AzureGatewayObjects, "STREAM_THRESHOLD", 1024)
    monkeypatch.setattr(AzureGatewayObjects, "STAGE_CHUNK", 1024)
    gw.make_bucket("cont")
    payload = bytes(range(256)) * 40          # 10240 B -> 10 blocks
    info = gw.put_object("cont", "streamed", _io.BytesIO(payload),
                         size=len(payload))
    assert info.size == len(payload)
    import hashlib as _hl
    assert info.etag == _hl.md5(payload).hexdigest()
    _i, stream = gw.get_object("cont", "streamed")
    assert b"".join(stream) == payload
    assert gw.get_object_info("cont", "streamed").etag == info.etag
