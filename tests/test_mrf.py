"""MRF heal-queue unit tests: retry/backoff/dedup/bounds on MRFHealer,
the engine's degraded-write hooks, and the background-plane error
counters (reference background-heal-ops.go + maintainMRFList intents)."""

from __future__ import annotations

import threading
import time

import pytest

from minio_tpu.object import ErasureSetObjects, api_errors
from minio_tpu.object.background import DiskMonitor, MRFHealer
from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage import XLStorage, errors as serr, new_format_erasure_v3
from minio_tpu.storage.naughty import NaughtyDisk

K, M = 4, 2
NDISKS = K + M
BLOCK = 1 << 16


# ---------------------------------------------------------------------------
# MRFHealer
# ---------------------------------------------------------------------------

def _healer(fn, **kw):
    kw.setdefault("max_retries", 3)
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("backoff_max", 0.05)
    return MRFHealer(fn, **kw)


def test_mrf_heals_and_drains():
    healed = []
    h = _healer(lambda b, o, v: healed.append((b, o, v)))
    assert h.enqueue("b", "o1")
    assert h.enqueue("b", "o2", "vid")
    assert h.drain(5.0)
    assert ("b", "o1", "") in healed and ("b", "o2", "vid") in healed
    s = h.stats()
    assert s["healed"] == 2 and s["pending"] == 0 and s["failed"] == 0
    h.close()


def test_mrf_retries_with_backoff_then_succeeds():
    attempts = []

    def flaky(b, o, v):
        attempts.append(time.monotonic())
        if len(attempts) < 3:
            raise api_errors.InsufficientReadQuorum("not yet")

    h = _healer(flaky)
    h.enqueue("b", "o")
    assert h.drain(5.0)
    s = h.stats()
    assert len(attempts) == 3
    assert s["healed"] == 1 and s["requeued"] == 2 and s["failed"] == 0
    # exponential: the second gap is at least as long as scheduled base
    assert attempts[1] - attempts[0] >= 0.004
    h.close()


def test_mrf_gives_up_after_max_retries():
    n = [0]

    def hopeless(b, o, v):
        n[0] += 1
        raise api_errors.InsufficientReadQuorum("never")

    h = _healer(hopeless, max_retries=2)
    h.enqueue("b", "o")
    assert h.drain(5.0)
    s = h.stats()
    assert n[0] == 3                      # first try + 2 retries
    assert s["failed"] == 1 and s["healed"] == 0 and s["pending"] == 0
    h.close()


def test_mrf_vanished_object_counts_skipped():
    h = _healer(lambda b, o, v: (_ for _ in ()).throw(
        api_errors.ObjectNotFound(b, o)))
    h.enqueue("b", "gone")
    assert h.drain(5.0)
    s = h.stats()
    assert s["skipped"] == 1 and s["failed"] == 0
    h.close()


def test_mrf_dedups_queued_and_rearms_inflight():
    gate = threading.Event()
    healed = []

    def slow(b, o, v):
        if o == "blocker":
            gate.wait(5.0)
        healed.append((b, o, v))

    h = _healer(slow)
    assert h.enqueue("b", "blocker")
    time.sleep(0.05)                       # blocker moves in-flight
    assert h.enqueue("b", "o")             # queued behind it
    assert not h.enqueue("b", "o")         # duplicate while QUEUED: drop
    assert h.enqueue("b", "o", "v2")       # distinct version: kept
    # a hint for an object whose heal is RUNNING is re-armed, not lost:
    # the heal re-runs once the current one finishes
    assert h.enqueue("b", "blocker")
    gate.set()
    assert h.drain(5.0)
    assert healed.count(("b", "o", "")) == 1
    assert healed.count(("b", "o", "v2")) == 1
    assert healed.count(("b", "blocker", "")) == 2
    h.close()


def test_mrf_partial_heal_retries_until_converged():
    """A heal that repaired something but left copies missing (target
    drive still offline) must NOT count healed — it retries until
    missing_after reaches 0."""
    from minio_tpu.object.healing import HealResultItem
    calls = []

    def partial(b, o, v):
        calls.append(1)
        return HealResultItem(disks_healed=1,
                              missing_after=0 if len(calls) >= 3 else 1)

    h = _healer(partial)
    h.enqueue("b", "o")
    assert h.drain(5.0)
    s = h.stats()
    assert len(calls) == 3
    assert s["healed"] == 1 and s["requeued"] == 2 and s["failed"] == 0
    h.close()


def test_mrf_bounded_queue_drops_overflow():
    gate = threading.Event()
    h = _healer(lambda b, o, v: gate.wait(5.0), maxsize=2)
    h.enqueue("b", "o1")
    time.sleep(0.05)          # let o1 move in-flight
    h.enqueue("b", "o2")
    h.enqueue("b", "o3")
    assert not h.enqueue("b", "o4")       # over maxsize: dropped
    assert h.stats()["dropped"] == 1
    gate.set()
    assert h.drain(5.0)
    h.close()


def test_mrf_close_stops_the_drain_thread():
    h = _healer(lambda b, o, v: None)
    h.close()
    assert not h.enqueue("b", "o")        # closed: enqueue refused


# ---------------------------------------------------------------------------
# engine degraded-write hooks
# ---------------------------------------------------------------------------

def make_engine(tmp_path, naughty_first=1):
    fmts = new_format_erasure_v3(1, NDISKS)
    disks = []
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        d.write_format(fmts[0][j])
        disks.append(NaughtyDisk(d) if j < naughty_first else d)
    e = ErasureSetObjects(disks, K, M, block_size=BLOCK)
    e.make_bucket("b")
    return e


def test_put_at_quorum_fires_degraded_write_hook(tmp_path):
    eng = make_engine(tmp_path)
    calls = []
    eng.on_degraded_write = lambda b, o, v: calls.append((b, o, v))
    eng.put_object("b", "clean", b"x" * 1000)
    assert calls == []                     # full-redundancy write: quiet
    eng.disks[0].fail_verbs["append_file"] = serr.FaultyDisk("boom")
    eng.put_object("b", "deg", b"y" * 1000)
    assert calls == [("b", "deg", "")]


def test_versioned_degraded_put_reports_version(tmp_path):
    from minio_tpu.object import PutOptions
    eng = make_engine(tmp_path)
    calls = []
    eng.on_degraded_write = lambda b, o, v: calls.append((b, o, v))
    eng.disks[0].offline = True
    oi = eng.put_object("b", "v", b"z" * 100,
                        opts=PutOptions(versioned=True))
    assert calls == [("b", "v", oi.version_id)]


def test_multipart_commit_at_quorum_fires_degraded_write_hook(tmp_path):
    """CompleteMultipartUpload that met quorum but lost a drive on the
    commit rename feeds the MRF queue (ROADMAP follow-up: the multipart
    commit path previously bypassed on_degraded_write)."""
    from minio_tpu.object import CompletePart
    eng = make_engine(tmp_path)
    calls = []
    eng.on_degraded_write = lambda b, o, v: calls.append((b, o))
    uid = eng.new_multipart_upload("b", "mp")
    part = eng.put_object_part("b", "mp", uid, 1, b"q" * 4000)
    eng.complete_multipart_upload(
        "b", "mp", uid, [CompletePart(1, part.etag)])
    assert calls == []                 # clean commit: quiet
    uid = eng.new_multipart_upload("b", "mp2")
    part = eng.put_object_part("b", "mp2", uid, 1, b"r" * 4000)
    eng.disks[0].fail_verbs["rename_data"] = serr.FaultyDisk("boom")
    eng.complete_multipart_upload(
        "b", "mp2", uid, [CompletePart(1, part.etag)])
    assert calls == [("b", "mp2")]     # degraded commit: MRF fed
    _, it = eng.get_object("b", "mp2")
    assert b"".join(it) == b"r" * 4000


def test_degraded_delete_fires_hook(tmp_path):
    eng = make_engine(tmp_path)
    eng.put_object("b", "o", b"d" * 200)
    calls = []
    eng.on_degraded_write = lambda b, o, v: calls.append((b, o, v))
    eng.disks[0].offline = True
    eng.delete_object("b", "o")
    assert calls == [("b", "o", "")]
    # clean delete of a fully-deleted object: drives answering
    # not-found are converged, no heal needed
    eng.disks[0].offline = False
    calls.clear()
    eng.put_object("b", "o2", b"d")
    eng.delete_object("b", "o2")
    assert calls == []


def test_degraded_delete_marker_fires_hook(tmp_path):
    eng = make_engine(tmp_path)
    from minio_tpu.object import PutOptions
    eng.put_object("b", "o", b"d", opts=PutOptions(versioned=True))
    calls = []
    eng.on_degraded_write = lambda b, o, v: calls.append((b, o, v))
    eng.disks[0].offline = True
    oi = eng.delete_object("b", "o", versioned=True)
    assert calls == [("b", "o", oi.version_id)]


def test_mrf_converges_degraded_write_end_to_end(tmp_path):
    """The full loop: PUT loses a drive at quorum -> MRF queues ->
    background heal restores the missing shard without any reader."""
    drives = []
    nd = None
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j == 0:
            nd = NaughtyDisk(d)
            drives.append(nd)
        else:
            drives.append(d)
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK,
        mrf_options=dict(max_retries=10, backoff_base=0.02,
                         backoff_max=0.2))
    try:
        sets.make_bucket("b")
        nd.fail_verbs["append_file"] = serr.FaultyDisk("boom")
        sets.put_object("b", "o", b"q" * (2 * BLOCK))
        assert sets.mrf_stats()["queued"] >= 1
        del nd.fail_verbs["append_file"]   # drive recovers
        assert sets.drain_mrf(15.0)
        stats = sets.mrf_stats()
        assert stats["pending"] == 0 and stats["healed"] >= 1
        # the failed drive now holds a verifiable shard
        eng = sets.sets[0]
        fi = eng.disks[0].read_version("b", "o")
        eng.disks[0].check_parts("b", "o", fi)
        eng.disks[0].verify_file("b", "o", fi)
    finally:
        sets.close()


def test_mrf_replicates_delete_marker_when_drive_returns(tmp_path):
    """A delete marker written while a drive was offline: the MRF heal
    must RETRY until the drive is back (a zero-write marker heal is a
    failure, mirroring the data path's 'heal wrote no shards'), then
    replicate the marker onto it."""
    from minio_tpu.object import PutOptions
    drives = []
    nd = None
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j == 0:
            nd = NaughtyDisk(d)
            drives.append(nd)
        else:
            drives.append(d)
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK,
        mrf_options=dict(max_retries=12, backoff_base=0.02,
                         backoff_max=0.2))
    try:
        sets.make_bucket("b")
        sets.put_object("b", "o", b"d" * 300,
                        opts=PutOptions(versioned=True))
        nd.offline = True
        oi = sets.delete_object("b", "o", versioned=True)
        time.sleep(0.1)                # let the first heal attempt fail
        nd.offline = False             # drive returns: retry succeeds
        assert sets.drain_mrf(15.0)
        stats = sets.mrf_stats()
        assert stats["pending"] == 0 and stats["healed"] >= 1
        fi = nd.inner.read_version("b", "o", oi.version_id)
        assert fi.deleted               # marker replicated to the drive
    finally:
        sets.close()


def test_mrf_purges_stale_copy_after_degraded_delete(tmp_path):
    """Delete that missed a drive: the MRF entry removes the dangling
    remnant once the drive is back (reference dangling-object GC)."""
    drives = []
    nd = None
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j == 0:
            nd = NaughtyDisk(d)
            drives.append(nd)
        else:
            drives.append(d)
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK,
        mrf_options=dict(max_retries=10, backoff_base=0.02,
                         backoff_max=0.2))
    try:
        sets.make_bucket("b")
        sets.put_object("b", "o", b"s" * 500)
        nd.offline = True
        sets.delete_object("b", "o")
        nd.offline = False
        assert sets.drain_mrf(15.0)
        with pytest.raises(serr.StorageError):
            nd.inner.read_version("b", "o")
    finally:
        sets.close()


def test_mrf_partial_heal_end_to_end_not_counted_healed(tmp_path):
    """PUT degraded on TWO drives, only one recovers: the MRF heal
    repairs the recovered drive but the entry must not count healed
    while the other slot is still missing a copy — it retries, then
    counts failed (heal_object's result flows back through the sets
    layer to MRFHealer's missing_after check)."""
    drives, naughty = [], []
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j < 2:
            nd = NaughtyDisk(d)
            naughty.append(nd)
            drives.append(nd)
        else:
            drives.append(d)
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK,
        mrf_options=dict(max_retries=2, backoff_base=0.01,
                         backoff_max=0.05))
    try:
        sets.make_bucket("b")
        naughty[0].offline = True
        naughty[1].fail_verbs["append_file"] = serr.FaultyDisk("boom")
        sets.put_object("b", "o", b"p" * (2 * BLOCK))
        del naughty[1].fail_verbs["append_file"]   # one drive recovers
        assert sets.drain_mrf(10.0)
        stats = sets.mrf_stats()
        assert stats["healed"] == 0 and stats["failed"] == 1
        # ...but the recovered drive WAS repaired along the way
        fi = naughty[1].inner.read_version("b", "o")
        naughty[1].inner.verify_file("b", "o", fi)
    finally:
        sets.close()


def test_heal_converges_metadata_only_divergence(tmp_path):
    """A drive that missed an in-place metadata update (same mod_time /
    data_dir) must be converged to the majority metadata — without a
    data rewrite, and without the stale copy winning."""
    eng = make_engine(tmp_path)            # drive 0 wrapped naughty
    eng.put_object("b", "o", b"m" * 500)
    nd = eng.disks[0]
    nd.fail_verbs["write_metadata"] = serr.FaultyDisk("boom")
    eng.update_object_metadata("b", "o", {"x-amz-meta-tag": "v2"})
    del nd.fail_verbs["write_metadata"]
    assert nd.inner.read_version("b", "o").metadata.get(
        "x-amz-meta-tag") is None          # stale copy on drive 0
    res = eng.heal_object("b", "o")
    assert res.disks_healed == 1
    got = nd.inner.read_version("b", "o").metadata
    assert got.get("x-amz-meta-tag") == "v2"
    assert "etag" in got                   # per-copy fields preserved
    # steady state: a second heal finds nothing to do
    res = eng.heal_object("b", "o")
    assert res.missing_before == 0 and res.disks_healed == 0


# ---------------------------------------------------------------------------
# zero-progress heals fail retryably (HealFailed is an ObjectApiError)
# ---------------------------------------------------------------------------

def test_heal_with_no_healable_drive_raises_object_api_error(tmp_path):
    """Copies missing on an OFFLINE slot: the heal can repair nothing
    this attempt — it must fail (so MRF retries and stats don't claim a
    no-op healed) with an ObjectApiError (so per-object sweep handlers
    skip it instead of aborting the whole pass)."""
    eng = make_engine(tmp_path, naughty_first=0)
    eng.put_object("b", "o", b"x" * 1000)
    saved = eng.disks[0]
    eng.disks[0] = None
    with pytest.raises(api_errors.HealFailed) as ei:
        eng.heal_object("b", "o")
    assert isinstance(ei.value, api_errors.ObjectApiError)
    # dry run still only reports
    res = eng.heal_object("b", "o", dry_run=True)
    assert res.missing_before == 1 and res.disks_healed == 0
    # drive returns: its copy is current again, heal is a clean no-op
    eng.disks[0] = saved
    res = eng.heal_object("b", "o")
    assert res.missing_after == 0


def test_mrf_retries_offline_slot_until_failed(tmp_path):
    """A PUT degraded by an offline slot must NOT count as healed while
    the slot is still gone: the MRF entry retries, then counts failed
    (the disk monitor's sweep is the backstop)."""
    drives = []
    nd = None
    for j in range(NDISKS):
        d = XLStorage(str(tmp_path / f"d{j}"))
        if j == 0:
            nd = NaughtyDisk(d)
            drives.append(nd)
        else:
            drives.append(d)
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK,
        mrf_options=dict(max_retries=2, backoff_base=0.01,
                         backoff_max=0.02))
    try:
        sets.make_bucket("b")
        nd.offline = True
        sets.put_object("b", "o", b"x" * 1000)
        assert sets.drain_mrf(10.0)
        stats = sets.mrf_stats()
        assert stats["failed"] == 1 and stats["healed"] == 0
    finally:
        sets.close()


def test_mrf_kick_collapses_pending_backoffs():
    """kick() makes backed-off entries ready immediately — the
    re-admission hook's primitive."""
    gate = {"open": False}
    attempts = []

    def heal(b, o, v):
        attempts.append(time.monotonic())
        if not gate["open"]:
            raise api_errors.InsufficientReadQuorum("drive still gone")

    # enormous backoff: without kick() the retry would wait ~minutes
    h = MRFHealer(heal, max_retries=5, backoff_base=120.0,
                  backoff_max=120.0)
    try:
        h.enqueue("b", "o")
        deadline = time.monotonic() + 5
        while not attempts and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(attempts) == 1          # first try failed, backed off
        gate["open"] = True
        assert h.kick() == 1
        assert h.drain(5.0)
        assert h.stats()["healed"] == 1
        assert len(attempts) == 2
    finally:
        h.close()


def test_disk_monitor_readmission_kicks_mrf(tmp_path):
    """A drive coming back online drains its pending MRF entries
    immediately instead of waiting out the retry window: the PUT that
    degraded while the drive was wiped heals the moment the monitor
    re-admits it (ROADMAP PR 1 follow-up)."""
    import shutil
    drives = []
    for j in range(NDISKS):
        drives.append(XLStorage(str(tmp_path / f"d{j}")))
    sets = ErasureSets.from_storage(
        drives, set_count=1, set_drive_count=NDISKS, parity=M,
        block_size=BLOCK,
        # backoff far beyond the test horizon: only kick() can finish it
        mrf_options=dict(max_retries=8, backoff_base=120.0,
                         backoff_max=120.0))
    try:
        sets.make_bucket("b")
        # kill slot 0 hard (wipe the directory) so the PUT degrades
        dead_root = drives[0].root
        sets.sets[0].disks[0] = None
        shutil.rmtree(dead_root)
        sets.put_object("b", "o", b"q" * 2000)
        stats = sets.mrf_stats()
        assert stats["queued"] >= 1
        deadline = time.monotonic() + 5
        while sets.mrf.stats()["pending"] == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.01)   # first heal attempt fails -> backs off
        mon = DiskMonitor(sets, interval=3600)
        admitted = mon.scan_once()         # drive returns: re-admission
        assert admitted >= 1
        assert sets.drain_mrf(10.0)        # immediate, despite backoff
        stats = sets.mrf_stats()
        assert stats["pending"] == 0 and stats["failed"] == 0
        # the healed copy verifies on the re-admitted drive
        d = sets.sets[0].disks[0]
        fi = d.read_version("b", "o")
        d.verify_file("b", "o", fi)
    finally:
        sets.close()


# ---------------------------------------------------------------------------
# background-plane error counters
# ---------------------------------------------------------------------------

def test_disk_monitor_counts_scan_failures(tmp_path):
    roots = [str(tmp_path / f"d{i}") for i in range(NDISKS)]
    sets = ErasureSets.from_drives(roots, 1, NDISKS, M, block_size=BLOCK,
                                   enable_mrf=False)
    try:
        mon = DiskMonitor(sets, interval=0.01)
        mon.scan_once = lambda: (_ for _ in ()).throw(RuntimeError("wedge"))
        mon.start()
        deadline = time.monotonic() + 5
        while mon.errors < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        mon.close()
        assert mon.errors >= 2
        assert mon.consecutive_errors >= 2
        assert "wedge" in mon.last_error
    finally:
        sets.close()
