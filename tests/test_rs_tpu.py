"""Identity tests: TPU/XLA RS kernels vs the numpy oracle.

Mirrors the reference's kernel-matrix test strategy (its
erasure-encode/decode test matrices over data x parity x size x missing
patterns), with the host oracle as ground truth.

Runs on the CPU backend (conftest forces JAX_PLATFORMS=cpu); the same code
path runs on real TPU where pallas kernels additionally activate.
"""

import numpy as np
import pytest

from minio_tpu.ops import rs_matrix, rs_ref, rs_tpu

CONFIGS = [(2, 2), (4, 2), (5, 3), (8, 4), (12, 4), (16, 4), (8, 8)]
SIZES = [1, 31, 128, 1000, 4096, 65536]


def _rand_shards(k, s, seed):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (k, s)).astype(np.uint8)


class TestEncodeIdentity:
    @pytest.mark.parametrize("k,m", CONFIGS)
    def test_single_block(self, k, m):
        data = _rand_shards(k, 1000, k * 7 + m)
        ref = rs_ref.encode(data, m)
        out = np.asarray(rs_tpu.encode(data, k, m, use_pallas=False))
        assert (out == ref).all()

    @pytest.mark.parametrize("size", SIZES)
    def test_sizes_12_4(self, size):
        k, m = 12, 4
        data = _rand_shards(k, size, size)
        ref = rs_ref.encode(data, m)
        out = np.asarray(rs_tpu.encode(data, k, m, use_pallas=False))
        assert (out == ref).all()

    def test_batched(self):
        k, m, b, s = 12, 4, 8, 512
        rng = np.random.default_rng(42)
        data = rng.integers(0, 256, (b, k, s)).astype(np.uint8)
        out = np.asarray(rs_tpu.encode(data, k, m, use_pallas=False))
        for i in range(b):
            assert (out[i] == rs_ref.encode(data[i], m)).all()

    def test_zeros_and_ones(self):
        k, m = 4, 2
        for fill in (0, 1, 255):
            data = np.full((k, 64), fill, dtype=np.uint8)
            out = np.asarray(rs_tpu.encode(data, k, m, use_pallas=False))
            assert (out == rs_ref.encode(data, m)).all()


class TestReconstructIdentity:
    @pytest.mark.parametrize("k,m", [(4, 2), (12, 4), (8, 8)])
    def test_reconstruct_data(self, k, m):
        n = k + m
        data = _rand_shards(k, 777, 5)
        full = rs_ref.encode(data, m)
        rng = np.random.default_rng(6)
        for _ in range(8):
            missing = set(int(i) for i in rng.choice(n, m, replace=False))
            mask = sum(1 << i for i in range(n) if i not in missing)
            _, used = rs_matrix.decode_matrix(k, m, mask)
            stack = full[list(used)]
            out = np.asarray(rs_tpu.reconstruct_data(
                stack, mask, k, m, use_pallas=False))
            assert (out == data).all(), sorted(missing)

    def test_recover_missing(self):
        k, m = 12, 4
        n = k + m
        data = _rand_shards(k, 300, 9)
        full = rs_ref.encode(data, m)
        # drop 2 data + 2 parity
        missing = [3, 7, 13, 15]
        mask = sum(1 << i for i in range(n) if i not in missing)
        r, used, miss = rs_matrix.recover_matrix(k, m, mask)
        assert list(miss) == missing
        stack = full[list(used)]
        out = np.asarray(rs_tpu.recover_missing(
            stack, mask, k, m, use_pallas=False))
        assert out.shape == (len(missing), 300)
        for row, idx in enumerate(missing):
            assert (out[row] == full[idx]).all()


class TestPallasOnCPU:
    """Pallas kernels run in interpret-ish mode on CPU backend via
    pallas_call lowering; if unsupported, skip (the TPU driver exercises
    them on hardware, and bench.py asserts identity there)."""

    def test_pallas_encode_matches(self):
        k, m = 12, 4
        data = _rand_shards(k, 4096, 11)
        try:
            out = np.asarray(rs_tpu.encode(data, k, m, use_pallas=True))
        except Exception as e:  # pragma: no cover - platform dependent
            pytest.skip(f"pallas unavailable on this backend: {type(e).__name__}")
        assert (out == rs_ref.encode(data, m)).all()


class TestBitPacking:
    def test_unpack_pack_roundtrip(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        x = rng.integers(0, 256, (3, 5, 64)).astype(np.uint8)
        bits = rs_tpu.unpack_bits(jnp.asarray(x))
        assert bits.shape == (3, 40, 64)
        back = np.asarray(rs_tpu.pack_bits(bits))
        assert (back == x).all()
