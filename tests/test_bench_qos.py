"""CI smoke for bench.py --ab-tenants: the multi-tenant QoS A/B must
run end-to-end inside the tier-1 budget, emit a JSON-serializable
payload, and prove the structural claims at smoke scale — the noisy
tenant's surplus streams really shed under reason=tenant while the
polite tenant is never refused, and the lone-tenant overhead phase
completes in both modes. Timing ratios (isolation_p99_x, the <= 1.05
overhead bar) are asserted by the full bench, not here: a loaded CI
box makes sub-millisecond p99 deltas meaningless at smoke scale."""

from __future__ import annotations

import json

import pytest

import bench

pytestmark = pytest.mark.chaos


def test_tenants_ab_smoke():
    out = bench.bench_tenants_ab(noisy_streams=2, size=1 << 18,
                                 drives=6, block=1 << 16,
                                 polite_ops=8, max_clients=2,
                                 overhead_rounds=2)
    json.dumps(out)                     # BENCH-compatible payload
    assert out["config"]["noisy_streams"] == 2
    # both phases produced latency percentiles in both modes
    for mode in ("off", "on"):
        assert out["isolation"][mode]["polite"]["p99_ms"] > 0
        assert out["overhead"][mode]["p99_ms"] > 0
    # with the plane off nothing sheds: the flood just queues at the
    # maxClients semaphore
    assert out["isolation"]["off"]["shed_total_delta"] == 0
    # with equal shares and capacity 2 the noisy tenant is bounded to
    # one in-flight slot, so its second stream sheds — and every one
    # of those refusals lands in requests_shed_total{reason=tenant}
    assert out["noisy_sheds"] > 0, out
    assert out["isolation"]["on"]["noisy_shed"] > 0, out
    # per-tenant accounting: the noisy tenant owns every shed, the
    # polite tenant was never refused
    noisy = out["tenant_stats"]["noisytenant123"]
    polite = out["tenant_stats"]["politetenant12"]
    assert noisy["shed"] > 0
    assert polite["shed"] == 0
    assert polite["requests"] >= 8     # >=: 503 retries re-count
    # the ratios exist and are sane numbers (the full bench pins the
    # actual bars: isolation > 1, overhead <= 1.05)
    assert out["isolation_p99_x"] > 0
    assert out["put_p99_overhead_x"] > 0
