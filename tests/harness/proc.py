"""Real-subprocess cluster harness for the crash matrix.

Every prior fault test killed THREADS inside one live process; the
durability claim ("an acknowledged write survives anything short of
losing quorum drives") is about PROCESS death. This harness spawns
actual ``python -m minio_tpu server`` processes over the HTTP edge,
seeds a crashpoint env per node (``MINIO_TPU_CRASHPOINT=<name>[:n]``
→ ``os._exit(137)`` at the Nth hit — see utils/crashpoint.py),
SIGKILLs, restarts, waits healthy, and hands back SigV4 S3/admin
clients bound to the node.

Fsync discipline (``MINIO_TPU_FSYNC=on``) is on by default so the
matrix exercises the barriers it exists to test. Drive directories
persist across restarts — that IS the point.
"""

from __future__ import annotations

import http.client
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ACCESS_KEY = "harness"
SECRET_KEY = "harness-secret-key"
CRASH_EXIT_CODE = 137


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcTimeout(AssertionError):
    pass


class ProcNode:
    """One server process over a persistent drive directory."""

    def __init__(self, workdir: str, n_drives: int = 4,
                 port: Optional[int] = None, name: str = "node",
                 fsync: bool = True, pools: int = 1,
                 cluster_nodes: Optional[list[str]] = None,
                 this: int = 0,
                 extra_args: Optional[list[str]] = None):
        self.workdir = str(workdir)
        self.name = name
        self.n_drives = n_drives
        self.pools = pools
        self.port = port or free_port()
        self.fsync = fsync
        # multi-node form: the full --node spec list (identical on
        # every node) + this node's index; empty = single-node server
        self.cluster_nodes = list(cluster_nodes or [])
        self.this = this
        self.extra_args = list(extra_args or [])
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = os.path.join(self.workdir, f"{name}.log")
        os.makedirs(self.workdir, exist_ok=True)

    @property
    def addr(self) -> str:
        """The node id this process speaks as on the cluster wire."""
        return f"127.0.0.1:{self.port}"

    # -- lifecycle ---------------------------------------------------------

    def drives(self, pool: int = 0) -> list[str]:
        tag = "" if pool == 0 else f"p{pool}"
        return [os.path.join(self.workdir, f"{self.name}{tag}d{i}")
                for i in range(self.n_drives)]

    def _env(self, crashpoint: Optional[str], extra_env: Optional[dict]
             ) -> dict:
        env = dict(os.environ)
        env.update({
            "MINIO_ACCESS_KEY": ACCESS_KEY,
            "MINIO_SECRET_KEY": SECRET_KEY,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO + (os.pathsep + env["PYTHONPATH"]
                                  if env.get("PYTHONPATH") else ""),
            "MINIO_TPU_FSYNC": "on" if self.fsync else "off",
            # persistent jit cache keeps per-process XLA compiles off
            # the matrix's wall clock
            "JAX_COMPILATION_CACHE_DIR": os.path.join(REPO,
                                                      ".jax_cache"),
        })
        env.pop("MINIO_TPU_CRASHPOINT", None)
        if crashpoint:
            env["MINIO_TPU_CRASHPOINT"] = crashpoint
        env.update(extra_env or {})
        return env

    def start(self, crashpoint: Optional[str] = None,
              extra_env: Optional[dict] = None,
              wait: bool = True, timeout: float = 90.0) -> "ProcNode":
        assert self.proc is None or self.proc.poll() is not None, \
            "node already running"
        if self.cluster_nodes:
            cmd = [sys.executable, "-m", "minio_tpu", "server"]
            for spec in self.cluster_nodes:
                cmd += ["--node", spec]
            cmd += ["--this", str(self.this)]
        else:
            cmd = [sys.executable, "-m", "minio_tpu", "server",
                   *self.drives(0), "--address", f"127.0.0.1:{self.port}"]
        cmd += self.extra_args
        for p in range(1, self.pools):
            base = os.path.join(self.workdir, f"{self.name}p{p}d")
            cmd += ["--pool",
                    base + "{0..." + str(self.n_drives - 1) + "}"]
        self._log = open(self.log_path, "ab")
        self._log.write(f"\n==== start crashpoint={crashpoint!r} "
                        f"====\n".encode())
        self._log.flush()
        self.proc = subprocess.Popen(
            cmd, env=self._env(crashpoint, extra_env),
            stdout=self._log, stderr=subprocess.STDOUT,
            cwd=self.workdir)
        if wait:
            self.wait_healthy(timeout)
            if self.pools > 1:
                self._wait_pools(timeout)
        return self

    def _wait_pools(self, timeout: float = 90.0) -> None:
        """Health goes ready BEFORE the CLI's --pool attach runs; a
        multi-pool scenario must not race the expansion."""
        from minio_tpu.madmin import AdminClientError
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                topo = self.admin().topology()
                if len(topo.get("pools", [])) >= self.pools:
                    return
            except (OSError, AdminClientError):
                pass
            time.sleep(0.2)
        raise ProcTimeout(
            f"{self.name}: {self.pools} pools never attached:\n"
            + self.tail_log())

    def wait_healthy(self, timeout: float = 90.0) -> None:
        """Ready = health endpoint green AND the late-boot subsystems
        (replication plane, tier registry — the LAST things cluster
        boot wires) answer their admin verbs: /minio/health/ready goes
        green as soon as the object layer mounts, well before the
        admin surface the crash triggers drive exists."""
        from minio_tpu.madmin import AdminClientError
        deadline = time.monotonic() + timeout
        healthy = False
        while time.monotonic() < deadline:
            rc = self.proc.poll()
            if rc is not None:
                raise AssertionError(
                    f"{self.name} exited rc={rc} during boot:\n"
                    + self.tail_log())
            try:
                if not healthy:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", self.port, timeout=2)
                    conn.request("GET", "/minio/health/ready")
                    healthy = conn.getresponse().status == 200
                    conn.close()
                if healthy:
                    self.admin().replicate_status()
                    self.admin().list_tiers()
                    return
            except OSError:
                pass
            except AdminClientError as e:
                if e.status != 501:
                    return      # wired, just unhappy — boot is done
            time.sleep(0.2)
        raise ProcTimeout(f"{self.name} not healthy in {timeout}s:\n"
                          + self.tail_log())

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def wait_exit(self, timeout: float = 60.0) -> int:
        """Block until the process dies (an armed crashpoint fired) —
        returns the exit code (137 for a fired crashpoint)."""
        try:
            return self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            raise ProcTimeout(
                f"{self.name} still alive after {timeout}s waiting "
                f"for a crash:\n" + self.tail_log()) from None

    def kill(self) -> None:
        """SIGKILL — no shutdown hooks, no flushes."""
        if self.alive():
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(30)

    def pause(self) -> None:
        """SIGSTOP — the process freezes mid-flight (a GC-pause /
        overloaded-VM stand-in): sockets stay open, peers see
        timeouts, not resets. Pair with resume()."""
        if self.alive():
            self.proc.send_signal(signal.SIGSTOP)

    def resume(self) -> None:
        """SIGCONT a paused node."""
        if self.alive():
            self.proc.send_signal(signal.SIGCONT)

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful SIGTERM stop (for seeding phases)."""
        if self.alive():
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self.kill()

    def close(self) -> None:
        self.kill()
        try:
            self._log.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def tail_log(self, n: int = 4000) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(max(os.path.getsize(self.log_path) - n, 0))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no log>"

    # -- clients -----------------------------------------------------------

    def s3(self):
        from minio_tpu.s3.credentials import Credentials
        from minio_tpu.utils.s3client import S3Client
        return S3Client("127.0.0.1", self.port,
                        Credentials(ACCESS_KEY, SECRET_KEY),
                        timeout=30.0)

    def admin(self):
        from minio_tpu.madmin import AdminClient
        return AdminClient("127.0.0.1", self.port, ACCESS_KEY,
                           SECRET_KEY)

    # -- harness verbs -----------------------------------------------------

    def put(self, bucket: str, key: str, body: bytes) -> str:
        return self.s3().put_object(bucket, key, body)

    def get(self, bucket: str, key: str) -> bytes:
        _h, stream = self.s3().get_object(bucket, key)
        return b"".join(stream)

    def exists(self, bucket: str, key: str) -> bool:
        from minio_tpu.utils.s3client import S3ClientError
        try:
            self.s3().head_object(bucket, key)
            return True
        except S3ClientError as e:
            if e.status in (404, 410):
                return False
            raise

    def multipart(self, bucket: str, key: str, parts: list[bytes]
                  ) -> None:
        """Raw multipart flow over the wire (the S3Client has no MPU
        verbs; crash tests need the real HTTP surface)."""
        cli = self.s3()
        _h, body = cli._request("POST", f"/{bucket}/{key}",
                                query={"uploads": ""})
        import xml.etree.ElementTree as ET
        root = ET.fromstring(body)
        uid = None
        for el in root.iter():
            if el.tag.endswith("UploadId"):
                uid = el.text
        assert uid, body
        etags = []
        for i, part in enumerate(parts, start=1):
            h, _ = cli._request(
                "PUT", f"/{bucket}/{key}",
                query={"partNumber": str(i), "uploadId": uid},
                body=part)
            etags.append(h.get("etag", "").strip('"'))
        xml = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
            for i, e in enumerate(etags, start=1)
        ) + "</CompleteMultipartUpload>"
        cli._request("POST", f"/{bucket}/{key}",
                     query={"uploadId": uid}, body=xml.encode())

    def fsck(self, repair: bool = True) -> dict:
        return self.admin().fsck(repair=repair, tmp_age_s=0)

    def naughtynet(self, payload: dict) -> dict:
        """Drive this node's in-process network fault injector (the
        node must run with MINIO_TPU_NAUGHTYNET=on in extra_env)."""
        return self.admin().naughtynet(payload)

    def list_keys(self, bucket: str) -> list[str]:
        objs, _prefixes, _token = self.s3().list_objects_v2(bucket)
        return sorted(o["key"] for o in objs)

    def listing(self, bucket: str) -> list[tuple[str, int, str]]:
        """(key, size, etag) rows — the convergence-comparison form."""
        objs, _prefixes, _token = self.s3().list_objects_v2(bucket)
        return sorted((o["key"], o["size"], o["etag"]) for o in objs)


def make_cluster(workdir: str, n_nodes: int = 2, n_drives: int = 4,
                 parity: Optional[int] = None,
                 set_drive_count: int = 0,
                 extra_args: Optional[list[str]] = None
                 ) -> list[ProcNode]:
    """Build (without starting) a real-subprocess multi-node cluster:
    every node gets the same --node spec list and its own --this index.
    Drives live under workdir/<name>d<i> exactly like single-node
    harness runs, so logs and data are inspectable after a failure."""
    nodes = [ProcNode(workdir, n_drives=n_drives, name=f"n{i}")
             for i in range(n_nodes)]
    specs = []
    for n in nodes:
        spec = ",".join(n.drives(0))
        specs.append(f"127.0.0.1:{n.port}={spec}")
    args = list(extra_args or [])
    if parity is not None:
        args += ["--parity", str(parity)]
    if set_drive_count:
        args += ["--set-drive-count", str(set_drive_count)]
    for i, n in enumerate(nodes):
        n.cluster_nodes = specs
        n.this = i
        n.extra_args = args
    return nodes


def partition(a: ProcNode, b: ProcNode, oneway: bool = False) -> None:
    """Sever the a<->b link on BOTH processes' injectors (each side
    blocks its own outbound AND refuses the other's inbound — the
    partition holds regardless of which side initiates a call).
    ``oneway=True`` models an asymmetric failure: a can reach b, b
    cannot reach a."""
    if not oneway:
        a.naughtynet({"op": "partition", "src": a.addr, "dst": b.addr})
        b.naughtynet({"op": "partition", "src": a.addr, "dst": b.addr})
        return
    # one-way b->a dead: b blocks its outbound to a, a refuses b's
    # inbound; the a->b direction stays untouched on both sides
    b.naughtynet({"op": "partition", "src": b.addr, "dst": a.addr,
                  "oneway": True})
    a.naughtynet({"op": "partition", "src": b.addr, "dst": a.addr,
                  "oneway": True})


def heal(*nodes: ProcNode) -> None:
    """Clear every partition rule on the given nodes."""
    for n in nodes:
        if n.alive():
            n.naughtynet({"op": "heal"})


def expect_request_death(fn) -> None:
    """Run a client call whose server is armed to die mid-request:
    any connection-level error (reset, EOF, refused on retry) is the
    EXPECTED outcome; a clean success is allowed only when the crash
    fires after the response commit (callers assert the process died
    separately)."""
    from minio_tpu.utils.s3client import S3ClientError
    try:
        fn()
    except (OSError, http.client.HTTPException, S3ClientError,
            ConnectionError):
        return

