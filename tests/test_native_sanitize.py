"""Sanitizer gate for the native C++ library (SURVEY §4: a
TSAN/ASAN-equivalent for the C++ pieces; the reference runs every Go
test under -race, buildscripts/race.sh).

`make -C native sanitize` builds libminio_tpu_native_san.so with
-fsanitize=address,undefined (no recover), and the test runs the GF and
HighwayHash identity matrices against the pure-Python oracles *inside a
subprocess* that LD_PRELOADs the sanitizer runtimes — the GFNI/portable
kernels do raw pointer arithmetic over caller buffers, which is exactly
what ASan/UBSan police. A sanitizer report aborts the subprocess, so a
nonzero exit fails the test.

Run explicitly with `pytest -m native` (included in the default run
too; it skips itself when g++/libasan are absent).
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
SANLIB = os.path.join(NATIVE, "libminio_tpu_native_san.so")

CHILD = r"""
import ctypes, os, sys
import numpy as np

sys.path.insert(0, os.environ["MINIO_TPU_REPO"])
import minio_tpu.utils.native as native
native._LIB_PATH = os.environ["MINIO_TPU_SANLIB"]

from minio_tpu.ops import gf256
from minio_tpu.ops.highwayhash_py import HighwayHash

assert native.available(), "sanitized library failed to load"
rng = np.random.default_rng(7)

# GF(2^8) matmul: portable (1) and, where the host supports it, GFNI (2)
# paths vs the table oracle, over shapes that stress tail handling
for force in ([1, 2] if native.has_gfni() else [1]):
    for r, k, L in [(4, 12, 1000), (2, 4, 1), (4, 16, 4096),
                    (6, 10, 65543), (1, 1, 17)]:
        m = rng.integers(0, 256, (r, k), dtype=np.uint8)
        d = rng.integers(0, 256, (k, L), dtype=np.uint8)
        got = native.gf_matmul(m, d, force_path=force)
        want = gf256.gf_matmul(m, d)
        assert np.array_equal(got, want), f"gf mismatch {force} {r},{k},{L}"

# HighwayHash-256 single-shot vs pure-python oracle, edge lengths
key = bytes(range(32))
for n in [0, 1, 31, 32, 33, 63, 64, 100, 1029, 4096]:
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    h = HighwayHash(key); h.update(data)
    assert native.hh256(key, data) == h.digest256(), f"hh256 len {n}"
    assert native.hh64(key, data) == h.digest64(), f"hh64 len {n}"

# batched rows (strided access in C++)
shards = rng.integers(0, 256, (5, 1029), dtype=np.uint8)
got = native.hh256_batch(key, shards)
for i in range(5):
    h = HighwayHash(key); h.update(shards[i].tobytes())
    assert got[i].tobytes() == h.digest256(), f"batch row {i}"

# streaming API consistency with single-shot (state layout: 128 bytes,
# update size in bytes — see bitrot._NativeHH256)
lib = native.get_lib()
state = np.zeros(128, dtype=np.uint8)
kb = np.frombuffer(key, dtype=np.uint8)
lib.hh_init(native._u8p(kb), native._u8p(state))
data = rng.integers(0, 256, 96, dtype=np.uint8)
lib.hh_update_packets(native._u8p(state), native._u8p(data), 96)
tail = rng.integers(0, 256, 7, dtype=np.uint8)
out = np.zeros(32, dtype=np.uint8)
lib.hh_final256(native._u8p(state), native._u8p(tail), 7,
                native._u8p(out))
assert out.tobytes() == native.hh256(
    key, np.concatenate([data, tail])), "streaming mismatch"

# snappy block codec: roundtrip fuzz + CRC32C vectors under ASan/UBSan
# (match finding does raw pointer walks over caller buffers)
assert native.crc32c(b"123456789") == 0xE3069283
import random as _random
_rng = _random.Random(11)
for _trial in range(40):
    n = _rng.randrange(0, 65536)
    base = bytes(_rng.randrange(256)
                 for _ in range(_rng.randrange(1, 200)))
    blob = (base * (n // max(len(base), 1) + 1))[:n]
    if _rng.random() < 0.5:
        blob = bytes(_rng.randrange(256) for _ in range(n))
    comp = native.snappy_compress_block(blob)
    assert native.snappy_uncompress_block(comp) == blob, n
# corrupt inputs must error, not overrun
for bad in (b"", b"\xff" * 12, b"\x05\x00", b"\x04\x08ab\x01\x09"):
    try:
        native.snappy_uncompress_block(bad)
    except (ValueError, NotImplementedError):
        pass
    else:
        raise AssertionError(f"corrupt block accepted: {bad!r}")
print("sanitized identity matrices OK")
"""


def _sanitizer_runtimes() -> list[str]:
    libs = []
    for name in ("libasan.so", "libubsan.so"):
        try:
            p = subprocess.run(["g++", f"-print-file-name={name}"],
                               capture_output=True, text=True,
                               timeout=30).stdout.strip()
        except Exception:
            return []
        if not p or p == name or not os.path.exists(p):
            return []
        libs.append(p)
    return libs


@pytest.mark.native
def test_native_library_under_asan_ubsan():
    runtimes = _sanitizer_runtimes()
    if not runtimes:
        pytest.skip("g++ sanitizer runtimes not available")
    build = subprocess.run(["make", "-C", NATIVE, "-s", "sanitize"],
                           capture_output=True, text=True, timeout=300)
    # the toolchain is present (runtimes check above) — a build failure
    # is a regression in the C++ sources, not an environment gap
    assert build.returncode == 0, \
        f"sanitized build failed: {build.stderr[-1500:]}"

    env = dict(os.environ)
    env["LD_PRELOAD"] = " ".join(runtimes)
    # Python itself leaks by design; leak checking would drown real
    # findings. halt_on_error keeps genuine reports fatal.
    env["ASAN_OPTIONS"] = "detect_leaks=0,halt_on_error=1"
    env["UBSAN_OPTIONS"] = "halt_on_error=1,print_stacktrace=1"
    env["MINIO_TPU_REPO"] = REPO
    env["MINIO_TPU_SANLIB"] = SANLIB
    proc = subprocess.run([sys.executable, "-c", CHILD], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"sanitized run failed\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    assert "identity matrices OK" in proc.stdout
