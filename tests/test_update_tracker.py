"""Data-update tracker + bloom-hinted heal scanner (reference
cmd/data-update-tracker.go:63-103): mutation marking, cycle rotation,
persistence across restart, and the scanner actually pruning unchanged
buckets while never missing changed objects.
"""

from __future__ import annotations

import pytest

from minio_tpu.object.background import HealScanner
from minio_tpu.object.update_tracker import DataUpdateTracker


def test_tracker_mark_and_cycles(tmp_path):
    t = DataUpdateTracker(str(tmp_path / "t.bin"))
    t.mark("bkt", "obj1")
    # current cycle content is visible at any since <= cycle
    assert t.changed_since(1, "bkt", "obj1")
    assert t.changed_since(1, "bkt")             # bucket-level mark
    assert not t.changed_since(1, "bkt", "other")
    assert not t.changed_since(1, "coldbkt")

    c2 = t.advance_cycle()
    assert c2 == 2
    # rotated history still answers for since=1
    assert t.changed_since(1, "bkt", "obj1")
    # but a scanner starting at cycle 2 sees nothing changed
    assert not t.changed_since(2, "bkt", "obj1")
    t.mark("bkt", "obj2")
    assert t.changed_since(2, "bkt", "obj2")


def test_tracker_history_expiry_fails_open():
    t = DataUpdateTracker()
    for _ in range(20):
        t.advance_cycle()
    # asking about a cycle older than the kept history => "changed"
    assert t.changed_since(1, "anything")
    assert t.changed_since(0, "anything")


def test_tracker_persistence_across_restart(tmp_path):
    p = str(tmp_path / "t.bin")
    t1 = DataUpdateTracker(p)
    t1.mark("bkt", "persisted")
    t1.advance_cycle()                 # rotation persists
    t2 = DataUpdateTracker(p)
    assert t2.current_cycle() == 2
    assert t2.changed_since(1, "bkt", "persisted")
    assert not t2.changed_since(2, "bkt", "persisted")


def test_heal_scanner_prunes_unchanged(tmp_path):
    """Pass 1 heals everything (no history); pass 2 with no mutations
    skips every bucket; a mutation re-includes exactly its bucket."""
    from tests.test_engine import make_engine
    eng = make_engine(tmp_path)
    eng.make_bucket("hot")
    eng.make_bucket("cold")
    eng.put_object("hot", "h1", b"x" * 100)
    eng.put_object("cold", "c1", b"y" * 100)

    tracker = DataUpdateTracker()
    scanner = HealScanner(eng, tracker, interval=3600)

    assert scanner.scan_once() == 2          # full first pass
    assert scanner.skipped_buckets == 0

    assert scanner.scan_once() == 0          # nothing changed
    assert scanner.skipped_buckets == 2

    tracker.mark("hot", "h1")                # the mutation funnel's job
    assert scanner.scan_once() == 1          # only hot/h1 rechecked
    assert scanner.skipped_buckets == 3      # cold skipped again


def test_mutations_feed_tracker_through_live_server(tmp_path):
    """The S3 mutation funnel marks the tracker (handlers._notify)."""
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.s3.server import S3Server
    from tests.test_s3 import CREDS, REGION, S3TestClient
    drives = [str(tmp_path / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1,
                                   set_drive_count=4, parity=2,
                                   block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    try:
        tracker = DataUpdateTracker()
        srv.api.update_tracker = tracker
        c = S3TestClient("127.0.0.1", srv.port)
        assert c.request("PUT", "/trkbkt")[0] == 200
        assert c.request("PUT", "/trkbkt/obj", body=b"t")[0] == 200
        assert tracker.changed_since(1, "trkbkt", "obj")
        assert not tracker.changed_since(1, "trkbkt", "untouched")
        # reads do NOT mark
        c.request("GET", "/trkbkt/obj")
        assert not tracker.changed_since(1, "trkbkt", "obj-read")
    finally:
        srv.stop()
        sets.close()


def test_heal_scanner_sees_peer_mutations(tmp_path):
    """Mutations through ANOTHER node's funnel (its own tracker) must
    not be pruned by the leader's scanner (review r3 finding 1): the
    scanner pulls rotated peer snapshots each pass."""
    from tests.test_engine import make_engine
    eng = make_engine(tmp_path)
    eng.make_bucket("shared")
    eng.put_object("shared", "o1", b"x" * 64)

    local = DataUpdateTracker()
    peer = DataUpdateTracker()          # another node's tracker
    scanner = HealScanner(
        eng, local, interval=3600,
        peer_snapshots=lambda: [peer.rotate_snapshot()])

    assert scanner.scan_once() == 1     # full first pass
    assert scanner.scan_once() == 0     # nothing changed anywhere

    peer.mark("shared", "o1")           # mutation via the OTHER node
    assert scanner.scan_once() == 1     # seen through the snapshot
    assert scanner.scan_once() == 0     # consumed; pruned again

    # unreachable peer => no pruning that pass (fail open)
    down = HealScanner(eng, DataUpdateTracker(), interval=3600,
                       peer_snapshots=lambda: [None])
    assert down.scan_once() == 1
    assert down.scan_once() == 1        # still full: peer unknown
