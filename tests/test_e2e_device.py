"""E2E device-path proof through the LIVE S3 server (VERDICT r2 item 6).

The default DEVICE_MIN_BYTES gate means a default-config server on a
CPU-only host never routes to the device in e2e; this test forces the
device route (XLA-CPU backend in tests — same code path as TPU) through
the FULL stack: HTTP SigV4 PUT -> handlers -> engine -> shared
BatchScheduler -> fused encode+digest device program -> bitrot-framed
shard writes, then HTTP GET (device-routed verify) and byte identity.
Scheduler coalescing counters prove concurrent streams shared device
dispatches (the cross-request batching of BASELINE config #2).

On the real-TPU host the same path is driven by bench_e2e.py; the axon
tunnel's ~15 MiB/s h2d makes it CPU-route there (documented in
BASELINE.md) — THIS test is what pins the integration correctness.
"""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

from minio_tpu.object import codec as codec_mod
from minio_tpu.object.sets import ErasureSets
from minio_tpu.parallel.scheduler import BatchScheduler
from minio_tpu.s3.server import S3Server

from tests.test_s3 import CREDS, REGION, S3TestClient

BLOCK = 1 << 16


@pytest.fixture()
def device_server(monkeypatch, tmp_path):
    monkeypatch.setattr(codec_mod, "_device_is_tpu", lambda: True)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
    # pin the SINGLE-device fused path: with _device_is_tpu faked true
    # on the 8-device virtual CPU mesh the codec would otherwise mesh-
    # dispatch (that serving path has its own e2e in test_mesh.py), and
    # the first cold compile of the 8-device program mid-PUT-storm can
    # blow the request timeouts
    monkeypatch.setenv("MINIO_TPU_MESH", "0")
    sched = BatchScheduler(max_wait=0.2)
    drives = [str(tmp_path / f"d{i}") for i in range(6)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=6,
                                   parity=2, block_size=BLOCK,
                                   scheduler=sched)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    yield srv, sched
    srv.stop()
    sets.close()


def test_live_server_device_path_concurrent_puts(device_server):
    """16 concurrent PUT streams through the live server must ride the
    device path, coalesce into shared dispatches, and round-trip
    byte-identically."""
    srv, sched = device_server
    n_streams = 16
    payloads = {
        f"obj{i}": np.random.default_rng(i).integers(
            0, 256, 3 * BLOCK + i * 17, dtype=np.uint8).tobytes()
        for i in range(n_streams)}

    c0 = S3TestClient("127.0.0.1", srv.port)
    assert c0.request("PUT", "/devbkt")[0] == 200

    barrier = threading.Barrier(n_streams)
    errors: list = []

    def put(name: str, body: bytes) -> None:
        try:
            client = S3TestClient("127.0.0.1", srv.port)
            barrier.wait(30)
            st, _, _ = client.request("PUT", f"/devbkt/{name}", body=body)
            assert st == 200, f"PUT {name} -> {st}"
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=put, args=(n, b))
          for n, b in payloads.items()]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errors, errors[:3]
    assert sched.batches > 0

    # the shared scheduler must coalesce concurrent streams into shared
    # dispatches (the whole point of the cross-request batch former).
    # Thread overlap is load-dependent, so allow extra volleys before
    # calling it a failure.
    for round_ in range(5):
        if sched.coalesced > 0:
            break
        vb = threading.Barrier(n_streams)
        vs = []

        def volley(name):
            client = S3TestClient("127.0.0.1", srv.port)
            vb.wait(30)
            client.request("PUT", f"/devbkt/{name}",
                           body=payloads[name])

        vs = [threading.Thread(target=volley, args=(n,))
              for n in payloads]
        for t in vs:
            t.start()
        for t in vs:
            t.join(60)
    assert sched.coalesced > 0, \
        f"no coalescing across {n_streams} concurrent streams"

    # GET every object back byte-identically (device-routed verify)
    for name, body in payloads.items():
        st, _, got = c0.request("GET", f"/devbkt/{name}")
        assert st == 200 and got == body, f"roundtrip diverged: {name}"
