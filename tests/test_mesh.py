"""Multi-device mesh path (VERDICT r4 #1): geometry matrix for the
sharded put/get/heal steps on the virtual CPU mesh, plus the serving
integration — Codec and BatchScheduler dispatching through
parallel/mesh.py when more than one device is visible.

Runs under conftest.py's 8-device virtual CPU mesh
(xla_force_host_platform_device_count). Sub-meshes of {2, 4} devices
and explicit (dp, sp) factorizations cover both axes; geometries
include shard counts that do NOT divide the sp axis (the pad-row
digest path) on both the put and get sides.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from minio_tpu import bitrot as bitrot_mod
from minio_tpu.ops import rs_matrix, rs_ref
from minio_tpu.parallel import mesh as pmesh

HH = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256


def _mesh(n, sp=None):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    return pmesh.make_mesh(n, sp=sp)


def _full(data, k, m):
    """Host oracle: (B, k, S) -> (B, k+m, S) data+parity."""
    return np.concatenate(
        [data, np.stack([rs_ref.encode(d, m)[k:] for d in data])],
        axis=1)


def _rand(b, k, s, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (b, k, s)).astype(np.uint8)


# ---------------------------------------------------------------------------
# sharded_put_step: encode + digest matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_dev,sp,k,m", [
    (2, None, 4, 2),     # sp=2, n=6 divides
    (4, None, 4, 2),     # sp=4, n=6 does NOT divide -> pad rows
    (8, None, 12, 4),    # sp=8, n=16 divides
    (8, 4, 12, 4),       # dp=2 x sp=4: both axes live
    (8, None, 16, 4),    # sp=8, n=20 does NOT divide -> pad rows
])
def test_sharded_put_matrix(n_dev, sp, k, m):
    mesh = _mesh(n_dev, sp)
    dp, sp_sz = mesh.devices.shape
    b, s = dp * 2, sp_sz * 64
    data = _rand(b, k, s, seed=n_dev * 100 + k)
    darr = pmesh.shard_array(mesh, data, P("dp", None, "sp"))
    parity, digests, _ = pmesh.sharded_put_step(mesh, k, m)(darr)
    parity, digests = np.asarray(parity), np.asarray(digests)
    full = _full(data, k, m)
    assert (parity == full[:, k:]).all()
    assert digests.shape == (b, k + m, 32)
    # every shard's digest against the host bitrot oracle — including
    # the last parity row (the first row dropped by n%sp padding)
    for bi in (0, b - 1):
        for si in (0, k - 1, k, k + m - 1):
            assert digests[bi, si].tobytes() == bitrot_mod.hash_shard(
                full[bi, si], HH), (bi, si)


def test_sharded_put_sha256():
    mesh = _mesh(4)
    k, m = 4, 2
    s = mesh.devices.shape[1] * 64
    data = _rand(2, k, s, seed=7)
    darr = pmesh.shard_array(mesh, data, P("dp", None, "sp"))
    _, digests, _ = pmesh.sharded_put_step(mesh, k, m, "sha256")(darr)
    full = _full(data, k, m)
    want = bitrot_mod.hash_shard(full[0, k],
                                 bitrot_mod.BitrotAlgorithm.SHA256)
    assert np.asarray(digests)[0, k].tobytes() == want


# ---------------------------------------------------------------------------
# sharded_get_step: verify+decode mask matrix
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_dev,sp,k,m,lost", [
    (4, None, 4, 2, [0, 2]),        # k%sp==0, two data rows lost
    (4, None, 4, 2, [1, 4]),        # data + parity lost
    (8, None, 12, 4, [3]),          # k=12 % sp=8 != 0 -> pad digests
    (8, None, 12, 4, [0, 5, 9, 13]),  # max m losses
    (8, 2, 16, 4, [1, 17]),         # dp=4 x sp=2
])
def test_sharded_get_matrix(n_dev, sp, k, m, lost):
    mesh = _mesh(n_dev, sp)
    dp, sp_sz = mesh.devices.shape
    b, s = dp * 2, sp_sz * 64
    data = _rand(b, k, s, seed=sum(lost) + k)
    full = _full(data, k, m)
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    _, used = rs_matrix.decode_matrix(k, m, mask)
    survivors = np.ascontiguousarray(full[:, list(used), :])
    sarr = pmesh.shard_array(mesh, survivors, P("dp", None, "sp"))
    run, missing = pmesh.sharded_get_step(mesh, k, m, mask)
    out, sdig = run(sarr)
    out, sdig = np.asarray(out), np.asarray(sdig)
    assert list(missing) == [i for i in lost if i < k]
    for row, idx in enumerate(missing):
        assert (out[:, row, :] == full[:, idx, :]).all(), idx
    assert sdig.shape == (b, k, 32)
    for si in (0, k - 1):
        assert sdig[0, si].tobytes() == bitrot_mod.hash_shard(
            survivors[0, si], HH)


# ---------------------------------------------------------------------------
# sharded_heal_step: verify+recover+rehash
# ---------------------------------------------------------------------------

def test_sharded_heal_all_rows_and_digests():
    mesh = _mesh(8, 4)               # dp=2 x sp=4
    k, m = 12, 4
    lost = [1, 5, 13]
    s = mesh.devices.shape[1] * 64
    data = _rand(4, k, s, seed=3)
    full = _full(data, k, m)
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    _, used = rs_matrix.decode_matrix(k, m, mask)
    survivors = np.ascontiguousarray(full[:, list(used), :])
    sarr = pmesh.shard_array(mesh, survivors, P("dp", None, "sp"))
    run, idxs = pmesh.sharded_heal_step(mesh, k, m, mask)
    out, sdig, odig = run(sarr)
    out, sdig, odig = map(np.asarray, (out, sdig, odig))
    assert idxs == lost
    for row, idx in enumerate(lost):
        assert (out[:, row, :] == full[:, idx, :]).all(), idx
        # rebuilt-shard digests are what the healer writes into the
        # new bitrot frames
        assert odig[0, row].tobytes() == bitrot_mod.hash_shard(
            full[0, idx], HH)
    assert sdig[0, 0].tobytes() == bitrot_mod.hash_shard(
        survivors[0, 0], HH)


def test_sharded_heal_row_filter():
    mesh = _mesh(4)
    k, m = 4, 2
    lost = [1, 5]
    s = mesh.devices.shape[1] * 64
    data = _rand(2, k, s, seed=11)
    full = _full(data, k, m)
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    _, used = rs_matrix.decode_matrix(k, m, mask)
    survivors = np.ascontiguousarray(full[:, list(used), :])
    sarr = pmesh.shard_array(mesh, survivors, P("dp", None, "sp"))
    run, idxs = pmesh.sharded_heal_step(mesh, k, m, mask, rows=(5,))
    out, _sdig, odig = run(sarr)
    assert idxs == [5]
    assert (np.asarray(out)[:, 0, :] == full[:, 5, :]).all()
    assert np.asarray(odig).shape == (2, 1, 32)


# ---------------------------------------------------------------------------
# serving dispatch helpers: batch padding + unshardable fallback
# ---------------------------------------------------------------------------

def test_mesh_helper_pads_uneven_batch():
    mesh = _mesh(8, 4)               # dp=2: B=3 needs padding
    k, m = 4, 2
    s = mesh.devices.shape[1] * 64
    data = _rand(3, k, s, seed=5)
    out = pmesh.mesh_encode_and_hash(mesh, data, k, m)
    assert out is not None
    full_got, digests = out
    full = _full(data, k, m)
    assert full_got.shape == (3, k + m, s)
    assert (full_got == full).all()
    assert digests.shape == (3, k + m, 32)
    assert digests[2, k].tobytes() == bitrot_mod.hash_shard(
        full[2, k], HH)


def test_mesh_helper_rejects_unshardable_columns():
    mesh = _mesh(8)                  # sp=8
    data = _rand(2, 4, 100, seed=6)  # 100 % 8 != 0
    assert pmesh.mesh_encode_and_hash(mesh, data, 4, 2) is None


# ---------------------------------------------------------------------------
# serving integration: Codec + BatchScheduler route through the mesh
# ---------------------------------------------------------------------------

@pytest.fixture()
def mesh_serving(monkeypatch):
    from minio_tpu.object import codec as codec_mod
    monkeypatch.setenv("MINIO_TPU_MESH", "1")
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
    return codec_mod


def test_codec_fused_paths_dispatch_on_mesh(mesh_serving):
    codec_mod = mesh_serving
    k, m = 4, 2
    s = 1 << 10                      # divides every sp <= 8
    codec = codec_mod.Codec(k, m, s * k)
    data = _rand(2, k, s, seed=8)
    before = pmesh.DISPATCHES.value

    out = codec.encode_and_hash_batch(data, HH)
    assert out is not None and pmesh.DISPATCHES == before + 1
    full_got, digests = out
    full = _full(data, k, m)
    assert (full_got == full).all()
    assert digests[0, 0].tobytes() == bitrot_mod.hash_shard(
        full[0, 0], HH)

    lost = [1, 4]
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    _, used = rs_matrix.decode_matrix(k, m, mask)
    survivors = np.ascontiguousarray(full[:, list(used), :])
    got = codec.verify_and_decode_batch(survivors, mask, s, HH)
    assert got is not None and pmesh.DISPATCHES == before + 2
    out_rows, missing, sdig = got
    assert list(missing) == [1]
    assert (out_rows[:, 0, :] == full[:, 1, :]).all()
    assert sdig[0, 0].tobytes() == bitrot_mod.hash_shard(
        survivors[0, 0], HH)

    got = codec.verify_and_recover_batch(survivors, mask, {1, 4}, s, HH)
    assert got is not None and pmesh.DISPATCHES == before + 3
    out_rows, idxs, sdig, odig = got
    assert idxs == [1, 4]
    for row, idx in enumerate(idxs):
        assert (out_rows[:, row, :] == full[:, idx, :]).all()
        assert odig[0, row].tobytes() == bitrot_mod.hash_shard(
            full[0, idx], HH)


def test_scheduler_routes_through_mesh(mesh_serving):
    from minio_tpu.parallel.scheduler import BatchScheduler
    codec_mod = mesh_serving
    k, m = 4, 2
    s = 1 << 10
    codec = codec_mod.Codec(k, m, s * k)
    data = _rand(2, k, s, seed=9)
    sched = BatchScheduler(max_wait=0.01)
    try:
        before = pmesh.DISPATCHES.value
        out = sched.encode_and_hash(codec, data, HH)
        assert out is not None
        assert pmesh.DISPATCHES > before
        full_got, digests = out
        full = _full(data, k, m)
        assert (full_got == full).all()
        assert digests[1, k + m - 1].tobytes() == bitrot_mod.hash_shard(
            full[1, k + m - 1], HH)
    finally:
        sched.close()


def test_e2e_multidevice_server_roundtrip(mesh_serving, tmp_path):
    """A live multi-device 'server': ErasureSets put/get/degraded-get
    with the codec forced onto the virtual CPU mesh — proves the
    serving stack (engine -> scheduler -> codec -> mesh collectives)
    round-trips objects when more than one device exists."""
    import os
    from minio_tpu.object.sets import ErasureSets

    sets = ErasureSets.from_drives(
        [str(tmp_path / f"md{i}") for i in range(6)], 1, 6, 2,
        block_size=1 << 16)
    try:
        before = pmesh.DISPATCHES.value
        payload = os.urandom((1 << 16) * 3 + 12345)
        sets.make_bucket("meshbkt")
        sets.put_object("meshbkt", "obj", payload)
        _info, stream = sets.get_object("meshbkt", "obj")
        assert b"".join(stream) == payload
        assert pmesh.DISPATCHES > before, \
            "PUT did not dispatch through the mesh"

        # degraded read: lose one drive directory
        import shutil
        shutil.rmtree(tmp_path / "md1")
        _info, stream = sets.get_object("meshbkt", "obj")
        assert b"".join(stream) == payload
    finally:
        sets.close()
