"""Persisted bucket metacache: index-served listings vs the merge-walk
oracle, bounded staleness, segment persistence + durability (drive
loss, bitrot), the shared scanner feed, and paging equivalence.

The oracle discipline: every index-served page must be result-identical
to the merge-walk page (the fallback path IS the oracle — flip the
manager off and compare)."""

from __future__ import annotations

import glob
import os
import random
import time

import pytest

from minio_tpu.object import PutOptions, api_errors
from minio_tpu.object.metacache import (MetacacheManager, manifest_key,
                                        walks_counter)
from minio_tpu.object.server_sets import ErasureServerSets
from minio_tpu.object.sets import ErasureSets
from minio_tpu.storage.xl_storage import MINIO_META_BUCKET

K, M, NDISKS = 4, 2, 6
BLOCK = 1 << 16


def make_zones(tmp_path, pools=1, tag="p"):
    zz = ErasureServerSets(
        [ErasureSets.from_drives(
            [str(tmp_path / f"{tag}{p}d{j}") for j in range(NDISKS)],
            1, NDISKS, M, block_size=BLOCK, enable_mrf=False)
         for p in range(pools)],
        load_topology=False)
    zz.make_bucket("b")
    return zz


@pytest.fixture()
def zz(tmp_path):
    z = make_zones(tmp_path)
    yield z
    z.close()


def attach(zz, start=True, **kw):
    kw.setdefault("staleness_s", 0.0)
    kw.setdefault("flush_s", 0.05)
    mgr = MetacacheManager(zz, **kw)
    if start:
        mgr.start()
    zz.attach_metacache(mgr)
    return mgr


def names_of(page):
    return [o.name for o in page[0]]


def oracle_pages(zz, prefix="", delimiter="", max_keys=1000):
    """(objects, prefixes) union collected by paging with the handler's
    next-marker rule, bypassing the metacache."""
    mc, zz.metacache = zz.metacache, None
    try:
        objs, pfx, marker = [], [], ""
        while True:
            o, p, trunc = zz.list_objects("b", prefix, marker, delimiter,
                                          max_keys)
            objs.extend(x.name for x in o)
            pfx.extend(p)
            if not trunc:
                return objs, sorted(set(pfx))
            if o and (not p or o[-1].name > p[-1]):
                marker = o[-1].name
            elif p:
                marker = p[-1]
            else:
                raise AssertionError("truncated page with no marker")
    finally:
        zz.metacache = mc


# ---------------------------------------------------------------------------
# oracle equivalence
# ---------------------------------------------------------------------------

def test_index_pages_equal_merge_walk_randomized(zz):
    """Randomized interleaving of PUT / DELETE / versioned-delete with
    listings: with staleness bound 0 every index-served page must be
    RESULT-IDENTICAL to the merge-walk page."""
    seed = int(os.environ.get("MINIO_TPU_CHAOS_SEED",
                              str(random.randrange(1 << 30))))
    print(f"MINIO_TPU_CHAOS_SEED={seed}")
    rng = random.Random(seed)
    mgr = attach(zz)
    assert mgr.build("b")
    live: dict[str, bool] = {}          # name -> has versioned writes
    for step in range(120):
        op = rng.random()
        name = f"d{rng.randrange(3)}/o{rng.randrange(40):03d}"
        if op < 0.55:
            versioned = rng.random() < 0.3
            zz.put_object("b", name, b"x" * rng.randrange(1, 64),
                          opts=PutOptions(versioned=versioned))
            live[name] = live.get(name, False) or versioned
        elif op < 0.75 and live:
            victim = rng.choice(sorted(live))
            if live[victim] or rng.random() < 0.5:
                # versioned history only ever deletes via a marker
                zz.delete_object("b", victim, versioned=True)
            else:
                zz.delete_object("b", victim)
            del live[victim]            # hidden from listings either way
        elif op < 0.9:
            prefix = rng.choice(["", "d0/", "d1/", "d"])
            mk = rng.choice([1, 3, 7, 1000])
            got = zz.list_objects("b", prefix, "", "", mk)
            mc, zz.metacache = zz.metacache, None
            try:
                want = zz.list_objects("b", prefix, "", "", mk)
            finally:
                zz.metacache = mc
            assert names_of(got) == names_of(want), (step, prefix, mk)
            assert got[1] == want[1] and got[2] == want[2]
        else:
            got = zz.list_object_versions("b", "", "", rng.choice([2, 5,
                                                                   1000]))
            mc, zz.metacache = zz.metacache, None
            try:
                want = zz.list_object_versions("b", "", "",
                                               len(got[0]) or 1000)
            finally:
                zz.metacache = mc
            assert [(v.name, v.version_id) for v in got[0]] == \
                [(v.name, v.version_id) for v in want[0][:len(got[0])]]
    assert mgr.stats()["serves"] > 0
    assert mgr.stats()["drops"] == 0


def test_index_delimiter_pages_equal_oracle(zz):
    mgr = attach(zz)
    for i in range(30):
        zz.put_object("b", f"a/{i % 3}/k{i:02d}", b"x")
        zz.put_object("b", f"top{i:02d}", b"y")
    assert mgr.build("b")
    for prefix in ("", "a/", "a/1/", "top"):
        for delim in ("", "/"):
            for mk in (1, 2, 5, 1000):
                got = zz.list_objects("b", prefix, "", delim, mk)
                mc, zz.metacache = zz.metacache, None
                try:
                    want = zz.list_objects("b", prefix, "", delim, mk)
                finally:
                    zz.metacache = mc
                assert names_of(got) == names_of(want)
                assert got[1] == want[1] and got[2] == want[2]


def test_index_delimiter_versions_equal_oracle(zz):
    """Delimiter-aware list_object_versions (satellite): rolled-up
    CommonPrefixes from the index must equal the merge-walk oracle
    page-for-page, and paging with the returned markers must replay
    the one-shot listing exactly — prefix entries included."""
    mgr = attach(zz)
    for i in range(18):
        zz.put_object("b", f"a/{i % 3}/k{i:02d}", b"x",
                      opts=PutOptions(versioned=(i % 2 == 0)))
        if i % 4 == 0:
            zz.put_object("b", f"a/{i % 3}/k{i:02d}", b"y",
                          opts=PutOptions(versioned=True))
        zz.put_object("b", f"top{i:02d}", b"z")
    assert mgr.build("b")
    for prefix in ("", "a/", "a/1/", "top"):
        for mk in (1, 2, 5, 1000):
            got = zz.list_object_versions("b", prefix, "", mk, "", "/")
            mc, zz.metacache = zz.metacache, None
            try:
                want = zz.list_object_versions("b", prefix, "", mk,
                                               "", "/")
            finally:
                zz.metacache = mc
            assert [(v.name, v.version_id) for v in got[0]] == \
                [(v.name, v.version_id) for v in want[0]], (prefix, mk)
            assert got[1] == want[1], (prefix, mk)      # CommonPrefixes
            assert got[2:] == want[2:], (prefix, mk)    # markers+trunc
    # paging with delimiter replays the one-shot page exactly
    one_vers, one_pfx, _, _, trunc = zz.list_object_versions(
        "b", "", "", 10000, "", "/")
    assert not trunc and one_pfx == ["a/"]
    for mk in (1, 2, 3, 7):
        vers, pfx, marker, vidm = [], [], "", ""
        while True:
            page, p, nkm, nvm, tr = zz.list_object_versions(
                "b", "", marker, mk, vidm, "/")
            vers.extend((v.name, v.version_id) for v in page)
            pfx.extend(p)
            assert len(page) + len(p) <= mk
            if not tr:
                break
            marker, vidm = nkm, nvm
        assert vers == [(v.name, v.version_id) for v in one_vers], mk
        assert pfx == one_pfx, mk


def test_staleness_bound_delta_becomes_visible(zz):
    """A delta OLDER than the staleness bound must be visible: the
    serve path force-drains the journal instead of cutting a stale
    page. (The daemon is not started, so only the bound enforces
    visibility.)"""
    mgr = attach(zz, staleness_s=0.15, start=False)
    zz.put_object("b", "old", b"x")
    assert mgr.build("b")
    zz.put_object("b", "young", b"y")           # delta sits journaled
    time.sleep(0.3)                             # now older than bound
    page = zz.list_objects("b", "", "", "", 100)
    assert "young" in names_of(page)
    assert mgr.stats()["sync_drains"] >= 1


def test_disabled_flag_restores_merge_walk(zz, monkeypatch):
    mgr = attach(zz)
    zz.put_object("b", "k", b"x")
    assert mgr.build("b")
    assert zz.metacache.serve_list_objects("b", "", "", "", 10) \
        is not None
    monkeypatch.setenv("MINIO_TPU_METACACHE", "off")
    assert zz.metacache.serve_list_objects("b", "", "", "", 10) is None
    assert mgr.namespace_feed("b") is None
    # the listing surface still answers (merge-walk fallback)
    assert names_of(zz.list_objects("b", "", "", "", 10)) == ["k"]


def test_journal_overflow_invalidates_never_lies(zz):
    mgr = attach(zz, journal_max=4, start=False)
    for i in range(4):
        zz.put_object("b", f"seed{i}", b"x")
    assert mgr.build("b")
    assert mgr.drain(5.0)
    for i in range(8):                  # overflow the 4-entry journal
        zz.put_object("b", f"of{i}", b"x")
    assert mgr.stats()["drops"] >= 1
    # invalid index: serves fall back to the (correct) merge-walk
    assert mgr.serve_list_objects("b", "", "", "", 100) is None
    got = names_of(zz.list_objects("b", "", "", "", 100))
    assert [n for n in got if n.startswith("of")] == \
        [f"of{i}" for i in range(8)]
    # reconcile repairs the drift and restores index serving
    mgr._drain_once()
    assert mgr.reconcile("b") >= 0
    assert mgr.serve_list_objects("b", "", "", "", 100) is not None
    assert names_of(zz.list_objects("b", "", "", "", 100)) == got


# ---------------------------------------------------------------------------
# persistence + durability
# ---------------------------------------------------------------------------

def test_persist_load_roundtrip_and_reconcile_drift(zz):
    mgr = attach(zz)
    for i in range(25):
        zz.put_object("b", f"k{i:03d}", b"x",
                      opts=PutOptions(versioned=(i % 5 == 0)))
    assert mgr.build("b")
    mgr._persist("b")
    assert manifest_key("b") in mgr.segment_objects()
    # mutate AFTER the persist: the reloaded index must repair drift
    zz.put_object("b", "post-persist", b"y")
    zz.delete_object("b", "k003")       # k003 is unversioned
    mgr.drain(5.0)

    mgr2 = MetacacheManager(zz, staleness_s=0.0)
    assert mgr2.build("b")              # loads segments, then reconciles
    zz.attach_metacache(mgr2)
    got = names_of(zz.list_objects("b", "", "", "", 1000))
    mc, zz.metacache = zz.metacache, None
    try:
        want = names_of(zz.list_objects("b", "", "", "", 1000))
    finally:
        zz.metacache = mc
    assert got == want
    assert "post-persist" in got and "k003" not in got


def test_segment_survives_drive_kill_and_heals(zz, tmp_path):
    """Kill a drive holding metacache segments: listings stay correct
    (the index reloads through erasure reconstruction), and the heal
    scanner's segment sweep re-protects the index objects."""
    import shutil
    mgr = attach(zz)
    for i in range(20):
        zz.put_object("b", f"k{i:03d}", b"x")
    assert mgr.build("b")
    mgr._persist("b")
    seg_keys = mgr.segment_objects()
    assert len(seg_keys) >= 2

    # kill drive 0 of the pool (it holds shards of every segment)
    dead = tmp_path / "p0d0"
    shutil.rmtree(dead)
    os.makedirs(dead)                   # wiped, like a replaced drive

    # a FRESH manager must still load the persisted index (reads
    # reconstruct around the dead drive) and serve correct listings
    mgr2 = MetacacheManager(zz, staleness_s=0.0)
    assert mgr2.build("b")
    zz.attach_metacache(mgr2)
    got = names_of(zz.list_objects("b", "", "", "", 1000))
    assert got == [f"k{i:03d}" for i in range(20)]

    # DiskMonitor re-admits the wiped drive (formats it for its slot),
    # then the heal scanner's segment sweep rewrites the index shards
    # onto it — the regular bucket walk never visits the meta bucket
    from minio_tpu.object.background import DiskMonitor, HealScanner
    assert DiskMonitor(zz.server_sets[0]).scan_once() >= 1
    healed = HealScanner(zz, tracker=None)._heal_metacache_segments(mgr2)
    assert healed >= len(seg_keys)
    shards = glob.glob(str(dead / MINIO_META_BUCKET / "buckets" / "b"
                           / ".metacache" / "**" / "part.1"),
                       recursive=True)
    assert shards, "healed drive holds no metacache segment shards"


def test_segment_bitrot_never_wrong_listing(zz, tmp_path):
    """Flip bytes in one drive's copy of a metacache segment: the GET
    path reconstructs (bitrot is detected per-shard), so the reloaded
    index stays CORRECT — and when damage exceeds parity the load
    fails closed into a walk rebuild, never a wrong listing."""
    mgr = attach(zz)
    for i in range(15):
        zz.put_object("b", f"k{i:03d}", b"x")
    assert mgr.build("b")
    mgr._persist("b")

    # corrupt every metacache shard file on ONE drive (<= parity)
    hits = 0
    for f in glob.glob(str(tmp_path / "p0d1" / MINIO_META_BUCKET
                           / "buckets" / "b" / ".metacache" / "**"
                           / "part.1"), recursive=True):
        with open(f, "r+b") as fh:
            data = bytearray(fh.read())
            for j in range(0, len(data), 7):
                data[j] ^= 0xFF
            fh.seek(0)
            fh.write(data)
        hits += 1
    assert hits >= 1

    mgr2 = MetacacheManager(zz, staleness_s=0.0)
    assert mgr2.build("b")
    zz.attach_metacache(mgr2)
    assert names_of(zz.list_objects("b", "", "", "", 100)) == \
        [f"k{i:03d}" for i in range(15)]

    # damage beyond parity: the load must FAIL (fall back to a walk
    # rebuild via reconcile), not parse garbage into a wrong listing
    for d in ("p0d2", "p0d3"):
        for f in glob.glob(str(tmp_path / d / MINIO_META_BUCKET
                               / "buckets" / "b" / ".metacache" / "**"
                               / "part.1"), recursive=True):
            with open(f, "r+b") as fh:
                data = bytearray(fh.read())
                for j in range(0, len(data), 7):
                    data[j] ^= 0xFF
                fh.seek(0)
                fh.write(data)
    mgr3 = MetacacheManager(zz, staleness_s=0.0)
    assert mgr3.build("b")              # walk rebuild path
    assert mgr3.stats()["buckets"]["b"]["names"] == 15


def test_persisted_reload_repairs_overwrite_after_overflow(zz):
    """Journal overflow loses an OVERWRITE delta (same name, new
    content): the rebuild must not trust the persisted snapshot's
    version for that name — presence drift alone cannot prove
    freshness, so a build that loads segments stays invalid until the
    immediate reconcile has refreshed every name."""
    mgr = attach(zz, journal_max=3, start=False)
    for i in range(3):
        zz.put_object("b", f"k{i}", b"old")
    assert mgr.build("b")
    assert mgr.drain(5.0)
    mgr._persist("b")

    for i in range(3):                  # fill the journal to its bound
        zz.put_object("b", f"f{i}", b"x")
    zz.put_object("b", "k1", b"the-new-bigger-content")  # delta LOST
    assert mgr.stats()["drops"] >= 1

    assert mgr.build("b")               # persisted load + reconcile
    assert mgr.drain(5.0)
    zz.attach_metacache(mgr)
    page = zz.list_objects("b", "", "", "", 100)
    assert mgr.serves >= 1              # index-served, not fallback
    k1 = next(o for o in page[0] if o.name == "k1")
    assert k1.size == len(b"the-new-bigger-content")


def test_delete_bucket_purges_persisted_index(zz):
    """DELETE bucket removes the persisted manifest + segments from the
    meta bucket — a recreated same-name bucket must not reload (or leak
    artifacts of) the dead incarnation's index."""
    mgr = attach(zz)
    for i in range(5):
        zz.put_object("b", f"old{i}", b"x")
    assert mgr.build("b")
    assert mgr.drain(5.0)
    mgr._persist("b")
    seg_keys = [s["key"] for s in mgr._indexes["b"].segments]
    zz.delete_bucket("b", force=True)
    for key in seg_keys + [manifest_key("b")]:
        with pytest.raises(api_errors.ObjectApiError):
            mgr._get_bytes(key)

    zz.make_bucket("b")
    zz.put_object("b", "fresh", b"y")
    assert mgr.build("b")               # no manifest: walk rebuild
    assert mgr.drain(5.0)
    assert names_of(zz.list_objects("b", "", "", "", 100)) == ["fresh"]


def test_persist_reclaims_superseded_segments(zz, tmp_path):
    """Unreferenced segment objects must not accumulate: a walk-rebuild
    persist reclaims the prior manifest's segments even though the
    fresh index never knew their keys (idx.segments is None)."""
    mgr = attach(zz, start=False)
    for i in range(10):
        zz.put_object("b", f"k{i}", b"x")
    assert mgr.build("b")
    assert mgr.drain(5.0)
    mgr._persist("b")

    def live_seg_dirs():
        return {os.path.basename(p) for p in glob.glob(
            str(tmp_path / "p0d0" / MINIO_META_BUCKET / "buckets" / "b"
                / ".metacache" / "seg-*"))}

    first = live_seg_dirs()
    assert first
    # a fresh manager whose load FAILS (manifest unreadable beyond
    # parity is hard to stage; simplest equivalent: blank segments)
    mgr2 = MetacacheManager(zz, staleness_s=0.0)
    assert mgr2.build("b")
    with mgr2._cond:
        mgr2._indexes["b"].segments = None        # walk-rebuild state
        mgr2._indexes["b"].dirty = {"k0"}
    mgr2._persist("b")
    second = live_seg_dirs()
    assert second and not (first & second), \
        "prior manifest's segment objects leaked"


# ---------------------------------------------------------------------------
# the shared namespace feed
# ---------------------------------------------------------------------------

def test_feed_replaces_scanner_walks(zz):
    """One crawler cycle with the feed attached performs ZERO merge
    walks; detached it walks per consumer — the walk-count metric the
    bench A/B gates on."""
    from minio_tpu.features.lifecycle import iter_version_groups
    from minio_tpu.object.background import DataUsageCrawler

    for i in range(12):
        zz.put_object("b", f"k{i:02d}", b"x",
                      opts=PutOptions(versioned=(i % 3 == 0)))
    c = walks_counter()

    def totals():
        with c._mu:
            items = dict(c._series)
        out = {"merge": 0.0, "index": 0.0}
        for key, v in items.items():
            out[dict(key).get("source", "merge")] += v
        return out

    crawler = DataUsageCrawler(zz, interval=1e9, persist=False)

    def cycle():
        before = totals()
        crawler.scan_once()
        for _ in iter_version_groups(zz, "b", consumer="lifecycle"):
            pass
        for _ in iter_version_groups(zz, "b", consumer="transition"):
            pass
        after = totals()
        return (after["merge"] - before["merge"],
                after["index"] - before["index"])

    merge_walks, index_reads = cycle()      # no metacache attached
    assert merge_walks >= 3 and index_reads == 0

    mgr = attach(zz)
    assert mgr.build("b")
    merge_walks, index_reads = cycle()
    assert merge_walks == 0, merge_walks
    assert index_reads >= 3
    # usage numbers from the feed match the walk
    assert crawler.usage["buckets"]["b"]["objects"] == 12


def test_feed_version_groups_match_listing(zz):
    mgr = attach(zz)
    for i in range(6):
        zz.put_object("b", "multi", b"x" * (i + 1),
                      opts=PutOptions(versioned=True))
    zz.put_object("b", "single", b"y")
    assert mgr.build("b")
    feed = dict(mgr.namespace_feed("b", versions=True))
    assert set(feed) == {"multi", "single"}
    assert len(feed["multi"]) == 6
    mods = [v.mod_time for v in feed["multi"]]
    assert mods == sorted(mods, reverse=True)


def test_rebalance_drains_via_feed(tmp_path):
    """Pool drain with the metacache attached: the walker takes its
    names from the index (no per-pass namespace walk) while moving
    pool-local versions — and the drain still empties the pool."""
    zz = make_zones(tmp_path, pools=2)
    datas = {}
    for i in range(8):
        data = os.urandom(256 + i)
        zz.server_sets[0].put_object("b", f"r-{i:02d}", data)
        datas[f"r-{i:02d}"] = data
    mgr = attach(zz)
    assert mgr.build("b")
    c = walks_counter()
    with c._mu:
        before = dict(c._series)
    from minio_tpu.object.rebalance import Rebalancer
    reb = Rebalancer(zz, 0, busy_fn=lambda: False)
    zz.topology.set_state(0, "draining")
    moved, failed, remaining = reb.run_pass()
    assert failed == 0 and remaining == 0 and moved == 8
    assert zz.server_sets[0].list_object_versions("b", max_keys=10)[0] \
        == []
    for name, data in datas.items():
        _, it = zz.get_object("b", name)
        assert b"".join(it) == data
    with c._mu:
        after = dict(c._series)
    rebal_merge = sum(v for k, v in after.items()
                      if dict(k).get("consumer") == "rebalance"
                      and dict(k).get("source") == "merge") - \
        sum(v for k, v in before.items()
            if dict(k).get("consumer") == "rebalance"
            and dict(k).get("source") == "merge")
    # exactly the hidden .minio.sys sweep (per-pool internals are never
    # indexed); the CLIENT bucket drained off the feed without a walk
    assert rebal_merge <= 1, "drain re-walked the client bucket"
    rebal_index = sum(v for k, v in after.items()
                      if dict(k).get("consumer") == "rebalance"
                      and dict(k).get("source") == "index")
    assert rebal_index >= 1
    zz.close()


# ---------------------------------------------------------------------------
# list_object_versions paging semantics (satellite)
# ---------------------------------------------------------------------------

def test_versions_paging_markers_resume_mid_object(zz):
    """A page boundary inside one key's version list must be marked
    (NextKeyMarker + NextVersionIdMarker) and resumable without loss
    or duplication — the old bare-list form cut silently."""
    for i in range(7):
        zz.put_object("b", "vk", b"x" * (i + 1),
                      opts=PutOptions(versioned=True))
    zz.put_object("b", "aa", b"1")
    zz.put_object("b", "zz", b"2")
    one_shot = [(v.name, v.version_id)
                for v in zz.list_object_versions("b", "", "", 1000)[0]]
    assert len(one_shot) == 9
    for mk in (1, 2, 3, 4, 5):
        got, marker, vidm, rounds = [], "", "", 0
        while True:
            page, _pfx, nkm, nvm, trunc = zz.list_object_versions(
                "b", "", marker, mk, vidm)
            got.extend((v.name, v.version_id) for v in page)
            rounds += 1
            assert rounds < 100
            if not trunc:
                break
            assert nkm and len(page) == mk
            marker, vidm = nkm, nvm
        assert got == one_shot, mk


def test_versions_paging_equivalence_randomized(zz):
    seed = int(os.environ.get("MINIO_TPU_CHAOS_SEED",
                              str(random.randrange(1 << 30))))
    print(f"MINIO_TPU_CHAOS_SEED={seed}")
    rng = random.Random(seed)
    for i in range(40):
        name = f"p{rng.randrange(4)}/k{rng.randrange(12):02d}"
        zz.put_object("b", name, b"x",
                      opts=PutOptions(versioned=rng.random() < 0.5))
        if rng.random() < 0.2:
            zz.delete_object("b", name, versioned=True)
    one_shot = [(v.name, v.version_id)
                for v in zz.list_object_versions("b", "", "", 10000)[0]]
    for mk in (1, 2, 3, 7):
        got, marker, vidm = [], "", ""
        while True:
            page, _pfx, nkm, nvm, trunc = zz.list_object_versions(
                "b", "", marker, mk, vidm)
            got.extend((v.name, v.version_id) for v in page)
            if not trunc:
                break
            marker, vidm = nkm, nvm
        assert got == one_shot, mk


# ---------------------------------------------------------------------------
# list_objects paging equivalence property (satellite)
# ---------------------------------------------------------------------------

def test_list_objects_paging_equivalence_property(zz):
    """Paging a seeded bucket in many small pages (varying max-keys,
    delimiter, marker, prefix) must equal the one-shot listing —
    pinned over the single-homed paginate_objects truncation loop."""
    seed = int(os.environ.get("MINIO_TPU_CHAOS_SEED",
                              str(random.randrange(1 << 30))))
    print(f"MINIO_TPU_CHAOS_SEED={seed}")
    rng = random.Random(seed)
    names = set()
    for i in range(60):
        parts = [rng.choice(["a", "b", "a0", "ab"])
                 for _ in range(rng.randint(1, 3))]
        names.add("/".join(parts) + str(i % 3))
    for n in sorted(names):
        zz.put_object("b", n, b"x",
                      opts=PutOptions(versioned=rng.random() < 0.3))
    for victim in rng.sample(sorted(names), len(names) // 4):
        zz.delete_object("b", victim, versioned=True)  # marker hides it
    for prefix in ("", "a", "a/", "ab/"):
        for delim in ("", "/", "0"):
            want = oracle_pages(zz, prefix, delim, 100000)
            for mk in (1, 2, 3, 5):
                got = oracle_pages(zz, prefix, delim, mk)
                assert got == want, (prefix, delim, mk)


def test_serve_raises_bucket_not_found_like_oracle(zz):
    mgr = attach(zz)
    zz.put_object("b", "k", b"x")
    assert mgr.build("b")
    with pytest.raises(api_errors.BucketNotFound):
        zz.list_objects("nope", "", "", "", 10)
    zz.delete_bucket("b", force=True)
    with pytest.raises(api_errors.BucketNotFound):
        zz.list_objects("b", "", "", "", 10)
