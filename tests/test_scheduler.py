"""Cross-request batch scheduler: coalescing, correctness of scatter,
failure propagation, buffer pool back-pressure, admission budget, and
the PR-6 multi-verb former (decode/recover verbs, full-bucket immediate
dispatch, close-with-pending flush, Counter metric semantics)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from minio_tpu import bitrot as bitrot_mod
from minio_tpu.object.codec import Codec
from minio_tpu.ops import gf256, rs_matrix, rs_ref
from minio_tpu.parallel.bpool import BytePool
from minio_tpu.parallel.scheduler import BatchScheduler, requests_budget

HH = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S


def _degraded(seed: int, b: int, k: int, m: int, s: int, lost):
    """(survivors in `used` order, mask, full) for a lost-shard set."""
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (b, k, s), dtype=np.int64
                        ).astype(np.uint8)
    full = np.stack([rs_ref.encode(blk, m) for blk in data])
    mask = sum(1 << i for i in range(k + m) if i not in lost)
    _dm, used, _missing = rs_matrix.missing_data_matrix(k, m, mask)
    surv = np.stack([full[:, u] for u in used], axis=1)
    return surv, mask, full


@pytest.fixture()
def device_codec(monkeypatch):
    """Force the codec's device route (runs on the CPU jax backend)."""
    from minio_tpu.object import codec as codec_mod
    monkeypatch.setattr(codec_mod, "_device_is_tpu", lambda: True)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
    return codec_mod


def test_scheduler_coalesces_concurrent_streams(device_codec):
    sched = BatchScheduler(max_batch=64, max_wait=0.05)
    codec = Codec(4, 2, 4 * 512)
    rng = np.random.default_rng(0)
    inputs = [rng.integers(0, 256, (2, 4, 512), dtype=np.uint8)
              for _ in range(6)]
    outs: list = [None] * len(inputs)

    def run(i):
        outs[i] = sched.encode_and_hash(codec, inputs[i], HH)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(inputs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)

    for i, out in enumerate(outs):
        assert out is not None
        full, digests = out
        want = codec.encode_batch(inputs[i], force="numpy")
        assert (full == want).all()
        want_dg = bitrot_mod.hash_shards_batch(
            want.reshape(-1, 512), HH).reshape(2, 6, 32)
        assert (digests == want_dg).all()
    # at least some requests shared a dispatch
    assert sched.batches < len(inputs)
    assert sched.coalesced > 0
    sched.close()


def test_scheduler_respects_max_batch(device_codec):
    sched = BatchScheduler(max_batch=3, max_wait=0.05)
    codec = Codec(4, 2, 4 * 256)
    rng = np.random.default_rng(1)
    inputs = [rng.integers(0, 256, (2, 4, 256), dtype=np.uint8)
              for _ in range(4)]            # 8 blocks > max_batch 3
    outs: list = [None] * 4
    threads = [threading.Thread(
        target=lambda i=i: outs.__setitem__(
            i, sched.encode_and_hash(codec, inputs[i], HH)))
        for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for i in range(4):
        full, _ = outs[i]
        assert (full == codec.encode_batch(inputs[i],
                                           force="numpy")).all()
    sched.close()


def test_scheduler_declines_unsupported_algo():
    sched = BatchScheduler()
    codec = Codec(4, 2, 4 * 128)
    data = np.zeros((1, 4, 128), np.uint8)
    assert sched.encode_and_hash(
        codec, data, bitrot_mod.BitrotAlgorithm.BLAKE2B512) is None
    sched.close()


def test_scheduler_propagates_errors(device_codec, monkeypatch):
    sched = BatchScheduler(max_wait=0.01)
    codec = Codec(4, 2, 4 * 128)

    def boom(*a, **k):
        raise RuntimeError("device on fire")

    from minio_tpu.object import codec as codec_mod
    monkeypatch.setattr(codec_mod.Codec, "encode_and_hash_batch", boom)
    data = np.zeros((1, 4, 128), np.uint8)
    with pytest.raises(RuntimeError):
        sched.encode_and_hash(codec, data, HH)
    sched.close()


def test_bytepool_backpressure():
    from minio_tpu.parallel.bpool import BytePoolExhausted
    pool = BytePool(1024, 2)
    a, b = pool.get(), pool.get()
    with pytest.raises(BytePoolExhausted):
        pool.get(timeout=0.05)
    assert pool.exhausted == 1 and pool.waits >= 1
    pool.put(a)
    c = pool.get(timeout=1.0)
    assert len(c) == 1024
    with pytest.raises(ValueError):
        pool.put(bytearray(5))  # foreign width: rejected loudly
    pool.put(b)
    pool.put(c)


def test_scheduler_submit_future_nonblocking():
    """submit() must return immediately; a declined submission resolves
    to None (the caller's CPU fallback) without waiting."""
    sched = BatchScheduler()
    codec = Codec(4, 2, 4 * 128)
    data = np.zeros((1, 4, 128), np.uint8)
    fut = sched.submit(codec, data,
                       bitrot_mod.BitrotAlgorithm.BLAKE2B512)
    assert fut.done() and fut.result() is None
    sched.close()


def test_scheduler_submit_resolves_on_device_route(device_codec):
    sched = BatchScheduler(max_batch=16, max_wait=0.01)
    codec = Codec(4, 2, 4 * 256)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (2, 4, 256), dtype=np.uint8)
    fut = sched.submit(codec, data, HH)
    out = fut.result(timeout=30)
    assert out is not None
    full, _dg = out
    assert (full == codec.encode_batch(data, force="numpy")).all()
    assert fut.done()
    sched.close()


def test_requests_budget_formula():
    n = requests_budget(1 << 22, 16)
    assert n >= 8
    # bigger blocks -> fewer admitted requests
    assert requests_budget(1 << 26, 16) <= n

def test_scheduler_no_head_of_line_across_geometries(device_codec):
    """Mixed geometries must dispatch in the SAME collector wakeup —
    one bucket per loop iteration serialized 4+2 traffic behind 12+4
    grace windows (VERDICT r2 weak #5)."""
    import time
    sched = BatchScheduler(max_batch=64, max_wait=0.4)
    rng = np.random.default_rng(3)
    geos = [(4, 2, 512), (6, 2, 256), (8, 4, 128)]
    outs = {}
    errs = []

    def run(gi, k, m, s):
        codec = Codec(k, m, k * s)
        data = rng.integers(0, 256, (2, k, s), dtype=np.int64
                            ).astype(np.uint8)
        try:
            outs[gi] = sched.encode_and_hash(codec, data, HH)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    # pre-warm: compile each geometry's device program outside the
    # timed window (first dispatch costs an XLA compile)
    for k, m, s in geos:
        Codec(k, m, k * s).encode_and_hash_batch(
            np.zeros((2, k, s), np.uint8), HH)

    t0 = time.perf_counter()
    ts = [threading.Thread(target=run, args=(gi, *geo))
          for gi, geo in enumerate(geos)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    elapsed = time.perf_counter() - t0
    sched.close()
    assert not errs and len(outs) == len(geos)
    assert all(v is not None for v in outs.values())
    # pre-fix: bucket N waits ~N grace windows (>= 0.8 s for the third);
    # post-fix: all drain in one wakeup (~0.4 s + dispatch)
    assert elapsed < 0.4 * len(geos) - 0.05, \
        f"geometry buckets serialized: {elapsed:.2f}s"


# ---------------------------------------------------------------------------
# PR 6: multi-verb former
# ---------------------------------------------------------------------------

def test_full_bucket_dispatches_immediately(device_codec):
    """A bucket already holding >= max_batch blocks must dispatch NOW,
    not after the grace window (the grace-window stall fix): with a
    5 s window, resolution must arrive orders of magnitude sooner."""
    codec = Codec(4, 2, 4 * 256)
    data = np.random.default_rng(21).integers(
        0, 256, (4, 4, 256), dtype=np.uint8)
    # pre-warm the device program outside the timed window
    codec.encode_and_hash_batch(data, HH)
    sched = BatchScheduler(max_batch=4, max_wait=5.0)
    try:
        t0 = time.perf_counter()
        out = sched.encode_and_hash(codec, data, HH)
        elapsed = time.perf_counter() - t0
        assert out is not None
        assert elapsed < 2.0, \
            f"full bucket slept the grace window: {elapsed:.2f}s"
    finally:
        sched.close()


def test_close_with_pending_flushes_to_cpu_fallback(device_codec):
    """close() must resolve queued waiters (CPU-route: result None so
    callers fall back) and JOIN the collector — nobody hangs."""
    sched = BatchScheduler(max_batch=64, max_wait=30.0)
    codec = Codec(4, 2, 4 * 128)
    data = np.zeros((1, 4, 128), np.uint8)
    fut = sched.submit(codec, data, HH)
    assert not fut.done()          # parked in the 30 s grace window
    t0 = time.perf_counter()
    sched.close()
    assert fut.result(timeout=5) is None      # CPU fallback, no hang
    assert time.perf_counter() - t0 < 10
    assert not sched._thread.is_alive()       # collector joined
    # post-close submissions decline instantly
    assert sched.submit(codec, data, HH).result() is None


def test_mixed_verb_mixed_geometry_coalescing(device_codec):
    """Concurrent encode + decode + recover groups of two geometries:
    same-key groups coalesce into shared dispatches, every verb's
    scatter is byte-identical to its host oracle."""
    sched = BatchScheduler(max_batch=64, max_wait=0.2)
    k, m, s = 4, 2, 256
    codec = Codec(k, m, k * s)
    codec6 = Codec(6, 2, 6 * 128)
    enc_in = [np.random.default_rng(30 + i).integers(
        0, 256, (2, k, s), dtype=np.int64).astype(np.uint8)
        for i in range(2)]
    surv, mask, full = _degraded(31, 2, k, m, s, lost=(1, 4))
    surv6, mask6, full6 = _degraded(32, 2, 6, 2, 128, lost=(0,))
    lost_rows = {1, 4}
    results: dict = {}
    errs: list = []

    def run(name, fn):
        try:
            results[name] = fn()
        except Exception as e:  # noqa: BLE001
            errs.append((name, e))

    jobs = {
        "enc0": lambda: sched.encode_and_hash(codec, enc_in[0], HH),
        "enc1": lambda: sched.encode_and_hash(codec, enc_in[1], HH),
        "dec0": lambda: sched.submit_decode(
            codec, surv, mask, s, HH).result(30),
        "dec1": lambda: sched.submit_decode(
            codec, surv, mask, s, HH).result(30),
        "dec6": lambda: sched.submit_decode(
            codec6, surv6, mask6, 128, HH).result(30),
        "rec0": lambda: sched.submit_recover(
            codec, surv, mask, lost_rows, s, HH).result(30),
    }
    threads = [threading.Thread(target=run, args=(nm, fn))
               for nm, fn in jobs.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    sched.close()
    assert not errs, errs
    assert set(results) == set(jobs)

    # encode oracle
    for i, nm in enumerate(("enc0", "enc1")):
        full_got, _dg = results[nm]
        assert (full_got == codec.encode_batch(enc_in[i],
                                               force="numpy")).all()
    # decode oracle: missing data rows + survivor digests
    dm, used, missing = rs_matrix.missing_data_matrix(k, m, mask)
    want = np.stack([gf256.gf_matmul(np.asarray(dm, np.uint8), sv)
                     for sv in surv])
    for nm in ("dec0", "dec1"):
        out, missing_idx, sdig = results[nm]
        assert tuple(missing_idx) == missing
        assert (out == want).all()
        for col, u in enumerate(used):
            assert sdig[0, col].tobytes() == bitrot_mod.hash_shard(
                full[0, u].tobytes(), HH)
    out6, midx6, _ = results["dec6"]
    assert (out6[:, 0] == full6[:, 0]).all() and midx6 == (0,)
    # recover oracle: rebuilt rows + their fresh digests
    rout, idxs, _sdig, odig = results["rec0"]
    assert tuple(idxs) == tuple(sorted(lost_rows))
    for r, mi in enumerate(idxs):
        assert (rout[:, r] == full[:, mi]).all()
        assert odig[0, r].tobytes() == bitrot_mod.hash_shard(
            full[0, mi].tobytes(), HH)
    # the two same-key decode groups shared one fused dispatch
    st = sched.stats()["verbs"]
    assert st["decode"]["coalesced"] >= 1
    assert st["decode"]["batches"] < 3
    assert st["encode"]["batches"] >= 1
    assert st["recover"]["batches"] == 1


def test_decode_dispatch_error_fans_out_to_all_waiters(device_codec,
                                                       monkeypatch):
    """One fused decode dying must surface the SAME error to every
    waiter that coalesced into it."""
    from minio_tpu.object import codec as codec_mod
    sched = BatchScheduler(max_batch=64, max_wait=0.2)
    k, m, s = 4, 2, 128
    codec = Codec(k, m, k * s)
    surv, mask, _full = _degraded(40, 1, k, m, s, lost=(0,))

    def boom(*a, **kw):
        raise RuntimeError("decode device on fire")

    monkeypatch.setattr(codec_mod.Codec, "verify_and_decode_batch", boom)
    errs: list = []

    def one():
        try:
            sched.submit_decode(codec, surv, mask, s, HH).result(30)
        except RuntimeError as e:
            errs.append(str(e))

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    sched.close()
    assert errs == ["decode device on fire"] * 3


def test_coalesced_decode_byte_identical_to_serial_cpu(device_codec):
    """Acceptance pin: shards reconstructed through a COALESCED fused
    decode are byte-identical to the serial CPU oracle path
    (gf256 matmul per block, no batching, no device)."""
    sched = BatchScheduler(max_batch=64, max_wait=0.2)
    k, m, s = 4, 2, 192
    codec = Codec(k, m, k * s)
    outs: list = [None] * 4
    inputs = []
    for i in range(4):
        surv, mask, full = _degraded(50 + i, 2, k, m, s, lost=(2, 5))
        inputs.append((surv, mask, full))

    def run(i):
        surv, mask, _ = inputs[i]
        outs[i] = sched.submit_decode(codec, surv, mask, s, HH
                                      ).result(30)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    sched.close()
    for i, (surv, mask, full) in enumerate(inputs):
        assert outs[i] is not None
        out, missing_idx, _sdig = outs[i]
        dm, _used, missing = rs_matrix.missing_data_matrix(k, m, mask)
        assert tuple(missing_idx) == missing
        # serial CPU oracle: one host matmul per block
        for bi in range(surv.shape[0]):
            want = gf256.gf_matmul(np.asarray(dm, np.uint8), surv[bi])
            assert out[bi].tobytes() == want.tobytes()
            for r, mi in enumerate(missing):
                assert (out[bi, r] == full[bi, mi]).all()
    assert sched.coalesced >= 1       # they actually shared dispatches


def test_sched_totals_exposed_as_prometheus_counters(device_codec):
    """minio_tpu_sched_batches_total / _coalesced_total are monotonic
    totals — they must expose as TYPE counter (rate()-able), labelled
    by verb, not as collector-set gauges."""
    from minio_tpu.utils import telemetry
    sched = BatchScheduler(max_batch=64, max_wait=0.05)
    codec = Codec(4, 2, 4 * 256)
    data = np.random.default_rng(60).integers(
        0, 256, (2, 4, 256), dtype=np.uint8)
    assert sched.encode_and_hash(codec, data, HH) is not None
    sched.close()
    text = telemetry.REGISTRY.render()
    assert "# TYPE minio_tpu_sched_batches_total counter" in text
    assert "# TYPE minio_tpu_sched_coalesced_total counter" in text
    assert 'minio_tpu_sched_batches_total{verb="encode"}' in text
    # occupancy stays a gauge (instantaneous, per-verb labelled)
    assert "# TYPE minio_tpu_sched_batch_occupancy_groups gauge" in text
