"""Cross-request batch scheduler: coalescing, correctness of scatter,
failure propagation, buffer pool back-pressure, admission budget."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from minio_tpu import bitrot as bitrot_mod
from minio_tpu.object.codec import Codec
from minio_tpu.parallel.bpool import BytePool
from minio_tpu.parallel.scheduler import BatchScheduler, requests_budget

HH = bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S


@pytest.fixture()
def device_codec(monkeypatch):
    """Force the codec's device route (runs on the CPU jax backend)."""
    from minio_tpu.object import codec as codec_mod
    monkeypatch.setattr(codec_mod, "_device_is_tpu", lambda: True)
    monkeypatch.setattr(codec_mod, "DEVICE_MIN_BYTES", 0)
    return codec_mod


def test_scheduler_coalesces_concurrent_streams(device_codec):
    sched = BatchScheduler(max_batch=64, max_wait=0.05)
    codec = Codec(4, 2, 4 * 512)
    rng = np.random.default_rng(0)
    inputs = [rng.integers(0, 256, (2, 4, 512), dtype=np.uint8)
              for _ in range(6)]
    outs: list = [None] * len(inputs)

    def run(i):
        outs[i] = sched.encode_and_hash(codec, inputs[i], HH)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(inputs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)

    for i, out in enumerate(outs):
        assert out is not None
        full, digests = out
        want = codec.encode_batch(inputs[i], force="numpy")
        assert (full == want).all()
        want_dg = bitrot_mod.hash_shards_batch(
            want.reshape(-1, 512), HH).reshape(2, 6, 32)
        assert (digests == want_dg).all()
    # at least some requests shared a dispatch
    assert sched.batches < len(inputs)
    assert sched.coalesced > 0
    sched.close()


def test_scheduler_respects_max_batch(device_codec):
    sched = BatchScheduler(max_batch=3, max_wait=0.05)
    codec = Codec(4, 2, 4 * 256)
    rng = np.random.default_rng(1)
    inputs = [rng.integers(0, 256, (2, 4, 256), dtype=np.uint8)
              for _ in range(4)]            # 8 blocks > max_batch 3
    outs: list = [None] * 4
    threads = [threading.Thread(
        target=lambda i=i: outs.__setitem__(
            i, sched.encode_and_hash(codec, inputs[i], HH)))
        for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for i in range(4):
        full, _ = outs[i]
        assert (full == codec.encode_batch(inputs[i],
                                           force="numpy")).all()
    sched.close()


def test_scheduler_declines_unsupported_algo():
    sched = BatchScheduler()
    codec = Codec(4, 2, 4 * 128)
    data = np.zeros((1, 4, 128), np.uint8)
    assert sched.encode_and_hash(
        codec, data, bitrot_mod.BitrotAlgorithm.BLAKE2B512) is None
    sched.close()


def test_scheduler_propagates_errors(device_codec, monkeypatch):
    sched = BatchScheduler(max_wait=0.01)
    codec = Codec(4, 2, 4 * 128)

    def boom(*a, **k):
        raise RuntimeError("device on fire")

    from minio_tpu.object import codec as codec_mod
    monkeypatch.setattr(codec_mod.Codec, "encode_and_hash_batch", boom)
    data = np.zeros((1, 4, 128), np.uint8)
    with pytest.raises(RuntimeError):
        sched.encode_and_hash(codec, data, HH)
    sched.close()


def test_bytepool_backpressure():
    from minio_tpu.parallel.bpool import BytePoolExhausted
    pool = BytePool(1024, 2)
    a, b = pool.get(), pool.get()
    with pytest.raises(BytePoolExhausted):
        pool.get(timeout=0.05)
    assert pool.exhausted == 1 and pool.waits >= 1
    pool.put(a)
    c = pool.get(timeout=1.0)
    assert len(c) == 1024
    with pytest.raises(ValueError):
        pool.put(bytearray(5))  # foreign width: rejected loudly
    pool.put(b)
    pool.put(c)


def test_scheduler_submit_future_nonblocking():
    """submit() must return immediately; a declined submission resolves
    to None (the caller's CPU fallback) without waiting."""
    sched = BatchScheduler()
    codec = Codec(4, 2, 4 * 128)
    data = np.zeros((1, 4, 128), np.uint8)
    fut = sched.submit(codec, data,
                       bitrot_mod.BitrotAlgorithm.BLAKE2B512)
    assert fut.done() and fut.result() is None
    sched.close()


def test_scheduler_submit_resolves_on_device_route(device_codec):
    sched = BatchScheduler(max_batch=16, max_wait=0.01)
    codec = Codec(4, 2, 4 * 256)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (2, 4, 256), dtype=np.uint8)
    fut = sched.submit(codec, data, HH)
    out = fut.result(timeout=30)
    assert out is not None
    full, _dg = out
    assert (full == codec.encode_batch(data, force="numpy")).all()
    assert fut.done()
    sched.close()


def test_requests_budget_formula():
    n = requests_budget(1 << 22, 16)
    assert n >= 8
    # bigger blocks -> fewer admitted requests
    assert requests_budget(1 << 26, 16) <= n

def test_scheduler_no_head_of_line_across_geometries(device_codec):
    """Mixed geometries must dispatch in the SAME collector wakeup —
    one bucket per loop iteration serialized 4+2 traffic behind 12+4
    grace windows (VERDICT r2 weak #5)."""
    import time
    sched = BatchScheduler(max_batch=64, max_wait=0.4)
    rng = np.random.default_rng(3)
    geos = [(4, 2, 512), (6, 2, 256), (8, 4, 128)]
    outs = {}
    errs = []

    def run(gi, k, m, s):
        codec = Codec(k, m, k * s)
        data = rng.integers(0, 256, (2, k, s), dtype=np.int64
                            ).astype(np.uint8)
        try:
            outs[gi] = sched.encode_and_hash(codec, data, HH)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    # pre-warm: compile each geometry's device program outside the
    # timed window (first dispatch costs an XLA compile)
    for k, m, s in geos:
        Codec(k, m, k * s).encode_and_hash_batch(
            np.zeros((2, k, s), np.uint8), HH)

    t0 = time.perf_counter()
    ts = [threading.Thread(target=run, args=(gi, *geo))
          for gi, geo in enumerate(geos)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    elapsed = time.perf_counter() - t0
    sched.close()
    assert not errs and len(outs) == len(geos)
    assert all(v is not None for v in outs.values())
    # pre-fix: bucket N waits ~N grace windows (>= 0.8 s for the third);
    # post-fix: all drain in one wakeup (~0.4 s + dispatch)
    assert elapsed < 0.4 * len(geos) - 0.05, \
        f"geometry buckets serialized: {elapsed:.2f}s"
