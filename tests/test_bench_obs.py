"""CI smoke for bench.py --ab-obs: the observability-plane A/B must
run end-to-end inside the tier-1 budget, emit JSON-serializable
results, and report all three phases — federated-scrape merge latency
vs node count, trace-follow overhead on foreground PUT p99, and
dispatch-attribution on/off overhead (telemetry_overhead_x)."""

from __future__ import annotations

import json

import bench


def test_obs_ab_smoke():
    out = bench.bench_obs_ab(streams=2, size=1 << 18, drives=6,
                             parity=2, block=1 << 16,
                             node_counts=(1, 2), put_rounds=2,
                             attrib_reps=3)
    json.dumps(out)                       # BENCH-compatible payload
    # phase 1: merge latency points per node count + the single-node
    # HTTP scrape floor (the real federated path is timed against a
    # live 2-node cluster in tests/test_obs.py)
    pts = out["cluster_scrape"]["points"]
    assert [p["nodes"] for p in pts] == [1, 2]
    for p in pts:
        assert p["merge_ms"] >= 0 and p["output_bytes"] > 0
    assert out["cluster_scrape"]["local_scrape_ms"] > 0
    assert out["cluster_scrape"]["local_scrape_bytes"] > 0
    # phase 2: follow subscriber consumed the foreground's records and
    # the overhead ratio is a sane positive number
    tf = out["trace_follow"]
    assert tf["entries_consumed"] >= 1
    assert tf["baseline"]["p99_ms"] > 0
    assert tf["put_p99_overhead_x"] > 0
    # phase 3: both attribution modes dispatched and the ratio exists
    at = out["attrib"]
    assert at["dispatch_ms_attrib_on"] > 0
    assert at["dispatch_ms_attrib_off"] > 0
    assert at["telemetry_overhead_x"] > 0
