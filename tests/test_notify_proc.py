"""Notification plane over REAL server processes.

Two pins the in-process suite cannot give:

* **single deliverer per bucket** — on a 2-node cluster only the
  bucket's rendezvous owner POSTs to the webhook, wherever the
  mutation landed (the non-owner forwards over the peer control
  plane): every key arrives exactly once, no double-fire, no loss;
* **kill/replay at ``notify.queue.persist``** — a process armed to
  die right after an event record lands in the durable per-target
  queue (before its delivery attempt) is killed by its own crashpoint;
  the restarted process redrives EXACTLY that entry at boot
  (at-least-once across process death, never lost).
"""

from __future__ import annotations

import http.server
import json
import threading
import time

import pytest

from tests.harness.proc import (CRASH_EXIT_CODE, ProcNode, free_port,
                                make_cluster)

pytestmark = pytest.mark.slow

BUCKET = "evt"


class _Receiver:
    """Webhook sink: one local HTTP server collecting event records."""

    def __init__(self):
        self.port = free_port()
        self.records: list[dict] = []
        self._cond = threading.Condition()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                with outer._cond:
                    outer.records.append(json.loads(body))
                    outer._cond.notify_all()
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self._srv = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"

    def keys(self) -> list[str]:
        with self._cond:
            return [r["Records"][0]["s3"]["object"]["key"]
                    for r in self.records]

    def wait_for(self, n: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self.records) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return False
            return True

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


def _configure(node: ProcNode, arn: str, bucket: str = BUCKET) -> None:
    xml = ("<NotificationConfiguration><QueueConfiguration>"
           f"<Queue>{arn}</Queue>"
           "<Event>s3:ObjectCreated:*</Event>"
           "<Event>s3:ObjectRemoved:*</Event>"
           "</QueueConfiguration></NotificationConfiguration>")
    node.s3()._request("PUT", f"/{bucket}",
                       query={"notification": ""}, body=xml.encode())


def test_two_node_single_deliverer_no_loss(tmp_path):
    """Writes land on BOTH nodes; the webhook sees every key EXACTLY
    once — the rendezvous owner is the only deliverer, and the
    non-owner's forward path carries its share without duplication."""
    rx = _Receiver()
    n0, n1 = make_cluster(str(tmp_path), n_nodes=2)
    boot_errs: list = []

    def boot(n):
        try:
            n.start(timeout=120.0)
        except Exception as e:  # noqa: BLE001 — surfaced below
            boot_errs.append((n.name, e))

    try:
        threads = [threading.Thread(target=boot, args=(n,))
                   for n in (n0, n1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180.0)
        assert not boot_errs, f"cluster boot failed: {boot_errs}"
        n0.s3().make_bucket(BUCKET)
        arn = n0.admin().add_notify_target(endpoint=rx.url)
        _configure(n0, arn)

        keys = []
        for i in range(4):
            k = f"from-n0/{i}"
            n0.put(BUCKET, k, b"x" * 256)
            keys.append(k)
        for i in range(4):
            k = f"from-n1/{i}"
            n1.put(BUCKET, k, b"y" * 256)
            keys.append(k)

        assert rx.wait_for(len(keys)), \
            (sorted(rx.keys()), n0.tail_log(), n1.tail_log())
        time.sleep(1.0)                     # a double-fire would trail
        got = rx.keys()
        assert sorted(got) == sorted(keys)  # zero loss, zero dupes

        # exactly one node delivered; the other forwarded its share
        s0 = n0.admin().notify_status()["stats"]
        s1 = n1.admin().notify_status()["stats"]
        assert s0["delivered"] + s1["delivered"] == len(keys)
        assert (s0["delivered"] == 0) != (s1["delivered"] == 0), (s0, s1)
        forwarder = s1 if s0["delivered"] else s0
        assert forwarder["forwarded"] == 4
        n0.stop()
        n1.stop()
    finally:
        rx.close()
        n0.close()
        n1.close()


def test_queue_persist_crashpoint_kill_replay(tmp_path):
    """Armed at ``notify.queue.persist`` the process dies after the
    event record is durable but before its POST; the restart redrives
    it at boot — the webhook sees the pre-crash key, nothing is
    lost."""
    rx = _Receiver()
    node = ProcNode(str(tmp_path), name="n0")
    try:
        node.start()
        node.s3().make_bucket(BUCKET)
        arn = node.admin().add_notify_target(endpoint=rx.url)
        _configure(node, arn)
        node.put(BUCKET, "warm", b"w" * 128)
        assert rx.wait_for(1), node.tail_log()   # pipeline is live
        node.stop()

        node.start(crashpoint="notify.queue.persist")
        # delivery is async: the PUT itself usually commits, then the
        # worker hits the crashpoint while persisting the event
        try:
            node.put(BUCKET, "crashed", b"c" * 128)
        except OSError:
            pass
        rc = node.wait_exit(90)
        assert rc == CRASH_EXIT_CODE, (rc, node.tail_log())
        assert len(rx.records) == 1              # not delivered yet

        node.start()                             # boot-time redrive
        assert rx.wait_for(2), (rx.keys(), node.tail_log())
        assert sorted(rx.keys()) == ["crashed", "warm"]
        assert node.get(BUCKET, "crashed") == b"c" * 128
        node.stop()
    finally:
        rx.close()
        node.close()
