"""Admin API, healthcheck, metrics over the live server (reference
cmd/admin-handlers_test.go / healthcheck intents)."""

from __future__ import annotations

import hashlib
import http.client
import json
import time
import urllib.parse

import pytest

from minio_tpu.iam import IAMSys
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.admin import mount_admin
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server

CREDS = Credentials("admintestkey", "admintestsecret1")
REGION = "us-east-1"


class Client:
    def __init__(self, port, creds=CREDS):
        self.port, self.creds = port, creds

    def request(self, method, path, query=None, body=b"", sign=True,
                headers=None):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {"host": f"127.0.0.1:{self.port}"}
        hdrs.update({k.lower(): v for k, v in (headers or {}).items()})
        if sign:
            payload_hash = hashlib.sha256(body).hexdigest()
            hdrs = sig.sign_v4(method, path, query, hdrs, payload_hash,
                               self.creds, REGION)
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=30)
        conn.request(method, path + (f"?{qs}" if qs else ""), body=body,
                     headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, data


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    root = tmp_path_factory.mktemp("admindrives")
    drives = [str(root / f"d{i}") for i in range(4)]
    sets = ErasureSets.from_drives(drives, set_count=1, set_drive_count=4,
                                   parity=2, block_size=1 << 16)
    iam = IAMSys(sets, root_cred=CREDS)
    srv = S3Server(sets, creds=CREDS, region=REGION, iam=iam).start()
    mount_admin(srv)
    yield srv
    srv.stop()
    sets.close()


@pytest.fixture(scope="module")
def client(server):
    return Client(server.port)


def test_health_endpoints(server):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    for sub, want in (("live", 200), ("ready", 200), ("cluster", 200),
                      ("nope", 404)):
        conn.request("GET", f"/minio/health/{sub}")
        r = conn.getresponse()
        r.read()
        assert r.status == want, sub
    conn.close()


def test_admin_requires_auth(server):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    conn.request("GET", "/minio/admin/v3/info")
    r = conn.getresponse()
    r.read()
    assert r.status == 403
    conn.close()


def test_admin_sts_requires_session_token(server):
    """Temp (STS) creds signing an admin call must present their session
    token — a leaked access/secret pair alone is not enough
    (ADVICE r2: admin _auth vs handlers.py authenticate parity)."""
    iam = server.api.iam
    temp = iam.assume_role(CREDS)
    # no X-Amz-Security-Token header: rejected
    naked = Client(server.port, creds=Credentials(
        temp.access_key, temp.secret_key))
    st, _ = naked.request("GET", "/minio/admin/v3/info")
    assert st == 403
    # wrong token: rejected
    st, _ = naked.request("GET", "/minio/admin/v3/info",
                          headers={"x-amz-security-token": "bogus"})
    assert st == 403
    # right token (root parent => implicit admin): accepted
    st, _ = naked.request(
        "GET", "/minio/admin/v3/info",
        headers={"x-amz-security-token": temp.session_token})
    assert st == 200


def test_admin_info_and_storage(client):
    st, body = client.request("GET", "/minio/admin/v3/info")
    assert st == 200
    info = json.loads(body)
    assert info["storage"]["online_disks"] == 4

    st, body = client.request("GET", "/minio/admin/v3/storageinfo")
    assert st == 200 and json.loads(body)["online_disks"] == 4


def test_admin_mrf_stats(client, server):
    """MRF heal-queue stats over the admin API + madmin SDK."""
    st, body = client.request("GET", "/minio/admin/v3/mrf")
    assert st == 200
    stats = json.loads(body)
    for key in ("pending", "queued", "healed", "failed", "dropped"):
        assert key in stats
    from minio_tpu.madmin import AdminClient
    mc = AdminClient("127.0.0.1", server.port, CREDS.access_key,
                     CREDS.secret_key)
    assert mc.mrf_status()["pending"] == stats["pending"]


def test_admin_iam_flow(client, server):
    st, _ = client.request("PUT", "/minio/admin/v3/add-user",
                           query={"accessKey": "adminmadeuser"},
                           body=json.dumps(
                               {"secretKey": "secretsecret1"}).encode())
    assert st == 200
    st, body = client.request("GET", "/minio/admin/v3/list-users")
    assert st == 200 and "adminmadeuser" in json.loads(body)["users"]

    st, _ = client.request(
        "PUT", "/minio/admin/v3/set-user-or-group-policy",
        query={"policyName": "readonly", "userOrGroup": "adminmadeuser"})
    assert st == 200
    cred = server.api.iam.get_credentials("adminmadeuser")
    assert server.api.iam.is_allowed(cred, "s3:GetObject", "b", "o")

    # a plain user may NOT call admin APIs
    user_client = Client(client.port,
                         Credentials("adminmadeuser", "secretsecret1"))
    st, _ = user_client.request("GET", "/minio/admin/v3/list-users")
    assert st == 403

    st, _ = client.request("DELETE", "/minio/admin/v3/remove-user",
                           query={"accessKey": "adminmadeuser"})
    assert st == 200
    assert server.api.iam.get_credentials("adminmadeuser") is None


def test_admin_policy_crud(client):
    pol = json.dumps({"Version": "2012-10-17", "Statement": [
        {"Effect": "Allow", "Action": ["s3:GetObject"],
         "Resource": ["arn:aws:s3:::x/*"]}]}).encode()
    st, _ = client.request("PUT", "/minio/admin/v3/add-canned-policy",
                           query={"name": "adminpol"}, body=pol)
    assert st == 200
    st, body = client.request("GET",
                              "/minio/admin/v3/list-canned-policies")
    assert st == 200 and "adminpol" in json.loads(body)["policies"]
    st, _ = client.request("DELETE",
                           "/minio/admin/v3/remove-canned-policy",
                           query={"name": "adminpol"})
    assert st == 200


def test_admin_heal_sequence(client, server):
    server.api.obj.make_bucket("healb")
    server.api.obj.put_object("healb", "o1", b"data1" * 100)
    server.api.obj.put_object("healb", "o2", b"data2" * 100)
    st, body = client.request("POST", "/minio/admin/v3/heal",
                              query={"bucket": "healb"})
    assert st == 200
    token = json.loads(body)["token"]
    deadline = time.time() + 10
    while time.time() < deadline:
        st, body = client.request("GET", "/minio/admin/v3/heal/status",
                                  query={"token": token})
        assert st == 200
        d = json.loads(body)
        if d["status"] != "running":
            break
        time.sleep(0.1)
    assert d["status"] == "done"
    assert d["items_scanned"] == 2


def test_metrics_endpoint(server):
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    conn.request("GET", "/minio/prometheus/metrics")
    r = conn.getresponse()
    text = r.read().decode()
    conn.close()
    assert r.status == 200
    assert "minio_disks_online 4" in text
    assert "minio_capacity_raw_total_bytes" in text
    # pipelined data path overlap accounting is always exported
    assert "minio_tpu_pipeline_enabled" in text
    assert "minio_tpu_pipeline_put_wall_seconds_total" in text
    assert "minio_tpu_pipeline_bpool_waits_total" in text

def test_admin_profiling(client, server):
    st, body = client.request("POST", "/minio/admin/v3/profiling/start")
    assert st == 200 and json.loads(body)["kinds"]["cpu"] == "started"
    # generate a little work, then collect the per-node zip
    client.request("GET", "/minio/admin/v3/info")
    st, body = client.request("POST", "/minio/admin/v3/profiling/stop")
    assert st == 200
    import io
    import zipfile
    with zipfile.ZipFile(io.BytesIO(body)) as zf:
        names = zf.namelist()
        assert names and names[0].startswith("profile-cpu-")
        assert "cumulative" in zf.read(names[0]).decode()  # pstats hdr
    # stop again: error
    st, _ = client.request("POST", "/minio/admin/v3/profiling/stop")
    assert st == 400


def test_madmin_client_sdk(server):
    """The typed admin SDK against the live server (pkg/madmin analog)."""
    from minio_tpu.madmin import AdminClient, AdminClientError
    mc = AdminClient("127.0.0.1", server.port, CREDS.access_key,
                     CREDS.secret_key)
    assert mc.alive()
    assert mc.server_info()["storage"]["online_disks"] == 4
    assert mc.storage_info()["online_disks"] == 4

    mc.add_user("sdkuser12345", "sdksecret12345")
    assert "sdkuser12345" in mc.list_users()
    mc.set_policy("readonly", "sdkuser12345")
    svc = mc.add_service_account("sdkuser12345")
    assert svc["accessKey"]
    mc.remove_user("sdkuser12345")
    assert "sdkuser12345" not in mc.list_users()

    pol = json.dumps({"Statement": [{"Effect": "Allow",
                                     "Action": ["s3:GetObject"],
                                     "Resource": ["*"]}]})
    mc.add_canned_policy("sdkpol", pol)
    assert "sdkpol" in mc.list_canned_policies()
    mc.remove_canned_policy("sdkpol")

    mc.set_config("scanner", interval="90s")
    assert mc.get_config()["scanner"]["interval"] == "90s"

    token = mc.heal_start()
    deadline = time.time() + 10
    while time.time() < deadline:
        st = mc.heal_status(token)
        if st["status"] != "running":
            break
        time.sleep(0.1)
    assert st["status"] == "done"
    assert "minio_disks_online" in mc.metrics_text()

    # bad creds -> typed error
    bad = AdminClient("127.0.0.1", server.port, "nope", "nopenopenope1")
    with pytest.raises(AdminClientError):
        bad.server_info()


def test_admin_service_action(client, server):
    """Service restart/stop routes validate the action and run the
    (injected) local hook after replying (VERDICT r2 item 10)."""
    import time as _time
    actions = []
    server.admin.service_action = lambda a: actions.append(a)
    st, body = client.request("POST", "/minio/admin/v3/service",
                              query={"action": "restart"})
    assert st == 200 and json.loads(body)["status"] == "success"
    deadline = _time.time() + 3
    while not actions and _time.time() < deadline:
        _time.sleep(0.05)
    assert actions == ["restart"]
    st, _ = client.request("POST", "/minio/admin/v3/service",
                           query={"action": "reboot"})
    assert st == 400


def test_admin_bucket_quota_and_remote_targets(server):
    """Quota admin CRUD + remote-target registry round-trip through the
    madmin SDK; the registered target lands in the live replication
    pool and persists in bucket metadata."""
    from minio_tpu.features.replication import ReplicationPool
    from minio_tpu.madmin import AdminClient
    mc = AdminClient("127.0.0.1", server.port, CREDS.access_key,
                     CREDS.secret_key)
    server.api.obj.make_bucket("qb")

    assert mc.get_bucket_quota("qb") == {}
    mc.set_bucket_quota("qb", 1 << 20, "hard")
    assert mc.get_bucket_quota("qb") == {"quota": 1 << 20,
                                         "type": "hard"}
    mc.set_bucket_quota("qb", 0)            # clear
    assert mc.get_bucket_quota("qb") == {}

    server.api.replication = ReplicationPool(server.api.obj,
                                             server.api.bucket_meta)
    arn = mc.set_remote_target("qb", "127.0.0.1", 9999, "destb",
                               "dak12345678", "dsk1234567890")
    assert arn.startswith("arn:minio:replication::")
    assert arn in server.api.replication.targets
    listed = mc.list_remote_targets("qb")
    assert listed[0]["arn"] == arn and listed[0]["bucket"] == "destb"
    assert "secret_key" not in listed[0]    # never leaked in listings
    # persisted in bucket metadata (visible to a fresh metadata sys)
    assert server.api.bucket_meta.get("qb").replication_targets

    mc.remove_remote_target("qb", arn)
    assert mc.list_remote_targets("qb") == []
    assert arn not in server.api.replication.targets


def test_admin_bandwidth_monitor(server):
    """Per-bucket ingress/egress rates flow into admin /bandwidth
    (reference pkg/bandwidth + admin BandwidthMonitor)."""
    from minio_tpu.madmin import AdminClient
    mc = AdminClient("127.0.0.1", server.port, CREDS.access_key,
                     CREDS.secret_key)
    c = Client(server.port)
    assert c.request("PUT", "/bwbucket")[0] == 200
    body = b"z" * 50_000
    assert c.request("PUT", "/bwbucket/o", body=body)[0] == 200
    st, got = c.request("GET", "/bwbucket/o")
    assert st == 200 and got == body

    buckets = mc.bandwidth()
    bw = buckets.get("bwbucket")
    assert bw is not None
    assert bw["rx_total"] >= len(body)
    assert bw["tx_total"] >= len(body)
    assert bw["rx_bps"] > 0 and bw["tx_bps"] > 0


def test_bandwidth_meter_window():
    from minio_tpu.utils.bandwidth import (BandwidthMonitor,
                                           merge_reports)
    m = BandwidthMonitor()
    m.record("b", "rx", 1000)
    rep = m.report()
    assert rep["b"]["rx_total"] == 1000 and rep["b"]["rx_bps"] == 100.0
    merged = merge_reports([rep, {"b": {"rx_bps": 50.0, "tx_bps": 0,
                                        "rx_total": 10, "tx_total": 0}}])
    assert merged["b"]["rx_total"] == 1010
    assert merged["b"]["rx_bps"] == 150.0


def test_admin_metacache_stats(tmp_path):
    """GET /minio/admin/v3/metacache + the madmin accessor: per-bucket
    index state, pending deltas, serve/fallback counters — and the
    {"enabled": False} form on a backend without the index."""
    from minio_tpu.madmin import AdminClient
    from minio_tpu.object.metacache import MetacacheManager
    from minio_tpu.object.server_sets import ErasureServerSets

    zz = ErasureServerSets([ErasureSets.from_drives(
        [str(tmp_path / f"mcd{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16, enable_mrf=False)], load_topology=False)
    zz.make_bucket("b")
    for i in range(3):
        zz.put_object("b", f"k{i}", b"x")
    mgr = MetacacheManager(zz, staleness_s=0.0).start()
    zz.attach_metacache(mgr)
    assert mgr.build("b")
    zz.list_objects("b", "", "", "", 10)        # one index serve
    iam = IAMSys(zz, root_cred=CREDS)
    srv = S3Server(zz, creds=CREDS, region=REGION, iam=iam).start()
    mount_admin(srv)
    cli = AdminClient("127.0.0.1", srv.port, CREDS.access_key,
                      CREDS.secret_key, region=REGION)
    try:
        st = cli.metacache_stats()
        assert st["enabled"] is True
        assert st["buckets"]["b"]["state"] == "ready"
        assert st["buckets"]["b"]["invalid"] is False
        assert st["buckets"]["b"]["names"] == 3
        assert st["serves"] >= 1 and "pending" in st
        assert "fallbacks" in st and "drops" in st
        # ?bucket= narrows to the one bucket
        narrowed = cli.metacache_stats(bucket="nope")
        assert narrowed["buckets"] == {}
        assert cli.metacache_stats(bucket="b")["buckets"].keys() == {"b"}
    finally:
        srv.stop()
        mgr.close()
        zz.close()


def test_admin_metacache_stats_disabled(client):
    """A backend without the index answers enabled=False."""
    st, body = client.request("GET", "/minio/admin/v3/metacache")
    assert st == 200
    assert json.loads(body) == {"enabled": False}


def test_admin_topology_and_rebalance(tmp_path):
    """The topology admin surface end-to-end over live HTTP + madmin:
    GET topology, suspend/resume a pool, start a decommission, poll it
    to completion, and see the rebalance metrics in the exposition."""
    from minio_tpu.madmin import AdminClient, AdminClientError
    from minio_tpu.object.server_sets import ErasureServerSets

    def zone(tag):
        return ErasureSets.from_drives(
            [str(tmp_path / f"{tag}d{i}") for i in range(4)], 1, 4, 2,
            block_size=1 << 16, enable_mrf=False)

    zz = ErasureServerSets([zone("p0"), zone("p1")])
    zz.make_bucket("b")
    for i in range(4):
        zz.server_sets[0].put_object("b", f"adm-{i}", b"m" * 500)
    iam = IAMSys(zz, root_cred=CREDS)
    srv = S3Server(zz, creds=CREDS, region=REGION, iam=iam).start()
    mount_admin(srv)
    cli = AdminClient("127.0.0.1", srv.port, CREDS.access_key,
                      CREDS.secret_key, region=REGION)
    try:
        topo = cli.topology()
        assert topo["pools"] == ["active", "active"]
        out = cli.set_pool_state(0, "suspended")
        assert out["epoch"] == 1
        assert cli.topology()["pools"][0] == "suspended"
        cli.set_pool_state(0, "active")
        with pytest.raises(AdminClientError):
            cli.start_rebalance(9)              # no such pool
        with pytest.raises(AdminClientError):
            cli.cancel_rebalance()              # nothing running
        out = cli.start_rebalance(0)
        assert out["status"] == "draining"
        deadline = time.monotonic() + 60
        st = {}
        while time.monotonic() < deadline:
            st = cli.rebalance_status()
            if st.get("rebalance", {}).get("status") == "complete":
                break
            time.sleep(0.05)
        assert st["rebalance"]["status"] == "complete", st
        assert st["rebalance"]["objects_moved"] == 4
        assert st["topology"]["pools"][0] == "draining"
        assert zz.server_sets[0].list_object_versions(
            "b", max_keys=10)[0] == []
        for i in range(4):
            _, it = zz.get_object("b", f"adm-{i}")
            assert b"".join(it) == b"m" * 500
        text = cli.metrics_text()
        assert 'minio_tpu_rebalance_objects_total{pool="0"}' in text
        assert "minio_tpu_rebalance_failed_total" in text
        # storage info surfaces per-pool states + the epoch
        info = cli.storage_info()
        assert info["zones"][0]["pool_state"] == "draining"
        assert info["topology_epoch"] >= 1
    finally:
        srv.stop()
        zz.close()
