"""CI pin for the HTTP-frontend A/B smoke: `bench.py --ab-edge-smoke`
must keep producing its shape — the edge holding ≥20× the threaded
frontend's idle keep-alive connections with NO extra threads, PUT/GET
percentiles for both transports at matched load, and the
shed-before-body probe proving every refusal is counted in
minio_tpu_requests_shed_total{reason} with zero body bytes sent —
in seconds; the gate beside tier1_diff that keeps the bench runnable."""


def test_ab_edge_smoke_shape():
    import bench
    ab = bench.bench_edge_ab(streams=(2,), size=1 << 18, rounds=2,
                             idle_conns=60, idle_ratio=20, drives=6,
                             block=1 << 16)
    assert set(ab) >= {"config", "edge", "threaded", "idle_conn_ratio_x",
                       "put_p99_edge_vs_threaded_x", "saturation_sheds"}
    # the acceptance pin: >= 20x the threaded frontend's idle conns,
    # held as sockets (no thread per connection) and still alive after
    # the load phase ran over them
    assert ab["idle_conn_ratio_x"] >= 20.0
    assert ab["edge"]["idle"]["conns"] >= 60
    # no thread PER CONNECTION: 60 held conns must not add ~60 threads.
    # A strict ==0 flakes when an unrelated lazily-started background
    # thread (engine flusher, MRF lane) races the measurement window.
    assert ab["edge"]["idle"]["threads_delta"] <= 2
    assert ab["edge"]["idle"]["alive_after_load"] is True
    assert ab["threaded"]["idle"]["alive_after_load"] is True
    for side in ("edge", "threaded"):
        for point in ab[side]["points"]:
            assert point["put"]["p99_ms"] > 0
            assert point["get"]["p99_ms"] > 0
    # every saturation shed counted, no body byte read for any of them
    sheds = ab["saturation_sheds"]
    assert sheds["refused_503"] >= 1
    assert sheds["counter_delta"].get("admission", 0) == \
        sheds["refused_503"]
    assert sheds["body_bytes_sent"] == 0
