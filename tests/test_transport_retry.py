"""Retrying transport tests: idempotent-verb retries under a per-call
deadline, offline only on true transport failures, exponential probe
backoff, clock-skew-tolerant internode tokens, and the mid-stream
disconnect -> retryable NetworkStorageError mapping."""

from __future__ import annotations

import threading
import time

import pytest

from minio_tpu.distributed import transport
from minio_tpu.distributed.storage_rpc import (
    STORAGE_RPC_PREFIX, RemoteStorage, StorageRPCServer, _RemoteStream)
from minio_tpu.distributed.transport import (
    NetworkError, RestClient, RPCError, RPCHandler, RPCServer,
    _StreamedResponse, make_token, verify_token)
from minio_tpu.storage import XLStorage, errors as serr

AK, SK = "minio", "miniosecret"


# ---------------------------------------------------------------------------
# token clock skew
# ---------------------------------------------------------------------------

def test_token_tolerates_clock_skew():
    # expired 10 s ago — within the +/-30 s window: still valid
    assert verify_token(make_token(AK, SK, ttl=-10), AK, SK)
    # expired beyond the window: rejected
    assert not verify_token(make_token(AK, SK, ttl=-45), AK, SK)
    # normal fresh token still verifies, wrong key still fails
    tok = make_token(AK, SK)
    assert verify_token(tok, AK, SK)
    assert not verify_token(tok, AK, "other")


# ---------------------------------------------------------------------------
# retry loop (no sockets: counted fake transport)
# ---------------------------------------------------------------------------

class CountingClient(RestClient):
    """RestClient whose wire layer is replaced by a scripted callable."""

    def __init__(self, script, **kw):
        kw.setdefault("timeout", 5.0)
        super().__init__("127.0.0.1", 1, "/t/v1", AK, SK, **kw)
        self.script = script
        self.attempts = 0

    def _call_once(self, verb, args, body, stream_response, body_length,
                   timeout):
        self.attempts += 1
        return self.script(self.attempts)


def test_idempotent_verb_retries_then_succeeds(monkeypatch):
    monkeypatch.setattr(transport, "RPC_RETRY_BACKOFF", 0.001)
    c = CountingClient(lambda n: b"ok" if n == 3 else (_ for _ in ()).throw(
        NetworkError("blip", conn_failure=True)))
    assert c.call("readall", idempotent=True) == b"ok"
    assert c.attempts == 3
    assert c.online                     # transient blip never went offline
    c.close()


def test_non_idempotent_verb_fails_fast(monkeypatch):
    monkeypatch.setattr(transport, "RPC_RETRY_BACKOFF", 0.001)

    def always_fail(n):
        raise NetworkError("refused", conn_failure=True)

    c = CountingClient(always_fail)
    with pytest.raises(NetworkError):
        c.call("createfile")            # mutation: never replayed
    assert c.attempts == 1
    assert not c.online                 # conn failure: offline
    c.close()


def test_conn_failure_marks_offline_after_retries(monkeypatch):
    monkeypatch.setattr(transport, "RPC_RETRY_BACKOFF", 0.001)

    def always_fail(n):
        raise NetworkError("refused", conn_failure=True)

    c = CountingClient(always_fail)
    with pytest.raises(NetworkError):
        c.call("readall", idempotent=True)
    assert c.attempts == 1 + transport.RPC_RETRIES
    assert not c.online
    c.close()


def test_protocol_failure_does_not_flip_online(monkeypatch):
    monkeypatch.setattr(transport, "RPC_RETRY_BACKOFF", 0.001)

    def garbage(n):
        raise NetworkError("bad status line", conn_failure=False)

    c = CountingClient(garbage)
    with pytest.raises(NetworkError):
        c.call("readall", idempotent=True)
    assert c.online                     # the peer answered: it is alive
    c.close()


def test_deadline_caps_all_attempts(monkeypatch):
    monkeypatch.setattr(transport, "RPC_RETRY_BACKOFF", 10.0)

    def always_fail(n):
        raise NetworkError("blip", conn_failure=True)

    c = CountingClient(always_fail)
    t0 = time.monotonic()
    with pytest.raises(NetworkError):
        # backoff (10 s) would blow the 50 ms deadline: exactly 1 attempt
        c.call("readall", idempotent=True, deadline=0.05)
    assert c.attempts == 1
    assert time.monotonic() - t0 < 1.0
    c.close()


def test_offline_host_fails_fast():
    c = CountingClient(lambda n: b"ok")
    c._online = False
    with pytest.raises(NetworkError):
        c.call("readall", idempotent=True)
    assert c.attempts == 0
    c.close()


# ---------------------------------------------------------------------------
# health probe backoff
# ---------------------------------------------------------------------------

def test_probe_brings_host_back_online(monkeypatch):
    monkeypatch.setattr(transport, "HEALTH_PROBE_INTERVAL", 0.05)
    srv = RPCServer(port=0)
    h = RPCHandler("/t/v1", AK, SK)
    srv.mount(h)
    srv.start()
    try:
        c = RestClient("127.0.0.1", srv.port, "/t/v1", AK, SK)
        c.mark_offline()
        deadline = time.monotonic() + 5
        while not c.online and time.monotonic() < deadline:
            time.sleep(0.02)
        assert c.online
        c.close()
    finally:
        srv.stop()


def test_probe_delay_grows_exponentially(monkeypatch):
    sleeps = []
    monkeypatch.setattr(transport, "HEALTH_PROBE_INTERVAL", 1.0)
    monkeypatch.setattr(transport, "HEALTH_PROBE_MAX", 8.0)

    c = RestClient("127.0.0.1", 1, "/t/v1", AK, SK)  # nothing listens

    real_sleep = time.sleep

    def fake_sleep(d):
        sleeps.append(d)
        if len(sleeps) >= 6:
            c._online = True            # stop the loop
        real_sleep(0)

    monkeypatch.setattr(transport.time, "sleep", fake_sleep)
    c._online = False
    c._probe_loop()
    # jittered exponential: each base delay in [0.75x, 1.25x] of
    # 1, 2, 4, 8 (capped at HEALTH_PROBE_MAX)
    for want, got in zip([1, 2, 4, 8, 8, 8], sleeps):
        assert 0.74 * want <= got <= 1.26 * want, (want, got)


# ---------------------------------------------------------------------------
# mid-stream disconnects
# ---------------------------------------------------------------------------

class _BrokenResp:
    def read(self, n=-1):
        raise ConnectionResetError("peer reset")


class _Conn:
    closed = False

    def close(self):
        self.closed = True


def test_streamed_response_maps_midstream_to_network_error():
    conn = _Conn()
    s = _StreamedResponse(conn, _BrokenResp())
    with pytest.raises(NetworkError):
        s.read(10)
    assert conn.closed


def test_remote_stream_maps_to_retryable_storage_error():
    class Broken:
        def read(self, n=-1):
            raise NetworkError("mid-stream: reset")

        def close(self):
            pass

    with pytest.raises(serr.NetworkStorageError):
        _RemoteStream(Broken()).read(10)


# ---------------------------------------------------------------------------
# RemoteStorage end-to-end: remote errors vs transport errors
# ---------------------------------------------------------------------------

def test_remote_rpc_error_does_not_flip_online(tmp_path):
    drive = XLStorage(str(tmp_path / "d0"))
    srv = RPCServer(port=0)
    rpc = StorageRPCServer({"/d0": drive}, AK, SK)
    srv.mount_route(STORAGE_RPC_PREFIX, rpc.handler)
    srv.start()
    try:
        rs = RemoteStorage("127.0.0.1", srv.port, "/d0", AK, SK)
        with pytest.raises(serr.StorageError):
            rs.read_all("novol", "nofile")   # remote storage error
        assert rs.is_online()                # ...but the peer is alive
        drive.make_vol("v")
        drive.write_all("v", "f", b"data")
        assert rs.read_all("v", "f") == b"data"
    finally:
        srv.stop()


def test_remote_transport_error_maps_to_network_storage_error():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()                                # nothing listens on `port`
    rs = RemoteStorage("127.0.0.1", port, "/d0", AK, SK, timeout=0.5)
    with pytest.raises(serr.NetworkStorageError):
        rs.read_all("v", "f")
    assert not rs.is_online()
    rs.close()
