"""SLO burn rates + black-box capture (ISSUE 18, tentpole layers 2+3).

Fast tier: the burn-rate algebra (multi-window baselines, breach /
clear hysteresis, min-sample gating), the incident recorder's bundle
shape / retention / debounce, and the end-to-end in-process scenario —
a seeded NaughtyDisk stall on EVERY drive makes HTTP reads slow, the
latency objective breaches, and the flight recorder captures a bundle
with the causal journal window and slow span trees.

Slow tier (real subprocesses): SIGKILL inside the journal's
segment-persist commit window (restart serves the surviving prefix,
fsck-clean), and a naughtynet partition on a 2-node cluster driving a
real SLO breach whose bundle is retrievable via the admin API from
either node after heal.
"""

from __future__ import annotations

import hashlib
import http.client
import os
import threading
import time

import pytest

from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.admin import mount_admin
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server
from minio_tpu.storage.naughty import NaughtyDisk
from minio_tpu.storage.xl_storage import XLStorage
from minio_tpu.utils import eventlog, incidents, slo, telemetry

CREDS = Credentials("inctestkey1234", "inctestsecret123")
REGION = "us-east-1"

READ_STALLS = ("read_file_stream", "read_file", "read_all")


def _totals(read=(0, 0, 0), write=(0, 0, 0)) -> dict:
    return {"read": list(read), "write": list(write)}


def _stub_engine(monkeypatch, feed: dict) -> slo.SLOEngine:
    """Fresh engine whose _collect returns whatever `feed['cls']`
    holds — the algebra tests drive cumulative totals by hand."""
    e = slo.SLOEngine()
    monkeypatch.setattr(
        e, "_collect", lambda now: slo._Totals(now, {
            c: list(v) for c, v in feed["cls"].items()}))
    return e


# ---------------------------------------------------------------------------
# burn-rate algebra
# ---------------------------------------------------------------------------

def test_api_class_membership():
    assert slo.api_class("GetObject") == "read"
    assert slo.api_class("HeadObject") == "read"
    assert slo.api_class("ListObjectsV2") == "read"
    assert slo.api_class("PutObject") == "write"
    assert slo.api_class("DeleteObject") == "write"
    assert slo.api_class("Admin") is None
    assert slo.api_class("PeerRPC") is None
    assert slo.api_class("") is None


def test_breach_and_clear_hysteresis(monkeypatch):
    """5xx spend past the threshold breaches (journal event, status
    flag); the breach clears only after burn cools to HALF the
    threshold — and the clear rides the journal too."""
    monkeypatch.setenv("MINIO_TPU_SLO_WINDOWS_S", "60")
    monkeypatch.setenv("MINIO_TPU_SLO_MIN_SAMPLES", "10")
    feed = {"cls": _totals()}
    e = _stub_engine(monkeypatch, feed)
    t0 = time.time()
    seq0 = eventlog.JOURNAL.seq
    e.evaluate_once(now=t0)

    # 100 read requests, 50 errors, inside one window: burn huge
    feed["cls"] = _totals(read=(100, 50, 0))
    st = e.evaluate_once(now=t0 + 61)
    obj = {o["objective"]: o for o in st["objectives"]}
    assert obj["read-availability"]["breached"] is True
    assert obj["read-availability"]["windows"]["60s"]["burn"] > 4
    assert obj["write-availability"]["breached"] is False
    breaches = eventlog.JOURNAL.recent(classes={"slo.breach"},
                                       since_seq=seq0)
    assert any(b["attrs"]["objective"] == "read-availability"
               for b in breaches)

    # no new traffic in the next window: burn 0 -> under half the
    # threshold -> clear (with its journal event)
    st = e.evaluate_once(now=t0 + 122)
    obj = {o["objective"]: o for o in st["objectives"]}
    assert obj["read-availability"]["breached"] is False
    clears = eventlog.JOURNAL.recent(classes={"slo.clear"},
                                     since_seq=seq0)
    assert any(c["attrs"]["objective"] == "read-availability"
               for c in clears)


def test_breach_requires_min_samples(monkeypatch):
    """Total failure of a trickle must not page: below MIN_SAMPLES in
    the window there is no breach no matter the ratio."""
    monkeypatch.setenv("MINIO_TPU_SLO_WINDOWS_S", "60")
    monkeypatch.setenv("MINIO_TPU_SLO_MIN_SAMPLES", "10")
    feed = {"cls": _totals()}
    e = _stub_engine(monkeypatch, feed)
    t0 = time.time()
    e.evaluate_once(now=t0)
    feed["cls"] = _totals(read=(5, 5, 0))       # 100% errors, 5 reqs
    st = e.evaluate_once(now=t0 + 61)
    obj = {o["objective"]: o for o in st["objectives"]}
    assert obj["read-availability"]["breached"] is False
    assert obj["read-availability"]["windows"]["60s"]["samples"] == 5


def test_half_filled_window_never_alerts(monkeypatch):
    """Until the snapshot ring spans a window there is no baseline —
    and no burn number at all (a booting node must not page)."""
    monkeypatch.setenv("MINIO_TPU_SLO_WINDOWS_S", "60")
    feed = {"cls": _totals(read=(1000, 1000, 0))}
    e = _stub_engine(monkeypatch, feed)
    t0 = time.time()
    e.evaluate_once(now=t0)
    st = e.evaluate_once(now=t0 + 10)           # only 10s of history
    obj = {o["objective"]: o for o in st["objectives"]}
    assert obj["read-availability"]["windows"] == {}
    assert obj["read-availability"]["breached"] is False


def test_latency_objective_uses_bucket_counts(monkeypatch):
    """The latency objective spends budget on over-threshold requests
    (the third totals slot) against the latency target's budget."""
    monkeypatch.setenv("MINIO_TPU_SLO_WINDOWS_S", "60")
    monkeypatch.setenv("MINIO_TPU_SLO_MIN_SAMPLES", "10")
    feed = {"cls": _totals()}
    e = _stub_engine(monkeypatch, feed)
    t0 = time.time()
    e.evaluate_once(now=t0)
    # 100 writes, none failed, 30 over the latency threshold:
    # burn = 0.3 / 0.01 = 30 >= 4 -> latency breaches, availability not
    feed["cls"] = _totals(write=(100, 0, 30))
    st = e.evaluate_once(now=t0 + 61)
    obj = {o["objective"]: o for o in st["objectives"]}
    assert obj["write-latency"]["breached"] is True
    assert obj["write-availability"]["breached"] is False


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------

def _fresh_recorder(tmp_path) -> incidents.IncidentRecorder:
    r = incidents.IncidentRecorder()
    r.attach(str(tmp_path / "incidents"))
    return r


def test_capture_bundle_shape_and_providers(tmp_path):
    r = _fresh_recorder(tmp_path)
    try:
        r.add_provider("good", lambda: {"answer": 42})
        r.add_provider("dead", lambda: 1 / 0)
        trig = eventlog.emit("net.partition", rule="both",
                             peers="x|y")
        inc_id = r.capture(trig)
        assert inc_id
        doc = r.get(inc_id)
        assert doc["trigger"]["class"] == "net.partition"
        assert doc["id"] == inc_id and doc["v"] == 1
        assert any(e["class"] == "net.partition"
                   for e in doc["events"])
        assert doc["state"]["good"] == {"answer": 42}
        assert "ZeroDivisionError" in doc["state"]["dead"]["error"]
        assert isinstance(doc["slow_spans"], list)
        assert isinstance(doc["metrics_delta"], dict)
        # capture itself is journaled
        caps = eventlog.JOURNAL.recent(classes={"incident.captured"})
        assert any(c["attrs"]["incident"] == inc_id for c in caps)
        # summaries list newest-first and carry the trigger class
        rows = [x for x in r.list() if x["id"] == inc_id]
        assert rows and rows[0]["trigger"] == "net.partition"
        # path traversal in the id never escapes the directory
        assert r.get("../" + inc_id) is None
    finally:
        r.stop()


def test_capture_retention_prunes_oldest(tmp_path, monkeypatch):
    monkeypatch.setenv("MINIO_TPU_INCIDENT_KEEP", "2")
    r = _fresh_recorder(tmp_path)
    try:
        trig = eventlog.emit("net.partition", rule="both", peers="p|q")
        ids = [r.capture(trig) for _ in range(4)]
        assert all(ids)
        kept = {x["id"] for x in r.list()}
        assert len(kept) == 2
        assert ids[-1] in kept and ids[0] not in kept
    finally:
        r.stop()


def test_trigger_loop_captures_and_debounces(tmp_path):
    """A registered trigger class landing in the journal produces a
    bundle without anyone calling capture(); an immediate repeat of
    the same class is debounced."""
    r = _fresh_recorder(tmp_path)
    try:
        def mine():
            return [x for x in r.list()
                    if x["trigger"] == "drive.probation"]

        eventlog.emit("drive.probation", drive="/inc/d0", set=0)
        deadline = time.monotonic() + 8
        while not mine() and time.monotonic() < deadline:
            time.sleep(0.1)
        assert mine(), "trigger event never produced a bundle"
        n = len(mine())
        eventlog.emit("drive.probation", drive="/inc/d0", set=0)
        time.sleep(1.0)
        assert len(mine()) == n, "debounce window did not hold"
        # a non-trigger class never captures
        eventlog.emit("net.heal", peers="p|q")
        time.sleep(0.5)
        assert not any(x["trigger"] == "net.heal" for x in r.list())
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# end to end in-process: stalled drives -> slow reads -> breach ->
# black-box bundle
# ---------------------------------------------------------------------------

def _signed_request(port, method, path, body=b""):
    hdrs = sig.sign_v4(method, path, {},
                       {"host": f"127.0.0.1:{port}"},
                       hashlib.sha256(body).hexdigest(), CREDS, REGION)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path, body=body, headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_stalled_reads_breach_slo_and_capture_bundle(tmp_path,
                                                     monkeypatch):
    """The incident plane end to end: EVERY drive stalls reads past
    the latency threshold (hedging cannot dodge an all-gray set), HTTP
    GETs go slow, the read-latency burn rate trips, the slo.breach
    event triggers a black-box bundle holding the journal window and
    at least one slow span tree."""
    monkeypatch.setenv("MINIO_TPU_SLO_WINDOWS_S", "60")
    monkeypatch.setenv("MINIO_TPU_SLO_MIN_SAMPLES", "5")
    drives: list = []
    naughties: list = []
    for j in range(4):
        nd = NaughtyDisk(XLStorage(str(tmp_path / f"d{j}")),
                         enabled=False)
        naughties.append(nd)
        drives.append(nd)
    sets = ErasureSets.from_storage(drives, set_count=1,
                                    set_drive_count=4, parity=1,
                                    block_size=1 << 16)
    srv = S3Server(sets, creds=CREDS, region=REGION).start()
    mount_admin(srv)
    was_spans = (telemetry.SPANS.slow_s, telemetry.SPANS.sample)
    telemetry.SPANS.configure(sample=1.0)
    engine = slo.SLOEngine()
    rec = incidents.IncidentRecorder()
    rec.attach(str(tmp_path / "incidents"))
    rec.add_provider("slo", engine.status)
    try:
        assert _signed_request(srv.port, "PUT", "/slostall")[0] == 200
        assert _signed_request(srv.port, "PUT", "/slostall/obj",
                               body=b"s" * 65536)[0] == 200
        t0 = time.time()
        engine.evaluate_once(now=t0)
        for nd in naughties:
            nd.stall_verbs = {v: 0.4 for v in READ_STALLS}
            nd.arm()
        for _ in range(6):
            st, body = _signed_request(srv.port, "GET",
                                       "/slostall/obj")
            assert st == 200 and len(body) == 65536
        for nd in naughties:
            nd.disarm()
            nd.stall_verbs = {}
        st = engine.evaluate_once(now=t0 + 61)
        obj = {o["objective"]: o for o in st["objectives"]}
        assert obj["read-latency"]["breached"] is True, obj
        # the recorder heard the breach event and captured
        deadline = time.monotonic() + 8
        bundle = None
        while bundle is None and time.monotonic() < deadline:
            for row in rec.list():
                if row["trigger"] == "slo.breach":
                    bundle = rec.get(row["id"])
                    break
            time.sleep(0.1)
        assert bundle, "breach never produced a bundle"
        assert bundle["trigger"]["attrs"]["objective"] == \
            "read-latency"
        assert bundle["events"], "bundle lost the journal window"
        assert bundle["slow_spans"], "bundle has no slow span trees"
        assert bundle["state"]["slo"]["objectives"]
    finally:
        rec.stop()
        telemetry.SPANS.configure(*was_spans)
        srv.stop()
        sets.close()


# ---------------------------------------------------------------------------
# real subprocesses: the crash window and the 2-node acceptance run
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_mid_segment_persist_serves_prefix(tmp_path):
    """Arm the eventlog.persist.segment crashpoint: the process dies
    inside a segment's commit window. Restart replays the SURVIVING
    segment prefix (earlier fsck.complete events are still served by
    /events) and the store itself is fsck-clean."""
    from tests.harness.proc import CRASH_EXIT_CODE, ProcNode
    from minio_tpu.madmin import AdminClientError

    node = ProcNode(str(tmp_path), n_drives=4, name="evseg")
    env = {
        "MINIO_TPU_EVENTLOG_SEGMENT_EVENTS": "1",   # flush per emit
        "MINIO_TPU_EVENTLOG_FLUSH_S": "120",        # cadence off
    }
    node.start(crashpoint="eventlog.persist.segment:4",
               extra_env=env)
    try:
        node.s3().make_bucket("evb")
        node.put("evb", "obj", b"x" * 4096)
        # each fsck emits fsck.complete -> kicks a flush -> one
        # crashpoint hit; the 4th flush dies BEFORE the rename commit
        for _ in range(8):
            if not node.alive():
                break
            try:
                node.fsck(repair=False)
            except (OSError, AdminClientError,
                    http.client.HTTPException):
                pass
            time.sleep(0.3)
        assert node.wait_exit(30) == CRASH_EXIT_CODE
        node.start(extra_env=env)          # no crashpoint this time
        survived = node.admin().events(classes="fsck.complete")
        assert survived, ("restart serves no pre-crash journal "
                          "prefix:\n" + node.tail_log())
        # ... and the torn flush hurt only the journal tail, not data
        rep = node.fsck(repair=True)
        assert rep["unrepaired"] == 0, rep
        assert node.get("evb", "obj") == b"x" * 4096
    finally:
        node.close()


@pytest.mark.slow
def test_partition_breach_capture_retrievable_from_either_node(
        tmp_path):
    """The ISSUE acceptance run on real subprocesses: a naughtynet
    partition starves write quorum on a 2-node cluster, failed PUTs
    burn the write-availability budget, slo.breach triggers a black-
    box bundle holding journal events from >= 3 subsystems, the
    breached objective, and >= 1 slow span tree — and after heal the
    bundle is retrievable via the admin API from EITHER node."""
    from tests.harness.proc import heal, make_cluster, partition
    from minio_tpu.madmin import AdminClientError
    from minio_tpu.utils.s3client import S3ClientError

    env = {
        "MINIO_TPU_NAUGHTYNET": "on",
        "MINIO_TPU_SLO_EVAL_S": "0.5",
        "MINIO_TPU_SLO_WINDOWS_S": "4",
        "MINIO_TPU_SLO_MIN_SAMPLES": "6",
        "MINIO_TPU_INCIDENT_DEBOUNCE_S": "1",
        "MINIO_TPU_EVENTLOG_FLUSH_S": "0.5",
        "MINIO_TPU_TRACE_SAMPLE": "1.0",
    }
    nodes = make_cluster(str(tmp_path), n_nodes=2, n_drives=4,
                         parity=2)
    a, b = nodes
    boot_errs: list = []

    def boot(n):
        try:
            n.start(extra_env=env, timeout=120.0)
        except Exception as e:  # noqa: BLE001 — surfaced below
            boot_errs.append((n.name, e))

    threads = [threading.Thread(target=boot, args=(n,))
               for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180.0)
    assert not boot_errs, f"cluster boot failed: {boot_errs}"
    try:
        a.s3().make_bucket("slob")
        a.put("slob", "warm", b"w" * 4096)
        # a pre-partition fsck seeds a third subsystem's events into
        # the journal window the bundle will carry
        a.fsck(repair=False)

        partition(a, b)
        # failed PUTs: remote shards unreachable -> lost write quorum.
        # Concurrent, with a client timeout ABOVE the server's 30s
        # lock-acquire deadline: every request completes as a server-
        # counted 5xx, and the whole burst lands inside one SLO
        # window instead of smearing 12 x 30s sequentially.
        from minio_tpu.s3.credentials import Credentials
        from minio_tpu.utils.s3client import S3Client
        from tests.harness.proc import ACCESS_KEY, SECRET_KEY
        failures = [0]
        fail_mu = threading.Lock()

        def try_put(i):
            cl = S3Client("127.0.0.1", a.port,
                          Credentials(ACCESS_KEY, SECRET_KEY),
                          timeout=60.0)
            try:
                cl.put_object("slob", f"k{i}", b"f" * 4096)
            except (S3ClientError, OSError,
                    http.client.HTTPException):
                with fail_mu:
                    failures[0] += 1

        putters = [threading.Thread(target=try_put, args=(i,))
                   for i in range(12)]
        for t in putters:
            t.start()
        for t in putters:
            t.join(90.0)
        assert failures[0] >= 6, "partition never failed writes"

        # the subprocess SLO engine breaches, its recorder captures
        inc_id = None
        deadline = time.monotonic() + 60
        while inc_id is None and time.monotonic() < deadline:
            try:
                for row in a.admin().incidents():
                    if row["trigger"] == "slo.breach":
                        inc_id = row["id"]
                        break
            except (OSError, AdminClientError):
                pass
            time.sleep(0.5)
        assert inc_id, ("no slo.breach bundle captured:\n"
                        + a.tail_log())

        bundle = a.admin().incident(inc_id)
        assert bundle["trigger"]["class"] == "slo.breach"
        assert bundle["trigger"]["attrs"]["objective"].startswith(
            "write-")
        subs = {e["sub"] for e in bundle["events"]}
        assert len(subs) >= 3, subs
        assert {"net", "slo"} <= subs, subs
        assert bundle["slow_spans"], "no slow span trees captured"

        heal(a, b)
        deadline = time.monotonic() + 30
        via_b = None
        while via_b is None and time.monotonic() < deadline:
            try:
                doc = b.admin().incident(inc_id)
                if doc and doc.get("id") == inc_id:
                    via_b = doc
            except (OSError, AdminClientError):
                pass
            time.sleep(0.5)
        assert via_b, ("bundle not retrievable from the peer after "
                       "heal:\n" + b.tail_log())
        assert via_b["trigger"]["class"] == "slo.breach"
    finally:
        for n in nodes:
            n.close()
