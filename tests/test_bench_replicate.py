"""CI pin for the replication A/B smoke: `bench.py
--ab-replicate-smoke` must keep producing its shape (baseline +
during-resync percentiles, resync completion, the lag histogram) in
seconds — the gate beside tier1_diff that keeps the bench runnable."""

def test_ab_replicate_smoke_shape():
    import bench
    ab = bench.bench_replicate_ab(streams=2, size=1 << 18, drives=6,
                                  preload=6, block=1 << 16)
    assert set(ab) >= {"config", "baseline", "during_resync",
                       "resync_final", "plane_final",
                       "put_p99_degradation_x", "lag_histogram"}
    for phase in ("baseline", "during_resync"):
        assert ab[phase]["p50_ms"] > 0 and ab[phase]["p99_ms"] > 0
    assert ab["resync_final"]["status"] == "complete"
    assert ab["resync_final"]["keys_scanned"] >= 6
    assert ab["plane_final"]["pending"] == 0
    assert ab["put_p99_degradation_x"] > 0
    assert ab["lag_histogram"].get("count", 0) >= 1
