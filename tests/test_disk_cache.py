"""Disk-cache depth (VERDICT r4 #4): range entries, streamed fills
with bounded memory, incremental cache-side bitrot, watermark LRU —
plus the erasure-path hot-object read cache of the device scan plane:
the decode-counter hit proof, access-frequency admission, namespace-
feed eviction for every mutation verb, and the cache/tiering interplay
(a transitioned stub evicts AND can never serve past the
InvalidObjectState gate). Complements tests/test_gateway_cache.py's
basic hit/invalidation coverage."""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time

import pytest

from minio_tpu.object.cache import AccessTracker, CacheObjects
from minio_tpu.object.fs import FSObjects

BLOCK = 1 << 14                       # small cache block for tests


@pytest.fixture()
def stack(tmp_path):
    fs = FSObjects(str(tmp_path / "origin"))
    fs.make_bucket("b")
    cache = CacheObjects(fs, str(tmp_path / "cache"),
                         budget_bytes=64 << 20, block_size=BLOCK)
    return fs, cache


def test_ranged_miss_caches_aligned_span(stack):
    fs, cache = stack
    payload = os.urandom(BLOCK * 6 + 777)
    fs.put_object("b", "o", payload)

    # ranged miss: only the covering aligned span lands in the cache
    _, s = cache.get_object("b", "o", offset=BLOCK + 100, length=300)
    assert b"".join(s) == payload[BLOCK + 100:BLOCK + 400]
    assert cache.misses == 1
    meta = cache._load_entry("b", "o")
    assert meta["ranges"] == [
        {"start": BLOCK, "end": 2 * BLOCK, "file": f"r{BLOCK}"}]

    # a hit fully inside the cached span serves from cache
    _, s = cache.get_object("b", "o", offset=BLOCK + 500, length=100)
    assert b"".join(s) == payload[BLOCK + 500:BLOCK + 600]
    assert cache.hits == 1

    # a span NOT covered is a miss and caches its own range
    _, s = cache.get_object("b", "o", offset=4 * BLOCK, length=BLOCK)
    assert b"".join(s) == payload[4 * BLOCK:5 * BLOCK]
    assert cache.misses == 2
    meta = cache._load_entry("b", "o")
    assert {r["start"] for r in meta["ranges"]} == {BLOCK, 4 * BLOCK}

    # a request spanning cached+uncached blocks is a miss (no single
    # covering span) and fills its full aligned span
    _, s = cache.get_object("b", "o", offset=BLOCK, length=3 * BLOCK)
    assert b"".join(s) == payload[BLOCK:4 * BLOCK]
    assert cache.misses == 3
    _, s = cache.get_object("b", "o", offset=BLOCK, length=3 * BLOCK)
    assert b"".join(s) == payload[BLOCK:4 * BLOCK]
    assert cache.hits == 2

    # the tail range (unaligned object end) caches and serves
    _, s = cache.get_object("b", "o", offset=BLOCK * 6, length=777)
    assert b"".join(s) == payload[BLOCK * 6:]
    _, s = cache.get_object("b", "o", offset=BLOCK * 6 + 700, length=77)
    assert b"".join(s) == payload[BLOCK * 6 + 700:]
    assert cache.hits == 3


def test_whole_object_entry_serves_any_range(stack):
    fs, cache = stack
    payload = os.urandom(3 * BLOCK + 5)
    fs.put_object("b", "w", payload)
    _, s = cache.get_object("b", "w")
    assert b"".join(s) == payload
    for off, ln in [(0, 10), (BLOCK - 1, 2), (2 * BLOCK, BLOCK + 5),
                    (0, len(payload))]:
        _, s = cache.get_object("b", "w", offset=off, length=ln)
        assert b"".join(s) == payload[off:off + ln], (off, ln)
    assert cache.misses == 1 and cache.hits == 4


def test_corrupt_block_detected_mid_stream_and_evicted(stack):
    """Incremental verification: blocks before the corruption stream
    verified; the corrupt block is never served — the rest comes from
    the backend and the bad file is evicted."""
    fs, cache = stack
    payload = os.urandom(5 * BLOCK)
    fs.put_object("b", "c", payload)
    b"".join(cache.get_object("b", "c")[1])          # populate

    d = cache._entry_dir("b", "c")
    # corrupt the PAYLOAD of the third frame (frame = 32-digest+block)
    with open(os.path.join(d, "data"), "r+b") as f:
        f.seek(2 * (32 + BLOCK) + 32 + 7)
        f.write(b"\xff")
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload                    # bytes all correct
    # the corrupt file is gone; next read is a clean miss that refills
    meta = cache._load_entry("b", "c")
    assert meta["ranges"] == []
    before = cache.misses
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload
    assert cache.misses == before + 1
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload                    # refilled → hit


def test_partial_fill_never_committed(stack):
    """A client that hangs up mid-download must not leave a partial
    cache entry that later reads would trust."""
    fs, cache = stack
    payload = os.urandom(6 * BLOCK)
    fs.put_object("b", "p", payload)
    _, s = cache.get_object("b", "p")
    next(s)                                          # one block only
    s.close()                                        # client hangup
    meta = cache._load_entry("b", "p")
    assert (meta or {}).get("ranges", []) == []
    d = cache._entry_dir("b", "p")
    leftovers = [f for f in os.listdir(d) if f != "meta.json"]
    assert leftovers == []
    # and the object still reads fine (miss -> refill)
    _, s = cache.get_object("b", "p")
    assert b"".join(s) == payload


def test_watermark_lru_prefers_cold_entries(tmp_path):
    fs = FSObjects(str(tmp_path / "o"))
    fs.make_bucket("b")
    cache = CacheObjects(fs, str(tmp_path / "c"),
                         budget_bytes=200_000, block_size=BLOCK)
    for i in range(12):
        fs.put_object("b", f"k{i}", bytes(BLOCK))
        b"".join(cache.get_object("b", f"k{i}")[1])
        time.sleep(0.01)
    # keep k0 hot: its clock refreshes on every hit
    b"".join(cache.get_object("b", "k0")[1])
    time.sleep(0.01)
    for i in range(12, 16):
        fs.put_object("b", f"k{i}", bytes(BLOCK))
        b"".join(cache.get_object("b", f"k{i}")[1])
    assert cache._usage() <= 200_000 * 0.95
    # the hot entry survived the purge; a cold early one did not
    hits_before = cache.hits
    b"".join(cache.get_object("b", "k0")[1])
    assert cache.hits == hits_before + 1
    misses_before = cache.misses
    b"".join(cache.get_object("b", "k1")[1])
    assert cache.misses == misses_before + 1


def test_oversized_object_reads_through(stack):
    fs, cache = stack
    cache.budget = 1 << 20                # max entry = 100 KiB
    payload = os.urandom(300_000)
    fs.put_object("b", "huge", payload)
    _, s = cache.get_object("b", "huge")
    assert b"".join(s) == payload
    meta = cache._load_entry("b", "huge")
    assert meta is None or meta.get("ranges", []) == []
    # but a small RANGE of the huge object still caches
    _, s = cache.get_object("b", "huge", offset=BLOCK, length=100)
    assert b"".join(s) == payload[BLOCK:BLOCK + 100]
    meta = cache._load_entry("b", "huge")
    assert meta and len(meta["ranges"]) == 1


_RSS_CHILD = r"""
import os, resource, sys
sys.path.insert(0, os.environ["REPO"])
from minio_tpu.object.cache import CacheObjects

SIZE = 256 << 20
CHUNK = 1 << 20

class FakeInfo:
    etag = "fixed"; size = SIZE; content_type = "application/x"
    user_defined = {}; mod_time = 0.0

class FakeInner:
    def get_object_info(self, b, k, opts=None):
        return FakeInfo()
    def get_object(self, b, k, offset=0, length=-1, opts=None):
        n = SIZE - offset if length < 0 else length
        def gen():
            left = n
            blob = b"\xab" * CHUNK
            while left > 0:
                yield blob[:min(CHUNK, left)]
                left -= min(CHUNK, left)
        return FakeInfo(), gen()

cache = CacheObjects(FakeInner(), os.environ["CACHEDIR"],
                     budget_bytes=SIZE * 20)
# a tiny warm-up fill loads every code path (incl. the hash kernels),
# so the big fill's delta over this high-water is pure buffering
_, warm = cache.get_object("b", "big", offset=0, length=1 << 20)
for _chunk in warm:
    pass
base_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024

_, stream = cache.get_object("b", "big")
total = 0
for chunk in stream:
    total += len(chunk)
assert total == SIZE, total
meta = cache._load_entry("b", "big")
assert any(r["start"] == 0 and r["end"] == SIZE
           for r in meta["ranges"]), "fill did not commit"
rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
print(f"rss_mb={rss_mb:.0f} base_mb={base_mb:.0f}")
assert rss_mb - base_mb < 100, \
    f"streamed 256 MiB fill grew RSS by {rss_mb - base_mb:.0f} MB"
"""


def test_fill_memory_is_bounded(tmp_path):
    """A 256 MiB fill must stream at constant memory (the r4 cache
    buffered the entire object in RAM — VERDICT weak: cache.py:146)."""
    cachedir = "/dev/shm/mt-cache-test" if os.path.isdir("/dev/shm") \
        else str(tmp_path / "c")
    env = dict(os.environ,
               REPO=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))),
               CACHEDIR=cachedir)
    try:
        proc = subprocess.run([sys.executable, "-c", _RSS_CHILD],
                              env=env, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "rss_mb=" in proc.stdout
    finally:
        import shutil
        shutil.rmtree(cachedir, ignore_errors=True)


# ---------------------------------------------------------------------------
# erasure-path hot-object read cache (device scan plane)
# ---------------------------------------------------------------------------

def _erasure_stack(tmp_path, **cache_kw):
    from minio_tpu.object.server_sets import ErasureServerSets
    from minio_tpu.object.sets import ErasureSets
    zz = ErasureServerSets([ErasureSets.from_drives(
        [str(tmp_path / f"ecd{i}") for i in range(4)], 1, 4, 2,
        block_size=1 << 16, enable_mrf=False)], load_topology=False)
    zz.make_bucket("b")
    cache_kw.setdefault("budget_bytes", 64 << 20)
    cache_kw.setdefault("block_size", BLOCK)
    cache = CacheObjects(zz, str(tmp_path / "cache"), **cache_kw)
    # cluster-boot wiring: invalidation rides the namespace feed
    zz.attach_read_cache(cache)
    return zz, cache


def _decode_streams() -> float:
    from minio_tpu.utils import telemetry
    return telemetry.REGISTRY.counter(
        "minio_tpu_erasure_get_streams_total",
        "Object read streams served through the erasure "
        "shard-read/verify/decode path").value()


def test_cache_hit_serves_without_erasure_decode(tmp_path):
    """THE acceptance proof: a cache hit streams the framed local
    entry — the erasure shard-read/verify/decode path must not run
    (flat minio_tpu_erasure_get_streams_total delta)."""
    zz, cache = _erasure_stack(tmp_path)
    payload = os.urandom(3 * BLOCK + 17)
    zz.put_object("b", "hot", payload)
    # miss + fill: the backend read pays one decode stream
    before = _decode_streams()
    _, s = cache.get_object("b", "hot")
    assert b"".join(s) == payload
    assert _decode_streams() == before + 1
    assert cache.misses == 1 and cache.fills == 1
    # hit: identical bytes, ZERO new decode streams
    before = _decode_streams()
    for _ in range(3):
        _, s = cache.get_object("b", "hot")
        assert b"".join(s) == payload
    assert _decode_streams() == before
    assert cache.hits == 3
    zz.close()


def test_admission_frequency_bar(tmp_path):
    """Admission is driven by in-window access frequency: below the
    bar the read passes through WITHOUT filling (one-shot bulk reads
    must not churn the LRU), at the bar the entry fills."""
    zz, cache = _erasure_stack(tmp_path, admit_hits=2,
                               admit_window_s=60.0)
    payload = os.urandom(BLOCK)
    zz.put_object("b", "k", payload)
    _, s = cache.get_object("b", "k")        # 1st access: below bar
    assert b"".join(s) == payload
    assert cache.admit_rejects == 1 and cache.fills == 0
    assert cache._load_entry("b", "k") is None
    _, s = cache.get_object("b", "k")        # 2nd: admitted, fills
    assert b"".join(s) == payload
    assert cache.fills == 1
    _, s = cache.get_object("b", "k")        # 3rd: hit
    assert b"".join(s) == payload
    assert cache.hits == 1
    zz.close()


def test_access_tracker_window_expiry():
    t = AccessTracker(admit_hits=2, window_s=0.05)
    assert t.record("b", "k") == 1
    time.sleep(0.08)
    # window expired: the count restarts — stale popularity never admits
    assert t.record("b", "k") == 1
    assert t.record("b", "k") == 2
    assert t.admitted(2) and not t.admitted(1)


def test_every_mutation_verb_evicts_via_namespace_feed(tmp_path):
    """Mutations that BYPASS the wrapper (engine-level writes: the
    rebalance/heal/lifecycle planes) must still evict through the
    namespace feed — overwrite, delete, delete-marker, metadata
    update each drop the entry."""
    from minio_tpu.object.engine import PutOptions
    zz, cache = _erasure_stack(tmp_path)
    payload = os.urandom(BLOCK)

    def fill(name):
        zz.put_object("b", name, payload)
        b"".join(cache.get_object("b", name)[1])
        assert cache._load_entry("b", name) is not None, name

    fill("ow")
    zz.put_object("b", "ow", os.urandom(BLOCK))       # raw overwrite
    assert cache._load_entry("b", "ow") is None
    fill("del")
    zz.delete_object("b", "del")
    assert cache._load_entry("b", "del") is None
    fill("marker")
    zz.delete_object("b", "marker", versioned=True)   # marker write
    assert cache._load_entry("b", "marker") is None
    fill("md")
    zz.update_object_metadata("b", "md", {"x-amz-meta-a": "1"})
    assert cache._load_entry("b", "md") is None
    assert cache.evictions >= 4
    # correctness after the overwrite eviction: fresh bytes, not stale
    new = os.urandom(BLOCK)
    zz.put_object("b", "ow", new)
    b"".join(cache.get_object("b", "ow")[1])
    _, s = cache.get_object("b", "ow")
    assert b"".join(s) == new
    zz.close()


def test_transition_evicts_and_gates_invalid_object_state(tmp_path):
    """Cache/tiering interplay (regression pair): a transitioned
    (stubbed) version evicts its cache entry via the namespace feed,
    and a cached copy must NEVER satisfy a GET that should answer
    InvalidObjectState — the backend gate is the single home."""
    from minio_tpu.object import api_errors
    from minio_tpu.tier.client import FSTierClient  # noqa: F401 — dep check
    from minio_tpu.tier.config import TierConfig, TierManager
    from minio_tpu.tier.transition import TransitionWorker, restore_object
    zz, cache = _erasure_stack(tmp_path)
    tiers = TierManager(zz)
    tiers.add(TierConfig("cold", "fs", {"path": str(tmp_path / "tier")}))
    worker = TransitionWorker(zz, tiers, busy_fn=lambda: False).start()
    payload = os.urandom(2 * BLOCK)
    info = zz.put_object("b", "doc", payload)
    b"".join(cache.get_object("b", "doc")[1])         # hot + cached
    assert cache._load_entry("b", "doc") is not None
    worker.enqueue("b", "doc", "", "cold", etag=info.etag)
    assert worker.drain(30), worker.stats()
    # the transition's namespace delta evicted the entry
    assert cache._load_entry("b", "doc") is None
    # and the serve path re-checks the backend even if an entry were
    # present: GET through the cache answers InvalidObjectState
    with pytest.raises(api_errors.InvalidObjectState):
        cache.get_object("b", "doc")
    # defense in depth: plant a STALE entry behind the stub — the
    # transitioned guard must refuse to serve it and drop it
    zz2, planted = _erasure_stack(tmp_path / "p2")
    zz2.put_object("b", "doc", payload)
    b"".join(planted.get_object("b", "doc")[1])
    import shutil
    src = planted._entry_dir("b", "doc")
    dst = cache._entry_dir("b", "doc")
    shutil.copytree(src, dst)
    assert cache._load_entry("b", "doc") is not None
    with pytest.raises(api_errors.InvalidObjectState):
        cache.get_object("b", "doc")
    assert cache._load_entry("b", "doc") is None      # evicted, cause=transition
    # restore: the object serves again (fresh backend read, no decode
    # skip until re-admitted)
    restore_object(zz, tiers, "b", "doc", days=1)
    _, s = cache.get_object("b", "doc")
    assert b"".join(s) == payload
    worker.close()
    zz2.close()
    zz.close()


def test_cache_bitrot_frame_falls_back_to_backend(tmp_path):
    """Chaos (satellite): a random bitrot flip inside a cached frame
    must fall back to the erasure backend read — correct bytes out,
    corrupt file evicted, fallback counted."""
    seed = int(os.environ.get("MINIO_TPU_CHAOS_SEED",
                              str(random.randrange(1 << 30))))
    print(f"MINIO_TPU_CHAOS_SEED={seed}")
    rng = random.Random(seed)
    zz, cache = _erasure_stack(tmp_path)
    payload = os.urandom(5 * BLOCK + 123)
    zz.put_object("b", "c", payload)
    b"".join(cache.get_object("b", "c")[1])           # populate
    d = cache._entry_dir("b", "c")
    data = os.path.join(d, "data")
    size = os.path.getsize(data)
    with open(data, "r+b") as f:                      # one random flip
        pos = rng.randrange(size)
        f.seek(pos)
        byte = f.read(1)[0]
        f.seek(pos)
        f.write(bytes([byte ^ (1 << rng.randrange(8))]))
    before = _decode_streams()
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload                     # NEVER bad bytes
    assert _decode_streams() > before                 # backend re-read
    meta = cache._load_entry("b", "c")
    assert (meta or {}).get("ranges", []) == []       # corrupt file gone
    zz.close()
