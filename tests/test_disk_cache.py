"""Disk-cache depth (VERDICT r4 #4): range entries, streamed fills
with bounded memory, incremental cache-side bitrot, watermark LRU.
Complements tests/test_gateway_cache.py's basic hit/invalidation
coverage."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from minio_tpu.object.cache import CacheObjects
from minio_tpu.object.fs import FSObjects

BLOCK = 1 << 14                       # small cache block for tests


@pytest.fixture()
def stack(tmp_path):
    fs = FSObjects(str(tmp_path / "origin"))
    fs.make_bucket("b")
    cache = CacheObjects(fs, str(tmp_path / "cache"),
                         budget_bytes=64 << 20, block_size=BLOCK)
    return fs, cache


def test_ranged_miss_caches_aligned_span(stack):
    fs, cache = stack
    payload = os.urandom(BLOCK * 6 + 777)
    fs.put_object("b", "o", payload)

    # ranged miss: only the covering aligned span lands in the cache
    _, s = cache.get_object("b", "o", offset=BLOCK + 100, length=300)
    assert b"".join(s) == payload[BLOCK + 100:BLOCK + 400]
    assert cache.misses == 1
    meta = cache._load_entry("b", "o")
    assert meta["ranges"] == [
        {"start": BLOCK, "end": 2 * BLOCK, "file": f"r{BLOCK}"}]

    # a hit fully inside the cached span serves from cache
    _, s = cache.get_object("b", "o", offset=BLOCK + 500, length=100)
    assert b"".join(s) == payload[BLOCK + 500:BLOCK + 600]
    assert cache.hits == 1

    # a span NOT covered is a miss and caches its own range
    _, s = cache.get_object("b", "o", offset=4 * BLOCK, length=BLOCK)
    assert b"".join(s) == payload[4 * BLOCK:5 * BLOCK]
    assert cache.misses == 2
    meta = cache._load_entry("b", "o")
    assert {r["start"] for r in meta["ranges"]} == {BLOCK, 4 * BLOCK}

    # a request spanning cached+uncached blocks is a miss (no single
    # covering span) and fills its full aligned span
    _, s = cache.get_object("b", "o", offset=BLOCK, length=3 * BLOCK)
    assert b"".join(s) == payload[BLOCK:4 * BLOCK]
    assert cache.misses == 3
    _, s = cache.get_object("b", "o", offset=BLOCK, length=3 * BLOCK)
    assert b"".join(s) == payload[BLOCK:4 * BLOCK]
    assert cache.hits == 2

    # the tail range (unaligned object end) caches and serves
    _, s = cache.get_object("b", "o", offset=BLOCK * 6, length=777)
    assert b"".join(s) == payload[BLOCK * 6:]
    _, s = cache.get_object("b", "o", offset=BLOCK * 6 + 700, length=77)
    assert b"".join(s) == payload[BLOCK * 6 + 700:]
    assert cache.hits == 3


def test_whole_object_entry_serves_any_range(stack):
    fs, cache = stack
    payload = os.urandom(3 * BLOCK + 5)
    fs.put_object("b", "w", payload)
    _, s = cache.get_object("b", "w")
    assert b"".join(s) == payload
    for off, ln in [(0, 10), (BLOCK - 1, 2), (2 * BLOCK, BLOCK + 5),
                    (0, len(payload))]:
        _, s = cache.get_object("b", "w", offset=off, length=ln)
        assert b"".join(s) == payload[off:off + ln], (off, ln)
    assert cache.misses == 1 and cache.hits == 4


def test_corrupt_block_detected_mid_stream_and_evicted(stack):
    """Incremental verification: blocks before the corruption stream
    verified; the corrupt block is never served — the rest comes from
    the backend and the bad file is evicted."""
    fs, cache = stack
    payload = os.urandom(5 * BLOCK)
    fs.put_object("b", "c", payload)
    b"".join(cache.get_object("b", "c")[1])          # populate

    d = cache._entry_dir("b", "c")
    # corrupt the PAYLOAD of the third frame (frame = 32-digest+block)
    with open(os.path.join(d, "data"), "r+b") as f:
        f.seek(2 * (32 + BLOCK) + 32 + 7)
        f.write(b"\xff")
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload                    # bytes all correct
    # the corrupt file is gone; next read is a clean miss that refills
    meta = cache._load_entry("b", "c")
    assert meta["ranges"] == []
    before = cache.misses
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload
    assert cache.misses == before + 1
    _, s = cache.get_object("b", "c")
    assert b"".join(s) == payload                    # refilled → hit


def test_partial_fill_never_committed(stack):
    """A client that hangs up mid-download must not leave a partial
    cache entry that later reads would trust."""
    fs, cache = stack
    payload = os.urandom(6 * BLOCK)
    fs.put_object("b", "p", payload)
    _, s = cache.get_object("b", "p")
    next(s)                                          # one block only
    s.close()                                        # client hangup
    meta = cache._load_entry("b", "p")
    assert (meta or {}).get("ranges", []) == []
    d = cache._entry_dir("b", "p")
    leftovers = [f for f in os.listdir(d) if f != "meta.json"]
    assert leftovers == []
    # and the object still reads fine (miss -> refill)
    _, s = cache.get_object("b", "p")
    assert b"".join(s) == payload


def test_watermark_lru_prefers_cold_entries(tmp_path):
    fs = FSObjects(str(tmp_path / "o"))
    fs.make_bucket("b")
    cache = CacheObjects(fs, str(tmp_path / "c"),
                         budget_bytes=200_000, block_size=BLOCK)
    for i in range(12):
        fs.put_object("b", f"k{i}", bytes(BLOCK))
        b"".join(cache.get_object("b", f"k{i}")[1])
        time.sleep(0.01)
    # keep k0 hot: its clock refreshes on every hit
    b"".join(cache.get_object("b", "k0")[1])
    time.sleep(0.01)
    for i in range(12, 16):
        fs.put_object("b", f"k{i}", bytes(BLOCK))
        b"".join(cache.get_object("b", f"k{i}")[1])
    assert cache._usage() <= 200_000 * 0.95
    # the hot entry survived the purge; a cold early one did not
    hits_before = cache.hits
    b"".join(cache.get_object("b", "k0")[1])
    assert cache.hits == hits_before + 1
    misses_before = cache.misses
    b"".join(cache.get_object("b", "k1")[1])
    assert cache.misses == misses_before + 1


def test_oversized_object_reads_through(stack):
    fs, cache = stack
    cache.budget = 1 << 20                # max entry = 100 KiB
    payload = os.urandom(300_000)
    fs.put_object("b", "huge", payload)
    _, s = cache.get_object("b", "huge")
    assert b"".join(s) == payload
    meta = cache._load_entry("b", "huge")
    assert meta is None or meta.get("ranges", []) == []
    # but a small RANGE of the huge object still caches
    _, s = cache.get_object("b", "huge", offset=BLOCK, length=100)
    assert b"".join(s) == payload[BLOCK:BLOCK + 100]
    meta = cache._load_entry("b", "huge")
    assert meta and len(meta["ranges"]) == 1


_RSS_CHILD = r"""
import os, resource, sys
sys.path.insert(0, os.environ["REPO"])
from minio_tpu.object.cache import CacheObjects

SIZE = 256 << 20
CHUNK = 1 << 20

class FakeInfo:
    etag = "fixed"; size = SIZE; content_type = "application/x"
    user_defined = {}; mod_time = 0.0

class FakeInner:
    def get_object_info(self, b, k, opts=None):
        return FakeInfo()
    def get_object(self, b, k, offset=0, length=-1, opts=None):
        n = SIZE - offset if length < 0 else length
        def gen():
            left = n
            blob = b"\xab" * CHUNK
            while left > 0:
                yield blob[:min(CHUNK, left)]
                left -= min(CHUNK, left)
        return FakeInfo(), gen()

cache = CacheObjects(FakeInner(), os.environ["CACHEDIR"],
                     budget_bytes=SIZE * 20)
# a tiny warm-up fill loads every code path (incl. the hash kernels),
# so the big fill's delta over this high-water is pure buffering
_, warm = cache.get_object("b", "big", offset=0, length=1 << 20)
for _chunk in warm:
    pass
base_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024

_, stream = cache.get_object("b", "big")
total = 0
for chunk in stream:
    total += len(chunk)
assert total == SIZE, total
meta = cache._load_entry("b", "big")
assert any(r["start"] == 0 and r["end"] == SIZE
           for r in meta["ranges"]), "fill did not commit"
rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
print(f"rss_mb={rss_mb:.0f} base_mb={base_mb:.0f}")
assert rss_mb - base_mb < 100, \
    f"streamed 256 MiB fill grew RSS by {rss_mb - base_mb:.0f} MB"
"""


def test_fill_memory_is_bounded(tmp_path):
    """A 256 MiB fill must stream at constant memory (the r4 cache
    buffered the entire object in RAM — VERDICT weak: cache.py:146)."""
    cachedir = "/dev/shm/mt-cache-test" if os.path.isdir("/dev/shm") \
        else str(tmp_path / "c")
    env = dict(os.environ,
               REPO=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))),
               CACHEDIR=cachedir)
    try:
        proc = subprocess.run([sys.executable, "-c", _RSS_CHILD],
                              env=env, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "rss_mb=" in proc.stdout
    finally:
        import shutil
        shutil.rmtree(cachedir, ignore_errors=True)
