"""dsync distributed-lock tests: quorum algebra, broadcast semantics,
partial-failure tolerance, RPC lockers over live internode servers
(reference pkg/dsync/drwmutex_test.go + dsync-server_test.go pattern)."""

from __future__ import annotations

import threading
import time

import pytest

from minio_tpu.distributed.dsync import (DistNSLockMap, DRWMutex,
                                         quorum_for)
from minio_tpu.distributed.local_locker import LocalLocker
from minio_tpu.distributed.lock_rpc import (LockRPCClient, LockRPCServer)
from minio_tpu.distributed.transport import (RPCServer, make_token,
                                             verify_token)

AK, SK = "internodekey", "internodesecret123"


def test_quorum_algebra():
    # (n, write) -> quorum (drwmutex.go:342-378)
    assert quorum_for(4, False) == 2
    assert quorum_for(4, True) == 3
    assert quorum_for(5, False) == 3
    assert quorum_for(5, True) == 3
    assert quorum_for(8, True) == 5
    assert quorum_for(1, True) == 1


def test_token_roundtrip():
    tok = make_token(AK, SK)
    assert verify_token(tok, AK, SK)
    assert not verify_token(tok, AK, "wrong")
    assert not verify_token(tok, "other", SK)
    # expired within the tolerated clock skew: still valid (internode
    # auth must not flap between hosts with drifting clocks)
    assert verify_token(make_token(AK, SK, ttl=-10), AK, SK)
    # expired beyond the skew window: rejected
    assert not verify_token(make_token(AK, SK, ttl=-60), AK, SK)


def test_local_locker_semantics():
    lk = LocalLocker()
    assert lk.lock("u1", ["res"], "o")
    assert not lk.lock("u2", ["res"], "o")       # exclusive
    assert not lk.rlock("u3", ["res"], "o")      # writer blocks readers
    assert lk.unlock("u1", ["res"])
    assert lk.rlock("u3", ["res"], "o")
    assert lk.rlock("u4", ["res"], "o")          # readers stack
    assert not lk.lock("u5", ["res"], "o")       # readers block writer
    lk.runlock("u3", ["res"])
    lk.runlock("u4", ["res"])
    assert lk.lock("u5", ["res"], "o")


def test_local_locker_expiry():
    lk = LocalLocker()
    lk.lock("u1", ["a"], "o")
    assert lk.expire_old_locks(validity=0.0) == 1
    assert lk.lock("u2", ["a"], "o")             # stale grant swept


def test_drwmutex_quorum_over_local_lockers():
    lockers = [LocalLocker() for _ in range(4)]
    dm = DRWMutex(lockers, ["bucket/obj"])
    assert dm.get_lock(timeout=2.0)
    # a second writer cannot acquire while held
    dm2 = DRWMutex(lockers, ["bucket/obj"])
    assert not dm2.get_lock(timeout=0.5)
    dm.unlock()
    assert dm2.get_lock(timeout=2.0)
    dm2.unlock()


def test_drwmutex_readers_share():
    lockers = [LocalLocker() for _ in range(4)]
    r1 = DRWMutex(lockers, ["res"])
    r2 = DRWMutex(lockers, ["res"])
    assert r1.get_rlock(timeout=2.0)
    assert r2.get_rlock(timeout=2.0)
    w = DRWMutex(lockers, ["res"])
    assert not w.get_lock(timeout=0.5)
    r1.unlock()
    r2.unlock()
    assert w.get_lock(timeout=2.0)
    w.unlock()


def test_drwmutex_tolerates_minority_down():
    # 1 of 5 lockers dead -> writes still proceed (tolerance = 2)
    lockers = [LocalLocker() for _ in range(4)] + [None]
    dm = DRWMutex(lockers, ["res"])
    assert dm.get_lock(timeout=2.0)
    dm.unlock()


def test_drwmutex_fails_without_quorum():
    # 3 of 5 dead -> write quorum 3 unreachable
    lockers = [LocalLocker(), LocalLocker(), None, None, None]
    dm = DRWMutex(lockers, ["res"])
    assert not dm.get_lock(timeout=0.5)
    # and the partial grants were rolled back
    assert not lockers[0].dump() and not lockers[1].dump()


def test_drwmutex_contention_one_winner():
    lockers = [LocalLocker() for _ in range(4)]
    wins = []

    def contender(i):
        dm = DRWMutex(lockers, ["hot"])
        if dm.get_lock(timeout=1.0):
            wins.append(i)
            time.sleep(0.8)
            dm.unlock()

    ts = [threading.Thread(target=contender, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(wins) >= 1
    # while one held it for 0.8s of the 1.0s window, most must have lost
    assert len(wins) <= 2


@pytest.fixture()
def lock_cluster():
    """3 lock RPC servers + clients (in-process 3-node cluster)."""
    servers, rpcs, clients = [], [], []
    for _ in range(3):
        srv = LockRPCServer(LocalLocker(), AK, SK, start_sweeper=False)
        host = RPCServer().start()
        host.mount(srv.handler)
        servers.append(srv)
        rpcs.append(host)
        clients.append(LockRPCClient("127.0.0.1", host.port, AK, SK,
                                     timeout=2.0))
    yield servers, clients
    for c in clients:
        c.close()
    for h in rpcs:
        h.stop()


def test_lock_rpc_roundtrip(lock_cluster):
    _, clients = lock_cluster
    c = clients[0]
    assert c.lock("uid1", ["b/o"], owner="me", source="test")
    assert not c.lock("uid2", ["b/o"])
    assert "b/o" in c.dump()
    assert c.unlock("uid1", ["b/o"])
    assert c.lock("uid2", ["b/o"])
    c.unlock("uid2", ["b/o"])


def test_lock_rpc_auth_rejected(lock_cluster):
    servers, clients = lock_cluster
    bad = LockRPCClient("127.0.0.1", clients[0].rc.port, AK,
                        "wrongsecret", timeout=2.0)
    assert not bad.lock("uid", ["x"])
    bad.close()


def test_dist_drwmutex_over_rpc(lock_cluster):
    _, clients = lock_cluster
    dm = DRWMutex(list(clients), ["shared/obj"])
    assert dm.get_lock(timeout=3.0)
    dm2 = DRWMutex(list(clients), ["shared/obj"])
    assert not dm2.get_lock(timeout=0.5)
    dm.unlock()
    assert dm2.get_lock(timeout=3.0)
    dm2.unlock()


def test_dist_nslock_engine_interface(lock_cluster):
    """DistNSLockMap satisfies the engine's ns_lock seam."""
    _, clients = lock_cluster
    ns = DistNSLockMap(list(clients))
    with ns.new_lock("bucket/key").write_locked(timeout=3.0):
        other = ns.new_lock("bucket/key")
        assert not other.get_lock(timeout=0.3)
    # released on ctx exit
    lk = ns.new_lock("bucket/key")
    assert lk.get_lock(timeout=3.0)
    lk.unlock()


def test_refresh_keeps_long_hold_alive(monkeypatch):
    """A lock held past the validity window survives the lockers' expiry
    sweep because the holder refreshes it (ADVICE r1: without refresh,
    any write lock held >LOCK_VALIDITY silently expired)."""
    from minio_tpu.distributed import dsync as dsync_mod
    monkeypatch.setattr(dsync_mod, "REFRESH_INTERVAL", 0.05)
    lockers = [LocalLocker() for _ in range(3)]
    dm = DRWMutex(lockers, ["bucket/long-op"])
    assert dm.get_lock(timeout=2.0)
    time.sleep(0.3)
    for lk in lockers:
        lk.expire_old_locks(validity=0.15)  # reaps only un-refreshed grants
    dm2 = DRWMutex(lockers, ["bucket/long-op"])
    assert not dm2.get_lock(timeout=0.3), "lock was lost while held"
    dm.unlock()
    assert dm2.get_lock(timeout=2.0)
    dm2.unlock()
