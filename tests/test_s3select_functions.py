"""S3 Select SQL function conformance table (VERDICT r4 #5): every
function mirrors pkg/s3select/sql/funceval.go + timestampfuncs.go +
stringfuncs.go semantics — one table row per documented behavior,
evaluated through the real parser."""

from __future__ import annotations

import datetime as dt

import pytest

from minio_tpu.s3select.sql import (SQLError, evaluate,
                                    format_sql_timestamp, parse,
                                    parse_sql_timestamp)

ROW = {"name": "Ada Lovelace", "n": "42", "pad": "  x  ",
       "ts": "2024-03-31T10:30:15Z", "ts2": "2024-05-01T09:00Z",
       "empty": "", "zz": "zzxzz"}


def ev(expr: str, row=None):
    q = parse(f"SELECT {expr} FROM S3Object")
    return evaluate(q.projections[0][0], ROW if row is None else row,
                    q.alias)


# ---------------------------------------------------------------------------
# conformance table: (expression, expected) — exact funceval.go behavior
# ---------------------------------------------------------------------------

TABLE = [
    # SUBSTRING — stringfuncs.go:144: 1-based; start<1 clamps to 1;
    # start past end -> ""; oversized length clamps; both arg forms
    ("SUBSTRING('abcdef' FROM 2)", "bcdef"),
    ("SUBSTRING('abcdef' FROM 2 FOR 3)", "bcd"),
    ("SUBSTRING('abcdef', 2, 3)", "bcd"),
    ("SUBSTRING('abcdef', 2)", "bcdef"),
    ("SUBSTRING('abcdef' FROM 0)", "abcdef"),
    ("SUBSTRING('abcdef' FROM -4)", "abcdef"),
    ("SUBSTRING('abcdef' FROM 99)", ""),
    ("SUBSTRING('abcdef' FROM 3 FOR 99)", "cdef"),
    ("SUBSTRING(name FROM 5)", "Lovelace"),
    # COALESCE / NULLIF — funceval.go:149/159
    ("COALESCE(NULL, NULL, 'x', 'y')", "x"),
    ("COALESCE(NULL, NULL)", None),
    ("COALESCE(missing_col, 'fallback')", "fallback"),
    ("NULLIF(1, 1)", None),
    ("NULLIF(1, 2)", 1),
    ("NULLIF('a', 'a')", None),
    ("NULLIF('a', 'b')", "a"),
    ("NULLIF(NULL, 1)", None),
    ("NULLIF('7', 7)", None),          # numeric coercion, like cmp
    # TRIM — stringfuncs.go:171 cutset semantics
    ("TRIM('  hi  ')", "hi"),
    ("TRIM(LEADING FROM '  hi  ')", "hi  "),
    ("TRIM(TRAILING FROM '  hi  ')", "  hi"),
    ("TRIM(BOTH FROM '  hi  ')", "hi"),
    ("TRIM(BOTH 'z' FROM 'zzxzz')", "x"),
    ("TRIM(LEADING 'z' FROM 'zzxzz')", "xzz"),
    ("TRIM(TRAILING 'z' FROM 'zzxzz')", "zzx"),
    ("TRIM('xy' FROM 'xyaxboyx')", "axbo"),    # chars as a SET
    # EXTRACT — timestampfuncs.go:91
    ("EXTRACT(YEAR FROM ts)", 2024),
    ("EXTRACT(MONTH FROM ts)", 3),
    ("EXTRACT(DAY FROM ts)", 31),
    ("EXTRACT(HOUR FROM ts)", 10),
    ("EXTRACT(MINUTE FROM ts)", 30),
    ("EXTRACT(SECOND FROM ts)", 15),
    ("EXTRACT(TIMEZONE_HOUR FROM '2024-01-01T05:00+05:30')", 5),
    ("EXTRACT(TIMEZONE_MINUTE FROM '2024-01-01T05:00+05:30')", 30),
    # Go truncating division: -05:30 -> hour -5, minute -30
    ("EXTRACT(TIMEZONE_HOUR FROM '2024-01-01T05:00-05:30')", -5),
    ("EXTRACT(TIMEZONE_MINUTE FROM '2024-01-01T05:00-05:30')", -30),
    # DATE_ADD — timestampfuncs.go:117 (Go AddDate overflow rules)
    ("TO_STRING(DATE_ADD(year, 1, ts), 'yyyy-MM-dd')", "2025-03-31"),
    ("TO_STRING(DATE_ADD(month, 2, ts), 'yyyy-MM-dd')", "2024-05-31"),
    # Jan 31 + 1 month normalizes into March (NOT clamp to Feb)
    ("TO_STRING(DATE_ADD(month, 1, '2024-01-31T'), 'yyyy-MM-dd')",
     "2024-03-02"),
    ("TO_STRING(DATE_ADD(day, 1, ts), 'yyyy-MM-dd')", "2024-04-01"),
    ("TO_STRING(DATE_ADD(hour, 14, ts), 'yyyy-MM-dd HH:mm')",
     "2024-04-01 00:30"),
    ("TO_STRING(DATE_ADD(minute, -31, ts), 'HH:mm:ss')", "09:59:15"),
    ("TO_STRING(DATE_ADD(second, 50, ts), 'HH:mm:ss')", "10:31:05"),
    # DATE_DIFF — timestampfuncs.go:146 calendar-field semantics
    ("DATE_DIFF(year, '2023-06-01T', '2024-05-31T')", 0),
    ("DATE_DIFF(year, '2023-06-01T', '2024-06-01T')", 1),
    ("DATE_DIFF(month, '2024-01-31T', '2024-02-28T')", 0),
    ("DATE_DIFF(month, '2024-01-28T', '2024-02-28T')", 1),
    ("DATE_DIFF(day, '2024-03-31T23:59Z', '2024-04-01T00:01Z')", 1),
    ("DATE_DIFF(hour, ts, ts2)", 742),
    ("DATE_DIFF(minute, '2024-01-01T10:00Z', '2024-01-01T10:59Z')",
     59),
    ("DATE_DIFF(second, '2024-01-01T10:00Z', '2024-01-01T10:01Z')",
     60),
    # reversed order negates
    ("DATE_DIFF(day, '2024-04-05T', '2024-04-01T')", -4),
    # TO_TIMESTAMP / CAST TIMESTAMP / comparisons
    ("TO_TIMESTAMP('2024-03-31T10:30:15Z') = CAST(ts AS TIMESTAMP)",
     True),
    ("CAST('2024-06-01T' AS TIMESTAMP) > CAST(ts AS TIMESTAMP)", True),
    ("CAST(CAST(ts AS TIMESTAMP) AS STRING)", "2024-03-31T10:30:15Z"),
    # TO_STRING pattern tokens (implemented past the reference's
    # errNotImplemented)
    ("TO_STRING(TO_TIMESTAMP(ts), 'y-MM-dd''T''HH:mm')",
     "2024-03-31T10:30"),
    ("TO_STRING(TO_TIMESTAMP(ts), 'MMM d, yyyy h:mm a')",
     "Mar 31, 2024 10:30 AM"),
    ("TO_STRING(TO_TIMESTAMP('2024-01-01T17:05+05:30'), 'hh a XXX')",
     "05 PM +05:30"),
    # existing scalars still conform
    ("CHAR_LENGTH('héllo')", 5),
    ("LOWER('AbC')", "abc"),
    ("UPPER('AbC')", "ABC"),
    ("ABS(-3.5)", 3.5),
    ("NULLIF(LENGTH(empty), 0)", None),
]


@pytest.mark.parametrize("expr,want", TABLE,
                         ids=[t[0][:60] for t in TABLE])
def test_function_conformance(expr, want):
    got = ev(expr)
    assert got == want, f"{expr} -> {got!r}, want {want!r}"


def test_error_modes():
    with pytest.raises(SQLError):
        ev("SUBSTRING('abc' FROM 1 FOR -1)")      # negative length
    with pytest.raises(SQLError):
        ev("EXTRACT(EPOCH FROM ts)")              # unknown part
    with pytest.raises(SQLError):
        ev("DATE_ADD(fortnight, 1, ts)")
    with pytest.raises(SQLError):
        ev("TO_TIMESTAMP('not a time')")
    with pytest.raises(SQLError):
        ev("UTCNOW(1)")
    with pytest.raises(SQLError):
        ev("CAST('x' AS TIMESTAMP)")


def test_timestamp_parse_format_roundtrip():
    # the reference's six layouts all parse; formatting picks the
    # shortest faithful layout (FormatSQLTimestamp)
    cases = ["2024T", "2024-03T", "2024-03-05T", "2024-03-05T08:30Z",
             "2024-03-05T08:30:09Z", "2024-03-05T08:30:09.25Z",
             "2024-03-05T08:30+05:30"]
    for s in cases:
        t = parse_sql_timestamp(s)
        assert format_sql_timestamp(t) == s, s
    assert parse_sql_timestamp("2024T") == dt.datetime(
        2024, 1, 1, tzinfo=dt.timezone.utc)


def test_timestamp_comparisons_are_instants():
    """Review r5: timestamp-vs-string comparisons parse the string and
    compare INSTANTS (same moment in different offsets is equal);
    naive datetimes (pyarrow) compare as UTC instead of raising."""
    # same instant, different offsets
    assert ev("TO_TIMESTAMP('2024-03-31T10:30:15Z') = "
              "'2024-03-31T12:30:15+02:00'") is True
    # ordering across offsets follows the instant, not the text
    assert ev("TO_TIMESTAMP('2024-06-01T05:00Z') < "
              "'2024-06-01T10:00+10:00'") is False   # 10:00+10 = 00:00Z
    # the exact source string equals its parsed value even though the
    # shortest re-format differs
    assert ev("TO_TIMESTAMP('2024-01-02T00:00Z') = "
              "'2024-01-02T00:00Z'") is True
    # naive datetime (e.g. a pyarrow timestamp column) vs aware
    naive_row = {"t": dt.datetime(2024, 3, 31, 10, 30, 15)}
    q = parse("SELECT t > TO_TIMESTAMP('2024-03-31T09:00Z') "
              "FROM S3Object")
    assert evaluate(q.projections[0][0], naive_row, q.alias) is True
    # MIN/MAX aggregation over mixed naive/aware rows must not raise
    from minio_tpu.s3select.sql import Aggregator
    q = parse("SELECT MIN(t), MAX(t) FROM S3Object")
    agg = Aggregator(q)
    agg.feed({"t": dt.datetime(2024, 1, 1)})
    agg.feed({"t": dt.datetime(2024, 2, 1, tzinfo=dt.timezone.utc)})
    out = agg.result()
    assert out["_1"] < out["_2"]


def test_fractional_seconds_are_digit_exact():
    """Review r5: .000249 must parse to exactly 249 µs (float math
    truncated it to 248)."""
    for frac, micro in [(".000249", 249), (".000251", 251),
                        (".000489", 489), (".5", 500000),
                        (".123456789", 123456)]:
        t = parse_sql_timestamp(f"2024-01-01T00:00:00{frac}Z")
        assert t.microsecond == micro, frac


def test_utcnow_is_now():
    v = ev("UTCNOW()")
    assert isinstance(v, dt.datetime)
    assert abs((dt.datetime.now(dt.timezone.utc) - v)
               .total_seconds()) < 5


def test_where_clause_uses_date_functions():
    """Date functions compose inside WHERE through the full engine."""
    from minio_tpu.s3select.select import SelectRequest, run_select
    req = SelectRequest()
    req.expression = ("SELECT name FROM S3Object s WHERE "
                      "EXTRACT(YEAR FROM TO_TIMESTAMP(joined)) >= 2024"
                      " AND DATE_DIFF(day, joined, '2024-12-31T') < "
                      "200")
    req.input_format = "CSV"
    req.csv_header = "USE"
    req.output_format = "CSV"
    data = (b"name,joined\n"
            b"old,2019-05-01T\n"
            b"early24,2024-01-15T\n"        # diff 351 days -> excluded
            b"late24,2024-08-01T\n")        # diff 152 -> included
    out = b"".join(run_select(req, data))
    assert out.strip() == b"late24"
