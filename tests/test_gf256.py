"""GF(2^8) field + RS matrix tests — the algebraic bedrock.

Known-value vectors pin the field to the same polynomial (0x11D, generator 2)
the reference's codec library uses, so shard bytes are comparable 1:1.
"""

import numpy as np
import pytest

from minio_tpu.ops import gf256, rs_matrix, rs_ref


class TestField:
    def test_exp_log_roundtrip(self):
        for a in range(1, 256):
            assert gf256.GF_EXP[gf256.GF_LOG[a]] == a

    def test_known_products(self):
        # Classic vectors for poly 0x11D
        assert gf256.gf_mul(0, 21) == 0
        assert gf256.gf_mul(1, 21) == 21
        assert gf256.gf_mul(2, 0x80) == 0x1D  # overflow reduces by 0x11D
        assert gf256.gf_mul(3, 7) == 9
        assert gf256.gf_mul(0xFF, 0xFF) == 0xE2
        # 0x53 * 0xCA == 1 only under the AES polynomial (0x11B); here it must not
        assert gf256.gf_mul(0x53, 0xCA) != 0x01

    def test_generator_order(self):
        # 2 generates the multiplicative group: 2^255 == 1, no smaller cycle
        seen = set()
        x = 1
        for _ in range(255):
            assert x not in seen
            seen.add(x)
            x = gf256.gf_mul(x, 2)
        assert x == 1
        assert len(seen) == 255

    def test_mul_commutative_distributive(self):
        rng = np.random.default_rng(0)
        for _ in range(200):
            a, b, c = (int(v) for v in rng.integers(0, 256, 3))
            assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
            assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)

    def test_inverse(self):
        for a in range(1, 256):
            assert gf256.gf_mul(a, int(gf256.GF_INV[a])) == 1

    def test_div(self):
        rng = np.random.default_rng(1)
        for _ in range(100):
            a, b = (int(v) for v in rng.integers(1, 256, 2))
            q = gf256.gf_div(a, b)
            assert gf256.gf_mul(q, b) == a
        with pytest.raises(ZeroDivisionError):
            gf256.gf_div(5, 0)

    def test_gf_exp_conventions(self):
        assert gf256.gf_exp(0, 0) == 1  # matches reference codec's galExp
        assert gf256.gf_exp(0, 5) == 0
        assert gf256.gf_exp(7, 1) == 7
        # a^255 == 1 for a != 0
        for a in (1, 2, 3, 0x1D, 255):
            assert gf256.gf_exp(a, 255) == 1


class TestMatrix:
    def test_inverse_roundtrip(self):
        rng = np.random.default_rng(2)
        for n in (1, 2, 4, 8, 13):
            # random invertible matrix: try until non-singular
            while True:
                m = rng.integers(0, 256, (n, n)).astype(np.uint8)
                try:
                    inv = gf256.gf_mat_inv(m)
                    break
                except ValueError:
                    continue
            eye = gf256.gf_matmul(m, inv)
            assert (eye == np.eye(n, dtype=np.uint8)).all()

    def test_singular_raises(self):
        m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
        with pytest.raises(ValueError):
            gf256.gf_mat_inv(m)

    def test_mul_bitmatrix_linearity(self):
        rng = np.random.default_rng(3)
        for _ in range(64):
            c = int(rng.integers(0, 256))
            bm = gf256.mul_bitmatrix(c)
            for _ in range(8):
                x = int(rng.integers(0, 256))
                bits_x = (x >> np.arange(8)) & 1
                bits_y = bm @ bits_x % 2
                y = int((bits_y << np.arange(8)).sum())
                assert y == gf256.gf_mul(c, x), (c, x)

    def test_expand_to_gf2(self):
        rng = np.random.default_rng(4)
        m = rng.integers(0, 256, (4, 3)).astype(np.uint8)
        bm = gf256.expand_to_gf2(m)
        assert bm.shape == (32, 24)
        x = rng.integers(0, 256, (3, 17)).astype(np.uint8)
        # bit-expand x: (24, 17)
        xb = ((x[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(24, 17)
        yb = bm.astype(np.int64) @ xb % 2
        y = (yb.reshape(4, 8, 17) << np.arange(8)[None, :, None]).sum(1).astype(np.uint8)
        assert (y == gf256.gf_matmul(m, x)).all()


class TestEncodeMatrix:
    def test_systematic(self):
        for k, m in [(2, 1), (4, 2), (12, 4), (16, 16), (8, 8)]:
            em = rs_matrix.encode_matrix(k, m)
            assert em.shape == (k + m, k)
            assert (em[:k] == np.eye(k, dtype=np.uint8)).all()

    def test_known_vandermonde_values(self):
        vm = rs_matrix.vandermonde(6, 4)
        assert vm[0].tolist() == [1, 0, 0, 0]
        assert vm[1].tolist() == [1, 1, 1, 1]
        assert vm[2].tolist() == [1, 2, 4, 8]
        assert vm[3].tolist() == [1, 3, 5, 15]

    def test_any_k_rows_invertible(self):
        # MDS property: every k-subset of encode matrix rows is invertible
        import itertools
        k, m = 4, 3
        em = rs_matrix.encode_matrix(k, m)
        for rows in itertools.combinations(range(k + m), k):
            gf256.gf_mat_inv(em[list(rows)])  # must not raise

    def test_decode_matrix_row_selection(self):
        k, m = 4, 2
        # shards 1 and 3 missing -> survivors 0,2,4,5; first k = 0,2,4,5
        mask = 0b110101
        _, used = rs_matrix.decode_matrix(k, m, mask)
        assert used == (0, 2, 4, 5)

    def test_too_few_shards(self):
        with pytest.raises(ValueError):
            rs_matrix.decode_matrix(4, 2, 0b000111)


class TestReferenceCodec:
    @pytest.mark.parametrize("k,m", [(2, 2), (4, 2), (12, 4), (16, 4), (5, 3)])
    def test_roundtrip_no_loss(self, k, m):
        rng = np.random.default_rng(k * 100 + m)
        data = rng.integers(0, 256, 1000).astype(np.uint8).tobytes()
        shards = rs_ref.encode_block(data, k, m)
        assert shards.shape[0] == k + m
        assert rs_ref.verify(shards, k)
        assert rs_ref.join(shards, k, len(data)) == data

    @pytest.mark.parametrize("k,m", [(4, 2), (12, 4), (8, 8)])
    def test_reconstruct_all_patterns(self, k, m):
        import itertools
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, 4099).astype(np.uint8).tobytes()
        full = rs_ref.encode_block(data, k, m)
        L = full.shape[1]
        n = k + m
        # all 1-loss and 2-loss patterns, plus random m-loss patterns
        patterns = [frozenset(c) for c in itertools.combinations(range(n), 1)]
        patterns += [frozenset(c) for c in itertools.combinations(range(n), min(2, m))]
        rng2 = np.random.default_rng(8)
        for _ in range(10):
            patterns.append(frozenset(
                int(i) for i in rng2.choice(n, size=m, replace=False)))
        for missing in patterns:
            avail = {i: full[i] for i in range(n) if i not in missing}
            out = rs_ref.reconstruct(avail, k, m, L)
            assert (out == full).all(), f"pattern {sorted(missing)}"

    def test_split_pads(self):
        out = rs_ref.split(b"abcdefg", 3)
        assert out.shape == (3, 3)
        assert bytes(out.reshape(-1)) == b"abcdefg\x00\x00"

    def test_zero_data(self):
        with pytest.raises(ValueError):
            rs_ref.split(b"", 4)
