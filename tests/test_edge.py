"""HTTP edge tests: connection hygiene of the event-loop frontend and
oracle parity with the threaded server (MINIO_TPU_EDGE=off).

The tier-1 pins of ISSUE 12's acceptance list: keep-alive reuse across
requests, slowloris partial-header sheds without a thread leak (the
conftest sentinel rides along on every test here), admission sheds
answered BEFORE any body byte is read with the counter delta proven,
mid-body client death freeing the staging reservation, and 503
SlowDown responses carrying Retry-After + close on BOTH transports.
"""

from __future__ import annotations

import hashlib
import http.client
import os
import socket
import time
import urllib.parse

import pytest

from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3 import signature as sig
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.server import S3Server
from minio_tpu.utils import telemetry

CREDS = Credentials("testadminkey", "testadminsecretkey")
REGION = "us-east-1"
BLOCK = 1 << 16


@pytest.fixture(scope="module")
def layer(tmp_path_factory):
    root = tmp_path_factory.mktemp("edgedrives")
    sets = ErasureSets.from_drives(
        [str(root / f"d{i}") for i in range(6)], 1, 6, 2,
        block_size=BLOCK)
    yield sets
    sets.close()


def _mk_server(layer, **env) -> S3Server:
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return S3Server(layer, creds=CREDS, region=REGION).start()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.fixture()
def edge_server(layer):
    srv = _mk_server(layer, MINIO_TPU_EDGE="on")
    yield srv
    srv.stop()


@pytest.fixture(params=["edge", "threaded"])
def any_server(request, layer):
    srv = _mk_server(
        layer,
        MINIO_TPU_EDGE="on" if request.param == "edge" else "off")
    assert srv.edge_enabled == (request.param == "edge")
    yield srv
    srv.stop()


def _signed_headers(method: str, path: str, port: int,
                    payload_hash: str = sig.UNSIGNED_PAYLOAD,
                    extra: dict | None = None) -> dict:
    hdrs = {"host": f"127.0.0.1:{port}"}
    hdrs.update(extra or {})
    return sig.sign_v4(method, urllib.parse.quote(path), {}, hdrs,
                       payload_hash, CREDS, REGION)


def _request(port: int, method: str, path: str, body: bytes = b"",
             sign: bool = True):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    hdrs = _signed_headers(method, path, port,
                           hashlib.sha256(body).hexdigest()) \
        if sign else {"host": f"127.0.0.1:{port}"}
    conn.request(method, urllib.parse.quote(path), body=body,
                 headers=hdrs)
    resp = conn.getresponse()
    data = resp.read()
    headers = {k.lower(): v for k, v in resp.getheaders()}
    conn.close()
    return resp.status, headers, data


def _read_http_response(sock: socket.socket) -> tuple[int, dict, bytes]:
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            break
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for line in lines[1:]:
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    want = int(headers.get("content-length", 0))
    while len(rest) < want:
        chunk = sock.recv(65536)
        if not chunk:
            break
        rest += chunk
    return status, headers, rest[:want], rest[want:]


def _shed_value(reason: str) -> float:
    return telemetry.REGISTRY.counter(
        "minio_tpu_requests_shed_total").value(reason=reason)


# ---------------------------------------------------------------------------
# keep-alive
# ---------------------------------------------------------------------------

def test_keepalive_reuse_across_requests(any_server):
    """One TCP connection serves a whole signed request sequence —
    bucket create, object PUT, GET, DELETE — without the server
    closing between requests (http.client raises on a dead reuse)."""
    port = any_server.port
    bucket = f"kab-{port}"            # module-shared layer: per-server
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    body = b"edge keep-alive payload " * 64

    def go(method, path, payload=b""):
        hdrs = _signed_headers(method, path, port,
                               hashlib.sha256(payload).hexdigest())
        conn.request(method, path, body=payload, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        assert not resp.will_close, (method, path)
        return resp.status, data

    assert go("PUT", f"/{bucket}")[0] == 200
    assert go("PUT", f"/{bucket}/obj", body)[0] == 200
    st, data = go("GET", f"/{bucket}/obj")
    assert st == 200 and data == body
    assert go("DELETE", f"/{bucket}/obj")[0] == 204
    conn.close()


def test_pipelined_requests_carry_over(edge_server):
    """Two requests written in ONE segment: the loop's leftover buffer
    must hand the second request over after the first response (the
    keep-alive re-arm path)."""
    port = edge_server.port
    req = (f"GET / HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
           "\r\n").encode()
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=30) as s:
        s.sendall(req + req)          # unsigned: both answer 403
        st1, h1, body1, extra = _read_http_response(s)
        assert st1 == 403 and b"<Error>" in body1
        if extra:
            # second response already buffered behind the first
            class _Pre:
                def __init__(self, pre, inner):
                    self.pre, self.inner = pre, inner

                def recv(self, n):
                    if self.pre:
                        out, self.pre = self.pre[:n], self.pre[n:]
                        return out
                    return self.inner.recv(n)
            st2, _, body2, _ = _read_http_response(_Pre(extra, s))
        else:
            st2, _, body2, _ = _read_http_response(s)
        assert st2 == 403 and b"<Error>" in body2


# ---------------------------------------------------------------------------
# sheds: before the first body byte, counted, Retry-After + close
# ---------------------------------------------------------------------------

def test_admission_shed_before_body_byte(edge_server):
    """The maxClients budget refuses BEFORE reading the body: the
    client sends headers announcing a 1 MiB body and NOTHING else — a
    server that waited for body bytes would hang; the edge answers 503
    with Retry-After + close, and the shed lands in
    minio_tpu_requests_shed_total{reason="admission"} (the counter
    delta this ISSUE's acceptance list pins)."""
    api = edge_server.api
    api.admission.resize(1)
    api.admission.deadline = 0.2
    hold = api.admission.admit("GET", "/held/k", {}, {})
    before = _shed_value("admission")
    try:
        port = edge_server.port
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=30) as s:
            head = (f"PUT /shedb/obj HTTP/1.1\r\n"
                    f"Host: 127.0.0.1:{port}\r\n"
                    f"Content-Length: {1 << 20}\r\n\r\n").encode()
            s.sendall(head)           # zero body bytes follow
            st, headers, body, _ = _read_http_response(s)
            assert st == 503 and b"SlowDown" in body
            assert headers.get("connection") == "close"
            assert int(headers.get("retry-after", 0)) >= 1
            assert s.recv(16) == b""  # server closed the socket
        assert _shed_value("admission") == before + 1
    finally:
        hold.release()
        api.admission.deadline = 10.0


def test_staging_shed_parity_retry_after_and_close(any_server):
    """A staging-window shed answers identically on BOTH transports:
    503 SlowDown XML, Retry-After, Connection: close (the threaded
    server is the oracle for the edge's shed path)."""
    api = any_server.api
    api.admission._shed_until = time.monotonic() + 30.0
    before = _shed_value("staging")
    try:
        st, headers, body = _request(any_server.port, "PUT",
                                     "/parb/obj", b"x" * 64,
                                     sign=False)
        assert st == 503 and b"SlowDown" in body
        assert headers.get("connection") == "close"
        assert int(headers.get("retry-after", 0)) >= 1
        assert _shed_value("staging") == before + 1
    finally:
        api.admission._shed_until = 0.0


def test_slowloris_partial_header_sheds_not_leaks(layer):
    """A trickled request line misses the header deadline: the loop
    sheds it (503 + close, reason="deadline") and the connection count
    returns to zero — no thread held, and the conftest thread-leak
    sentinel proves no worker leaked."""
    srv = _mk_server(layer, MINIO_TPU_EDGE="on",
                     MINIO_TPU_EDGE_HEADER_S="0.3")
    try:
        before = _shed_value("deadline")
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=30) as s:
            s.sendall(b"PUT /slow/loris HTTP/1.1\r\nHost: tri")
            t0 = time.monotonic()
            st, headers, body, _ = _read_http_response(s)
            assert st == 503 and b"SlowDown" in body
            assert headers.get("connection") == "close"
            assert "retry-after" in headers
            assert 0.2 < time.monotonic() - t0 < 10.0
            assert s.recv(16) == b""
        assert _shed_value("deadline") == before + 1
        deadline = time.monotonic() + 5.0
        while srv._edge.conn_count() > 0 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv._edge.conn_count() == 0
    finally:
        srv.stop()


def test_idle_connection_reaped_quietly(layer):
    """An idle keep-alive connection past the idle deadline closes
    WITHOUT a shed (reaping idle sockets is bookkeeping, not load
    shedding)."""
    srv = _mk_server(layer, MINIO_TPU_EDGE="on",
                     MINIO_TPU_EDGE_IDLE_S="0.3")
    try:
        before = _shed_value("deadline")
        with socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=30) as s:
            assert s.recv(16) == b""      # quiet close, no response
        assert _shed_value("deadline") == before
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# mid-body client death
# ---------------------------------------------------------------------------

def test_midbody_death_frees_staging_and_slot(edge_server):
    """A client dying mid-PUT-body must not strand its admission slot
    or its BytePool staging reservation: after several kills the gate
    reads zero in-flight and a normal PUT still succeeds (leaked
    staging buffers would wedge it)."""
    port = edge_server.port
    api = edge_server.api
    size = 1 << 20
    for _ in range(6):
        hdrs = _signed_headers("PUT", "/killb/obj", port)
        hdrs["content-length"] = str(size)
        head = "PUT /killb/obj HTTP/1.1\r\n" + "".join(
            f"{k}: {v}\r\n" for k, v in hdrs.items()) + "\r\n"
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        s.sendall(head.encode() + b"z" * (size // 4))
        s.close()                         # die mid-body
    deadline = time.monotonic() + 15.0
    while api.admission.in_use() > 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert api.admission.in_use() == 0
    # staging rings intact: a full-size PUT round-trips
    _request(port, "PUT", "/killb", sign=True)
    body = os.urandom(size)
    st, _, _ = _request(port, "PUT", "/killb/whole", body)
    assert st == 200
    st, _, got = _request(port, "GET", "/killb/whole")
    assert st == 200 and got == body
