"""Live bucket features: lifecycle expiry via the crawler, webhook/
in-memory event notification on object ops, async replication to a
second live S3 endpoint (reference data-crawler applyActions,
pkg/event dispatch, bucket-replication e2e intents)."""

from __future__ import annotations

import http.server
import json
import threading
import time

import pytest

from minio_tpu.features import (EventNotifier, Lifecycle,
                                ReplicationConfig, ReplicationPool)
from minio_tpu.features.events import MemoryTarget, WebhookTarget
from minio_tpu.features.lifecycle import crawler_action
from minio_tpu.features.replication import ReplicationTarget
from minio_tpu.object.background import DataUsageCrawler
from minio_tpu.object.sets import ErasureSets
from minio_tpu.s3.credentials import Credentials
from minio_tpu.s3.handlers import S3ApiHandlers
from minio_tpu.s3.server import S3Server

LC_XML = """<LifecycleConfiguration>
  <Rule><ID>exp-tmp</ID><Status>Enabled</Status>
    <Filter><Prefix>tmp/</Prefix></Filter>
    <Expiration><Days>1</Days></Expiration></Rule>
  <Rule><ID>off</ID><Status>Disabled</Status>
    <Filter><Prefix>keep/</Prefix></Filter>
    <Expiration><Days>1</Days></Expiration></Rule>
</LifecycleConfiguration>"""

NOTIF_XML = """<NotificationConfiguration>
  <QueueConfiguration>
    <Queue>arn:minio:sqs::t1:webhook</Queue>
    <Event>s3:ObjectCreated:*</Event>
    <Filter><S3Key>
      <FilterRule><Name>suffix</Name><Value>.log</Value></FilterRule>
    </S3Key></Filter>
  </QueueConfiguration>
  <QueueConfiguration>
    <Queue>arn:minio:sqs::t2:webhook</Queue>
    <Event>s3:ObjectRemoved:*</Event>
  </QueueConfiguration>
</NotificationConfiguration>"""


def _mk_sets(root, n=4, parity=2):
    drives = [str(root / f"d{i}") for i in range(n)]
    return ErasureSets.from_drives(drives, set_count=1, set_drive_count=n,
                                   parity=parity, block_size=1 << 16)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def test_lifecycle_parse_and_eval():
    lc = Lifecycle.from_xml(LC_XML)
    assert len(lc.rules) == 2
    now = time.time()
    old = now - 2 * 86400
    assert lc.is_expired("tmp/a", old, now)
    assert not lc.is_expired("tmp/a", now, now)         # too young
    assert not lc.is_expired("data/a", old, now)        # prefix miss
    assert not lc.is_expired("keep/a", old, now)        # disabled rule


def test_lifecycle_enforced_by_crawler(tmp_path):
    sets = _mk_sets(tmp_path)
    api = S3ApiHandlers(sets)
    sets.make_bucket("lc")
    sets.put_object("lc", "tmp/old", b"stale")
    sets.put_object("lc", "data/fresh", b"fresh")
    api.bucket_meta.update("lc", lifecycle_xml=LC_XML)

    # pretend 2 days pass (inject the clock instead of rewriting mtimes)
    future = time.time() + 2 * 86400
    crawler = DataUsageCrawler(
        sets, persist=False,
        actions=[crawler_action(api.bucket_meta, sets,
                                now_fn=lambda: future)])
    crawler.scan_once()

    from minio_tpu.object import api_errors
    with pytest.raises(api_errors.ObjectNotFound):
        sets.get_object_info("lc", "tmp/old")
    assert sets.get_object_info("lc", "data/fresh").size == 5
    sets.close()


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------

def test_event_rules_and_memory_target(tmp_path):
    sets = _mk_sets(tmp_path)
    api = S3ApiHandlers(sets)
    sets.make_bucket("ev")
    api.bucket_meta.update("ev", notification_xml=NOTIF_XML)
    notifier = EventNotifier(api.bucket_meta)
    t1, t2 = MemoryTarget("arn:minio:sqs::t1:webhook"), \
        MemoryTarget("arn:minio:sqs::t2:webhook")
    notifier.register_target(t1)
    notifier.register_target(t2)

    notifier.send("s3:ObjectCreated:Put", "ev", "app.log", 42, "etag1")
    notifier.send("s3:ObjectCreated:Put", "ev", "app.txt")   # suffix miss
    notifier.send("s3:ObjectRemoved:Delete", "ev", "x")
    notifier.drain()
    assert t1.wait_for(1) and len(t1.records) == 1
    rec = t1.records[0]["Records"][0]
    assert rec["eventName"] == "s3:ObjectCreated:Put"
    assert rec["s3"]["object"]["key"] == "app.log"
    assert rec["s3"]["object"]["size"] == 42
    assert t2.wait_for(1) and \
        t2.records[0]["Records"][0]["eventName"] == "s3:ObjectRemoved:Delete"
    notifier.close()
    sets.close()


def test_webhook_target_delivery(tmp_path):
    got = []

    class Hook(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            got.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    sets = _mk_sets(tmp_path)
    api = S3ApiHandlers(sets)
    sets.make_bucket("wh")
    api.bucket_meta.update("wh", notification_xml=NOTIF_XML.replace(
        "t1", "hook").replace(".log", ".bin"))
    notifier = EventNotifier(api.bucket_meta)
    notifier.register_target(WebhookTarget(
        "arn:minio:sqs::hook:webhook",
        f"http://127.0.0.1:{httpd.server_address[1]}/events"))
    notifier.send("s3:ObjectCreated:Put", "wh", "a.bin", 7)
    notifier.drain()
    deadline = time.time() + 5
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got and got[0]["Records"][0]["s3"]["object"]["key"] == "a.bin"
    notifier.close()
    httpd.shutdown()
    sets.close()


# ---------------------------------------------------------------------------
# replication (two live S3 endpoints in-process)
# ---------------------------------------------------------------------------

REPL_XML = """<ReplicationConfiguration>
  <Role>arn:minio:replication</Role>
  <Rule><ID>r1</ID><Status>Enabled</Status>
    <Prefix></Prefix>
    <DeleteMarkerReplication><Status>Enabled</Status>
    </DeleteMarkerReplication>
    <Destination><Bucket>arn:minio:replication::dst:target</Bucket>
    </Destination></Rule>
</ReplicationConfiguration>"""


def test_replication_end_to_end(tmp_path):
    creds = Credentials("replsrckey1", "replsrcsecret1")
    src = _mk_sets(tmp_path / "src")
    dst = _mk_sets(tmp_path / "dst")
    dst_srv = S3Server(dst, creds=creds).start()
    try:
        src.make_bucket("srcb")
        dst.make_bucket("dstb")
        api = S3ApiHandlers(src, creds=creds)
        api.bucket_meta.update("srcb", replication_xml=REPL_XML)

        pool = ReplicationPool(src, api.bucket_meta)
        pool.register_target(ReplicationTarget(
            arn="arn:minio:replication::dst:target",
            host="127.0.0.1", port=dst_srv.port, bucket="dstb",
            access_key=creds.access_key, secret_key=creds.secret_key))
        api.replication = pool

        assert pool.must_replicate("srcb", "obj1")
        src.put_object("srcb", "obj1", b"replicate me",
                       )
        api._notify("s3:ObjectCreated:Put", "srcb", "obj1")
        pool.drain()
        deadline = time.time() + 5
        while pool.replicated < 1 and time.time() < deadline:
            time.sleep(0.05)
        _, stream = dst.get_object("dstb", "obj1")
        assert b"".join(stream) == b"replicate me"

        # delete replication
        src.delete_object("srcb", "obj1")
        api._notify("s3:ObjectRemoved:Delete", "srcb", "obj1")
        pool.drain()
        deadline = time.time() + 5
        from minio_tpu.object import api_errors
        while time.time() < deadline:
            try:
                dst.get_object_info("dstb", "obj1")
                time.sleep(0.05)
            except api_errors.ObjectApiError:
                break
        with pytest.raises(api_errors.ObjectApiError):
            dst.get_object_info("dstb", "obj1")
        pool.close()
    finally:
        dst_srv.stop()
        src.close()
        dst.close()

def test_noncurrent_version_expiry(tmp_path):
    """NoncurrentVersionExpiration: the clock starts when a version
    BECAME noncurrent (its successor's mod time), the sweep runs per
    bucket so delete-marker-latest keys are covered too."""
    from minio_tpu.features.lifecycle import noncurrent_sweep_action
    sets = _mk_sets(tmp_path)
    api = S3ApiHandlers(sets)
    sets.make_bucket("ncb")
    api.bucket_meta.update("ncb", versioning="Enabled")
    from minio_tpu.object.engine import PutOptions
    for i in range(3):
        sets.put_object("ncb", "doc", f"v{i}".encode(),
                        opts=PutOptions(versioned=True))
    assert len(sets.list_object_versions("ncb", prefix="doc")[0]) == 3
    # a second key whose LATEST is a delete marker (invisible to
    # object listings)
    sets.put_object("ncb", "gone", b"old",
                    opts=PutOptions(versioned=True))
    sets.delete_object("ncb", "gone", versioned=True)

    lc = ("<LifecycleConfiguration><Rule><ID>nc</ID>"
          "<Status>Enabled</Status><Filter><Prefix></Prefix></Filter>"
          "<NoncurrentVersionExpiration><NoncurrentDays>1"
          "</NoncurrentDays></NoncurrentVersionExpiration>"
          "</Rule></LifecycleConfiguration>")
    api.bucket_meta.update("ncb", lifecycle_xml=lc)

    # versions became noncurrent "now": a sweep at +12h must keep them
    now = time.time()
    act = noncurrent_sweep_action(api.bucket_meta, sets,
                                  now_fn=lambda: now + 12 * 3600)
    act("ncb")
    assert len(sets.list_object_versions("ncb", prefix="doc")[0]) == 3

    # at +2d they are past NoncurrentDays=1: only the latest survives,
    # and the delete-marker key's data version is purged too
    act2 = noncurrent_sweep_action(api.bucket_meta, sets,
                                   now_fn=lambda: now + 2 * 86400)
    act2("ncb")
    versions = sets.list_object_versions("ncb", prefix="doc")[0]
    assert len(versions) == 1 and versions[0].is_latest
    _, stream = sets.get_object("ncb", "doc")
    assert b"".join(stream) == b"v2"
    gone = sets.list_object_versions("ncb", prefix="gone")[0]
    assert all(v.delete_marker for v in gone)
    sets.close()


def test_stale_multipart_abort(tmp_path):
    """AbortIncompleteMultipartUpload: uploads older than the cutoff are
    aborted; younger ones survive."""
    from minio_tpu.features.lifecycle import mpu_abort_action
    sets = _mk_sets(tmp_path)
    api = S3ApiHandlers(sets)
    sets.make_bucket("mab")
    uid_a = sets.new_multipart_upload("mab", "upload-a")
    uid_b = sets.new_multipart_upload("mab", "upload-b")
    lc = ("<LifecycleConfiguration><Rule><ID>abort</ID>"
          "<Status>Enabled</Status><Filter><Prefix></Prefix></Filter>"
          "<AbortIncompleteMultipartUpload><DaysAfterInitiation>3"
          "</DaysAfterInitiation></AbortIncompleteMultipartUpload>"
          "</Rule></LifecycleConfiguration>")
    api.bucket_meta.update("mab", lifecycle_xml=lc)
    now = time.time()

    # +2 days: both uploads younger than the 3-day cutoff -> kept
    mpu_abort_action(api.bucket_meta, sets,
                     now_fn=lambda: now + 2 * 86400)("mab")
    assert {u["upload_id"] for u in sets.list_multipart_uploads("mab")} \
        == {uid_a, uid_b}

    # +4 days: both past the cutoff -> aborted
    mpu_abort_action(api.bucket_meta, sets,
                     now_fn=lambda: now + 4 * 86400)("mab")
    assert sets.list_multipart_uploads("mab") == []
    sets.close()


# ---------------------------------------------------------------------------
# lifecycle Transition / NoncurrentVersionTransition parsing (ILM tiering)
# ---------------------------------------------------------------------------

def test_lifecycle_transition_parse_days_and_storage_class():
    lc = Lifecycle.from_xml("""<LifecycleConfiguration>
      <Rule><ID>t1</ID><Status>Enabled</Status><Prefix>logs/</Prefix>
        <Transition><Days>30</Days><StorageClass>GLACIER</StorageClass>
        </Transition>
        <NoncurrentVersionTransition><NoncurrentDays>7</NoncurrentDays>
          <StorageClass>DEEP</StorageClass>
        </NoncurrentVersionTransition>
      </Rule>
    </LifecycleConfiguration>""")
    r = lc.rules[0]
    assert r.transition_days == 30
    assert r.transition_tier == "GLACIER"
    assert r.noncurrent_transition_days == 7
    assert r.noncurrent_transition_tier == "DEEP"
    now = time.time()
    # not due before 30 days, due after; prefix must match
    assert lc.transition_due("logs/a", now - 10 * 86400, now) == ""
    assert lc.transition_due("logs/a", now - 31 * 86400, now) == "GLACIER"
    assert lc.transition_due("other/a", now - 31 * 86400, now) == ""
    assert lc.noncurrent_transition("logs/a") == (7, "DEEP")
    assert lc.noncurrent_transition("other/a") == (0, "")


def test_lifecycle_transition_parse_date():
    lc = Lifecycle.from_xml("""<LifecycleConfiguration>
      <Rule><Status>Enabled</Status><Prefix></Prefix>
        <Transition><Date>2020-01-01T00:00:00Z</Date>
          <StorageClass>cold</StorageClass></Transition>
      </Rule>
    </LifecycleConfiguration>""")
    r = lc.rules[0]
    assert r.transition_date > 0 and r.transition_days == 0
    # the date is long past: any object is due regardless of age
    assert lc.transition_due("k", time.time(), time.time()) == "cold"


def test_lifecycle_transition_namespaced_xml():
    ns = "http://s3.amazonaws.com/doc/2006-03-01/"
    lc = Lifecycle.from_xml(
        f'<LifecycleConfiguration xmlns="{ns}">'
        "<Rule><Status>Enabled</Status><Prefix></Prefix>"
        "<Transition><Days>1</Days><StorageClass>tz</StorageClass>"
        "</Transition></Rule></LifecycleConfiguration>")
    assert lc.rules[0].transition_tier == "tz"
    assert lc.rules[0].transition_days == 1


def test_lifecycle_transition_precedence_vs_expiry():
    """Expiry wins when both are due (transition_due answers "" — the
    reference's ComputeAction precedence: never upload data the same
    pass deletes)."""
    lc = Lifecycle.from_xml("""<LifecycleConfiguration>
      <Rule><Status>Enabled</Status><Prefix></Prefix>
        <Expiration><Days>5</Days></Expiration>
        <Transition><Days>1</Days><StorageClass>cold</StorageClass>
        </Transition>
      </Rule>
    </LifecycleConfiguration>""")
    now = time.time()
    # only the transition is due: transition wins
    assert lc.transition_due("k", now - 2 * 86400, now) == "cold"
    # both due: expiry wins
    assert lc.transition_due("k", now - 6 * 86400, now) == ""
    assert lc.is_expired("k", now - 6 * 86400, now)


def test_lifecycle_transition_disabled_and_tierless_rules_ignored():
    lc = Lifecycle.from_xml("""<LifecycleConfiguration>
      <Rule><Status>Disabled</Status><Prefix></Prefix>
        <Transition><Days>1</Days><StorageClass>cold</StorageClass>
        </Transition></Rule>
      <Rule><Status>Enabled</Status><Prefix></Prefix>
        <Transition><Days>1</Days></Transition></Rule>
    </LifecycleConfiguration>""")
    now = time.time()
    # disabled rule + rule with no StorageClass: nothing actionable
    assert lc.transition_due("k", now - 9 * 86400, now) == ""


def test_lifecycle_malformed_xml_raises():
    import xml.etree.ElementTree as ET
    with pytest.raises(ET.ParseError):
        Lifecycle.from_xml("<LifecycleConfiguration><Rule>")
    with pytest.raises(ValueError):
        Lifecycle.from_xml("""<LifecycleConfiguration>
          <Rule><Status>Enabled</Status><Prefix></Prefix>
            <Transition><Days>NaN</Days>
              <StorageClass>c</StorageClass></Transition>
          </Rule></LifecycleConfiguration>""")


def test_lifecycle_noncurrent_transition_strictest_rule_wins():
    lc = Lifecycle.from_xml("""<LifecycleConfiguration>
      <Rule><Status>Enabled</Status><Prefix></Prefix>
        <NoncurrentVersionTransition><NoncurrentDays>30</NoncurrentDays>
          <StorageClass>warm</StorageClass>
        </NoncurrentVersionTransition></Rule>
      <Rule><Status>Enabled</Status><Prefix></Prefix>
        <NoncurrentVersionTransition><NoncurrentDays>7</NoncurrentDays>
          <StorageClass>cold</StorageClass>
        </NoncurrentVersionTransition></Rule>
    </LifecycleConfiguration>""")
    assert lc.noncurrent_transition("any") == (7, "cold")
