"""Streamed remote CreateFile/ReadFileStream (VERDICT r4 weak #5 /
next-round #7): large shard bodies flow through the storage RPC in
bounded chunks — no whole-shard staging on either end — with a
subprocess RSS assertion for a big remote write+read."""

from __future__ import annotations

import io
import os
import subprocess
import sys

import pytest

from minio_tpu.distributed.storage_rpc import (RemoteStorage,
                                               StorageRPCServer)
from minio_tpu.distributed.transport import RPCServer
from minio_tpu.storage import errors as serr
from minio_tpu.storage import new_format_erasure_v3
from minio_tpu.storage.xl_storage import XLStorage

AK, SK = "streamkey", "streamsecret123"


@pytest.fixture()
def node(tmp_path):
    fmts = new_format_erasure_v3(1, 1)
    drive = XLStorage(str(tmp_path / "d0"))
    drive.write_format(fmts[0][0])
    srv = StorageRPCServer({"/d0": drive}, AK, SK)
    host = RPCServer().start()
    host.mount(srv.handler)
    remote = RemoteStorage("127.0.0.1", host.port, "/d0", AK, SK)
    yield drive, remote, srv
    remote.close()
    host.stop()
    drive.close()


class ChunkTracker(io.RawIOBase):
    """Reader that records how the client consumes it: a streaming
    sender issues many bounded read(n) calls; a buffering one slurps
    everything at once."""

    def __init__(self, total: int, chunk: int = 64 << 10):
        self.total = total
        self.served = 0
        self.max_read = 0
        self.calls = 0

    def read(self, n: int = -1) -> bytes:
        self.calls += 1
        if n is None or n < 0:
            n = self.total - self.served
        self.max_read = max(self.max_read, n)
        n = min(n, self.total - self.served)
        if n <= 0:
            return b""
        start = self.served
        self.served += n
        # deterministic but position-dependent content
        return bytes(((start + i) * 31 + 7) & 0xFF for i in range(n))


def _expected(total: int) -> bytes:
    return bytes(((i) * 31 + 7) & 0xFF for i in range(total))


def test_create_file_streams_in_bounded_chunks(node):
    drive, remote, _srv = node
    remote.make_vol("v")
    total = 8 << 20
    tracker = ChunkTracker(total)
    remote.create_file("v", "big/shard.bin", total, tracker)
    # the client pulled bounded chunks, never the whole body at once
    assert tracker.max_read <= 1 << 20, tracker.max_read
    assert tracker.calls >= total // (1 << 20)
    # bytes landed intact on the serving drive
    got = drive.read_file("v", "big/shard.bin", 0, total)
    assert got == _expected(total)


def test_read_file_stream_is_chunked_and_correct(node):
    drive, remote, _srv = node
    remote.make_vol("v")
    payload = _expected(4 << 20)
    drive.create_file("v", "r/shard.bin", len(payload),
                      io.BytesIO(payload))
    stream = remote.read_file_stream("v", "r/shard.bin", 0,
                                     len(payload))
    # file-like, incremental reads
    first = stream.read(1000)
    assert first == payload[:1000]
    rest = b""
    while True:
        chunk = stream.read(1 << 20)
        if not chunk:
            break
        rest += chunk
    stream.close()
    assert first + rest == payload
    # ranged stream
    stream = remote.read_file_stream("v", "r/shard.bin", 4096, 1 << 20)
    got = b""
    while True:
        chunk = stream.read(1 << 18)
        if not chunk:
            break
        got += chunk
    stream.close()
    assert got == payload[4096:4096 + (1 << 20)]


def test_read_file_stream_falls_back_without_verb(node):
    """Peers that predate the streaming verb still serve via the
    buffered readfile path."""
    _drive, remote, srv = node
    remote.make_vol("v")
    payload = _expected(1 << 16)
    remote.create_file("v", "fb.bin", len(payload),
                       io.BytesIO(payload))
    del srv.handler._verbs["readfilestream"]
    stream = remote.read_file_stream("v", "fb.bin", 0, len(payload))
    assert stream.read(-1) == payload


def test_short_body_surfaces_as_error(node):
    drive, remote, _srv = node
    remote.make_vol("v")

    class Short(io.RawIOBase):
        def read(self, n=-1):
            return b""                    # claims 1 MiB, sends none

    with pytest.raises(serr.StorageError):
        remote.create_file("v", "short.bin", 1 << 20, Short())


def test_missing_file_stream_error_maps(node):
    _drive, remote, _srv = node
    remote.make_vol("v")
    with pytest.raises(serr.StorageError):
        s = remote.read_file_stream("v", "ghost.bin", 0, 100)
        s.read(100)


_RSS_CHILD = r"""
import io, os, resource, sys
sys.path.insert(0, os.environ["REPO"])
from minio_tpu.distributed.storage_rpc import (RemoteStorage,
                                               StorageRPCServer)
from minio_tpu.distributed.transport import RPCServer
from minio_tpu.storage import new_format_erasure_v3
from minio_tpu.storage.xl_storage import XLStorage

root = os.environ["WORKDIR"]
fmts = new_format_erasure_v3(1, 1)
drive = XLStorage(os.path.join(root, "d0"))
drive.write_format(fmts[0][0])
host = RPCServer().start()
host.mount(StorageRPCServer({"/d0": drive}, "k", "s" * 12).handler)
remote = RemoteStorage("127.0.0.1", host.port, "/d0", "k", "s" * 12)
remote.make_vol("v")

TOTAL = 128 << 20

class Zeros(io.RawIOBase):
    def __init__(self):
        self.left = TOTAL
        self.blob = b"\xcd" * (1 << 20)
    def read(self, n=-1):
        if n is None or n < 0:
            n = self.left
        n = min(n, self.left, len(self.blob))
        self.left -= n
        return self.blob[:n]

# warm-up: load every code path before measuring
remote.create_file("v", "warm.bin", 1 << 20,
                   io.BytesIO(b"w" * (1 << 20)))
base_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024

# remote heal-style write of a 128 MiB shard (client+server in THIS
# process: the bound covers both ends)
remote.create_file("v", "big.bin", TOTAL, Zeros())
# and stream it back
stream = remote.read_file_stream("v", "big.bin", 0, TOTAL)
count = 0
while True:
    chunk = stream.read(1 << 20)
    if not chunk:
        break
    count += len(chunk)
stream.close()
assert count == TOTAL, count
rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
print(f"rss_mb={rss_mb:.0f} base_mb={base_mb:.0f}")
assert rss_mb - base_mb < 100, \
    f"remote shard write/read grew RSS by {rss_mb - base_mb:.0f} MB"
remote.close(); host.stop(); drive.close()
"""


def test_remote_big_shard_memory_bounded(tmp_path):
    workdir = "/dev/shm/mt-rpc-stream-test" if \
        os.path.isdir("/dev/shm") else str(tmp_path / "w")
    os.makedirs(workdir, exist_ok=True)
    env = dict(os.environ,
               REPO=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))),
               WORKDIR=workdir)
    try:
        proc = subprocess.run([sys.executable, "-c", _RSS_CHILD],
                              env=env, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "rss_mb=" in proc.stdout
    finally:
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
